"""Tensor (model) parallelism — Megatron-style param sharding rules.

Reference status: TP was absent (SURVEY §2.2 row "Tensor/model parallel —
partial": only pserver-sharded embeddings via parameter_prefetch.cc). This is
a first-class capability here: parameters get PartitionSpec annotations and
GSPMD inserts the all-reduces a hand-written Megatron implementation would.

Rules map param-name regexes → PartitionSpec tuples. Column-parallel weights
shard the output dim, row-parallel shard the input dim; GSPMD then emits one
psum per transformer block (after attn-out and ffn2), exactly the Megatron
communication pattern, riding ICI.
"""
from __future__ import annotations

import re
import warnings
from typing import Dict, Optional, Sequence, Tuple

from ..core.program import Parameter, Program

# rule: regex on param name → spec template with 'tp' marking the sharded dim
MEGATRON_RULES: Sequence[Tuple[str, Tuple]] = (
    (r".*\.qkv\.w$", (None, "tp")),      # column parallel
    (r".*\.qkv\.b$", ("tp",)),
    (r".*\.attn_out\.w$", ("tp", None)),  # row parallel
    (r".*\.ffn1\.w$", (None, "tp")),
    (r".*\.ffn1\.b$", ("tp",)),
    (r".*\.ffn2\.w$", ("tp", None)),
    (r"word_embedding$", ("tp", None)),   # vocab-sharded embedding
    (r"mlm_out\.w$", (None, "tp")),
    (r"mlm_out\.b$", ("tp",)),
)

# transformer_nmt (models/transformer_nmt.py) naming: separate q/k/v
# projections, `o` attention output, shared ffn1/ffn2 naming, vocab-sharded
# embeddings and output projection.
NMT_RULES: Sequence[Tuple[str, Tuple]] = (
    (r".*\.(q|k|v)\.w$", (None, "tp")),   # column parallel
    (r".*\.o\.w$", ("tp", None)),         # row parallel
    (r".*\.ffn1\.w$", (None, "tp")),
    (r".*\.ffn1\.b$", ("tp",)),
    (r".*\.ffn2\.w$", ("tp", None)),
    (r"(src|tgt)_embedding$", ("tp", None)),
    (r"out_proj\.w$", (None, "tp")),
)

# DeepFM (models/deepfm.py): the Criteo-scale tables are the only params
# worth sharding — vocab(row)-split, the pserver-lookup-table replacement.
DEEPFM_RULES: Sequence[Tuple[str, Tuple]] = (
    (r"fm_emb$", ("tp", None)),
    (r"fm_w1$", ("tp", None)),
)


def annotate_tp(program: Program, rules: Sequence[Tuple[str, Tuple]] = MEGATRON_RULES,
                axis: str = "tp") -> int:
    """Attach shard_spec to matching parameters. Returns #annotated.
    CompiledProgram.with_mesh then places them (compiler.py _state_sharding).

    Build-time alternative: any layer accepts
    ``param_attr=ParamAttr(shard_spec=(..., "tp"))`` — LayerHelper carries it
    onto the Parameter directly, no rules needed (models/bert.py uses this)."""
    count = 0
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    params = list(program.all_parameters())
    for p in params:
        for pat, spec in compiled:
            if pat.match(p.name):
                p.shard_spec = tuple(axis if s == "tp" else s for s in spec)
                count += 1
                break
    if count == 0 and params:
        warnings.warn(
            "annotate_tp matched ZERO of the program's "
            f"{len(params)} parameters — the rules do not fit this model's "
            "param names (first few: "
            f"{[p.name for p in params[:5]]}); no tensor-parallel sharding "
            "will be applied. Pass model-specific rules (e.g. NMT_RULES, "
            "DEEPFM_RULES) or set ParamAttr(shard_spec=...) at build time.",
            stacklevel=2)
    return count


def embedding_shard_spec(axis: str = "tp"):
    """Row(vocab)-sharded embedding table spec — the TPU replacement for the
    reference's distributed_lookup_table pserver path (SURVEY §2.2)."""
    return (axis, None)


# ops a Megatron shard region may flow through without leaving the region:
# pure per-position transforms plus the attention internals (softmax over
# head-sharded scores, var-var matmuls). layer_norm / batch_norm / the
# reductions are BARRIERS: Megatron normalizes on replicated activations,
# so a chain crossing one is not a col→row pair.
_PASS_OPS = frozenset({
    "elementwise_add", "elementwise_sub", "elementwise_mul", "dropout",
    "relu", "gelu", "tanh", "sigmoid", "swish", "silu", "leaky_relu",
    "reshape", "reshape2", "transpose", "transpose2", "scale", "split",
    "concat", "stack", "unsqueeze", "unsqueeze2", "squeeze", "squeeze2",
    "cast", "softmax",
})
_ACT_SET = frozenset({"relu", "gelu", "tanh", "sigmoid", "swish", "silu",
                      "leaky_relu"})


def derive_tp_specs(program: Program, axis: str = "tp",
                    min_embed_rows: int = 1024,
                    min_matmul_dim: int = 512) -> Dict[str, Tuple]:
    """Derive Megatron-style shard specs STRUCTURALLY — from the
    program's op patterns, with no per-model name-regex table (VERDICT r3
    weak #4 / next #7). Returns {param_name: spec} without mutating the
    program; :func:`annotate_tp_auto` applies them.

    Patterns recognized (each mirrors a hand rule in
    MEGATRON_RULES/NMT_RULES/DEEPFM_RULES):

    - **embedding tables**: a `lookup_table(_v2)` weight with ≥
      ``min_embed_rows`` rows is vocab(row)-sharded — the
      parameter_prefetch.cc replacement. The row threshold keeps small
      position/segment tables replicated.
    - **col→row matmul pairs**: a 2-D weight whose matmul output flows
      through per-position ops / attention internals into ANOTHER 2-D
      weight's matmul is column-parallel, the second weight
      row-parallel (ffn1→ffn2; q/k/v or fused qkv → attention output
      projection — the split/softmax/var-var-matmul internals are
      pass-through). Chains never cross layer_norm/batch_norm/reductions
      (Megatron normalizes replicated activations).
    - **vocab heads**: a weight whose matmul output reaches
      softmax(_with_cross_entropy) with no later param matmul is
      column-parallel (mlm_out, out_proj).
    - **column biases**: a 1-D param added to a column-sharded output
      BEFORE any further matmul/softmax is sharded too; a row-parallel
      output's bias (added after the implied psum) stays replicated.
    - dims below ``min_matmul_dim`` stay replicated (DeepFM's 400-wide
      MLP is cheaper replicated than gathered).
    """
    all_ops = [op for blk in program.blocks for op in blk.ops]
    params = {p.name for p in program.all_parameters()}
    shapes = {p.name: tuple(p.shape) for p in program.all_parameters()}
    consumers: Dict[str, list] = {}
    for op in all_ops:
        for slot, names in op.inputs.items():
            for n in names:
                consumers.setdefault(n, []).append((op, slot))

    specs: Dict[str, Tuple] = {}

    def set_spec(name, spec):
        if name in specs and specs[name] != spec:
            warnings.warn(
                f"derive_tp_specs: {name} matches conflicting patterns "
                f"{specs[name]} vs {spec}; leaving it replicated",
                stacklevel=3)
            specs[name] = None
            return
        specs[name] = spec

    # 1. embedding tables
    for op in all_ops:
        if op.type in ("lookup_table", "lookup_table_v2"):
            (w,) = op.inputs.get("W", [None]) or [None]
            if w in params and shapes[w][0] >= min_embed_rows:
                set_spec(w, (axis, None))

    # 2/3. matmul-weight chains. candidates: mul/matmul with a 2-D param
    # as Y and a non-param activation as X
    def _transposed(op):
        return bool(op.attrs.get("transpose_Y") or op.attrs.get("trans_y"))

    def _out_dim(w, op):
        # output dim of y in x@y (or x@y.T): the dim a COLUMN shard splits
        return shapes[w][0] if _transposed(op) else shapes[w][1]

    def _in_dim(w, op):
        return shapes[w][1] if _transposed(op) else shapes[w][0]

    def _col_spec(op):
        return (axis, None) if _transposed(op) else (None, axis)

    def _row_spec(op):
        return (None, axis) if _transposed(op) else (axis, None)

    weight_matmuls = {}          # out var -> (weight name, matmul op)
    for op in all_ops:
        if op.type in ("mul", "matmul"):
            xs = op.inputs.get("X", [])
            ys = op.inputs.get("Y", [])
            if (len(ys) == 1 and ys[0] in params
                    and len(shapes[ys[0]]) == 2
                    and (not xs or xs[0] not in params)):
                weight_matmuls[op.outputs["Out"][0]] = (ys[0], op)

    row_proposals: Dict[str, Tuple] = {}
    for out_var, (w, w_op) in weight_matmuls.items():
        col_ok = _out_dim(w, w_op) >= min_matmul_dim
        # BFS through the shard region
        seen = set()
        frontier = [(out_var, True)]   # (var, still-pure-elementwise)
        biases = []
        paired_row = None            # (name, its matmul op)
        is_head = False
        while frontier:
            var, pure = frontier.pop()
            if var in seen:
                continue
            seen.add(var)
            for cop, slot in consumers.get(var, ()):
                if cop.type in ("mul", "matmul"):
                    w2 = cop.inputs.get("Y", [None])
                    w2 = w2[0] if w2 else None
                    if (slot == "X" and w2 in params
                            and len(shapes[w2]) == 2):
                        paired_row = paired_row or (w2, cop)
                        continue   # the pair ends this branch
                    # var-var matmul (attention scores/context): continue
                    for o in cop.outputs.get("Out", []):
                        frontier.append((o, False))
                    continue
                if cop.type in ("softmax_with_cross_entropy",
                                "cross_entropy"):
                    if slot in ("Logits", "X"):
                        is_head = True
                    continue
                if cop.type == "softmax" and pure:
                    # a softmax DIRECTLY on the matmul(+bias) output is a
                    # classifier head (attention softmaxes arrive through
                    # var-var score matmuls, i.e. pure=False)
                    is_head = True
                if cop.type not in _PASS_OPS:
                    continue       # barrier (layer_norm, reduce, ...)
                if cop.type == "elementwise_add" and pure:
                    others = [n for s, ns in cop.inputs.items()
                              for n in ns if n != var]
                    for b in others:
                        if b in params and len(shapes[b]) == 1:
                            biases.append(b)
                nxt_pure = pure and cop.type not in ("softmax",) \
                    and cop.type not in _ACT_SET
                for onames in cop.outputs.values():
                    for o in onames:
                        frontier.append((o, nxt_pure))
        if (paired_row or is_head) and col_ok:
            set_spec(w, _col_spec(w_op))
            for b in biases:
                set_spec(b, (axis,))
        if paired_row and col_ok:
            w2, w2_op = paired_row
            if _in_dim(w2, w2_op) >= min_matmul_dim:
                prop = _row_spec(w2_op)
                if w2 in row_proposals and row_proposals[w2] != prop:
                    warnings.warn(
                        f"derive_tp_specs: {w2} terminates col→row chains "
                        f"with conflicting orientations "
                        f"{row_proposals[w2]} vs {prop} (mixed transpose_y "
                        f"uses); leaving it replicated", stacklevel=2)
                    row_proposals[w2] = None
                elif w2 not in row_proposals:
                    row_proposals[w2] = prop

    # row-parallel is the WEAKEST classification: a tied embedding+head
    # weight is both the terminus of a col→row chain AND a vocab head /
    # lookup table — the head/lookup spec (shard the vocab dim) serves
    # every use, so it wins and the row proposal is dropped silently.
    for name, spec in row_proposals.items():
        if name not in specs and spec is not None:
            specs[name] = spec

    return {n: s for n, s in specs.items() if s is not None}


def annotate_tp_auto(program: Program, axis: str = "tp", **kwargs) -> int:
    """Structural :func:`annotate_tp`: derive specs from the program's op
    graph (derive_tp_specs) and attach them. Returns #annotated."""
    specs = derive_tp_specs(program, axis=axis, **kwargs)
    for p in program.all_parameters():
        if p.name in specs:
            p.shard_spec = specs[p.name]
    if not specs and list(program.all_parameters()):
        warnings.warn(
            "annotate_tp_auto derived ZERO shardable parameters — the "
            "program has no large embedding tables, Megatron matmul "
            "pairs, or vocab heads; everything stays replicated.",
            stacklevel=2)
    return len(specs)
