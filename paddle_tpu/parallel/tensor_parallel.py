"""Tensor (model) parallelism — Megatron-style param sharding rules.

Reference status: TP was absent (SURVEY §2.2 row "Tensor/model parallel —
partial": only pserver-sharded embeddings via parameter_prefetch.cc). This is
a first-class capability here: parameters get PartitionSpec annotations and
GSPMD inserts the all-reduces a hand-written Megatron implementation would.

Rules map param-name regexes → PartitionSpec tuples. Column-parallel weights
shard the output dim, row-parallel shard the input dim; GSPMD then emits one
psum per transformer block (after attn-out and ffn2), exactly the Megatron
communication pattern, riding ICI.
"""
from __future__ import annotations

import re
import warnings
from typing import Dict, Optional, Sequence, Tuple

from ..core.program import Parameter, Program

# rule: regex on param name → spec template with 'tp' marking the sharded dim
MEGATRON_RULES: Sequence[Tuple[str, Tuple]] = (
    (r".*\.qkv\.w$", (None, "tp")),      # column parallel
    (r".*\.qkv\.b$", ("tp",)),
    (r".*\.attn_out\.w$", ("tp", None)),  # row parallel
    (r".*\.ffn1\.w$", (None, "tp")),
    (r".*\.ffn1\.b$", ("tp",)),
    (r".*\.ffn2\.w$", ("tp", None)),
    (r"word_embedding$", ("tp", None)),   # vocab-sharded embedding
    (r"mlm_out\.w$", (None, "tp")),
    (r"mlm_out\.b$", ("tp",)),
)

# transformer_nmt (models/transformer_nmt.py) naming: separate q/k/v
# projections, `o` attention output, shared ffn1/ffn2 naming, vocab-sharded
# embeddings and output projection.
NMT_RULES: Sequence[Tuple[str, Tuple]] = (
    (r".*\.(q|k|v)\.w$", (None, "tp")),   # column parallel
    (r".*\.o\.w$", ("tp", None)),         # row parallel
    (r".*\.ffn1\.w$", (None, "tp")),
    (r".*\.ffn1\.b$", ("tp",)),
    (r".*\.ffn2\.w$", ("tp", None)),
    (r"(src|tgt)_embedding$", ("tp", None)),
    (r"out_proj\.w$", (None, "tp")),
)

# DeepFM (models/deepfm.py): the Criteo-scale tables are the only params
# worth sharding — vocab(row)-split, the pserver-lookup-table replacement.
DEEPFM_RULES: Sequence[Tuple[str, Tuple]] = (
    (r"fm_emb$", ("tp", None)),
    (r"fm_w1$", ("tp", None)),
)


def annotate_tp(program: Program, rules: Sequence[Tuple[str, Tuple]] = MEGATRON_RULES,
                axis: str = "tp") -> int:
    """Attach shard_spec to matching parameters. Returns #annotated.
    CompiledProgram.with_mesh then places them (compiler.py _state_sharding).

    Build-time alternative: any layer accepts
    ``param_attr=ParamAttr(shard_spec=(..., "tp"))`` — LayerHelper carries it
    onto the Parameter directly, no rules needed (models/bert.py uses this)."""
    count = 0
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    params = list(program.all_parameters())
    for p in params:
        for pat, spec in compiled:
            if pat.match(p.name):
                p.shard_spec = tuple(axis if s == "tp" else s for s in spec)
                count += 1
                break
    if count == 0 and params:
        warnings.warn(
            "annotate_tp matched ZERO of the program's "
            f"{len(params)} parameters — the rules do not fit this model's "
            "param names (first few: "
            f"{[p.name for p in params[:5]]}); no tensor-parallel sharding "
            "will be applied. Pass model-specific rules (e.g. NMT_RULES, "
            "DEEPFM_RULES) or set ParamAttr(shard_spec=...) at build time.",
            stacklevel=2)
    return count


def embedding_shard_spec(axis: str = "tp"):
    """Row(vocab)-sharded embedding table spec — the TPU replacement for the
    reference's distributed_lookup_table pserver path (SURVEY §2.2)."""
    return (axis, None)
