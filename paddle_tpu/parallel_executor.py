"""fluid.parallel_executor (reference parallel_executor.py ParallelExecutor).

Compat wrapper: the C++ ParallelExecutor's role (clone graph per device +
NCCL all-reduce, parallel_executor.cc:356) is played by
`CompiledProgram.with_data_parallel` over GSPMD. This class keeps the
constructor/run surface for scripts that used ParallelExecutor directly.
"""
from __future__ import annotations

from typing import Optional

from .core.compiler import (BuildStrategy, CompiledProgram,
                            ExecutionStrategy, ShardingStrategy)
from .core.executor import Executor, TPUPlace
from .core.program import default_main_program
from .observability import get_registry, trace_span

__all__ = ["ParallelExecutor", "BuildStrategy", "ExecutionStrategy",
           "ShardingStrategy"]


class ParallelExecutor:
    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None):
        self._program = main_program or default_main_program()
        build_strategy = build_strategy or BuildStrategy()
        self._compiled = CompiledProgram(self._program).with_data_parallel(
            loss_name=loss_name, build_strategy=build_strategy,
            exec_strategy=exec_strategy,
            share_vars_from=getattr(share_vars_from, "_compiled", None))
        self._exe = Executor(TPUPlace())
        self._scope = scope
        # build_strategy.sharding_strategy (ZeRO state sharding) is honored
        # by the compiled program; surfaced here for introspection
        self.sharding_strategy = getattr(
            build_strategy, "sharding_strategy", ShardingStrategy.off)
        # set on EVERY construction — a later ParallelExecutor over a
        # different device set must not leave the first one's count exported
        get_registry().gauge("executor/device_count").set(self.device_count)

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else feed_dict
        with trace_span("parallel_executor/run"):
            return self._exe.run(self._compiled, feed=feed,
                                 fetch_list=list(fetch_list),
                                 scope=self._scope, return_numpy=return_numpy)

    @property
    def device_count(self):
        mesh = getattr(self._compiled, "_mesh", None)
        if mesh is not None:
            return int(mesh.size)
        import jax
        return jax.local_device_count()
