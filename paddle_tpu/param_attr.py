"""ParamAttr (reference python/paddle/fluid/param_attr.py)."""
from __future__ import annotations

from typing import Optional


class ParamAttr:
    def __init__(self, name: Optional[str] = None, initializer=None,
                 learning_rate: float = 1.0, regularizer=None,
                 trainable: bool = True, do_model_average: bool = False,
                 need_clip: bool = True, shard_spec=None):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip
        # TPU-native extension: PartitionSpec-style sharding for pjit lowering
        self.shard_spec = shard_spec

    @staticmethod
    def _to_attr(arg) -> Optional["ParamAttr"]:
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if arg is False:
            return None
        if isinstance(arg, (list, tuple)):
            return [ParamAttr._to_attr(a) for a in arg]
        raise TypeError(f"cannot convert {arg!r} to ParamAttr")


WeightNormParamAttr = ParamAttr  # weight-norm reparam: not yet specialized
