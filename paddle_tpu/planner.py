"""Pre-compile HBM budget planner.

A device OOM on TPU is a bare ``RESOURCE_EXHAUSTED`` that arrives AFTER
minutes of compilation — the most expensive possible way to learn that a
config doesn't fit. This module moves the discovery before the first real
compile: it walks a ladder of (sharding stage, remat policy, microbatch K)
candidates from cheapest-to-run to most-memory-frugal, estimates each one's
per-device footprint, and picks the first that fits a configurable budget.

Estimation prefers the compiler's own numbers: the candidate step function
is lowered and compiled against ``jax.ShapeDtypeStruct`` arguments (no
values are materialized) and XLA's ``memory_analysis()`` supplies
per-device argument/temp/output bytes — exact for the given shapes, and
cheap relative to one training step on real inputs. When the backend
exposes no cost model the planner falls back to an analytic lower bound
(shard-aware state + gradient + feed bytes) and says so in the plan.

The decision is observable: registry gauges (``planner/*``, served at
``/metrics.json``), a flight-recorder event, and a ``hbm_plan`` forensic
dump section so a later OOM post-mortem shows what the planner believed.
When nothing fits, `plan_for` raises `HbmBudgetError` naming the
best-found plan — a structured answer instead of RESOURCE_EXHAUSTED.

Reference analog: the reference framework's ``memory_optimize`` transpiler
pass reused variable memory by liveness analysis at graph-build time; here
the same "fit the device" decision is made against XLA's cost model over
whole-config candidates (sharding/remat/microbatching), which is the form
the decision actually takes on TPU.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = [
    "Plan",
    "HbmBudgetError",
    "default_candidates",
    "estimate_plan",
    "plan_for",
    "plan_for_footprint",
    "guard",
    "last_plan",
]

# remat policy -> gauge value (gauges are numeric; the event carries the
# string)
_REMAT_GAUGE = {"none": 0, "minimal": 1, "full": 2}


@dataclass
class Plan:
    """One (sharding stage, remat policy, microbatch K) point, plus what
    the planner learned about it."""

    stage: int = 0
    remat: str = "none"
    microbatch: int = 1
    est_bytes_per_device: Optional[int] = None
    budget_bytes: Optional[int] = None
    source: str = "unevaluated"  # "measured" | "analytic" | "unconstrained"
    fits: Optional[bool] = None
    error: Optional[str] = None
    # XLA's predicted per-microbatch-step cost (cost_analysis of the
    # candidate executable the memory estimate already compiles) — the
    # "predicted" half of predicted-vs-achieved: the perf ledger's
    # perf/achieved_* gauges supply the achieved half at dispatch time
    predicted_flops: Optional[float] = None
    predicted_bytes_accessed: Optional[float] = None

    def describe(self) -> str:
        est = ("?" if self.est_bytes_per_device is None
               else _fmt_bytes(self.est_bytes_per_device))
        return (f"stage{self.stage}/remat={self.remat}/K={self.microbatch}"
                f" (~{est}/device, {self.source})")

    def to_dict(self) -> dict:
        return {"stage": self.stage, "remat": self.remat,
                "microbatch": self.microbatch,
                "est_bytes_per_device": self.est_bytes_per_device,
                "budget_bytes": self.budget_bytes,
                "source": self.source, "fits": self.fits,
                "error": self.error,
                "predicted_flops": self.predicted_flops,
                "predicted_bytes_accessed": self.predicted_bytes_accessed}


class HbmBudgetError(RuntimeError):
    """No candidate fits the HBM budget (or a guarded run still OOMed).
    Carries the best plan found and every candidate's estimate, so the
    caller can print a table instead of a stack trace."""

    def __init__(self, message: str, plan: Optional[Plan] = None,
                 candidates: Sequence[Plan] = ()):
        super().__init__(message)
        self.plan = plan
        self.candidates = list(candidates)


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.2f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024.0
    return f"{n}B"


def default_candidates(batch: Optional[int] = None,
                       dp: int = 1) -> List[Plan]:
    """The escalation ladder, cheapest step first: turn on ZeRO stages
    before remat (sharding is ~free bandwidth on ICI, remat re-burns
    flops), and only then split the batch. Microbatch candidates keep the
    per-step batch divisible by both K and dp."""
    plans = [Plan(0, "none", 1), Plan(1, "none", 1), Plan(2, "none", 1),
             Plan(3, "none", 1), Plan(3, "minimal", 1), Plan(3, "full", 1)]
    for k in (2, 4, 8):
        if batch is not None and (batch % k or (batch // k) % max(dp, 1)):
            continue
        plans.append(Plan(3, "full", k))
    return plans


def resolve_budget_bytes() -> Optional[int]:
    """Budget in bytes, or None when unconstrained (CPU has no allocator
    stats). ``PDTPU_HBM_BUDGET`` (bytes) overrides; otherwise
    ``PDTPU_HBM_BUDGET_FRACTION`` (default 0.9) of the device's
    ``bytes_limit`` — the headroom covers XLA's own scratch and the
    transient double-buffering a donated update needs."""
    env = os.environ.get("PDTPU_HBM_BUDGET")
    if env:
        return int(float(env))
    from .observability.memory import device_memory_stats
    stats = device_memory_stats()
    if not stats or not stats.get("bytes_limit"):
        return None
    frac = float(os.environ.get("PDTPU_HBM_BUDGET_FRACTION", "0.9"))
    return int(stats["bytes_limit"] * frac)


def _compiled_for(program, loss_name: str, plan: Plan):
    from .core.compiler import BuildStrategy, CompiledProgram
    bs = BuildStrategy()
    bs.sharding_strategy = plan.stage
    bs.remat_policy = plan.remat
    return CompiledProgram(program).with_data_parallel(
        loss_name=loss_name, build_strategy=bs)


def _feed_with_microbatch(feed: Dict[str, np.ndarray], k: int):
    if k <= 1:
        return feed
    out = {}
    for n, a in feed.items():
        a = np.asarray(a)
        if a.ndim and a.shape[0] % k == 0:
            a = a[: a.shape[0] // k]
        out[n] = a
    return out


def _measured_bytes(cp, program, feed, loss_name: str) -> int:
    """Per-device footprint from XLA's own cost model: lower+compile the
    candidate step against shape structs (nothing is materialized) and
    read `memory_analysis()`. arg+temp+output−alias: the alias bytes are
    the donated state buffers counted on both sides."""
    import jax

    from .core.executor import _RNG_STATE, _make_key

    pads = cp._zero_pad_map()
    state_structs = {}
    for v in program.list_vars():
        if not v.persistable or v.name == _RNG_STATE:
            continue
        shp = list(v.shape)
        if v.name in pads:
            shp[0] = pads[v.name][1]
        state_structs[v.name] = jax.ShapeDtypeStruct(
            tuple(int(d) for d in shp),
            jax.dtypes.canonicalize_dtype(v.dtype),
            sharding=cp._state_sharding(v.name))
    feed_structs = {
        n: jax.ShapeDtypeStruct(np.asarray(a).shape, np.asarray(a).dtype,
                                sharding=cp._feed_sharding(np.asarray(a).ndim))
        for n, a in feed.items()}
    names = sorted(state_structs)
    fn = cp._build(sorted(feed_structs), [loss_name], names, names,
                   {n: np.asarray(a).ndim for n, a in feed.items()})
    compiled = fn.lower(state_structs, feed_structs, _make_key(0)).compile()
    ma = compiled.memory_analysis()
    est = (int(ma.argument_size_in_bytes) + int(ma.temp_size_in_bytes)
           + int(ma.output_size_in_bytes) - int(ma.alias_size_in_bytes))
    # the same compile also carries XLA's flops/bytes prediction — free
    # to read here, and the other half of predicted-vs-achieved once the
    # perf ledger attributes real dispatches
    from .observability import perf
    return max(est, 0), perf.cost_from_executable(compiled)


def _analytic_bytes(cp, program, feed) -> int:
    """Shard-aware lower bound when the backend has no cost model: state
    (params + accumulators) at their planned shardings, one gradient set
    (sharded from stage2), and the feeds. Activations are deliberately
    NOT guessed — this is a lower bound and the plan says `analytic`."""
    import jax

    dp = 1
    if cp._mesh is not None and cp._data_axis is not None:
        dp = cp._mesh.shape[cp._data_axis]
    state = 0
    grads = 0
    for v in program.list_vars():
        if not v.persistable:
            continue
        try:
            nbytes = int(np.prod([int(d) for d in v.shape]) *
                         jax.dtypes.canonicalize_dtype(v.dtype).itemsize)
        except Exception:
            continue
        factor = dp if cp._zero_plan(v) is not None else 1
        state += nbytes // factor
        if getattr(v, "trainable", False):
            gfactor = dp if cp._zero_stage() >= 2 else 1
            grads += nbytes // gfactor
    feeds = sum(np.asarray(a).nbytes // max(dp, 1) for a in feed.values())
    return state + grads + feeds


def estimate_plan(plan: Plan, program, feed, loss_name: str) -> Plan:
    """Fill in `est_bytes_per_device` + `source` for one candidate."""
    mfeed = _feed_with_microbatch(feed, plan.microbatch)
    cp = _compiled_for(program, loss_name, plan)
    try:
        plan.est_bytes_per_device, cost = _measured_bytes(cp, program, mfeed,
                                                          loss_name)
        if cost is not None:
            plan.predicted_flops = cost["flops"]
            plan.predicted_bytes_accessed = cost["bytes_accessed"]
        plan.source = "measured"
    except Exception as e:
        plan.error = f"{type(e).__name__}: {e}"[:300]
        try:
            plan.est_bytes_per_device = _analytic_bytes(cp, program, mfeed)
            plan.source = "analytic"
        except Exception as e2:
            plan.error += f"; analytic: {type(e2).__name__}: {e2}"[:200]
    return plan


_last_plan: Optional[Plan] = None
_last_candidates: List[Plan] = []


def last_plan() -> Optional[Plan]:
    return _last_plan


def _dump_section() -> object:
    return {"chosen": _last_plan.to_dict() if _last_plan else None,
            "candidates": [p.to_dict() for p in _last_candidates]}


def _record(plan: Plan, candidates: List[Plan], where: str) -> None:
    global _last_plan, _last_candidates
    _last_plan, _last_candidates = plan, list(candidates)
    from .observability.flight import (get_flight_recorder,
                                       register_dump_section)
    from .observability.registry import get_registry
    reg = get_registry()
    reg.gauge("planner/chosen_stage").set(plan.stage)
    reg.gauge("planner/chosen_remat").set(_REMAT_GAUGE.get(plan.remat, -1))
    reg.gauge("planner/chosen_microbatch").set(plan.microbatch)
    if plan.est_bytes_per_device is not None:
        reg.gauge("planner/est_bytes_per_device").set(
            plan.est_bytes_per_device)
    if plan.budget_bytes is not None:
        reg.gauge("planner/budget_bytes").set(plan.budget_bytes)
    # predicted side of predicted-vs-achieved: read these against the
    # perf/achieved_* gauges the cost ledger sets at dispatch time
    if plan.predicted_flops is not None:
        reg.gauge("planner/predicted_flops").set(plan.predicted_flops)
    if plan.predicted_bytes_accessed is not None:
        reg.gauge("planner/predicted_bytes_accessed").set(
            plan.predicted_bytes_accessed)
    register_dump_section("hbm_plan", _dump_section)
    get_flight_recorder().note_event(
        "info", "hbm_plan", where=where, **plan.to_dict())


def plan_for(program, feed: Dict[str, np.ndarray], loss_name: str,
             budget_bytes: Optional[int] = None,
             candidates: Optional[Sequence[Plan]] = None,
             where: str = "planner") -> Plan:
    """Pick the first candidate on the ladder whose estimated per-device
    bytes fit `budget_bytes` (default: `resolve_budget_bytes()`). With no
    budget (CPU, or stats unavailable and no env override) the baseline
    candidate wins unevaluated — the planner never slows down a machine
    that cannot OOM. Raises `HbmBudgetError` naming the most frugal plan
    found when nothing fits."""
    import jax

    if budget_bytes is None:
        budget_bytes = resolve_budget_bytes()
    if candidates is None:
        batch = None
        for a in feed.values():
            a = np.asarray(a)
            if a.ndim:
                batch = a.shape[0]
                break
        candidates = default_candidates(batch, dp=len(jax.devices()))
    candidates = [Plan(p.stage, p.remat, p.microbatch) if p.fits is not None
                  else p for p in candidates]

    if budget_bytes is None:
        plan = candidates[0]
        plan.source = "unconstrained"
        plan.fits = True
        _record(plan, candidates, where)
        return plan

    evaluated: List[Plan] = []
    for plan in candidates:
        plan.budget_bytes = budget_bytes
        estimate_plan(plan, program, feed, loss_name)
        evaluated.append(plan)
        if plan.est_bytes_per_device is None:
            plan.fits = False
            continue
        plan.fits = plan.est_bytes_per_device <= budget_bytes
        if plan.fits:
            _record(plan, evaluated, where)
            return plan

    best = min((p for p in evaluated if p.est_bytes_per_device is not None),
               key=lambda p: p.est_bytes_per_device, default=None)
    _record(best or evaluated[-1], evaluated, where)
    lines = "; ".join(p.describe() for p in evaluated)
    raise HbmBudgetError(
        f"no (sharding, remat, microbatch) candidate fits the HBM budget "
        f"of {_fmt_bytes(budget_bytes)}/device — best found: "
        f"{best.describe() if best else 'none'} [{lines}]",
        plan=best, candidates=evaluated)


def plan_for_footprint(candidates: Sequence, where: str = "planner",
                       budget_bytes: Optional[int] = None) -> Plan:
    """`plan_for` for workloads that are raw jnp arrays rather than a
    Program (op microbenches, the ring-attention bench): each candidate is
    a ``(Plan, est_bytes)`` pair with a caller-computed analytic footprint
    instead of a compiled estimate. Picks the first fitting plan and
    records it through the same observability path (`planner/*` gauges,
    flight event, ``hbm_plan`` dump section), so a later `guard`-caught
    OOM names it. Raises `HbmBudgetError` when nothing fits."""
    if not candidates:
        raise ValueError("plan_for_footprint: empty candidate list")
    if budget_bytes is None:
        budget_bytes = resolve_budget_bytes()
    evaluated: List[Plan] = []
    for plan, est in candidates:
        plan.est_bytes_per_device = int(est)
        plan.budget_bytes = budget_bytes
        evaluated.append(plan)
        if budget_bytes is None:
            plan.source = "unconstrained"
            plan.fits = True
            _record(plan, evaluated, where)
            return plan
        plan.source = "analytic"
        plan.fits = plan.est_bytes_per_device <= budget_bytes
        if plan.fits:
            _record(plan, evaluated, where)
            return plan
    best = min(evaluated, key=lambda p: p.est_bytes_per_device)
    _record(best, evaluated, where)
    lines = "; ".join(p.describe() for p in evaluated)
    raise HbmBudgetError(
        f"{where}: no candidate footprint fits the HBM budget of "
        f"{_fmt_bytes(budget_bytes)}/device — best found: "
        f"{best.describe()} [{lines}]",
        plan=best, candidates=evaluated)


class guard:
    """Context manager for the dispatch that runs a planner-chosen config:
    a residual OOM (the cost model under-counted, or the budget lied) is
    re-raised as `HbmBudgetError` carrying the active plan and the
    original RESOURCE_EXHAUSTED text, after the flight recorder takes its
    post-mortem. Non-OOM errors pass through untouched."""

    def __init__(self, where: str, plan: Optional[Plan] = None):
        self.where = where
        self.plan = plan  # None -> whatever plan is active at exit time

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is None:
            return False
        from .observability.flight import get_flight_recorder, is_oom
        if not is_oom(exc):
            return False
        plan = self.plan if self.plan is not None else _last_plan
        get_flight_recorder().record_failure(
            exc, context={"where": self.where,
                          "plan": plan.to_dict() if plan else None})
        plan_txt = plan.describe() if plan else "none recorded"
        raise HbmBudgetError(
            f"{self.where}: OOM under plan {plan_txt}; {str(exc)[:500]}",
            plan=plan, candidates=_last_candidates) from exc
