"""Profiler surface.

Reference analog: ``python/paddle/fluid/profiler.py`` (profiler()
contextmanager, start/stop_profiler) over the C++ RecordEvent/DeviceTracer
CUPTI stack (platform/profiler.h:166, device_tracer.cc), exported to
chrome://tracing by tools/timeline.py.

TPU-native: jax.profiler captures an XPlane trace viewable in
TensorBoard/Perfetto (the chrome-trace analog); RecordEvent becomes
TraceAnnotation (named scopes visible in the trace and in HLO metadata).
"""
from __future__ import annotations

import contextlib
import os
import time
from collections import defaultdict
from typing import Optional

import jax


_active = {}


def start_profiler(state: str = "All", tracer_option=None,
                   log_dir: str = "/tmp/paddle_tpu_profile"):
    """Begin one jax.profiler trace session. Exactly one session can be
    active per process (a jax.profiler limitation); a second start — e.g.
    a nested `profiler()` context — raises a clear error instead of
    clobbering the session state and crashing inside jax at stop time."""
    if _active.get("dir") is not None:
        raise RuntimeError(
            f"start_profiler: a profiling session is already active "
            f"(writing to {_active['dir']!r}) — nested profiler()/"
            f"start_profiler calls are not supported; stop_profiler() "
            f"first. For cheap always-on host spans inside a profiled "
            f"region use observability.trace_span instead.")
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    _active["dir"] = log_dir


def stop_profiler(sorted_key: Optional[str] = None, profile_path: Optional[str] = None):
    """End the active session and return its log dir. Raises a clear
    error when no session is active (previously this surfaced as an
    opaque failure from inside jax.profiler)."""
    if _active.get("dir") is None:
        raise RuntimeError(
            "stop_profiler without a matching start_profiler: no "
            "profiling session is active")
    log_dir = _active.pop("dir")  # cleared even if stop_trace raises,
    jax.profiler.stop_trace()     # so a new session can still start
    return log_dir


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: Optional[str] = None,
             profile_path: str = "/tmp/paddle_tpu_profile"):
    """fluid.profiler.profiler parity: wraps a training region; writes an
    XPlane trace under profile_path (open with TensorBoard)."""
    start_profiler(state, log_dir=profile_path)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def record_event(name: str, **args):
    """RecordEvent RAII parity (platform/profiler.h:81): annotates the
    device trace AND the compiled HLO (jax.profiler.TraceAnnotation,
    visible per-fusion in XLA tooling) AND records a host-side span in
    `observability.get_tracer()` — so the same named region lines up in
    the XPlane trace and the chrome-trace export of the host tracer.
    Extra kwargs become chrome-trace span args."""
    from .observability.tracer import trace_span

    with trace_span(name, **args), jax.profiler.TraceAnnotation(name):
        yield


class _OpTimer:
    """Host-side per-op wall-time table for eager mode — the analog of the
    reference's EnableProfiler sorted per-op summary."""

    def __init__(self):
        self.times = defaultdict(float)
        self.counts = defaultdict(int)

    def summary(self, sorted_key: str = "total"):
        rows = [(k, self.counts[k], self.times[k] * 1e3,
                 self.times[k] / max(self.counts[k], 1) * 1e3)
                for k in self.times]
        rows.sort(key=lambda r: -r[2])
        lines = [f"{'op':<32}{'calls':>8}{'total_ms':>12}{'avg_ms':>10}"]
        for name, c, tot, avg in rows:
            lines.append(f"{name:<32}{c:>8}{tot:>12.3f}{avg:>10.4f}")
        return "\n".join(lines)


_op_timer: Optional[_OpTimer] = None


def export_op_profile(timer: _OpTimer) -> None:
    """Publish an eager per-op timing table to the process registry —
    ``eager/op_ms{op=}`` (cumulative host ms per op type, gauge) and
    ``eager/op_calls{op=}`` (counter) — so the summary that used to be
    print-only reaches ``/metrics``, ``/metrics.json``, flight dumps,
    and federation like every other series."""
    from .observability.registry import get_registry

    reg = get_registry()
    for op, secs in timer.times.items():
        g = reg.gauge("eager/op_ms", op=op)
        g.set(g.value + secs * 1e3)
        reg.counter("eager/op_calls", op=op).inc(timer.counts[op])


@contextlib.contextmanager
def op_profiler():
    """Eager per-op timing: patches the dygraph tracer dispatch. On exit
    the collected table is exported to the registry (export_op_profile)
    in addition to being available via ``timer.summary()``."""
    global _op_timer
    from .dygraph import tracer as tr_mod

    _op_timer = _OpTimer()
    orig = tr_mod.Tracer.trace_op

    def timed(self, op_type, inputs, attrs=None):
        t0 = time.perf_counter()
        out = orig(self, op_type, inputs, attrs)
        jax.block_until_ready(
            [v.value for vs in out.values() for v in vs])
        _op_timer.times[op_type] += time.perf_counter() - t0
        _op_timer.counts[op_type] += 1
        return out

    tr_mod.Tracer.trace_op = timed
    try:
        yield _op_timer
    finally:
        tr_mod.Tracer.trace_op = orig
        timer, _op_timer = _op_timer, None
        try:
            export_op_profile(timer)
        except Exception:
            pass


def reset_profiler():
    """Reference profiler.py reset_profiler: clear collected per-op stats."""
    global _op_timer
    if _op_timer is not None:
        _op_timer.times.clear()
        _op_timer.counts.clear()


from contextlib import contextmanager as _contextmanager


@_contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """Reference profiler.py cuda_profiler (nvprof hooks): no CUDA in the
    TPU build — use `profiler()`/jax.profiler traces instead. No-op shim."""
    yield
