"""paddle_tpu.ps — sharded parameter-server embedding tier.

The sparse half of the reference's large-scale stack (Downpour pservers +
device workers behind ``FleetWrapper``/``Communicator``), rebuilt on this
repo's packed row-major tables:

* :mod:`.shard` — ``RangeSpec`` (contiguous row-range partition) and
  ``EmbeddingShard`` (one table slice as packed ``[n, 128] uint16`` rows;
  numpy-only so pserver processes never import JAX);
* :mod:`.transport` — ``ShardClient`` (in-process direct dispatch or a
  length-prefixed socket protocol with reconnect + capped-backoff retry
  and a typed ``TransportError(transient)`` taxonomy) and ``ShardServer``
  (what ``fleet.run_server()`` runs);
* :mod:`.table` — ``ShardedTable``: sorted-id fan-out pull/push with
  per-shard byte accounting, plus the client-side push journal and
  ``recover_shard`` (lossless rebuild of a restarted shard from the
  newest verified checkpoint + journal replay);
* :mod:`.dynamic` — ``DynamicEmbeddingShard``: the online-learning
  variant — rows materialize on first pull (init-on-pull) into a bounded
  slab and cold ids are swept out by TTL + watermark LFU eviction, so
  the vocab is no longer provisioned up front;
* :mod:`.health` — ``ShardMonitor``: periodic shard pings driving
  ``ps/shard_up`` gauges and the ``ps/shards`` /healthz check;
* :mod:`.tier` — ``PsEmbeddingTier``: the worker-side training driver
  with async pull prefetch (rides ``dataio.DeviceLoader``) and bounded-
  depth async push, bitwise-exact vs the single-table packed baseline;
  ``attach_checkpointer`` arms recover-and-resume on shard outages.

Configured through ``DistributedStrategy`` (``embedding_shards``,
``pull_ahead``, ``push_depth``) and the fleet role makers
(``TRAINING_ROLE=PSERVER`` + ``PADDLE_PSERVER_ENDPOINTS``). Failure
semantics (retry env knobs, journal durability contract, recovery
walkthrough) are documented in docs/migration.md "Distributed
embeddings → Failure semantics".
"""
from .dynamic import (DynamicEmbeddingShard,  # noqa: F401
                      make_dynamic_shards, zero_init_rows)
from .health import ShardMonitor  # noqa: F401
from .hot_cache import HotRowCache  # noqa: F401
from .shard import EmbeddingShard, RangeSpec, make_shards  # noqa: F401
from .slab import FreqSketch, LruOrder, SlotMap  # noqa: F401
from .table import ShardedTable  # noqa: F401
from .tier import PsEmbeddingTier, PsTableBinding  # noqa: F401
from .transport import (InProcessClient, ShardClient,  # noqa: F401
                        ShardRestartedError, ShardServer, SocketClient,
                        TransportError, connect, probe)

__all__ = [
    "RangeSpec", "EmbeddingShard", "make_shards",
    "ShardClient", "InProcessClient", "SocketClient", "ShardServer",
    "TransportError", "ShardRestartedError", "connect", "probe",
    "ShardedTable", "ShardMonitor", "PsTableBinding", "PsEmbeddingTier",
    "HotRowCache", "SlotMap", "LruOrder", "FreqSketch",
    "DynamicEmbeddingShard", "make_dynamic_shards", "zero_init_rows",
]
