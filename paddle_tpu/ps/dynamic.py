"""Dynamic-vocab embedding shards — tables that grow past provisioning.

Reference analog: pslib's DownpourSparseTable in *online* mode — ids are
not provisioned up front; a row materializes the first time a worker
touches it (init-on-pull) and a background shrink pass reclaims ids that
went cold (``FleetWrapper::ShrinkSparseTable``). That is what lets the
production CTR table hold billions of *live* ids inside a bounded DRAM
budget: the id SPACE is huge, the resident row set is capped.

:class:`DynamicEmbeddingShard` keeps the :class:`~.shard.EmbeddingShard`
wire contract (global-id pull/push, scatter-SET semantics, dense
dump/load for the checkpoint path) but stores rows in a fixed
``capacity``-row slab with the shared :mod:`.slab` bookkeeping:

* ``SlotMap`` — global id -> slab slot (dict mode; the id universe is
  unbounded by design);
* ``LruOrder`` + per-slot touch timestamps — the TTL/recency half of the
  eviction policy;
* ``FreqSketch`` — the frequency half: a cold-by-recency row whose
  estimated frequency is still high gets one second chance per sweep.

Semantics the tests pin down:

* a pull of a never-seen id returns the DETERMINISTIC init row
  (``init_row_fn``, default all-zero packed rows = 0.0 embedding and
  zero optimizer state) and materializes it;
* evicting a row discards its bytes *and optimizer state*: a later
  touch re-materializes the init row, never stale bytes;
* ``sweep()`` runs under the same mutation lock as pull/push (eviction
  can never interleave with an in-flight push's scatter) and skips
  pinned rows (``pin``/``unpin`` — the hot-cache-style in-flight guard);
* ``dump``/``load`` stay bitwise round-trips: dump scatters live rows
  over an init-filled dense slice, load re-materializes exactly the rows
  that differ from init (a row equal to its init row pulls the same
  bytes whether or not it occupies a slot).

Observability: ``ps/vocab_rows`` / ``ps/vocab_capacity`` gauges and
``ps/materialized_rows`` / ``ps/evicted_rows`` counters (labelled by
table + shard range) land in the process registry — a socket pserver
exports them through the transport ``metrics`` op into the PR 13
federation surface; ``tools/ps_admin.py stats`` renders them as the
``vocab`` block.

Like the static shard, this module is numpy + stdlib only: pserver
processes never import JAX.
"""
from __future__ import annotations

import os
import time
from typing import Callable, List, Optional

import numpy as np

from ..observability import get_registry
from .shard import PACK_LANES, EmbeddingShard, RangeSpec
from .slab import FreqSketch, LruOrder, SlotMap

__all__ = ["DynamicEmbeddingShard", "make_dynamic_shards", "zero_init_rows"]


def zero_init_rows(ids: np.ndarray, lanes: int = PACK_LANES) -> np.ndarray:
    """The default deterministic init: all-zero packed rows (0.0 visible
    columns, zero optimizer state) — the standard cold-start for online
    CTR ids, and trivially reproducible across evict/re-touch cycles."""
    return np.zeros((np.asarray(ids).shape[0], lanes), dtype=np.uint16)


class DynamicEmbeddingShard(EmbeddingShard):
    """A ``[lo, hi)`` range served out of a ``capacity``-row slab.

    ``hi - lo`` (the id space) may vastly exceed ``capacity`` (the
    provisioned rows, i.e. the memory cap: ``capacity * lanes * 2``
    bytes). When the slab is full, admitting a new id evicts the
    coldest unpinned resident on demand; ``sweep()`` does the same
    proactively on a TTL/watermark policy so steady-state stays under
    the high watermark instead of thrashing at 100%.
    """

    def __init__(self, name: str, lo: int, hi: int, capacity: int,
                 lanes: int = PACK_LANES,
                 init_row_fn: Optional[Callable[[np.ndarray], np.ndarray]]
                 = None,
                 ttl_s: Optional[float] = None,
                 high_watermark: float = 0.95,
                 low_watermark: float = 0.80,
                 keep_freq: int = 0):
        if capacity < 1:
            raise ValueError(
                f"DynamicEmbeddingShard {name!r}: capacity must be >= 1")
        if not (0.0 < low_watermark <= high_watermark <= 1.0):
            raise ValueError(
                f"DynamicEmbeddingShard {name!r}: need 0 < low <= high <= 1 "
                f"watermarks, got {low_watermark}/{high_watermark}")
        # base init allocates [hi-lo, lanes]; bypass it — the whole point
        # is that the dense range never exists in memory. Re-implement the
        # small amount of base state instead.
        if hi <= lo:
            raise ValueError(f"DynamicEmbeddingShard {name!r}: empty range "
                             f"[{lo}, {hi})")
        self.name = str(name)
        self.lo, self.hi = int(lo), int(hi)
        self.capacity = int(capacity)
        self.lanes = int(lanes)
        self.rows = np.zeros((self.capacity, lanes), dtype=np.uint16)  # slab
        import threading
        self._lock = threading.Lock()
        self.bytes_pulled = 0
        self.bytes_pushed = 0
        self.n_pulls = 0
        self.n_pushes = 0
        self._init_row_fn = init_row_fn or (
            lambda ids: zero_init_rows(ids, self.lanes))
        self.ttl_s = (float(ttl_s) if ttl_s is not None else
                      float(os.environ.get("PDTPU_PS_VOCAB_TTL_S", "0")) or
                      None)
        self.high_watermark = float(high_watermark)
        self.low_watermark = float(low_watermark)
        self.keep_freq = int(keep_freq)
        self._slots = SlotMap(self.capacity)          # global id -> slot
        self._lru = LruOrder()
        self._freq = FreqSketch(width=1 << 12)
        self._touched = np.zeros(self.capacity, np.float64)  # per-slot ts
        self._born = np.zeros(self.capacity, np.float64)
        self._pins: dict = {}                          # global id -> refcount
        self.materialized_total = 0
        self.evicted_total = 0
        reg = get_registry()
        rng = f"{self.lo}:{self.hi}"
        self._g_rows = reg.gauge("ps/vocab_rows", table=self.name, shard=rng)
        self._g_cap = reg.gauge("ps/vocab_capacity", table=self.name,
                                shard=rng)
        self._g_oldest = reg.gauge("ps/vocab_oldest_age_s", table=self.name,
                                   shard=rng)
        self._c_mat = reg.counter("ps/materialized_rows", table=self.name,
                                  shard=rng)
        self._c_evict = reg.counter("ps/evicted_rows", table=self.name,
                                    shard=rng)
        self._g_cap.set(float(self.capacity))
        self._g_rows.set(0.0)

    # ------------------------------------------------------------ internals
    def _init_rows_for(self, gids: np.ndarray) -> np.ndarray:
        rows = np.asarray(self._init_row_fn(np.asarray(gids, np.int64)),
                          dtype=np.uint16)
        if rows.shape != (np.asarray(gids).shape[0], self.lanes):
            raise ValueError(
                f"shard {self.name!r}: init_row_fn returned {rows.shape}, "
                f"expected ({np.asarray(gids).shape[0]}, {self.lanes})")
        return rows

    def _evict_one_locked(self, now: float) -> bool:
        """Evict the coldest unpinned resident; False when every resident
        is pinned. Caller holds the lock."""
        skipped = []
        evicted = False
        while len(self._lru):
            uid = self._lru.pop_coldest()
            if self._pins.get(uid):
                skipped.append(uid)  # pinned: re-insert, keep looking
                continue
            self._slots.pop(uid)
            self.evicted_total += 1
            self._c_evict.inc()
            evicted = True
            break
        # pinned uids go back at the COLD end in original order so their
        # relative recency is preserved once unpinned
        for i, uid in enumerate(reversed(skipped)):
            self._od_prepend(uid)
        return evicted

    def _od_prepend(self, uid: int) -> None:
        od = self._lru._od
        od[uid] = None
        od.move_to_end(uid, last=False)

    def _materialize_locked(self, gids: np.ndarray, now: float) -> np.ndarray:
        """Assign slots + write init rows for absent global ids (caller
        holds the lock). Returns the slot per id."""
        init = self._init_rows_for(gids)
        slots = np.empty(gids.shape[0], np.int64)
        for j, uid in enumerate(gids.tolist()):
            if not self._slots.free_slots and not self._evict_one_locked(now):
                raise RuntimeError(
                    f"shard {self.name!r}: slab full ({self.capacity} rows) "
                    "and every resident row is pinned — raise the capacity "
                    "or unpin before admitting new ids")
            s = self._slots.assign(uid)
            self.rows[s] = init[j]
            self._born[s] = now
            self._touched[s] = now
            self._lru.touch(uid)
            slots[j] = s
        self.materialized_total += gids.shape[0]
        self._c_mat.inc(gids.shape[0])
        return slots

    def _resolve_locked(self, gids: np.ndarray, now: float) -> np.ndarray:
        """Slot per global id, materializing the absent ones."""
        slots = self._slots.get_many(gids).astype(np.int64)
        missing = slots < 0
        if missing.any():
            slots[missing] = self._materialize_locked(gids[missing], now)
        present = ~missing
        if present.any():
            self._touched[slots[present]] = now
            for uid in gids[present].tolist():
                self._lru.touch(uid)
        self._freq.observe(gids)
        self._g_rows.set(float(len(self._slots)))
        return slots

    # ------------------------------------------------------------- pull/push
    def pull(self, ids: np.ndarray) -> np.ndarray:
        gids = self._local(ids) + self.lo  # range-validate, keep global
        now = time.monotonic()
        with self._lock:
            slots = self._resolve_locked(gids, now)
            out = self.rows[slots]  # fancy index: already a copy
            self.bytes_pulled += out.nbytes
            self.n_pulls += 1
        return out

    def push(self, ids: np.ndarray, rows: np.ndarray) -> None:
        gids = self._local(ids) + self.lo
        rows = np.asarray(rows, dtype=np.uint16)
        if rows.shape != (gids.shape[0], self.lanes):
            raise ValueError(
                f"shard {self.name!r}: push rows shape {rows.shape} != "
                f"({gids.shape[0]}, {self.lanes})")
        now = time.monotonic()
        with self._lock:
            slots = self._resolve_locked(gids, now)
            self.rows[slots] = rows
            self.bytes_pushed += rows.nbytes
            self.n_pushes += 1

    # ---------------------------------------------------------------- pins
    def pin(self, ids: np.ndarray) -> None:
        """Protect global ids from eviction (in-flight async push / dirty
        hot-cache rows). Refcounted; pinning a non-resident id is legal
        (it guards the id through a future materialize)."""
        with self._lock:
            for uid in np.asarray(ids, np.int64).tolist():
                self._pins[uid] = self._pins.get(uid, 0) + 1

    def unpin(self, ids: np.ndarray) -> None:
        with self._lock:
            for uid in np.asarray(ids, np.int64).tolist():
                n = self._pins.get(uid, 0) - 1
                if n <= 0:
                    self._pins.pop(uid, None)
                else:
                    self._pins[uid] = n

    # --------------------------------------------------------------- sweep
    def sweep(self, now: Optional[float] = None) -> int:
        """One TTL/frequency eviction pass; returns rows evicted.

        Policy, under the mutation lock (never interleaves a push):

        1. TTL: every unpinned resident not touched within ``ttl_s`` is
           evicted (skipped when no TTL is configured);
        2. watermark: while occupancy exceeds ``high_watermark`` ×
           capacity, evict from the cold end down to ``low_watermark`` —
           except a cold row whose sketch frequency is still >=
           ``keep_freq`` gets ONE second chance (re-touched instead of
           evicted) per pass.
        """
        now = time.monotonic() if now is None else float(now)
        evicted = 0
        with self._lock:
            if self.ttl_s is not None:
                uids, slots = self._slots.residents()
                expired = uids[(now - self._touched[slots])
                               > self.ttl_s].tolist()
                for uid in expired:
                    if self._pins.get(uid):
                        continue
                    self._slots.pop(uid)
                    self._lru.discard(uid)
                    self.evicted_total += 1
                    evicted += 1
            target = int(self.low_watermark * self.capacity)
            spared: List[int] = []
            if len(self._slots) > int(self.high_watermark * self.capacity):
                while len(self._slots) > target and len(self._lru):
                    uid = self._lru.pop_coldest()
                    if self._pins.get(uid):
                        spared.append(uid)
                        continue
                    if (self.keep_freq > 0 and int(
                            self._freq.estimate(
                                np.asarray([uid]))[0]) >= self.keep_freq):
                        # still hot by frequency: one second chance
                        self._lru.touch(uid)
                        spared.append(-1)  # sentinel: progress guard below
                        if len(spared) >= len(self._slots):
                            break
                        continue
                    self._slots.pop(uid)
                    self.evicted_total += 1
                    evicted += 1
                for uid in reversed([u for u in spared if u >= 0]):
                    self._od_prepend(uid)
            if evicted:
                self._c_evict.inc(evicted)
            self._g_rows.set(float(len(self._slots)))
            if len(self._slots):
                _, slots = self._slots.residents()
                self._g_oldest.set(float(now - self._touched[slots].min()))
            else:
                self._g_oldest.set(0.0)
        return evicted

    # ------------------------------------------------------------ dump/load
    def dump(self) -> np.ndarray:
        """Dense ``[hi-lo, lanes]`` slice for the checkpoint path: init
        rows everywhere, live rows scattered on top. Guarded by
        ``PDTPU_PS_DYNAMIC_DUMP_MAX_MB`` (default 512) — a huge id space
        should checkpoint through ``Checkpointer.save_delta`` instead."""
        cap_mb = float(os.environ.get("PDTPU_PS_DYNAMIC_DUMP_MAX_MB", "512"))
        nbytes = (self.hi - self.lo) * self.lanes * 2
        if nbytes > cap_mb * (1 << 20):
            raise RuntimeError(
                f"shard {self.name!r}: dense dump of [{self.lo}, {self.hi}) "
                f"is {nbytes / (1 << 20):.0f} MB > "
                f"PDTPU_PS_DYNAMIC_DUMP_MAX_MB={cap_mb:.0f} — use "
                "Checkpointer.save_delta for dynamic tables this large")
        with self._lock:
            out = self._init_rows_for(
                np.arange(self.lo, self.hi, dtype=np.int64))
            uids, slots = self._slots.residents()
            if uids.size:
                out[uids - self.lo] = self.rows[slots]
            return out

    def load(self, rows: np.ndarray) -> None:
        """Replace the slice from a dense checkpoint: drop every resident
        row, then materialize exactly the rows that differ from their
        init row (bitwise-equal-to-init rows stay virtual — pulling them
        yields identical bytes either way, and slab occupancy stays
        proportional to genuinely-trained ids)."""
        rows = np.ascontiguousarray(rows, dtype=np.uint16)
        if rows.shape != (self.hi - self.lo, self.lanes):
            raise ValueError(
                f"shard {self.name!r}: load shape {rows.shape} != "
                f"({self.hi - self.lo}, {self.lanes})")
        gids = np.arange(self.lo, self.hi, dtype=np.int64)
        init = self._init_rows_for(gids)
        touched = np.flatnonzero((rows != init).any(axis=1))
        if touched.size > self.capacity:
            raise ValueError(
                f"shard {self.name!r}: checkpoint slice holds "
                f"{touched.size} non-init rows > capacity {self.capacity}")
        now = time.monotonic()
        with self._lock:
            self._slots.clear()
            self._lru.clear()
            self._touched.fill(0.0)
            self._born.fill(0.0)
            if touched.size:
                slots = self._materialize_locked(gids[touched], now)
                self.rows[slots] = rows[touched]
            self._g_rows.set(float(len(self._slots)))

    # --------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._lock:
            live = len(self._slots)
            oldest = 0.0
            if live:
                _, slots = self._slots.residents()
                oldest = float(time.monotonic() - self._touched[slots].min())
            return {"name": self.name, "lo": self.lo, "hi": self.hi,
                    "rows": self.hi - self.lo,
                    "bytes_pulled": self.bytes_pulled,
                    "bytes_pushed": self.bytes_pushed,
                    "n_pulls": self.n_pulls, "n_pushes": self.n_pushes,
                    "dynamic": True,
                    "live_rows": live, "capacity": self.capacity,
                    "materialized": self.materialized_total,
                    "evicted": self.evicted_total,
                    "pinned": len(self._pins),
                    "oldest_age_s": oldest,
                    "slab_bytes": int(self.rows.nbytes)}


def make_dynamic_shards(name: str, spec: RangeSpec, capacity_per_shard: int,
                        lanes: int = PACK_LANES,
                        **kw) -> List[DynamicEmbeddingShard]:
    """The dynamic analog of :func:`.shard.make_shards`: one slab-backed
    shard per range of `spec`, each provisioned `capacity_per_shard`
    resident rows. Extra kwargs flow to every shard (ttl_s, watermarks,
    init_row_fn, keep_freq)."""
    return [DynamicEmbeddingShard(name, *spec.bounds(i),
                                  capacity=capacity_per_shard, lanes=lanes,
                                  **kw)
            for i in range(spec.num_shards)]
