"""ShardMonitor — client-side liveness tracking for the PS shard fleet.

The transport's retry loop answers "is THIS rpc going to survive a
restart"; the monitor answers the orchestrator's question: "is the tier
healthy RIGHT NOW, and if not, is it a blip or a wedge". A daemon thread
pings every shard each ``PDTPU_PS_MONITOR_INTERVAL`` seconds (default 1)
and publishes three views of the same facts:

* ``ps/shard_up{shard=i}`` gauges (1/0) in the process metrics registry —
  the /metrics scrape and ``tools/ps_admin.py dump-health``;
* a registered ``/healthz`` check named ``ps/shards``: ``ok`` when every
  shard answered its last ping, ``degraded`` while any shard is down
  (recovery in progress — keep the process alive), escalating to
  ``failing`` once a shard has been down longer than
  ``PDTPU_WEDGE_TIMEOUT`` seconds (default 300, same knob the elastic
  step-progress check uses) — that is the "restart the job" signal;
* :meth:`status` — the structured form, for code.

Pings never ride the training connections: socket shards are probed with
a fresh one-shot connection (``transport.probe``), so a monitor sweep can
neither queue behind a large pull nor trip the persistent client's
restart detection. In-process shards are dispatched directly.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Union

from ..observability.http import (register_health_check,
                                  unregister_health_check)
from ..observability.registry import get_registry
from .transport import ShardClient, SocketClient, probe

__all__ = ["ShardMonitor"]

CHECK_NAME = "ps/shards"


def _pinger(target) -> Callable[[], bool]:
    """A zero-arg liveness probe for one shard (never raises)."""
    if isinstance(target, str):
        return lambda: probe(target)
    if isinstance(target, SocketClient):
        # fresh socket, NOT the training connection (see module docstring)
        return lambda: probe(target.endpoint)
    if isinstance(target, ShardClient):
        def ping():
            try:
                return bool(target.ping())
            except Exception:
                return False
        return ping
    raise TypeError(f"ShardMonitor: cannot ping {type(target).__name__}")


class ShardMonitor:
    """Pings every shard on an interval; gauges + /healthz + status().

    ``targets`` may mix ``"host:port"`` endpoint strings and
    ``ShardClient`` objects (the tier passes its pull clients). Use as a
    context manager or call ``start()``/``stop()``; ``poll_now()`` runs
    one synchronous sweep — tests use it to avoid timing races.
    """

    def __init__(self, targets: Sequence[Union[str, ShardClient]],
                 interval_s: Optional[float] = None,
                 check_name: str = CHECK_NAME):
        if not targets:
            raise ValueError("ShardMonitor: no shards to watch")
        self._pingers = [_pinger(t) for t in targets]
        self._labels = [t if isinstance(t, str)
                        else getattr(t, "endpoint", f"in-process:{i}")
                        for i, t in enumerate(targets)]
        self._interval = interval_s
        self._check_name = check_name
        self._up: List[bool] = [True] * len(self._pingers)
        self._down_since: List[Optional[float]] = [None] * len(self._pingers)
        self._polled = False
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = get_registry()
        self._gauges = [reg.gauge("ps/shard_up", shard=str(i))
                        for i in range(len(self._pingers))]
        # the autoscaler-facing aggregate: the federation scraper reads
        # the per-shard gauges, but a single-process consumer (or an
        # alert rule) wants the count directly
        self._g_down = reg.gauge("ps/shards_down")

    @classmethod
    def for_endpoints(cls, endpoints: Sequence[str],
                      interval_s: Optional[float] = None) -> "ShardMonitor":
        return cls(list(endpoints), interval_s=interval_s)

    # ------------------------------------------------------------- polling
    def _cfg_interval(self) -> float:
        if self._interval is not None:
            return self._interval
        return float(os.environ.get("PDTPU_PS_MONITOR_INTERVAL", "1.0"))

    def poll_now(self) -> List[bool]:
        """One synchronous sweep; returns the per-shard up flags."""
        results = [p() for p in self._pingers]
        now = time.monotonic()
        with self._lock:
            for i, up in enumerate(results):
                self._up[i] = up
                if up:
                    self._down_since[i] = None
                elif self._down_since[i] is None:
                    self._down_since[i] = now
                self._gauges[i].set(1.0 if up else 0.0)
            self._g_down.set(sum(1 for up in results if not up))
            self._polled = True
        return results

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.poll_now()
            except Exception:
                pass  # a monitor must never kill the worker
            self._stop.wait(self._cfg_interval())

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ShardMonitor":
        if self._thread is not None:
            return self
        self._stop.clear()
        register_health_check(self._check_name, self._health)
        self._thread = threading.Thread(target=self._loop,
                                        name="ps-shard-monitor",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        unregister_health_check(self._check_name)

    def __enter__(self) -> "ShardMonitor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -------------------------------------------------------------- status
    def _health(self):
        """The registered /healthz check (see module docstring)."""
        wedge = float(os.environ.get("PDTPU_WEDGE_TIMEOUT", "300"))
        now = time.monotonic()
        with self._lock:
            if not self._polled:
                return "ok", "no sweep completed yet"
            down = [(i, now - t) for i, t in enumerate(self._down_since)
                    if t is not None]
        if not down:
            return "ok", f"{len(self._pingers)} shards up"
        worst = max(s for _, s in down)
        names = ", ".join(f"shard {i} ({self._labels[i]}) down {s:.1f}s"
                          for i, s in down)
        if worst > wedge:
            return "failing", f"wedged past {wedge:g}s: {names}"
        return "degraded", names

    def status(self) -> Dict[str, object]:
        now = time.monotonic()
        with self._lock:
            shards = [{
                "shard": i,
                "endpoint": self._labels[i],
                "up": self._up[i],
                "down_for_s": (0.0 if self._down_since[i] is None
                               else round(now - self._down_since[i], 3)),
            } for i in range(len(self._pingers))]
        st = self._health()
        status, detail = (st if isinstance(st, tuple) else (st, ""))
        return {"status": status, "detail": detail, "shards": shards}
