"""HotRowCache — device-resident hot rows between the tier and the shards.

BENCH_r05 put deepfm's exact-Adagrad path at 0.957 of its streaming
roofline: the step already moves the touched rows at memory speed, so the
next factor must come from *not moving them*. CTR id streams are heavily
Zipfian — a small fraction of the 33.5M-row table absorbs almost all
touches — so the tier keeps those rows resident in HBM and lets the PS
shards hold only the cold tail.

Layout. The program's table param becomes one persistent
``[capacity + step_rows, lanes] uint16`` slab:

* rows ``[0, capacity)`` — the RESIDENT region, managed by LFU admission
  (``FreqSketch`` over the recent uid stream; one-touch ids never enter);
* rows ``[capacity, capacity + step_rows)`` — the STAGING tail, reused
  every step for bypass rows exactly like today's per-step pull cache.

Each step the tier remaps global ids to slab rows, scatters only the
*miss* rows in, runs the program unchanged (``uniq_merge``'s update math
depends on id equality structure, not id values, so an arbitrary
monotone->slab remap leaves every float op bit-identical), and pushes
back only what left the slab: eviction victims and staging rows. Hits
never cross HBM<->host — that is the entire win.

Plan/commit protocol (the concurrency contract). With ``pull_ahead >= 1``
the DeviceLoader converts batches on a worker thread while the main
thread dispatches earlier ones, so cache decisions are split:

* ``plan(uids)`` runs on the CONVERT thread: metadata only — classify
  hit/miss, admit or bypass each miss (evicting victims from the map),
  and hand back slab slots. No device work, no slab bytes move.
* the tier DISPATCHES plans in order on the main thread: write back the
  plan's victims, scatter its pulled miss rows, run, then ``commit``.

Two rules make a concurrent ``flush()`` (checkpoint save) exact between
a plan and its dispatch:

* dirty bits are set at COMMIT, not at plan time — a flush between plan
  and dispatch must push the row's *current* slab bytes, not assume the
  not-yet-run update already happened;
* a victim's bytes stay in its old slot until the admitting plan's
  dispatch scatters over it, so planned-but-uncommitted evictions are
  carried in a pending list that ``flush_rows`` also drains, and slots
  referenced by any in-flight plan are never chosen as victims
  (``_inflight`` refcounts).

Write-backs ride the tier's ``_Pusher`` and therefore the push journal:
``recover_shard`` replay and the ``@ps_mark@`` checkpoint protocol see
cache write-backs as ordinary pushes — crash recovery stays lossless and
bitwise with zero new machinery.

Device ops (gather for write-back, scatter for admission) go through the
Pallas row kernels in ``ops.pallas_kernels.sparse_adagrad`` when the
backend can run them, else a jitted XLA gather/scatter producing the
same bytes. All index vectors are padded to power-of-two buckets by
repeating their last element — identical-value duplicate writes keep the
scatter deterministic while the executable set stays O(log slab).

Metrics (process-wide, unlabeled so multiple tables sum):
``ps/cache_hits|misses|admitted|evictions|bypass|writeback_bytes``
counters and ``ps/cache_resident_rows|dirty_rows|capacity`` gauges —
surfaced by ``tools/ps_admin stats``/``dump-health`` and the bench.
``hits``/``misses`` count UNIQUE rows per step (the tier dedups before
planning — that is the unit of pull/push traffic); the
``lookup_hits``/``lookup_misses`` pair weights each uid by its raw
occurrence count, i.e. the fraction of embedding LOOKUPS served from
resident HBM rows — the number the Zipfian bench claim is stated in.
"""
from __future__ import annotations

import os
import threading
from typing import List, Optional, Tuple

import numpy as np

from ..observability import get_registry
from .slab import FreqSketch, SlotMap

__all__ = ["HotRowCache", "CachePlan"]


def _bucket(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << max(0, int(n) - 1).bit_length()


class CachePlan:
    """One step's cache decisions (metadata only; made on the convert
    thread, applied in order on the dispatch thread).

    ``slots[j]`` is the slab row of ``uids[j]``: resident ``[0, capacity)``
    for hits and admitted misses, staging tail for bypass misses.
    ``miss_*`` aligns with the pulled miss buffer (ascending uids, row i
    of the pull lands in ``miss_slots[i]``); ``evict_*`` are this plan's
    victims, uid-ascending for the push contract.
    """
    __slots__ = ("uids", "slots", "miss_uids", "miss_slots",
                 "bypass_uids", "bypass_slots", "evict_uids", "evict_slots",
                 "n_hit", "n_admit", "touched_resident")

    def __init__(self, uids, slots, miss_uids, miss_slots, bypass_uids,
                 bypass_slots, evict_uids, evict_slots, n_hit, n_admit,
                 touched_resident):
        self.uids = uids
        self.slots = slots
        self.miss_uids = miss_uids
        self.miss_slots = miss_slots
        self.bypass_uids = bypass_uids
        self.bypass_slots = bypass_slots
        self.evict_uids = evict_uids
        self.evict_slots = evict_slots
        self.n_hit = n_hit
        self.n_admit = n_admit
        self.touched_resident = touched_resident


class HotRowCache:
    """LFU-admitted, write-back, device-resident row cache for one table.

    ``capacity`` resident rows + ``step_rows`` staging rows; the program's
    cache param must be ``[capacity + step_rows, lanes]``. ``vocab`` sizes
    the dense uid->slot index (4 bytes/row host-side). Admission needs an
    estimated frequency >= ``min_freq`` (``PDTPU_PS_ADMIT_MIN_FREQ``,
    default 2 — one-touch ids bypass) and, when full, strictly above the
    sampled-LFU victim's estimate.
    """

    def __init__(self, capacity: int, step_rows: int, lanes: int = 128, *,
                 vocab: int, name: str = "", min_freq: Optional[int] = None,
                 sample: int = 16, seed: int = 0):
        if capacity < 1 or step_rows < 1:
            raise ValueError(
                f"HotRowCache: capacity={capacity}/step_rows={step_rows} "
                "must both be >= 1")
        self.capacity = int(capacity)
        self.step_rows = int(step_rows)
        self.lanes = int(lanes)
        self.name = str(name)
        if min_freq is None:
            min_freq = int(os.environ.get("PDTPU_PS_ADMIT_MIN_FREQ", "2"))
        self.min_freq = max(1, int(min_freq))
        self.sample = max(1, int(sample))
        self._slots = SlotMap(self.capacity, vocab=int(vocab))
        self._sketch = FreqSketch(seed=0x9E3779B9 + seed)
        self._dirty = np.zeros(self.capacity, bool)
        self._inflight = np.zeros(self.capacity, np.int32)
        self._uncommitted: List[CachePlan] = []
        self._lock = threading.Lock()
        self._rng = np.random.RandomState(0x5EED + seed)
        self.slab = None          # device [capacity+step_rows, lanes] u16
        self._gather_fn = None    # lazily bound (no JAX at import)
        self._scatter_fn = None
        # local mirrors for per-table stats(); registry gets the same
        # increments process-wide
        self.hits = self.misses = self.admitted = 0
        self.evictions = self.bypass = self.writeback_bytes = 0
        self.lookup_hits = self.lookup_misses = 0
        reg = get_registry()
        self._c_hits = reg.counter("ps/cache_hits")
        self._c_misses = reg.counter("ps/cache_misses")
        self._c_lhits = reg.counter("ps/cache_lookup_hits")
        self._c_lmisses = reg.counter("ps/cache_lookup_misses")
        self._c_admitted = reg.counter("ps/cache_admitted")
        self._c_evictions = reg.counter("ps/cache_evictions")
        self._c_bypass = reg.counter("ps/cache_bypass")
        self._c_wb = reg.counter("ps/cache_writeback_bytes")
        self._g_resident = reg.gauge("ps/cache_resident_rows")
        self._g_dirty = reg.gauge("ps/cache_dirty_rows")
        reg.gauge("ps/cache_capacity").add(float(self.capacity))
        self._last_resident = 0
        self._last_dirty = 0

    # ------------------------------------------------------------- planning
    def plan(self, uids: np.ndarray,
             counts: Optional[np.ndarray] = None) -> CachePlan:
        """Classify one step's ascending unique `uids`; returns the plan.
        Mutates only host metadata (map/sketch/inflight/pending).
        `counts` (optional, aligned with `uids`) are raw occurrence
        counts — they feed the lookup-weighted hit metrics only, never
        the admission decisions."""
        uids = np.asarray(uids, np.int64)
        with self._lock:
            self._sketch.observe(uids)
            slots = self._slots.get_many(uids)
            hit = slots >= 0
            n_hit = int(hit.sum())
            if counts is None:
                l_hit, l_miss = n_hit, int(uids.size) - n_hit
            else:
                counts = np.asarray(counts, np.int64)
                l_hit = int(counts[hit].sum())
                l_miss = int(counts.sum()) - l_hit
            miss_idx = np.flatnonzero(~hit)
            n_miss = int(miss_idx.size)
            if n_miss > self.step_rows:
                raise ValueError(
                    f"batch touches {n_miss} non-resident rows of table "
                    f"{self.name!r} but the slab has only {self.step_rows} "
                    "staging rows; rebuild the program with a larger "
                    "[hot_rows + per-step rows] cache param")
            # slots THIS plan touches: never valid eviction victims
            # (evicting a row the same step reads/updates it would hand
            # one slab row to two uids at dispatch time)
            mine = set(slots[hit].tolist())
            est = (self._sketch.estimate(uids[miss_idx]) if n_miss
                   else np.zeros(0, np.uint32))
            evict_uids: List[int] = []
            evict_slots: List[int] = []
            n_stage = 0
            n_admit = 0
            for k in range(n_miss):
                j = int(miss_idx[k])
                f = int(est[k])
                s = -1
                if f >= self.min_freq:
                    if self._slots.free_slots:
                        s = self._slots.assign(int(uids[j]))
                    else:
                        victim = self._pick_victim(mine, f)
                        if victim is not None:
                            vu, vs = victim
                            self._slots.pop(vu)
                            # the victim's post-eviction truth is whatever
                            # the slab holds when the admitting dispatch
                            # writes it back; its dirty bit is retired
                            # here so flush_rows reports it exactly once
                            # (via the pending-evict list, below)
                            self._dirty[vs] = False
                            evict_uids.append(vu)
                            evict_slots.append(vs)
                            s = self._slots.assign(int(uids[j]))  # reuses vs
                if s >= 0:
                    n_admit += 1
                    mine.add(s)
                else:
                    s = self.capacity + n_stage
                    n_stage += 1
                slots[j] = s
            resident = slots[slots < self.capacity].astype(np.int64)
            np.add.at(self._inflight, resident, 1)
            miss_uids = uids[miss_idx]
            miss_slots = slots[miss_idx].astype(np.int32)
            byp = miss_slots >= self.capacity
            ev_u = np.asarray(evict_uids, np.int64)
            ev_s = np.asarray(evict_slots, np.int32)
            order = np.argsort(ev_u, kind="stable")
            plan = CachePlan(
                uids=uids, slots=slots.astype(np.int32),
                miss_uids=miss_uids, miss_slots=miss_slots,
                bypass_uids=miss_uids[byp], bypass_slots=miss_slots[byp],
                evict_uids=ev_u[order], evict_slots=ev_s[order],
                n_hit=n_hit, n_admit=n_admit,
                touched_resident=resident.astype(np.int32))
            self._uncommitted.append(plan)
            self.hits += n_hit
            self.misses += n_miss
            self.lookup_hits += l_hit
            self.lookup_misses += l_miss
            self.admitted += n_admit
            self.evictions += len(evict_uids)
            self.bypass += n_stage
            self._c_hits.inc(n_hit)
            self._c_misses.inc(n_miss)
            self._c_lhits.inc(l_hit)
            self._c_lmisses.inc(l_miss)
            self._c_admitted.inc(n_admit)
            self._c_evictions.inc(len(evict_uids))
            self._c_bypass.inc(n_stage)
            self._publish_gauges()
        return plan

    def _pick_victim(self, exclude, cand_freq: int
                     ) -> Optional[Tuple[int, int]]:
        """Sampled LFU: random resident slots, skipping any slot an
        in-flight plan references; evict the lowest-estimate one iff the
        candidate is strictly hotter (ties keep the incumbent — churn
        without evidence costs two row moves for nothing)."""
        cand_slots = []
        for s in self._rng.randint(0, self.capacity,
                                   size=4 * self.sample).tolist():
            if self._inflight[s] or s in exclude:
                continue
            if self._slots.uid_of(s) is None:
                continue
            cand_slots.append(s)
            if len(cand_slots) >= self.sample:
                break
        if not cand_slots:
            return None
        cand_slots = np.asarray(cand_slots, np.int64)
        cand_uids = self._slots.uids_at(cand_slots)
        ests = self._sketch.estimate(cand_uids)
        k = int(np.argmin(ests))
        if int(ests[k]) >= cand_freq:
            return None
        return int(cand_uids[k]), int(cand_slots[k])

    # ------------------------------------------------------------- dispatch
    def commit(self, plan: CachePlan) -> None:
        """Retire a dispatched plan: its resident rows now hold post-step
        bytes (dirty), its slots are no longer pinned, its evictions have
        been written back."""
        with self._lock:
            np.add.at(self._inflight, plan.touched_resident.astype(np.int64),
                      -1)
            self._dirty[plan.touched_resident] = True
            self._uncommitted.remove(plan)
            self._publish_gauges()

    def note_writeback(self, n_rows: int) -> None:
        nb = int(n_rows) * self.lanes * 2
        self.writeback_bytes += nb
        self._c_wb.inc(nb)

    def flush_rows(self) -> Tuple[np.ndarray, np.ndarray]:
        """(uids, slots), uid-ascending, of every row whose newest bytes
        exist only in the slab: dirty residents plus planned-but-not-yet-
        dispatched eviction victims (their bytes still sit in their old
        slots). Clears the dirty bits — the caller gathers and pushes."""
        with self._lock:
            ds = np.flatnonzero(self._dirty)
            du = self._slots.uids_at(ds)
            extra_u: List[int] = []
            extra_s: List[int] = []
            for p in self._uncommitted:
                extra_u.extend(p.evict_uids.tolist())
                extra_s.extend(p.evict_slots.tolist())
            self._dirty[:] = False
            self._publish_gauges()
        u = np.concatenate([du, np.asarray(extra_u, np.int64)])
        s = np.concatenate([ds.astype(np.int32),
                            np.asarray(extra_s, np.int32)])
        order = np.argsort(u, kind="stable")
        return u[order], s[order]

    def drop_rows(self, uids: np.ndarray) -> int:
        """Delta-subscriber invalidation: another writer published fresher
        PS bytes for `uids`, so drop their CLEAN, not-in-flight resident
        entries — the next touch misses and re-pulls the new bytes. A
        dirty row holds a local update the shards haven't seen (dropping
        it would lose the write) and an in-flight row is referenced by a
        planned-but-undispatched step, so both are kept; so is a pending
        eviction victim (its write-back is already scheduled). Returns
        #dropped."""
        n = 0
        with self._lock:
            pending = set()
            for p in self._uncommitted:
                pending.update(p.evict_uids.tolist())
            for u in np.asarray(uids, np.int64).tolist():
                if u in pending:
                    continue
                s = self._slots.get(u)
                if (s is not None and not self._dirty[s]
                        and not self._inflight[s]):
                    self._slots.pop(u)
                    n += 1
            self._publish_gauges()
        return n

    def _publish_gauges(self) -> None:
        res, dirt = len(self._slots), int(self._dirty.sum())
        self._g_resident.add(float(res - self._last_resident))
        self._g_dirty.add(float(dirt - self._last_dirty))
        self._last_resident, self._last_dirty = res, dirt

    # ----------------------------------------------------------- device ops
    def ensure_slab(self):
        if self.slab is None:
            import jax.numpy as jnp
            self.slab = jnp.zeros(
                (self.capacity + self.step_rows, self.lanes), jnp.uint16)
        return self.slab

    def _bind_ops(self):
        import jax
        import jax.numpy as jnp
        from ..ops.pallas_kernels import sparse_adagrad as fsa

        if fsa.rows_enabled(self.lanes):
            self._gather_fn = fsa.fused_row_gather
            self._scatter_fn = fsa.fused_row_scatter
        else:
            self._gather_fn = jax.jit(
                lambda t, i: jnp.take(t, i, axis=0))
            # padded duplicate targets carry identical bytes, so the
            # scatter stays deterministic despite non-unique indices
            self._scatter_fn = jax.jit(
                lambda t, tgt, rows, src: t.at[tgt].set(rows[src]))

    def take_rows(self, slots: np.ndarray):
        """Gather ``slab[slots]`` -> device ``[bucket(n), lanes]``; pad
        rows repeat the last slot (the pusher slices ``[:n]``)."""
        import jax.numpy as jnp

        if self._gather_fn is None:
            self._bind_ops()
        idx = np.asarray(slots, np.int32)
        n = int(idx.shape[0])
        pad = _bucket(n) - n
        if pad:
            idx = np.concatenate([idx, np.full(pad, idx[-1], np.int32)])
        return self._gather_fn(self.ensure_slab(), jnp.asarray(idx))

    def insert_rows(self, tgt_slots: np.ndarray, rows) -> None:
        """Scatter ``rows[:n]`` into ``slab[tgt_slots]`` (n = len(tgt));
        index vectors pad to a power-of-two bucket by repeating the last
        (tgt, src) pair — identical-value rewrites, deterministic."""
        import jax.numpy as jnp

        if self._scatter_fn is None:
            self._bind_ops()
        tgt = np.asarray(tgt_slots, np.int32)
        n = int(tgt.shape[0])
        src = np.arange(n, dtype=np.int32)
        pad = _bucket(n) - n
        if pad:
            tgt = np.concatenate([tgt, np.full(pad, tgt[-1], np.int32)])
            src = np.concatenate([src, np.full(pad, src[-1], np.int32)])
        self.slab = self._scatter_fn(self.ensure_slab(), jnp.asarray(tgt),
                                     rows, jnp.asarray(src))

    # ---------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            ltotal = self.lookup_hits + self.lookup_misses
            return {
                "capacity": self.capacity, "step_rows": self.step_rows,
                "resident": len(self._slots),
                "dirty": int(self._dirty.sum()),
                "hits": self.hits, "misses": self.misses,
                "hit_rate": (self.hits / total) if total else None,
                "lookup_hits": self.lookup_hits,
                "lookup_misses": self.lookup_misses,
                "lookup_hit_rate": ((self.lookup_hits / ltotal)
                                    if ltotal else None),
                "admitted": self.admitted, "evictions": self.evictions,
                "bypass": self.bypass,
                "writeback_bytes": self.writeback_bytes,
            }
