"""Range-partitioned embedding shards — the server half of the PS tier.

Reference analog: the Downpour-style sparse tables behind ``FleetWrapper``
(pslib DownpourSparseTable: rows live on pserver processes, workers pull
the touched rows and push updates through the Communicator). Here a shard
holds a contiguous row range of ONE table in the packed row-major
state-in-row layout (``ops/deferred_rows.py``: ``[n, 128] uint16`` rows,
each bit-splitting up to 64 f32 values — embedding columns plus optimizer
state columns in the same row), so the exact-Adagrad contract of the
packed single-table path is preserved per shard: the worker computes the
identical update math on the pulled bytes and pushes whole new rows back
(scatter-set semantics), and a shard never reinterprets them.

Shards are plain numpy + stdlib on purpose: a shard server process needs
no JAX (and must not fight the trainer for the TPU), and host DRAM — not
HBM — is what bounds table size, which is the entire point of the tier.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["RangeSpec", "EmbeddingShard", "make_shards"]

PACK_LANES = 128  # mirror of ops.deferred_rows.PACK_LANES (no jax import)


class RangeSpec:
    """Range partition of ``[0, vocab)`` row ids into N contiguous shards.

    ``boundaries`` is the N+1 ascending cut vector ``[0, b1, …, vocab]``;
    row id ``r`` lives on shard ``i`` iff ``boundaries[i] <= r <
    boundaries[i+1]`` — an id exactly on a cut ``b_i`` belongs to shard
    ``i`` (the right-hand side), which the tests pin down. ``even()``
    builds the balanced split (first ``vocab % n`` shards get the extra
    row, so every id is covered with no empty tail shard).
    """

    def __init__(self, vocab: int, boundaries: Sequence[int]):
        b = [int(x) for x in boundaries]
        if len(b) < 2 or b[0] != 0 or b[-1] != int(vocab):
            raise ValueError(
                f"RangeSpec boundaries must run [0, …, vocab={vocab}]; "
                f"got {b}")
        if any(y <= x for x, y in zip(b, b[1:])):
            raise ValueError(f"RangeSpec boundaries must be strictly "
                             f"ascending (no empty shards); got {b}")
        self.vocab = int(vocab)
        self.boundaries = np.asarray(b, dtype=np.int64)

    @classmethod
    def even(cls, vocab: int, num_shards: int) -> "RangeSpec":
        if num_shards < 1 or num_shards > vocab:
            raise ValueError(
                f"RangeSpec.even: need 1 <= num_shards <= vocab, got "
                f"num_shards={num_shards}, vocab={vocab}")
        base, rem = divmod(int(vocab), int(num_shards))
        cuts = [0]
        for i in range(num_shards):
            cuts.append(cuts[-1] + base + (1 if i < rem else 0))
        return cls(vocab, cuts)

    @property
    def num_shards(self) -> int:
        return len(self.boundaries) - 1

    def bounds(self, shard: int):
        return int(self.boundaries[shard]), int(self.boundaries[shard + 1])

    def shard_of(self, ids: np.ndarray) -> np.ndarray:
        """Shard index per id (vectorized)."""
        ids = np.asarray(ids)
        if ids.size and (ids.min() < 0 or ids.max() >= self.vocab):
            bad = ids[(ids < 0) | (ids >= self.vocab)]
            raise ValueError(
                f"ids out of range [0, {self.vocab}): {bad[:8].tolist()}")
        return np.searchsorted(self.boundaries, ids, side="right") - 1

    def cuts_into(self, sorted_ids: np.ndarray) -> np.ndarray:
        """Cut points of an ASCENDING id vector at the shard boundaries:
        shard ``i``'s slice is ``sorted_ids[cuts[i]:cuts[i+1]]``. Because
        the partition is by contiguous range, a sorted pull re-assembles
        by plain concatenation in shard order — no scatter needed."""
        return np.searchsorted(sorted_ids, self.boundaries, side="left")

    def to_dict(self) -> dict:
        return {"vocab": self.vocab,
                "boundaries": self.boundaries.tolist()}

    @classmethod
    def from_dict(cls, d: dict) -> "RangeSpec":
        return cls(d["vocab"], d["boundaries"])

    def __eq__(self, other):
        return (isinstance(other, RangeSpec)
                and self.vocab == other.vocab
                and np.array_equal(self.boundaries, other.boundaries))

    def __repr__(self):
        return (f"RangeSpec(vocab={self.vocab}, "
                f"shards={self.num_shards})")


class EmbeddingShard:
    """One table's contiguous row slice ``[lo, hi)`` as packed u16 rows.

    ``pull``/``push`` speak GLOBAL row ids (the shard subtracts its own
    ``lo``), so the transport and the client never translate. ``push`` is
    scatter-SET of whole rows — the worker owns the optimizer math; the
    shard is storage with byte accounting. A lock serializes mutation:
    the in-process client may be driven from the trainer thread and the
    async pusher concurrently, and the socket server is one-thread-per-
    connection.
    """

    def __init__(self, name: str, lo: int, hi: int,
                 rows: Optional[np.ndarray] = None,
                 lanes: int = PACK_LANES):
        if hi <= lo:
            raise ValueError(f"EmbeddingShard {name!r}: empty range "
                             f"[{lo}, {hi})")
        self.name = str(name)
        self.lo, self.hi = int(lo), int(hi)
        n = self.hi - self.lo
        if rows is None:
            rows = np.zeros((n, lanes), dtype=np.uint16)
        rows = np.ascontiguousarray(rows, dtype=np.uint16)
        if rows.shape != (n, lanes):
            raise ValueError(
                f"EmbeddingShard {self.name!r}: rows shape {rows.shape} "
                f"!= ({n}, {lanes}) for range [{lo}, {hi})")
        self.rows = rows
        self._lock = threading.Lock()
        self.bytes_pulled = 0
        self.bytes_pushed = 0
        self.n_pulls = 0
        self.n_pushes = 0

    def _local(self, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids, dtype=np.int64)
        if ids.size and (ids.min() < self.lo or ids.max() >= self.hi):
            bad = ids[(ids < self.lo) | (ids >= self.hi)]
            raise ValueError(
                f"shard {self.name!r}[{self.lo}:{self.hi}): ids outside "
                f"range: {bad[:8].tolist()}")
        return ids - self.lo

    def pull(self, ids: np.ndarray) -> np.ndarray:
        """Rows for global ids (a fresh copy — later pushes never alias
        into a buffer the caller is still reading)."""
        loc = self._local(ids)
        with self._lock:
            out = self.rows[loc]  # fancy index: already a copy
            self.bytes_pulled += out.nbytes
            self.n_pulls += 1
        return out

    def push(self, ids: np.ndarray, rows: np.ndarray) -> None:
        """Scatter-set whole rows at global ids."""
        loc = self._local(ids)
        rows = np.asarray(rows, dtype=np.uint16)
        if rows.shape != (loc.shape[0], self.rows.shape[1]):
            raise ValueError(
                f"shard {self.name!r}: push rows shape {rows.shape} != "
                f"({loc.shape[0]}, {self.rows.shape[1]})")
        with self._lock:
            self.rows[loc] = rows
            self.bytes_pushed += rows.nbytes
            self.n_pushes += 1

    def dump(self) -> np.ndarray:
        """The full slice (copy) — the checkpoint save path."""
        with self._lock:
            return self.rows.copy()

    def load(self, rows: np.ndarray) -> None:
        """Replace the full slice — the checkpoint restore path."""
        rows = np.ascontiguousarray(rows, dtype=np.uint16)
        if not rows.flags.writeable:
            # socket transport hands us np.frombuffer views (read-only, and
            # ascontiguousarray passes them through); the slice must stay
            # pushable after restore
            rows = rows.copy()
        if rows.shape != self.rows.shape:
            raise ValueError(
                f"shard {self.name!r}: load shape {rows.shape} != "
                f"{self.rows.shape}")
        with self._lock:
            self.rows = rows

    def stats(self) -> dict:
        with self._lock:
            # live_rows/capacity mirror the dynamic shard's vocab fields
            # (a dense shard is always at 100% occupancy by construction)
            # so health/vocab tooling reads every shard kind uniformly
            return {"name": self.name, "lo": self.lo, "hi": self.hi,
                    "rows": self.hi - self.lo,
                    "bytes_pulled": self.bytes_pulled,
                    "bytes_pushed": self.bytes_pushed,
                    "n_pulls": self.n_pulls, "n_pushes": self.n_pushes,
                    "dynamic": False,
                    "live_rows": self.hi - self.lo,
                    "capacity": self.hi - self.lo}


def make_shards(name: str, spec: RangeSpec,
                full_rows: Optional[np.ndarray] = None,
                lanes: int = PACK_LANES) -> List[EmbeddingShard]:
    """Build the shard set for one table, optionally slicing an existing
    full ``[vocab, lanes]`` packed table (each shard copies its slice, so
    the source array can be dropped)."""
    if full_rows is not None:
        full_rows = np.asarray(full_rows, dtype=np.uint16)
        if full_rows.shape != (spec.vocab, lanes):
            raise ValueError(
                f"make_shards: full_rows shape {full_rows.shape} != "
                f"({spec.vocab}, {lanes})")
    out = []
    for i in range(spec.num_shards):
        lo, hi = spec.bounds(i)
        rows = full_rows[lo:hi].copy() if full_rows is not None else None
        out.append(EmbeddingShard(name, lo, hi, rows, lanes=lanes))
    return out
