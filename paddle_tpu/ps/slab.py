"""Shared slab bookkeeping for packed-row caches.

Two caches in this repo pin packed ``[*, lanes] uint16`` rows into a
fixed-capacity slab and need the same uid->slot accounting underneath:
the serving side's ``inference.ps_lookup.RowCache`` (host LRU in front of
read-only pulls) and the training side's ``ps.hot_cache.HotRowCache``
(device-resident LFU with write-back). This module is that common core —
numpy-only (it is imported from paths that must never pull in JAX) and
policy-free: eviction *choice* stays with the caller, the classes here
only answer "where does this uid live", "who was touched least recently",
and "how often has this uid been seen lately".

* :class:`SlotMap` — uid -> slot over a fixed pool, with a free list and
  a reverse slot -> uid view. Backed by a dict, or by a dense int32
  array when the id universe (``vocab``) is known — the dense form makes
  ``get_many`` a single vectorized gather, which is what keeps the hot
  cache's per-step planning off the training critical path.
* :class:`LruOrder` — recency list (the serving cache's eviction policy).
* :class:`FreqSketch` — Count-Min sketch with periodic counter halving
  (TinyLFU-style aging); the hot cache's admission filter. Approximate by
  design: collisions only ever OVER-estimate a frequency, so a sketch
  decision can admit a cold row early but never silently starve a hot
  one, and no correctness property anywhere rests on its answers.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np

__all__ = ["SlotMap", "LruOrder", "FreqSketch"]


class SlotMap:
    """uid -> slot bookkeeping over ``capacity`` fixed slots.

    Slots are recycled LIFO: ``pop`` returns a slot to the free list and
    the next ``assign`` hands that same slot back — callers that evict
    then admit in one breath reuse the victim's slot, which is what both
    caches' slab-storage invariants assume.
    """

    def __init__(self, capacity: int, vocab: Optional[int] = None):
        if capacity < 1:
            raise ValueError("SlotMap capacity must be >= 1")
        self.capacity = int(capacity)
        self.vocab = None if vocab is None else int(vocab)
        self._free = list(range(self.capacity - 1, -1, -1))
        self._uid_of = np.full(self.capacity, -1, np.int64)
        if self.vocab is None:
            self._dense = None
            self._slot: Optional[dict] = {}
        else:
            self._dense = np.full(self.vocab, -1, np.int32)
            self._slot = None

    def __len__(self) -> int:
        return self.capacity - len(self._free)

    def __contains__(self, uid: int) -> bool:
        return self.get(int(uid)) is not None

    @property
    def free_slots(self) -> int:
        return len(self._free)

    def get(self, uid: int) -> Optional[int]:
        if self._dense is not None:
            s = int(self._dense[uid])
            return None if s < 0 else s
        return self._slot.get(uid)

    def get_many(self, uids: np.ndarray) -> np.ndarray:
        """Slot per uid, -1 where absent — vectorized in dense mode."""
        uids = np.asarray(uids, np.int64)
        if self._dense is not None:
            return self._dense[uids].astype(np.int32, copy=True)
        out = np.empty(uids.shape[0], np.int32)
        get = self._slot.get
        for j, u in enumerate(uids.tolist()):
            out[j] = get(u, -1)
        return out

    def assign(self, uid: int) -> int:
        """Bind `uid` to a free slot; the caller evicts first when full."""
        if not self._free:
            raise RuntimeError("SlotMap is full — pop a resident uid first")
        s = self._free.pop()
        self._uid_of[s] = uid
        if self._dense is not None:
            self._dense[uid] = s
        else:
            self._slot[uid] = s
        return s

    def pop(self, uid: int) -> int:
        """Unbind `uid`, returning its (now free) slot."""
        if self._dense is not None:
            s = int(self._dense[uid])
            if s < 0:
                raise KeyError(uid)
            self._dense[uid] = -1
        else:
            s = self._slot.pop(uid)
        self._uid_of[s] = -1
        self._free.append(s)
        return s

    def uid_of(self, slot: int) -> Optional[int]:
        u = int(self._uid_of[slot])
        return None if u < 0 else u

    def uids_at(self, slots: np.ndarray) -> np.ndarray:
        """Vectorized reverse lookup (every slot must be occupied)."""
        return self._uid_of[np.asarray(slots, np.int64)].copy()

    def residents(self) -> Tuple[np.ndarray, np.ndarray]:
        """(uids, slots) of every occupied slot, in slot order."""
        occ = np.flatnonzero(self._uid_of >= 0)
        return self._uid_of[occ].copy(), occ.astype(np.int32)

    def clear(self) -> None:
        self._free = list(range(self.capacity - 1, -1, -1))
        self._uid_of.fill(-1)
        if self._dense is not None:
            self._dense.fill(-1)
        else:
            self._slot.clear()


class LruOrder:
    """Recency order over uids; coldest pops first."""

    def __init__(self):
        self._od: "OrderedDict[int, None]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._od)

    def touch(self, uid: int) -> None:
        self._od[uid] = None
        self._od.move_to_end(uid)

    def discard(self, uid: int) -> None:
        self._od.pop(uid, None)

    def pop_coldest(self) -> int:
        return self._od.popitem(last=False)[0]

    def clear(self) -> None:
        self._od.clear()


class FreqSketch:
    """Count-Min sketch with halving decay (the TinyLFU aging trick).

    ``depth`` counter rows of ``width`` uint32 cells, indexed by
    multiply-shift hashes (odd 64-bit multiplier, top ``log2(width)``
    bits). An estimate is the min over rows, so it can only over-count.
    Every ``decay_every`` observations all counters halve — recency
    keeps mattering and one ancient hot streak cannot pin a dead id's
    frequency forever.
    """

    def __init__(self, width: int = 1 << 15, depth: int = 4,
                 decay_every: Optional[int] = None, seed: int = 0x9E3779B9):
        if width < 2 or width & (width - 1):
            raise ValueError("FreqSketch width must be a power of two >= 2")
        self.width = int(width)
        self.depth = int(depth)
        self._shift = np.uint64(64 - (int(width).bit_length() - 1))
        self._c = np.zeros((self.depth, self.width), np.uint32)
        rng = np.random.RandomState(seed)
        self._salt = (rng.randint(1, 1 << 62, size=self.depth,
                                  dtype=np.int64).astype(np.uint64)
                      * np.uint64(2) + np.uint64(1))
        self.decay_every = (int(decay_every) if decay_every
                            else 8 * self.width)
        self._seen = 0

    def _hash(self, uids: np.ndarray) -> np.ndarray:
        u = np.asarray(uids, np.int64).astype(np.uint64)
        return (u[None, :] * self._salt[:, None]) >> self._shift

    def observe(self, uids: np.ndarray) -> None:
        uids = np.asarray(uids)
        if uids.size == 0:
            return
        h = self._hash(uids)
        for d in range(self.depth):
            np.add.at(self._c[d], h[d], 1)
        self._seen += int(uids.size)
        if self._seen >= self.decay_every:
            self._c >>= 1
            self._seen //= 2

    def estimate(self, uids: np.ndarray) -> np.ndarray:
        uids = np.asarray(uids)
        if uids.size == 0:
            return np.zeros(0, np.uint32)
        h = self._hash(uids)
        est = self._c[0][h[0]]
        for d in range(1, self.depth):
            est = np.minimum(est, self._c[d][h[d]])
        return est
