"""ShardedTable — one logical embedding table fanned out over N shards.

The worker-side aggregation point: takes SORTED unique global row ids
(what ``uniq_merge`` / ``np.unique`` produce), slices them into per-shard
contiguous chunks via the range spec, fans pull/push out across the shard
clients, and re-assembles pulls by concatenation (sorted ids + ordered
ranges ⇒ shard chunks are adjacent slices — no scatter on the hot path).

Fan-out uses one long-lived thread per shard only when there is more than
one shard: for the in-process single-shard case direct dispatch is
cheaper, and for socket shards the threads are what actually buys
parallelism (each client connection is its own TCP stream).

Metrics: ``ps/pull_ms`` / ``ps/push_ms`` histograms and
``ps/bytes_pulled`` / ``ps/bytes_pushed`` counters land in the process
`observability` Registry; per-shard byte counters are kept here as plain
ints (read by the bench's ``ps_embedding`` record and ``stats()``).
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..observability import get_registry
from .shard import EmbeddingShard, RangeSpec, make_shards
from .transport import InProcessClient, ShardClient

__all__ = ["ShardedTable"]


class ShardedTable:
    """Client-side view of one range-partitioned table.

    ``clients[i]`` serves rows ``spec.bounds(i)``; several tables may
    share the same client objects (one worker process per shard hosting
    every table's slice), so the executor pool is per-table but sized by
    shard count.
    """

    def __init__(self, name: str, spec: RangeSpec,
                 clients: Sequence[ShardClient], lanes: int = 128,
                 push_clients: Optional[Sequence[ShardClient]] = None):
        """push_clients: optional dedicated channel for pushes. A socket
        client serializes requests on its one connection, so when an
        async pusher (push_depth >= 1) shares clients with the pull
        prefetcher, every push queues behind — and delays — the next
        prefetch pull to the same shard. A second connection per shard
        lets them truly overlap; read-your-writes patching in the tier
        already covers the pull/push race. Defaults to `clients`
        (in-process dispatch has no per-connection serialization)."""
        if len(clients) != spec.num_shards:
            raise ValueError(
                f"ShardedTable {name!r}: {len(clients)} clients for "
                f"{spec.num_shards} shards")
        if (push_clients is not None
                and len(push_clients) != spec.num_shards):
            raise ValueError(
                f"ShardedTable {name!r}: {len(push_clients)} push clients "
                f"for {spec.num_shards} shards")
        self.name = str(name)
        self.spec = spec
        self.clients = list(clients)
        self.push_clients = (list(push_clients) if push_clients is not None
                             else self.clients)
        self.lanes = int(lanes)
        self.bytes_pulled_per_shard = [0] * spec.num_shards
        self.bytes_pushed_per_shard = [0] * spec.num_shards
        self._acct = threading.Lock()
        # with a dual channel, pulls and pushes run concurrently — size
        # the pool so one side never starves the other of workers
        self._pool = (ThreadPoolExecutor(
            max_workers=spec.num_shards * (
                2 if push_clients is not None else 1),
            thread_name_prefix=f"ps-{name}")
            if spec.num_shards > 1 else None)
        reg = get_registry()
        self._h_pull = reg.histogram("ps/pull_ms")
        self._h_push = reg.histogram("ps/push_ms")
        self._c_pulled = reg.counter("ps/bytes_pulled")
        self._c_pushed = reg.counter("ps/bytes_pushed")

    @classmethod
    def build_in_process(cls, name: str, spec: RangeSpec,
                         full_rows: Optional[np.ndarray] = None,
                         lanes: int = 128) -> "ShardedTable":
        """Single-host convenience: materialize the shards in this
        process (optionally pre-loaded from a full packed table) behind
        in-process clients."""
        shards = make_shards(name, spec, full_rows, lanes=lanes)
        return cls(name, spec, [InProcessClient([s]) for s in shards],
                   lanes=lanes)

    # ------------------------------------------------------------- fan-out
    def _chunks(self, sorted_ids: np.ndarray):
        """(shard_index, id-slice) for each shard that owns any of the
        ids. ``sorted_ids`` must be ascending (asserted cheaply at the
        ends — full monotonicity is the caller's contract)."""
        sorted_ids = np.asarray(sorted_ids, dtype=np.int64)
        if sorted_ids.size and sorted_ids[0] > sorted_ids[-1]:
            raise ValueError(
                f"ShardedTable {self.name!r}: ids must be ascending "
                f"(first={int(sorted_ids[0])} > last={int(sorted_ids[-1])}); "
                f"an unsorted pull would reassemble rows in the wrong order")
        cuts = self.spec.cuts_into(sorted_ids)
        out = []
        for i in range(self.spec.num_shards):
            a, b = int(cuts[i]), int(cuts[i + 1])
            if b > a:
                out.append((i, slice(a, b)))
        return sorted_ids, out

    def _run(self, jobs):
        """Execute (shard_index, thunk) jobs, parallel across shards."""
        if self._pool is None or len(jobs) <= 1:
            return [(i, fn()) for i, fn in jobs]
        futs = [(i, self._pool.submit(fn)) for i, fn in jobs]
        return [(i, f.result()) for i, f in futs]

    def pull(self, sorted_uids: np.ndarray) -> np.ndarray:
        """Packed rows ``[k, lanes] uint16`` for ascending unique ids."""
        t0 = time.perf_counter()
        ids, chunks = self._chunks(sorted_uids)
        if not chunks:
            out = np.zeros((0, self.lanes), dtype=np.uint16)
        else:
            jobs = [(i, (lambda i=i, sl=sl: self.clients[i].pull(
                self.name, ids[sl]))) for i, sl in chunks]
            parts = self._run(jobs)
            out = (parts[0][1] if len(parts) == 1
                   else np.concatenate([r for _, r in parts], axis=0))
        nb = out.nbytes
        with self._acct:
            for (i, sl) in chunks:
                self.bytes_pulled_per_shard[i] += (
                    (sl.stop - sl.start) * self.lanes * 2)
        self._c_pulled.inc(nb)
        self._h_pull.observe((time.perf_counter() - t0) * 1e3)
        return out

    def push(self, sorted_uids: np.ndarray, rows: np.ndarray) -> None:
        """Scatter-set whole rows at ascending unique ids."""
        t0 = time.perf_counter()
        ids, chunks = self._chunks(sorted_uids)
        rows = np.asarray(rows, dtype=np.uint16)
        if rows.shape != (ids.shape[0], self.lanes):
            raise ValueError(
                f"ShardedTable {self.name!r}: push rows {rows.shape} != "
                f"({ids.shape[0]}, {self.lanes})")
        jobs = [(i, (lambda i=i, sl=sl: self.push_clients[i].push(
            self.name, ids[sl], rows[sl]))) for i, sl in chunks]
        self._run(jobs)
        nb = rows.nbytes
        with self._acct:
            for (i, sl) in chunks:
                self.bytes_pushed_per_shard[i] += (
                    (sl.stop - sl.start) * self.lanes * 2)
        self._c_pushed.inc(nb)
        self._h_push.observe((time.perf_counter() - t0) * 1e3)

    # -------------------------------------------------------- full-table io
    def dump_shard(self, i: int) -> np.ndarray:
        return self.clients[i].dump(self.name)

    def dump_full(self) -> np.ndarray:
        """Assemble the whole ``[vocab, lanes]`` table (checkpoint save;
        ranges are ordered and exhaustive so this is a concat)."""
        parts = self._run([(i, (lambda i=i: self.clients[i].dump(self.name)))
                           for i in range(self.spec.num_shards)])
        return np.concatenate([p for _, p in parts], axis=0)

    def load_full(self, full_rows: np.ndarray) -> None:
        """Re-partition a full table onto the LIVE spec — this is what
        makes restore-onto-a-different-shard-count work: the checkpoint
        stores per-shard slices, `_assemble_shards` merges them into the
        full array, and this scatter follows the current boundaries."""
        full_rows = np.asarray(full_rows, dtype=np.uint16)
        if full_rows.shape != (self.spec.vocab, self.lanes):
            raise ValueError(
                f"ShardedTable {self.name!r}: load_full shape "
                f"{full_rows.shape} != ({self.spec.vocab}, {self.lanes})")
        jobs = []
        for i in range(self.spec.num_shards):
            lo, hi = self.spec.bounds(i)
            jobs.append((i, (lambda i=i, lo=lo, hi=hi:
                             self.clients[i].load(
                                 self.name, full_rows[lo:hi]))))
        self._run(jobs)

    # -------------------------------------------------------------- stats
    def stats(self) -> dict:
        per_shard = []
        for i in range(self.spec.num_shards):
            lo, hi = self.spec.bounds(i)
            per_shard.append({
                "shard": i, "lo": lo, "hi": hi, "rows": hi - lo,
                "bytes_pulled": self.bytes_pulled_per_shard[i],
                "bytes_pushed": self.bytes_pushed_per_shard[i],
            })
        return {"name": self.name, "vocab": self.spec.vocab,
                "num_shards": self.spec.num_shards,
                "lanes": self.lanes, "shards": per_shard}

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)
