"""ShardedTable — one logical embedding table fanned out over N shards.

The worker-side aggregation point: takes SORTED unique global row ids
(what ``uniq_merge`` / ``np.unique`` produce), slices them into per-shard
contiguous chunks via the range spec, fans pull/push out across the shard
clients, and re-assembles pulls by concatenation (sorted ids + ordered
ranges ⇒ shard chunks are adjacent slices — no scatter on the hot path).

Fan-out uses one long-lived thread per shard only when there is more than
one shard: for the in-process single-shard case direct dispatch is
cheaper, and for socket shards the threads are what actually buys
parallelism (each client connection is its own TCP stream).

Metrics: ``ps/pull_ms`` / ``ps/push_ms`` histograms and
``ps/bytes_pulled`` / ``ps/bytes_pushed`` counters land in the process
`observability` Registry; per-shard byte counters are kept here as plain
ints (read by the bench's ``ps_embedding`` record and ``stats()``).

Fault tolerance (the lossless-recovery half; transport retries are the
other half). The table keeps a client-side **push journal**: every push
batch is appended — per shard, BEFORE the remote send — and entries stay
until a checkpoint that contains them commits (``journal_truncate``,
driven by the Checkpointer's commit callback). A restarted shard is then
rebuilt exactly: ``recover_shard(i)`` loads the shard's slice of the
newest verified checkpoint and replays that shard's journal entries past
the checkpoint's mark, in issue order. Because entries are retained even
after a SUCCESSFUL remote push, replay is a superset of what the shard
may have lost — and pushes carry absolute rows (scatter-SET), so
re-applying one is idempotent. Net: checkpoint slice + replay ==
every push ever issued == what a never-crashed shard would hold.

The journal is bounded (``PDTPU_PS_JOURNAL_MAX_MB``, default 256):
past the cap the oldest entries are evicted and the eviction horizon
recorded — a later recovery that would need an evicted entry fails
loudly ("checkpoint too old for the journal") instead of rebuilding a
silently stale shard. Checkpoint cadence therefore bounds journal
growth; ``ps/journal_bytes`` gauges it.

Recovery runs under a write-lock while pull/push hold read-locks: a
concurrent push can never land between the checkpoint load and the
replay (where the load would erase it from the shard but the replay
snapshot would miss it).
"""
from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..observability import get_registry
from ..observability import context as _trace_ctx
from .shard import EmbeddingShard, RangeSpec, make_shards
from .transport import InProcessClient, ShardClient, TransportError

__all__ = ["ShardedTable"]


class _RWLock:
    """Many readers (pull/push fan-outs) XOR one writer (shard
    recovery). Writer-preference is irrelevant at this contention level;
    keep it minimal."""

    def __init__(self):
        self._cv = threading.Condition()
        self._readers = 0
        self._writer = False

    def acquire_read(self):
        with self._cv:
            while self._writer:
                self._cv.wait()
            self._readers += 1

    def release_read(self):
        with self._cv:
            self._readers -= 1
            if self._readers == 0:
                self._cv.notify_all()

    def acquire_write(self):
        with self._cv:
            while self._writer or self._readers:
                self._cv.wait()
            self._writer = True

    def release_write(self):
        with self._cv:
            self._writer = False
            self._cv.notify_all()


class ShardedTable:
    """Client-side view of one range-partitioned table.

    ``clients[i]`` serves rows ``spec.bounds(i)``; several tables may
    share the same client objects (one worker process per shard hosting
    every table's slice), so the executor pool is per-table but sized by
    shard count.
    """

    def __init__(self, name: str, spec: RangeSpec,
                 clients: Sequence[ShardClient], lanes: int = 128,
                 push_clients: Optional[Sequence[ShardClient]] = None):
        """push_clients: optional dedicated channel for pushes. A socket
        client serializes requests on its one connection, so when an
        async pusher (push_depth >= 1) shares clients with the pull
        prefetcher, every push queues behind — and delays — the next
        prefetch pull to the same shard. A second connection per shard
        lets them truly overlap; read-your-writes patching in the tier
        already covers the pull/push race. Defaults to `clients`
        (in-process dispatch has no per-connection serialization)."""
        if len(clients) != spec.num_shards:
            raise ValueError(
                f"ShardedTable {name!r}: {len(clients)} clients for "
                f"{spec.num_shards} shards")
        if (push_clients is not None
                and len(push_clients) != spec.num_shards):
            raise ValueError(
                f"ShardedTable {name!r}: {len(push_clients)} push clients "
                f"for {spec.num_shards} shards")
        self.name = str(name)
        self.spec = spec
        self.clients = list(clients)
        self.push_clients = (list(push_clients) if push_clients is not None
                             else self.clients)
        self.lanes = int(lanes)
        self.bytes_pulled_per_shard = [0] * spec.num_shards
        self.bytes_pushed_per_shard = [0] * spec.num_shards
        self._acct = threading.Lock()
        # push journal: per-shard [(seq, ids, rows)] since the last
        # committed checkpoint (see module docstring)
        self._jlock = threading.Lock()
        self._journal: List[List[tuple]] = [[] for _ in range(spec.num_shards)]
        self._journal_seq = 0
        self._journal_nbytes = 0
        # highest seq ever evicted from shard i's journal by the size cap
        self._evicted_upto = [0] * spec.num_shards
        self._rw = _RWLock()
        # delta-push taps: called (sorted_uids, rows) AFTER a push has
        # been applied on the shards — the streaming DeltaPublisher rides
        # this to stream touched rows to serving replicas
        self._push_listeners: List[Callable] = []
        self._recovery: Optional[Callable[[int, BaseException], None]] = None
        # armed by the tier: Checkpointer.save() calls it before taking
        # the journal mark / dumping shards, so device-resident dirty rows
        # (hot cache) and queued async pushes land first (see
        # set_flush_hook)
        self.flush_hook: Optional[Callable[[], None]] = None
        # with a dual channel, pulls and pushes run concurrently — size
        # the pool so one side never starves the other of workers
        self._pool = (ThreadPoolExecutor(
            max_workers=spec.num_shards * (
                2 if push_clients is not None else 1),
            thread_name_prefix=f"ps-{name}")
            if spec.num_shards > 1 else None)
        reg = get_registry()
        self._h_pull = reg.histogram("ps/pull_ms")
        self._h_push = reg.histogram("ps/push_ms")
        # per-shard pull time, observed INSIDE the fan-out thunk so each
        # sample is one shard's RPC (not the whole fan-out): the
        # federation layer's per-shard p99 straggler signal (ROADMAP 5)
        self._h_shard_pull = [reg.histogram("ps/shard_pull_ms", shard=str(i))
                              for i in range(spec.num_shards)]
        self._c_pulled = reg.counter("ps/bytes_pulled")
        self._c_pushed = reg.counter("ps/bytes_pushed")
        self._g_journal = reg.gauge("ps/journal_bytes", table=self.name)

    @classmethod
    def build_in_process(cls, name: str, spec: RangeSpec,
                         full_rows: Optional[np.ndarray] = None,
                         lanes: int = 128) -> "ShardedTable":
        """Single-host convenience: materialize the shards in this
        process (optionally pre-loaded from a full packed table) behind
        in-process clients."""
        shards = make_shards(name, spec, full_rows, lanes=lanes)
        return cls(name, spec, [InProcessClient([s]) for s in shards],
                   lanes=lanes)

    # ------------------------------------------------------------- fan-out
    def _chunks(self, sorted_ids: np.ndarray):
        """(shard_index, id-slice) for each shard that owns any of the
        ids. ``sorted_ids`` must be ascending (asserted cheaply at the
        ends — full monotonicity is the caller's contract)."""
        sorted_ids = np.asarray(sorted_ids, dtype=np.int64)
        if sorted_ids.size and sorted_ids[0] > sorted_ids[-1]:
            raise ValueError(
                f"ShardedTable {self.name!r}: ids must be ascending "
                f"(first={int(sorted_ids[0])} > last={int(sorted_ids[-1])}); "
                f"an unsorted pull would reassemble rows in the wrong order")
        cuts = self.spec.cuts_into(sorted_ids)
        out = []
        for i in range(self.spec.num_shards):
            a, b = int(cuts[i]), int(cuts[i + 1])
            if b > a:
                out.append((i, slice(a, b)))
        return sorted_ids, out

    def _run(self, jobs):
        """Execute (shard_index, thunk) jobs, parallel across shards.
        A TransportError is tagged with ``shard_index`` (the recovery
        hook needs to know WHICH shard died); with a pool, every future
        is drained before the first error re-raises, so a retry never
        races a still-in-flight sibling job."""
        if self._pool is None or len(jobs) <= 1:
            out = []
            for i, fn in jobs:
                try:
                    out.append((i, fn()))
                except TransportError as e:
                    e.shard_index = i
                    raise
            return out
        futs = [(i, self._pool.submit(fn)) for i, fn in jobs]
        results, first_err = [], None
        for i, f in futs:
            try:
                results.append((i, f.result()))
            except BaseException as e:
                if isinstance(e, TransportError):
                    e.shard_index = i
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err
        return results

    def _run_shared(self, jobs):
        """_run under the read side of the recovery lock (dump/load
        paths: they must not interleave with a recovery's load+replay)."""
        self._rw.acquire_read()
        try:
            return self._run(jobs)
        finally:
            self._rw.release_read()

    def _run_recovering(self, jobs):
        """_run, retrying through the recovery hook: a transient
        transport failure on shard i hands (i, exc) to the hook (the
        tier's recover-and-resume path) and, when the hook returns,
        re-runs ALL the jobs — safe because pull is a read and push
        scatter-sets absolute rows (re-applying identical data is a
        no-op). The hook is invoked with no locks held; it raises to
        abort (no hook installed, wedge deadline exceeded, unrecoverable
        taxonomy) and that abort propagates to the training loop."""
        while True:
            try:
                self._rw.acquire_read()
                try:
                    return self._run(jobs)
                finally:
                    self._rw.release_read()
            except TransportError as e:
                hook = self._recovery
                i = getattr(e, "shard_index", None)
                if hook is None or i is None or not e.transient:
                    raise
                hook(i, e)

    def _shard_pull(self, i: int, ids_chunk: np.ndarray, ctx):
        """One shard's pull, on whatever thread the fan-out picked:
        re-activate the caller's trace context (thread-locals don't
        follow pool jobs) and time the shard individually."""
        with _trace_ctx.use(ctx):
            t0 = time.perf_counter()
            out = self.clients[i].pull(self.name, ids_chunk)
            self._h_shard_pull[i].observe((time.perf_counter() - t0) * 1e3)
            return out

    def pull(self, sorted_uids: np.ndarray) -> np.ndarray:
        """Packed rows ``[k, lanes] uint16`` for ascending unique ids."""
        t0 = time.perf_counter()
        ctx = _trace_ctx.current()
        ids, chunks = self._chunks(sorted_uids)
        if not chunks:
            out = np.zeros((0, self.lanes), dtype=np.uint16)
        else:
            jobs = [(i, (lambda i=i, sl=sl: self._shard_pull(
                i, ids[sl], ctx))) for i, sl in chunks]
            parts = self._run_recovering(jobs)
            out = (parts[0][1] if len(parts) == 1
                   else np.concatenate([r for _, r in parts], axis=0))
        nb = out.nbytes
        with self._acct:
            for (i, sl) in chunks:
                self.bytes_pulled_per_shard[i] += (
                    (sl.stop - sl.start) * self.lanes * 2)
        self._c_pulled.inc(nb)
        self._h_pull.observe((time.perf_counter() - t0) * 1e3)
        return out

    def _shard_push(self, i: int, ids_chunk, rows_chunk, ctx):
        with _trace_ctx.use(ctx):
            self.push_clients[i].push(self.name, ids_chunk, rows_chunk)

    def push(self, sorted_uids: np.ndarray, rows: np.ndarray) -> None:
        """Scatter-set whole rows at ascending unique ids."""
        t0 = time.perf_counter()
        ids, chunks = self._chunks(sorted_uids)
        rows = np.asarray(rows, dtype=np.uint16)
        if rows.shape != (ids.shape[0], self.lanes):
            raise ValueError(
                f"ShardedTable {self.name!r}: push rows {rows.shape} != "
                f"({ids.shape[0]}, {self.lanes})")
        # journal BEFORE the remote send: if the shard dies mid-push the
        # batch is already replayable
        self._journal_append(ids, rows, chunks)
        ctx = _trace_ctx.current()
        jobs = [(i, (lambda i=i, sl=sl: self._shard_push(
            i, ids[sl], rows[sl], ctx))) for i, sl in chunks]
        self._run_recovering(jobs)
        nb = rows.nbytes
        with self._acct:
            for (i, sl) in chunks:
                self.bytes_pushed_per_shard[i] += (
                    (sl.stop - sl.start) * self.lanes * 2)
        self._c_pushed.inc(nb)
        self._h_push.observe((time.perf_counter() - t0) * 1e3)
        # notify AFTER the remote apply: a listener that forwards these
        # bytes to a serving cache never races ahead of the shard state.
        # Listener arrays are read-only by contract (not re-copied here).
        for fn in self._push_listeners:
            try:
                fn(ids, rows)
            except Exception:
                get_registry().counter("stream/listener_errors",
                                       table=self.name).inc()

    def add_push_listener(self, fn: Callable) -> None:
        """Register `fn(sorted_uids, rows)` to observe every applied push
        (the train->serve delta stream tap). A listener must not mutate
        its arguments and must not block — it runs on whatever thread
        issued the push (trainer or async flusher)."""
        self._push_listeners.append(fn)

    def remove_push_listener(self, fn: Callable) -> None:
        try:
            self._push_listeners.remove(fn)
        except ValueError:
            pass

    # ------------------------------------------------------ journal/recovery
    def _journal_append(self, ids: np.ndarray, rows: np.ndarray, chunks):
        max_bytes = int(float(os.environ.get(
            "PDTPU_PS_JOURNAL_MAX_MB", "256")) * (1 << 20))
        with self._jlock:
            self._journal_seq += 1
            seq = self._journal_seq
            for i, sl in chunks:
                # own copies: the caller's buffers are reused across steps
                e = (seq, ids[sl].copy(), rows[sl].copy())
                self._journal[i].append(e)
                self._journal_nbytes += e[1].nbytes + e[2].nbytes
            while self._journal_nbytes > max_bytes:
                # evict the globally-oldest entry (smallest head seq)
                heads = [(sh[0][0], i) for i, sh in enumerate(self._journal)
                         if sh]
                if not heads:
                    break
                _, i = min(heads)
                s, eids, erows = self._journal[i].pop(0)
                self._journal_nbytes -= eids.nbytes + erows.nbytes
                self._evicted_upto[i] = max(self._evicted_upto[i], s)
            self._g_journal.set(float(self._journal_nbytes))

    def journal_mark(self) -> int:
        """The current push seq. A checkpoint taken AFTER a flush records
        this mark: every journal entry with seq <= mark is contained in
        the checkpoint's shard bytes."""
        with self._jlock:
            return self._journal_seq

    def journal_truncate(self, mark: int) -> None:
        """Drop entries a committed checkpoint at `mark` made redundant
        (the Checkpointer's on-commit callback). Idempotent."""
        with self._jlock:
            for i, sh in enumerate(self._journal):
                kept = [e for e in sh if e[0] > mark]
                self._journal_nbytes -= sum(
                    e[1].nbytes + e[2].nbytes for e in sh) - sum(
                    e[1].nbytes + e[2].nbytes for e in kept)
                self._journal[i] = kept
            self._g_journal.set(float(self._journal_nbytes))

    def journal_reset(self, mark: int) -> None:
        """Restore-time coherence: the shards were just load_full'd from
        a checkpoint whose mark is `mark` — the journal (possibly from a
        DIFFERENT process lifetime, where seq counting restarted at 0) no
        longer describes deltas over the live shard state. Clear it and
        fast-forward the seq counter past the mark so future marks stay
        monotonic."""
        with self._jlock:
            self._journal = [[] for _ in range(self.spec.num_shards)]
            self._journal_nbytes = 0
            self._journal_seq = max(self._journal_seq, int(mark))
            self._evicted_upto = [int(mark)] * self.spec.num_shards
            self._g_journal.set(0.0)

    def journal_bytes(self) -> int:
        with self._jlock:
            return self._journal_nbytes

    def journal_entries_since(self, mark: int) -> List[tuple]:
        """Every journaled push past `mark` as ``[(seq, ids, rows)]`` in
        ascending seq order — the payload of an incremental checkpoint
        (``Checkpointer.save_delta``). Per-shard slices of one original
        push (same seq) are re-merged in shard order, so each returned
        entry has ascending ids and replays as one valid ``push``.
        Raises when the journal cap evicted entries the range needs: a
        delta built over a hole would restore silently stale rows."""
        mark = int(mark)
        with self._jlock:
            for i, ev in enumerate(self._evicted_upto):
                if ev > mark:
                    raise RuntimeError(
                        f"ShardedTable {self.name!r}: cannot build a delta "
                        f"since mark {mark}: shard {i}'s journal evicted "
                        f"entries up to seq {ev} (PDTPU_PS_JOURNAL_MAX_MB "
                        "cap) — save deltas/checkpoints more often or "
                        "raise the cap")
            by_seq: Dict[int, list] = {}
            for i, sh in enumerate(self._journal):
                for seq, ids, rows in sh:
                    if seq > mark:
                        by_seq.setdefault(seq, []).append((i, ids, rows))
        out = []
        for seq in sorted(by_seq):
            parts = sorted(by_seq[seq], key=lambda p: p[0])
            if len(parts) == 1:
                out.append((seq, parts[0][1], parts[0][2]))
            else:
                out.append((seq,
                            np.concatenate([p[1] for p in parts]),
                            np.concatenate([p[2] for p in parts], axis=0)))
        return out

    def set_flush_hook(self, hook: Optional[Callable[[], None]]) -> None:
        """Install (or clear) the make-shards-authoritative callback the
        Checkpointer invokes before snapshotting this table. The tier
        points it at its flush path — dirty hot-cache rows are written
        back and the pusher drained — so ``journal_mark()`` taken right
        after really covers the dumped shard bytes, without every save
        call site having to remember ``tier.flush()``."""
        self.flush_hook = hook

    def set_recovery(self,
                     hook: Optional[Callable[[int, BaseException], None]]
                     ) -> None:
        """Install the shard-outage handler pull/push retry through (the
        tier's wait-for-shard + recover_shard orchestration)."""
        self._recovery = hook

    def recover_shard(self, i: int, base_rows: np.ndarray,
                      base_mark: int) -> int:
        """Rebuild restarted shard `i` losslessly: load its slice of
        `base_rows` (the full ``[vocab, lanes]`` table from the newest
        VERIFIED checkpoint, whose journal mark is `base_mark`), then
        replay this shard's journal entries past the mark in issue
        order. Returns the number of batches replayed. Runs under the
        write lock — no pull/push interleaves. Raises if the journal's
        size cap evicted entries the replay needs (checkpoint older than
        the journal horizon): recovery would be silently lossy."""
        base_rows = np.asarray(base_rows, dtype=np.uint16)
        if base_rows.shape != (self.spec.vocab, self.lanes):
            raise ValueError(
                f"ShardedTable {self.name!r}: recover_shard base shape "
                f"{base_rows.shape} != ({self.spec.vocab}, {self.lanes})")
        lo, hi = self.spec.bounds(i)
        self._rw.acquire_write()
        try:
            with self._jlock:
                if base_mark < self._evicted_upto[i]:
                    raise RuntimeError(
                        f"ShardedTable {self.name!r}: cannot recover shard "
                        f"{i} from checkpoint mark {base_mark}: the journal "
                        f"evicted entries up to seq {self._evicted_upto[i]} "
                        f"(PDTPU_PS_JOURNAL_MAX_MB cap) — checkpoint more "
                        "often or raise the cap")
                replay = [e for e in self._journal[i] if e[0] > base_mark]
            # the restarted server carries a fresh instance id; expect it
            clients = {id(self.clients[i]): self.clients[i],
                       id(self.push_clients[i]): self.push_clients[i]}
            for c in clients.values():
                c.reset_instance_expectation()
            self.clients[i].load(self.name, base_rows[lo:hi])
            for _seq, ids, rows in replay:
                self.push_clients[i].push(self.name, ids, rows)
            return len(replay)
        finally:
            self._rw.release_write()

    def sweep(self) -> int:
        """Fan a dynamic-vocab eviction pass out to every shard; returns
        total rows evicted (0 when every shard is static)."""
        parts = self._run_shared(
            [(i, (lambda i=i: self.clients[i].sweep(self.name)))
             for i in range(self.spec.num_shards)])
        return int(sum(n for _, n in parts))

    # -------------------------------------------------------- full-table io
    def dump_shard(self, i: int) -> np.ndarray:
        return self.clients[i].dump(self.name)

    def dump_full(self) -> np.ndarray:
        """Assemble the whole ``[vocab, lanes]`` table (checkpoint save;
        ranges are ordered and exhaustive so this is a concat)."""
        parts = self._run_shared(
            [(i, (lambda i=i: self.clients[i].dump(self.name)))
             for i in range(self.spec.num_shards)])
        return np.concatenate([p for _, p in parts], axis=0)

    def load_full(self, full_rows: np.ndarray) -> None:
        """Re-partition a full table onto the LIVE spec — this is what
        makes restore-onto-a-different-shard-count work: the checkpoint
        stores per-shard slices, `_assemble_shards` merges them into the
        full array, and this scatter follows the current boundaries."""
        full_rows = np.asarray(full_rows, dtype=np.uint16)
        if full_rows.shape != (self.spec.vocab, self.lanes):
            raise ValueError(
                f"ShardedTable {self.name!r}: load_full shape "
                f"{full_rows.shape} != ({self.spec.vocab}, {self.lanes})")
        jobs = []
        for i in range(self.spec.num_shards):
            lo, hi = self.spec.bounds(i)
            jobs.append((i, (lambda i=i, lo=lo, hi=hi:
                             self.clients[i].load(
                                 self.name, full_rows[lo:hi]))))
        self._run_shared(jobs)

    # -------------------------------------------------------------- stats
    def stats(self) -> dict:
        per_shard = []
        for i in range(self.spec.num_shards):
            lo, hi = self.spec.bounds(i)
            per_shard.append({
                "shard": i, "lo": lo, "hi": hi, "rows": hi - lo,
                "bytes_pulled": self.bytes_pulled_per_shard[i],
                "bytes_pushed": self.bytes_pushed_per_shard[i],
            })
        with self._jlock:
            journal = {"bytes": self._journal_nbytes,
                       "seq": self._journal_seq,
                       "entries": sum(len(s) for s in self._journal),
                       "evicted_upto": list(self._evicted_upto)}
        return {"name": self.name, "vocab": self.spec.vocab,
                "num_shards": self.spec.num_shards,
                "lanes": self.lanes, "shards": per_shard,
                "journal": journal}

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)
