"""PsEmbeddingTier — overlapped pull/push training over sharded tables.

Reference analog: the worker half of the Downpour loop — ``FleetWrapper::
PullSparseVarsSync`` before the forward, ``PushSparseVarsWithLabelAsync``
after the backward, with the Communicator batching the pushes. Here the
same overlap rides the repo's own machinery:

* the **pull prefetcher** is a ``dataio.DeviceLoader`` with a custom
  ``convert``: while step N computes, the loader worker peeks batch N+1,
  extracts the global row ids of every bound table, dedups them host-side
  (``np.unique`` — the host mirror of ``uniq_merge``'s device contract:
  ascending uniques + inverse positions), rewrites the id feeds to LOCAL
  cache rows, fans per-shard pulls out through ``ShardedTable``, and
  lands the gathered rows on device — all before dispatch;
* the step itself runs unchanged: the program's table param is a CACHE of
  ``cache_rows`` packed rows; ``scope.set_var`` swaps the pulled cache in,
  the packed optimizer (``adagrad_row_packed`` et al.) updates it
  in-scope, and rows ``[0, U)`` of the result are exactly the new values
  of the step's U unique ids;
* **push** slices those U rows and hands them to a per-table pusher —
  ``push_depth`` 0 applies synchronously (staleness-0 exact), k ≥ 1
  queues them on a flusher thread with at most k batches in flight, so
  the host→shard write happens under the next step's compute.

Exactness. The global→local id remap is strictly monotone (uids are
ascending), ``jnp.argsort`` is stable, so the in-step ``uniq_merge``
permutation — and therefore the duplicate-gradient merge order and every
downstream float op — is bit-identical to the single-table run. With
``push_depth ≥ 1`` a prefetched pull can race an in-flight push; the tier
repairs that at dispatch with read-your-writes patching: every pull
records the pusher's ``applied_seq`` snapshot, and any push issued after
it is scatter-patched into the cache device-side before the step (pushes
carry absolute rows, so patching is idempotent under the race). A pull so
stale that its missing pushes have left the patch window falls back to
flush + re-pull (``ps/repulls``). Net: single-worker training is bitwise
exact at ANY depth; ``push_depth`` only relaxes cross-worker visibility.

Hot-row cache (``hot_rows`` > 0, or ``PDTPU_PS_HOT_ROWS``): the cache
param becomes a persistent ``[hot_rows + step_rows]`` slab managed by
``ps.hot_cache.HotRowCache`` — LFU-admitted hot rows stay resident in
HBM across steps (hits are never pulled OR pushed), misses flow through
the staging tail exactly like the uncached per-step path, and evicted
dirty rows are written back through the same pusher/journal machinery.
The bitwise contract is unchanged: the id remap per step is injective
and the update math depends only on id equality structure, while miss
pulls and write-backs observe the same read-your-writes patching — so
single-worker runs stay bit-identical to the uncached tier at any push
depth.

Metrics: ``ps/prefetch_hit``/``ps/prefetch_miss`` (was batch N+1 already
converted+pulled when the loop asked?), ``ps/patched_rows``,
``ps/repulls`` — plus ``ps/pull_ms``/``ps/push_ms``/``ps/bytes_*`` from
the table layer and ``ps/cache_*`` from the hot cache.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

from ..faults import fault_point
from ..observability import get_registry
from .table import ShardedTable
from .transport import ShardRestartedError, TransportError

__all__ = ["PsTableBinding", "PsEmbeddingTier"]


class PsTableBinding:
    """One PS-backed table: which program param is its cache and which
    feed names carry its global row ids."""

    def __init__(self, param: str, table: ShardedTable,
                 id_feeds: Sequence[str]):
        if not id_feeds:
            raise ValueError(f"PsTableBinding {param!r}: need at least "
                             f"one id feed")
        self.param = str(param)
        self.table = table
        self.id_feeds = list(id_feeds)


class _Entry:
    """One table's pulled state for one batch. In hot-cache mode `uids`
    are the step's MISS uids (hits never leave the slab) and `plan` is
    the HotRowCache.CachePlan that owns the slab slot assignment."""
    __slots__ = ("uids", "n", "cache", "version", "plan")

    def __init__(self, uids, n, cache, version, plan=None):
        self.uids = uids      # ascending unique global ids, [n] int64
        self.n = n
        self.cache = cache    # [cache_rows, lanes] u16 device array
        self.version = version  # pusher.applied_seq snapshot before pull
        self.plan = plan


class _Prepared:
    """One converted batch: device feeds (ids already local) + per-table
    pull entries."""
    __slots__ = ("feed", "entries")

    def __init__(self, feed, entries):
        self.feed = feed
        self.entries = entries  # param -> _Entry


class _Pusher:
    """Per-table push applier with bounded in-flight depth.

    depth 0: ``submit`` applies inline (synchronous exact). depth k: a
    flusher thread drains a queue of maxsize k — ``submit`` blocks only
    when k batches are already in flight. ``recent`` keeps the last few
    submitted batches for read-your-writes patching; it is touched ONLY
    by the submitting thread.
    """

    def __init__(self, table: ShardedTable, depth: int, window: int):
        self.table = table
        self.depth = int(depth)
        self.issued_seq = 0
        self.applied_seq = 0
        self.recent = deque(maxlen=window)  # (seq, uids_np, rows_dev)
        self._cv = threading.Condition()
        self._err: Optional[BaseException] = None
        self._q = None
        self._thread = None
        if self.depth > 0:
            import queue as _qm
            self._q = _qm.Queue(maxsize=self.depth)
            self._thread = threading.Thread(
                target=self._drain, daemon=True,
                name=f"ps-push-{table.name}")
            self._thread.start()

    def _apply(self, seq, uids, rows):
        fault_point("ps.push")
        # rows is the FULL cache (fixed shape — keeps the patcher's
        # device ops at a handful of compiled shapes); np.asarray is the
        # device sync — on depth>0 it happens HERE, on the flusher
        # thread, off the step path — and the host-side slice keeps the
        # shard write at the batch's n rows
        if uids.size:
            self.table.push(uids, np.asarray(rows)[:uids.size])
        with self._cv:
            self.applied_seq = seq
            self._cv.notify_all()

    def _drain(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            seq, uids, rows = item
            try:
                self._apply(seq, uids, rows)
            except BaseException as e:
                with self._cv:
                    self._err = e
                    self.applied_seq = seq  # unblock flush(); err re-raised
                    self._cv.notify_all()

    def submit(self, uids: np.ndarray, rows) -> int:
        """Queue (or apply) one push batch; returns its seq."""
        self._check()
        self.issued_seq += 1
        seq = self.issued_seq
        self.recent.append((seq, uids, rows))
        if self.depth == 0:
            self._apply(seq, uids, rows)
        else:
            self._q.put((seq, uids, rows))  # blocks at depth in flight
        return seq

    def flush(self):
        """Block until every submitted push is applied on the shards."""
        with self._cv:
            while self.applied_seq < self.issued_seq and self._err is None:
                self._cv.wait(timeout=0.5)
        self._check()

    def _check(self):
        # a drained-push failure means a batch was DROPPED on the shards;
        # the pusher stays poisoned so no later submit/flush (e.g. a
        # retried checkpoint save) can report success over missing rows —
        # recovery is rebuilding the tier from a known-good state
        if self._err is not None:
            raise RuntimeError(
                f"ps push to table {self.table.name!r} failed; pusher is "
                f"poisoned — rebuild the tier") from self._err

    def close(self):
        if self._thread is not None:
            self._q.put(None)
            self._thread.join(timeout=5.0)
            self._thread = None


class PsEmbeddingTier:
    """Drives a program whose sparse tables live on PS shards.

    Usage (what ``fleet.init_worker`` + the bench do)::

        tier = PsEmbeddingTier(program, bindings,
                               pull_ahead=strategy.pull_ahead,
                               push_depth=strategy.push_depth)
        for prepared in tier.steps(reader, scope=sc):
            loss, = tier.run_step(exe, prepared, fetch_list=[loss_var],
                                  scope=sc)
        tier.flush()

    ``pull_ahead`` ≥ 1 prefetches (a DeviceLoader of that capacity runs
    convert+pull on a worker thread); 0 converts inline on the calling
    thread — the honest A/B for the overlap benchmark.
    """

    def __init__(self, program, bindings: Sequence[PsTableBinding],
                 pull_ahead: int = 1, push_depth: int = 0,
                 hot_rows: Optional[int] = None):
        if pull_ahead < 0 or push_depth < 0:
            raise ValueError(f"pull_ahead/push_depth must be >= 0, got "
                             f"{pull_ahead}/{push_depth}")
        self.program = program
        self.bindings = list(bindings)
        if not self.bindings:
            raise ValueError("PsEmbeddingTier: no table bindings")
        self.pull_ahead = int(pull_ahead)
        self.push_depth = int(push_depth)
        if hot_rows is None:
            hot_rows = int(os.environ.get("PDTPU_PS_HOT_ROWS", "0"))
        self.hot_rows = max(0, int(hot_rows))
        block = program.global_block()
        self._cache_shape: Dict[str, tuple] = {}
        self._id_dtype: Dict[str, object] = {}
        for b in self.bindings:
            v = block.var(b.param)
            rows, lanes = int(v.shape[0]), int(v.shape[1])
            if lanes != b.table.lanes:
                raise ValueError(
                    f"cache param {b.param!r} has {lanes} lanes but table "
                    f"{b.table.name!r} has {b.table.lanes}")
            self._cache_shape[b.param] = (rows, lanes)
        # device-resident hot-row cache (ps/hot_cache.py): the cache param
        # becomes a persistent [hot_rows + step_rows] slab instead of a
        # per-step scratch pull target
        self._hot: Dict[str, object] = {}
        if self.hot_rows:
            from .hot_cache import HotRowCache
            for b in self.bindings:
                rows_cap, lanes = self._cache_shape[b.param]
                step_rows = rows_cap - self.hot_rows
                if step_rows < 1:
                    raise ValueError(
                        f"hot_rows={self.hot_rows} leaves no staging rows "
                        f"in cache param {b.param!r} ({rows_cap} rows); "
                        "rebuild the program with a [hot_rows + per-step "
                        "rows] cache param")
                self._hot[b.param] = HotRowCache(
                    self.hot_rows, step_rows, lanes=lanes,
                    vocab=b.table.spec.vocab, name=b.table.name)
        # patch window: every pull can be behind by at most the prefetch
        # depth plus the in-flight pushes (+ slack for the re-pull path);
        # hot-cache mode submits up to three batches per step (eviction
        # write-back, staging push, flush) instead of one, so the window
        # widens accordingly — overflow is still safe (repull fallback)
        window = ((self.pull_ahead + self.push_depth + 2)
                  * (3 if self.hot_rows else 1))
        self._pushers = {b.param: _Pusher(b.table, push_depth, window)
                         for b in self.bindings}
        for b in self.bindings:
            # checkpoint flush hook: Checkpointer.save() calls it before
            # taking the journal mark + dumping shards, so slab-dirty rows
            # and queued pushes are on the shards the mark covers
            b.table.set_flush_hook(lambda p=b.param: self._flush_param(p))
        reg = get_registry()
        self._c_hit = reg.counter("ps/prefetch_hit")
        self._c_miss = reg.counter("ps/prefetch_miss")
        self._c_patched = reg.counter("ps/patched_rows")
        self._c_repulls = reg.counter("ps/repulls")
        self._c_recoveries = reg.counter("ps/recoveries")
        self._loader = None
        self._patch_fn = None  # lazily-jitted gather+scatter (no jax here)
        self._ck = None        # Checkpointer armed by attach_checkpointer
        self._recover_lock = threading.Lock()

    # --------------------------------------------------------- shard outage
    def attach_checkpointer(self, ck) -> None:
        """Arm lossless shard recovery. With a Checkpointer attached,
        a transient shard outage no longer kills the step: the failing
        pull/push blocks (which naturally pauses the prefetcher and the
        pusher — they are the threads doing the failing calls), the tier
        waits for the shard to answer pings again (bounded by
        ``PDTPU_WEDGE_TIMEOUT``), rebuilds it from the newest VERIFIED
        checkpoint slice plus the table's push-journal replay
        (``ShardedTable.recover_shard``), and the interrupted op retries
        — bitwise-identical to a never-crashed run at staleness 0. Save
        a checkpoint (``ps_tables=``) before training so a recovery base
        exists. Without attachment, outages surface as TransportError
        after transport-level retries, exactly as before."""
        self._ck = ck
        for b in self.bindings:
            b.table.set_recovery(
                lambda i, exc, t=b.table: self._recover(t, i, exc))

    def _recover(self, table: ShardedTable, i: int,
                 exc: BaseException) -> None:
        """Recovery hook (runs on whichever thread hit the dead shard —
        prefetcher, pusher, or the step thread). Serialized: concurrent
        victims of the same outage queue here, and all but the first
        find the shard already healthy and return to their retry."""
        with self._recover_lock:
            deadline = time.monotonic() + float(
                os.environ.get("PDTPU_WEDGE_TIMEOUT", "300"))
            client = table.clients[i]
            while True:
                try:
                    client.ping()
                    # reachable under the SAME instance id: the process
                    # never died (blip / slow shard) — rows are intact,
                    # no rebuild needed, let the op retry
                    return
                except ShardRestartedError:
                    break  # reachable but reborn: rebuild below
                except TransportError as e:
                    if not e.transient or time.monotonic() > deadline:
                        raise RuntimeError(
                            f"ps shard {i} of table {table.name!r} "
                            "unreachable past PDTPU_WEDGE_TIMEOUT — tier "
                            "is wedged, not recovering") from e
                    time.sleep(0.1)
            self._c_recoveries.inc()
            if self._ck is None:
                raise RuntimeError(
                    f"ps shard {i} of table {table.name!r} restarted and "
                    "lost its rows, but no checkpointer is attached — "
                    "call tier.attach_checkpointer(ck) for lossless "
                    "recovery") from exc
            full_rows, mark, step = self._ck.load_ps_table(table.name)
            replayed = table.recover_shard(i, full_rows, mark)
            del full_rows
            get_registry().counter(
                "ps/recovered_batches", table=table.name).inc(replayed)

    # ----------------------------------------------------------- pull path
    def _pull_cache(self, binding: PsTableBinding, uids: np.ndarray,
                    version: int, cap: Optional[int] = None):
        """Pull rows for `uids`, pad to the cache shape (or `cap` rows —
        the hot path's miss buffer), land on device."""
        import jax
        import jax.numpy as jnp

        fault_point("ps.pull")
        rows_cap, lanes = self._cache_shape[binding.param]
        if cap is not None:
            rows_cap = int(cap)
        if uids.shape[0] > rows_cap:
            raise ValueError(
                f"batch touches {uids.shape[0]} unique rows of table "
                f"{binding.table.name!r} but cache param {binding.param!r} "
                f"holds only {rows_cap}; rebuild the program with a larger "
                f"cache (>= max unique ids per batch)")
        pulled = binding.table.pull(uids)
        cache = np.zeros((rows_cap, lanes), dtype=np.uint16)
        cache[:uids.shape[0]] = pulled
        return _Entry(uids, int(uids.shape[0]),
                      jax.device_put(jnp.asarray(cache)), version)

    def _convert(self, batch: Dict[str, object]) -> _Prepared:
        """Loader-worker work: dedup ids, localize feeds, pull caches,
        then the standard feed validation + device_put."""
        from ..dataio.loader import _default_convert

        out = dict(batch)
        entries: Dict[str, _Entry] = {}
        for b in self.bindings:
            arrs = [np.asarray(out[f]) for f in b.id_feeds]
            flat = (np.concatenate([a.ravel() for a in arrs])
                    if arrs else np.zeros((0,), np.int64))
            uids, inv = np.unique(flat.astype(np.int64),
                                  return_inverse=True)
            hot = self._hot.get(b.param)
            if hot is None:
                loc_all = inv
            else:
                # hot path: ids map to SLAB rows (resident slot for hits
                # and admitted misses, staging tail for bypass) and only
                # the miss rows are pulled. The remap stays injective per
                # step, so uniq_merge's equality structure — and every
                # float op — matches the uncached run bit-for-bit.
                # Occurrence counts feed the lookup-weighted hit metrics.
                plan = hot.plan(uids, np.bincount(inv))
                loc_all = plan.slots[inv]
            off = 0
            for f, a in zip(b.id_feeds, arrs):
                loc = loc_all[off:off + a.size].reshape(a.shape)
                out[f] = loc.astype(a.dtype if a.dtype.kind in "iu"
                                    else np.int64)
                off += a.size
            version = self._pushers[b.param].applied_seq
            if hot is None:
                entries[b.param] = self._pull_cache(b, uids, version)
            else:
                entry = self._pull_cache(b, plan.miss_uids, version,
                                         cap=hot.step_rows)
                entry.plan = plan
                entries[b.param] = entry
        feed = _default_convert(self.program.global_block())(out)
        return _Prepared(feed, entries)

    def steps(self, reader, scope=None) -> Iterable[_Prepared]:
        """Iterate prepared batches. With ``pull_ahead >= 1`` the convert
        + pull runs on a DeviceLoader worker `pull_ahead` batches ahead;
        with 0 it runs inline."""
        if self.pull_ahead == 0:
            it = reader() if callable(reader) else reader
            for batch in it:
                self._c_miss.inc()  # inline = never overlapped
                yield self._convert(batch)
            return
        from ..dataio.loader import DeviceLoader
        loader = DeviceLoader(reader, capacity=self.pull_ahead,
                              convert=self._convert,
                              name="ps_prefetch")
        self._loader = loader
        try:
            it = iter(loader)
            while True:
                ready = loader.queue_depth > 0
                try:
                    prepared = next(it)
                except StopIteration:
                    return
                (self._c_hit if ready else self._c_miss).inc()
                yield prepared
        finally:
            loader.close()
            self._loader = None

    # ------------------------------------------------------ dispatch + push
    def _patched_cache(self, binding: PsTableBinding, entry: _Entry):
        """Read-your-writes repair: overlay every push the pull could not
        have seen. Ascending-seq order so later pushes win."""
        import jax
        import jax.numpy as jnp

        if self._patch_fn is None:
            # one fused gather+scatter per pending push; jitted so the
            # step path pays one dispatch, not a chain of eager ops
            self._patch_fn = jax.jit(
                lambda cache, prows, tgt, src: cache.at[tgt].set(prows[src]))
        pusher = self._pushers[binding.param]
        pusher._check()
        if pusher.issued_seq == entry.version:
            return entry.cache  # pull already reflects everything issued
        oldest_kept = (pusher.recent[0][0] if pusher.recent
                       else pusher.issued_seq + 1)
        if entry.version + 1 < oldest_kept:
            # pushes this pull missed have left the window: flush and
            # re-pull (rare — only when a consumer stalls far behind)
            self._c_repulls.inc()
            pusher.flush()
            fresh = self._pull_cache(binding, entry.uids,
                                     pusher.applied_seq,
                                     cap=int(entry.cache.shape[0]))
            return fresh.cache
        cache = entry.cache
        n = entry.n
        for seq, puids, prows in list(pusher.recent):
            if seq <= entry.version or puids.size == 0 or n == 0:
                continue
            pos = np.searchsorted(entry.uids, puids)
            posc = np.minimum(pos, n - 1)
            mask = entry.uids[posc] == puids
            if not mask.any():
                continue
            tgt = posc[mask].astype(np.int32)
            src = np.nonzero(mask)[0].astype(np.int32)
            k = int(tgt.size)
            # pad to a power-of-two bucket by repeating the last pair —
            # the duplicate writes carry identical values, so the scatter
            # stays deterministic while XLA sees O(log cache) distinct
            # shapes instead of one fresh compile per step
            pad = (1 << (k - 1).bit_length()) - k
            if pad:
                tgt = np.concatenate([tgt, np.full(pad, tgt[-1], np.int32)])
                src = np.concatenate([src, np.full(pad, src[-1], np.int32)])
            cache = self._patch_fn(cache, jnp.asarray(prows),
                                   jnp.asarray(tgt), jnp.asarray(src))
            self._c_patched.inc(k)
        return cache

    def _dispatch_hot(self, binding: PsTableBinding, hot, entry: _Entry):
        """Slab maintenance for one step, in plan order: write back the
        plan's eviction victims (gathered BEFORE their slots are
        overwritten), read-your-writes-patch the pulled miss rows, and
        scatter them into their slab slots. Returns the slab to run on."""
        plan = entry.plan
        hot.ensure_slab()
        if plan.evict_uids.size:
            # always write evicted rows back — for a clean row the push
            # rewrites identical bytes (idempotent); for a dirty one this
            # IS the write-back that makes eviction lossless
            rows = hot.take_rows(plan.evict_slots)
            self._pushers[binding.param].submit(plan.evict_uids, rows)
            hot.note_writeback(int(plan.evict_uids.size))
        if entry.n:
            patched = self._patched_cache(binding, entry)
            hot.insert_rows(plan.miss_slots, patched)
        return hot.slab

    def run_step(self, exe, prepared: _Prepared, fetch_list=None,
                 scope=None, **run_kw):
        """One training step: swap caches in, run, push updated rows.
        The step is the root of a distributed trace: every shard pull
        and async push it causes carries this step's trace_id over the
        wire, so the merged fleet timeline shows one step spanning
        worker and pserver processes."""
        from ..observability.tracer import start_trace

        with start_trace("ps/train_step"):
            return self._run_step(exe, prepared, fetch_list, scope,
                                  **run_kw)

    def _run_step(self, exe, prepared: _Prepared, fetch_list=None,
                  scope=None, **run_kw):
        from ..core.scope import _scope  # thread-local default scope

        sc = scope if scope is not None else _scope()
        for b in self.bindings:
            entry = prepared.entries[b.param]
            hot = self._hot.get(b.param)
            if hot is None:
                sc.set_var(b.param, self._patched_cache(b, entry))
            else:
                sc.set_var(b.param, self._dispatch_hot(b, hot, entry))
        out = exe.run(self.program, feed=prepared.feed,
                      fetch_list=fetch_list, scope=sc, **run_kw)
        for b in self.bindings:
            entry = prepared.entries[b.param]
            hot = self._hot.get(b.param)
            new_cache = sc.find_var(b.param)
            if hot is None:
                # hand the pusher the full fixed-shape cache: the patcher
                # can then gather from it without a per-n recompile, and
                # the device→host sync + [:n] slice happen in the pusher;
                # the buffer is never re-fed to the program (set_var
                # replaces it before the next run), so it cannot be
                # donated out from under the flusher
                self._pushers[b.param].submit(entry.uids, new_cache)
                continue
            # hot path: the program's output IS the next step's slab;
            # only rows that leave it cross back to the shards — the
            # staging (bypass) rows now, resident rows on eviction/flush
            hot.slab = new_cache
            plan = entry.plan
            if plan.bypass_uids.size:
                rows = hot.take_rows(plan.bypass_slots)
                self._pushers[b.param].submit(plan.bypass_uids, rows)
            hot.commit(plan)
        return out

    def train(self, exe, reader, fetch_list=None, scope=None,
              max_steps: Optional[int] = None):
        """Convenience loop: yields each step's fetch results."""
        done = 0
        for prepared in self.steps(reader, scope=scope):
            yield self.run_step(exe, prepared, fetch_list=fetch_list,
                                scope=scope)
            done += 1
            if max_steps is not None and done >= max_steps:
                break
        self.flush()

    # ------------------------------------------------------------ lifecycle
    def _flush_param(self, param: str) -> None:
        """Make the shards authoritative for one table: push every row
        whose newest bytes live only in the hot slab (dirty residents +
        planned-but-undispatched eviction victims), then drain the
        pusher. This is also the table's checkpoint flush hook, so
        ``Checkpointer.save(ps_tables=...)`` dumps shard bytes that the
        ``@ps_mark@`` journal mark really covers."""
        hot = self._hot.get(param)
        pusher = self._pushers[param]
        if hot is not None and hot.slab is not None:
            fuids, fslots = hot.flush_rows()
            if fuids.size:
                rows = hot.take_rows(fslots)
                pusher.submit(fuids, rows)
                hot.note_writeback(int(fuids.size))
        pusher.flush()

    def flush(self):
        """Drain every pusher — after this the shards hold every update
        (checkpoint save and the exactness tests call this). In hot-cache
        mode, dirty resident rows are written back first."""
        for b in self.bindings:
            self._flush_param(b.param)

    def stats(self) -> dict:
        out = {b.param: b.table.stats() for b in self.bindings}
        for p, hot in self._hot.items():
            out[p]["hot_cache"] = hot.stats()
        return out

    def close(self):
        if self._loader is not None:
            self._loader.close()
            self._loader = None
        for p in self._pushers.values():
            p.close()
        for b in self.bindings:
            b.table.set_flush_hook(None)
            b.table.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        try:
            self.flush()
        finally:
            self.close()
        return False
