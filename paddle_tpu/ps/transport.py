"""Transport for the PS embedding tier: clients and the shard server.

Two transports behind one interface:

* ``InProcessClient`` — the shard object lives in this process; calls are
  direct method dispatch. This is what single-host training and the tier-1
  tests use (no sockets, no pickling, zero copies beyond the pull itself).
* ``SocketClient`` / ``ShardServer`` — a length-prefixed binary protocol
  over TCP so shards can live in other processes or hosts (the reference's
  pserver processes; ``fleet.run_server()`` ends up in
  ``ShardServer.serve_forever``). The server side is numpy + stdlib only —
  a pserver must never import JAX or touch the TPU.

Wire format: every message is ``<u32 length><u32 json_len><json
header><array blobs>``. The header is plain JSON (op names, table names,
counters); each ndarray in the message is replaced by a
``{"__nd__": [dtype, shape, offset, nbytes]}`` marker pointing into the
raw blob region that follows, so decoding an array costs one
``np.frombuffer``. Deliberately NOT pickle: a pserver port accepts
connections from anything that can reach it, and ``pickle.loads`` on that
input is arbitrary code execution — JSON + validated buffer slices can
only ever produce dicts/lists/scalars/ndarrays. The port should still be
network-isolated (trainer-cluster only): the protocol is unauthenticated,
so anyone who can reach it can read and overwrite table rows. One
request, one reply; the server is thread-per-connection and a client
keeps one persistent connection per shard (requests on it are serialized
by a lock, concurrency comes from fanning out across shards).
"""
from __future__ import annotations

import json
import math
import socket
import socketserver
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .shard import EmbeddingShard

__all__ = ["ShardClient", "InProcessClient", "SocketClient", "ShardServer",
           "connect"]

_LEN = struct.Struct("<I")
_MAX_MSG = 1 << 30  # 1 GiB sanity cap on a single message


# ---------------------------------------------------------------- encoding

_ND = "__nd__"  # reserved header key marking an array blob


def _pack_msg(obj) -> bytes:
    """JSON header + concatenated array blobs (see module docstring)."""
    blobs: List[bytes] = []
    off = 0

    def enc(v):
        nonlocal off
        if isinstance(v, np.ndarray):
            a = np.ascontiguousarray(v)
            raw = a.tobytes()
            mark = {_ND: [str(a.dtype), list(a.shape), off, len(raw)]}
            blobs.append(raw)
            off += len(raw)
            return mark
        if isinstance(v, dict):
            if _ND in v:
                raise ValueError(f"ps transport: key {_ND!r} is reserved")
            return {k: enc(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [enc(x) for x in v]
        if isinstance(v, (np.integer, np.floating, np.bool_)):
            return v.item()
        if v is None or isinstance(v, (bool, int, float, str)):
            return v
        raise TypeError(
            f"ps transport cannot encode {type(v).__name__}")

    head = json.dumps(enc(obj), separators=(",", ":")).encode("utf-8")
    return b"".join([_LEN.pack(len(head)), head] + blobs)


def _unpack_msg(payload: bytes):
    if len(payload) < _LEN.size:
        raise ConnectionError("ps transport: truncated frame")
    (nhead,) = _LEN.unpack_from(payload)
    blob0 = _LEN.size + nhead
    if blob0 > len(payload):
        raise ConnectionError("ps transport: header overruns frame")
    try:
        head = json.loads(payload[_LEN.size:blob0].decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise ConnectionError(f"ps transport: bad header: {e}") from None

    def dec_arr(mark) -> np.ndarray:
        try:
            dt, shape, off, nbytes = mark
            dtype = np.dtype(dt)
            shape = tuple(int(s) for s in shape)
            off, nbytes = int(off), int(nbytes)
        except (TypeError, ValueError) as e:
            raise ConnectionError(
                f"ps transport: bad array marker: {e}") from None
        if dtype.hasobject or any(s < 0 for s in shape) or off < 0:
            raise ConnectionError("ps transport: bad array marker")
        count = math.prod(shape)
        if nbytes != count * dtype.itemsize \
                or blob0 + off + nbytes > len(payload):
            raise ConnectionError("ps transport: array segment out of "
                                  "bounds")
        return np.frombuffer(payload, dtype=dtype, count=count,
                             offset=blob0 + off).reshape(shape)

    def dec(v):
        if isinstance(v, dict):
            if _ND in v:
                return dec_arr(v[_ND])
            return {k: dec(x) for k, x in v.items()}
        if isinstance(v, list):
            return [dec(x) for x in v]
        return v

    return dec(head)


def _send_msg(sock: socket.socket, obj) -> None:
    payload = _pack_msg(obj)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError("ps transport: peer closed mid-message")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def _recv_msg(sock: socket.socket):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > _MAX_MSG:
        raise ConnectionError(f"ps transport: message of {n} bytes exceeds "
                              f"{_MAX_MSG} cap")
    return _unpack_msg(_recv_exact(sock, n))


# ----------------------------------------------------------------- clients

class ShardClient:
    """What the table/tier layer codes against — one client per shard.

    All ids are GLOBAL row ids (the shard translates). ``pull`` returns
    packed ``[k, lanes] uint16`` rows; ``push`` scatter-sets whole rows.
    """

    def pull(self, name: str, ids: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def push(self, name: str, ids: np.ndarray, rows: np.ndarray) -> None:
        raise NotImplementedError

    def dump(self, name: str) -> np.ndarray:
        raise NotImplementedError

    def load(self, name: str, rows: np.ndarray) -> None:
        raise NotImplementedError

    def meta(self) -> dict:
        """{table_name: {"lo": int, "hi": int, "lanes": int}}"""
        raise NotImplementedError

    def stats(self) -> dict:
        """{table_name: shard.stats()} — byte/pull/push counters."""
        raise NotImplementedError

    def ping(self) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InProcessClient(ShardClient):
    """Direct dispatch onto shard objects living in this process. One
    'shard worker' may host the matching slice of several tables (the
    common case: all sparse tables of a model partitioned the same way)."""

    def __init__(self, shards: Sequence[EmbeddingShard]):
        self._shards: Dict[str, EmbeddingShard] = {}
        for s in shards:
            if s.name in self._shards:
                raise ValueError(f"InProcessClient: duplicate table "
                                 f"{s.name!r}")
            self._shards[s.name] = s

    def _get(self, name: str) -> EmbeddingShard:
        try:
            return self._shards[name]
        except KeyError:
            raise KeyError(f"shard client has no table {name!r}; tables: "
                           f"{sorted(self._shards)}") from None

    def pull(self, name, ids):
        return self._get(name).pull(ids)

    def push(self, name, ids, rows):
        self._get(name).push(ids, rows)

    def dump(self, name):
        return self._get(name).dump()

    def load(self, name, rows):
        self._get(name).load(rows)

    def meta(self):
        return {n: {"lo": s.lo, "hi": s.hi, "lanes": s.rows.shape[1]}
                for n, s in self._shards.items()}

    def stats(self):
        return {n: s.stats() for n, s in self._shards.items()}

    def ping(self):
        return True


class SocketClient(ShardClient):
    """Persistent-connection client for a remote ``ShardServer``."""

    def __init__(self, endpoint: str, timeout: float = 30.0):
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def _call(self, op: str, **kw):
        msg = {"op": op, **kw}
        with self._lock:
            _send_msg(self._sock, msg)
            rep = _recv_msg(self._sock)
        if rep.get("err"):
            raise RuntimeError(f"ps shard {self.endpoint} {op}: "
                               f"{rep['err']}")
        return rep.get("out")

    def pull(self, name, ids):
        return self._call("pull", name=name,
                          ids=np.asarray(ids, dtype=np.int64))

    def push(self, name, ids, rows):
        self._call("push", name=name,
                   ids=np.asarray(ids, dtype=np.int64),
                   rows=np.asarray(rows, dtype=np.uint16))

    def dump(self, name):
        return self._call("dump", name=name)

    def load(self, name, rows):
        self._call("load", name=name,
                   rows=np.asarray(rows, dtype=np.uint16))

    def meta(self):
        return self._call("meta")

    def stats(self):
        return self._call("stats")

    def ping(self):
        return bool(self._call("ping"))

    def shutdown_server(self):
        """Ask the server process to stop (tests / orderly teardown)."""
        try:
            self._call("shutdown")
        except (ConnectionError, OSError):
            pass  # server may close before replying

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


def connect(endpoint_or_shards) -> ShardClient:
    """``"host:port"`` → SocketClient; a shard list → InProcessClient."""
    if isinstance(endpoint_or_shards, str):
        return SocketClient(endpoint_or_shards)
    return InProcessClient(endpoint_or_shards)


# ------------------------------------------------------------------ server

class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        srv: "ShardServer" = self.server.ps_server  # type: ignore
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                msg = _recv_msg(sock)
            except (ConnectionError, OSError):
                return
            op = msg.get("op")
            if op == "shutdown":
                try:
                    _send_msg(sock, {"out": True})
                finally:
                    threading.Thread(target=self.server.shutdown,
                                     daemon=True).start()
                return
            try:
                rep = {"out": srv.dispatch(op, msg)}
            except Exception as e:  # report, keep the connection alive
                rep = {"err": f"{type(e).__name__}: {e}"}
            try:
                _send_msg(sock, rep)
            except (ConnectionError, OSError):
                return


class _TCP(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class ShardServer:
    """Serves a set of ``EmbeddingShard`` objects over the socket
    protocol. ``serve_in_thread()`` for tests / co-hosted shards,
    ``serve_forever()`` for a dedicated pserver process
    (``fleet.run_server()``)."""

    def __init__(self, shards: Sequence[EmbeddingShard],
                 host: str = "127.0.0.1", port: int = 0,
                 delay_ms: float = 0.0):
        """delay_ms: simulated per-request network latency on pull/push
        (tests and single-host benches modelling cross-host RTT — a
        loopback server has none, so overlap A/Bs would otherwise be
        measuring pure serialization CPU time)."""
        self.local = InProcessClient(shards)
        self.delay_ms = float(delay_ms)
        self._tcp = _TCP((host, port), _Handler)
        self._tcp.ps_server = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def endpoint(self) -> str:
        host, port = self._tcp.server_address[:2]
        return f"{host}:{port}"

    def dispatch(self, op: str, msg: dict):
        if op == "ping":
            return True
        if op == "meta":
            return self.local.meta()
        if op == "stats":
            return self.local.stats()
        name = msg.get("name")
        if op in ("pull", "push") and self.delay_ms:
            time.sleep(self.delay_ms / 1e3)
        if op == "pull":
            return self.local.pull(name, msg["ids"])
        if op == "push":
            self.local.push(name, msg["ids"], msg["rows"])
            return True
        if op == "dump":
            return self.local.dump(name)
        if op == "load":
            self.local.load(name, msg["rows"])
            return True
        raise ValueError(f"unknown ps op {op!r}")

    def serve_in_thread(self) -> "ShardServer":
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        name=f"ps-server@{self.endpoint}",
                                        daemon=True)
        self._thread.start()
        return self

    def serve_forever(self):
        self._tcp.serve_forever()

    def stop(self):
        self._tcp.shutdown()
        self._tcp.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
