"""Transport for the PS embedding tier: clients and the shard server.

Two transports behind one interface:

* ``InProcessClient`` — the shard object lives in this process; calls are
  direct method dispatch. This is what single-host training and the tier-1
  tests use (no sockets, no pickling, zero copies beyond the pull itself).
* ``SocketClient`` / ``ShardServer`` — a length-prefixed binary protocol
  over TCP so shards can live in other processes or hosts (the reference's
  pserver processes; ``fleet.run_server()`` ends up in
  ``ShardServer.serve_forever``). The server side is numpy + stdlib only —
  a pserver must never import JAX or touch the TPU.

Wire format: every message is ``<u32 length><u32 json_len><json
header><array blobs>``. The header is plain JSON (op names, table names,
counters); each ndarray in the message is replaced by a
``{"__nd__": [dtype, shape, offset, nbytes]}`` marker pointing into the
raw blob region that follows, so decoding an array costs one
``np.frombuffer``. Deliberately NOT pickle: a pserver port accepts
connections from anything that can reach it, and ``pickle.loads`` on that
input is arbitrary code execution — JSON + validated buffer slices can
only ever produce dicts/lists/scalars/ndarrays. The port should still be
network-isolated (trainer-cluster only): the protocol is unauthenticated,
so anyone who can reach it can read and overwrite table rows. One
request, one reply; the server is thread-per-connection and a client
keeps one persistent connection per shard (requests on it are serialized
by a lock, concurrency comes from fanning out across shards).

Failure taxonomy. Every transport-level failure is a
:class:`TransportError` carrying ``transient``:

* ``transient=True`` — connect refused, timeout, peer closed / short
  read, ``ECONNRESET``: the kind of error a restarting or briefly
  unreachable shard produces. ``SocketClient`` retries these itself —
  reconnect + capped exponential backoff, ``PDTPU_PS_RETRIES`` attempts
  (default 5) starting at ``PDTPU_PS_RETRY_BACKOFF_MS`` (default 50,
  capped at 5 s), per-socket ``PDTPU_PS_TIMEOUT`` seconds (default 30) —
  counting each retry on ``ps/rpc_retries``. Only when retries are
  exhausted does the error reach the caller (still ``transient=True``:
  the shard may yet come back — this is what the tier's recovery hook
  keys on).
* ``transient=False`` — a structurally invalid frame (bad header JSON,
  bad array marker, > cap message): reconnecting cannot fix a peer that
  speaks garbage, so these surface immediately.

Restart detection: every server reply carries the server's random
instance id; a client that sees the id change between replies raises
:class:`ShardRestartedError` (transient) instead of silently reading a
freshly-booted — and therefore EMPTY — shard. Recovery code calls
``reset_instance_expectation()`` after repopulating the shard.

Chaos: the server probes ``fault_point("ps.rpc")`` on every request
(paddle_tpu.faults) — ``drop`` swallows the request and closes the
connection with no reply, ``reset`` closes with an RST (``SO_LINGER 0``),
``delay_ms`` models a slow shard, ``crash`` is a real pserver death — so
every client-visible failure mode is deterministically injectable.

Distributed tracing: when a `observability.context.TraceContext` is
active on the calling thread, each RPC attempt carries a ``"trace"``
dict in the JSON header (``{"trace_id", "span_id"}`` — re-sent frames
add ``"retry": n`` and a FRESH span_id under the SAME trace_id), the
client records a ``ps/rpc/<op>`` span, and the server opens a
``ps/<op>`` span parented to the client's — so one training step's pulls
show up as one trace across worker and pserver processes. The server
additionally answers ``metrics`` (the registry's structured
`series()`) and ``trace_export`` (its chrome trace) ops, which is how a
JAX-free pserver with no HTTP port gets federated.
"""
from __future__ import annotations

import json
import math
import os
import socket
import socketserver
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..faults import InjectedNetworkFault, fault_point
from ..observability import context as _trace_ctx
from ..observability.registry import get_registry
from ..observability.tracer import get_tracer, server_span
from .shard import EmbeddingShard

__all__ = ["TransportError", "ShardRestartedError", "ShardClient",
           "InProcessClient", "SocketClient", "ShardServer", "connect",
           "probe"]

_LEN = struct.Struct("<I")
_MAX_MSG = 1 << 30  # 1 GiB sanity cap on a single message

_RPC_RETRIES = get_registry().counter("ps/rpc_retries")


class TransportError(ConnectionError):
    """A PS transport failure. ``transient=True`` means a reconnect might
    succeed (shard restarting / network blip) — retry loops and the
    recovery hook key on it; ``transient=False`` means the peer is
    speaking a broken protocol and retrying is pointless. A
    ``ConnectionError`` subclass so pre-taxonomy ``except`` clauses (and
    the server's per-connection loop) keep working."""

    def __init__(self, msg: str, transient: bool, endpoint: str = "",
                 attempts: int = 0):
        if endpoint:
            msg = f"ps shard {endpoint}: {msg}"
        if attempts > 1:
            msg += f" (after {attempts} attempts)"
        super().__init__(msg)
        self.transient = bool(transient)
        self.endpoint = endpoint
        self.attempts = attempts


class ShardRestartedError(TransportError):
    """The shard answered with a different server instance id than the
    last reply: the pserver process restarted (losing its in-memory rows)
    between two RPCs. Always transient — the fix is repopulating the
    shard (``ShardedTable.recover_shard``), not giving up."""

    def __init__(self, endpoint: str, old: str, new: str):
        super().__init__(
            f"server instance changed {old!r} -> {new!r}: the pserver "
            "restarted and its in-memory rows are gone; recover the shard "
            "before trusting reads", transient=True, endpoint=endpoint)


# ---------------------------------------------------------------- encoding

_ND = "__nd__"  # reserved header key marking an array blob


def _pack_msg(obj) -> bytes:
    """JSON header + concatenated array blobs (see module docstring)."""
    blobs: List[bytes] = []
    off = 0

    def enc(v):
        nonlocal off
        if isinstance(v, np.ndarray):
            a = np.ascontiguousarray(v)
            raw = a.tobytes()
            mark = {_ND: [str(a.dtype), list(a.shape), off, len(raw)]}
            blobs.append(raw)
            off += len(raw)
            return mark
        if isinstance(v, dict):
            if _ND in v:
                raise ValueError(f"ps transport: key {_ND!r} is reserved")
            return {k: enc(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [enc(x) for x in v]
        if isinstance(v, (np.integer, np.floating, np.bool_)):
            return v.item()
        if v is None or isinstance(v, (bool, int, float, str)):
            return v
        raise TypeError(
            f"ps transport cannot encode {type(v).__name__}")

    head = json.dumps(enc(obj), separators=(",", ":")).encode("utf-8")
    return b"".join([_LEN.pack(len(head)), head] + blobs)


def _unpack_msg(payload: bytes):
    if len(payload) < _LEN.size:
        raise TransportError("truncated frame", transient=False)
    (nhead,) = _LEN.unpack_from(payload)
    blob0 = _LEN.size + nhead
    if blob0 > len(payload):
        raise TransportError("header overruns frame", transient=False)
    try:
        head = json.loads(payload[_LEN.size:blob0].decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise TransportError(f"bad header: {e}", transient=False) from None

    def dec_arr(mark) -> np.ndarray:
        try:
            dt, shape, off, nbytes = mark
            dtype = np.dtype(dt)
            shape = tuple(int(s) for s in shape)
            off, nbytes = int(off), int(nbytes)
        except (TypeError, ValueError) as e:
            raise TransportError(
                f"bad array marker: {e}", transient=False) from None
        if dtype.hasobject or any(s < 0 for s in shape) or off < 0:
            raise TransportError("bad array marker", transient=False)
        count = math.prod(shape)
        if nbytes != count * dtype.itemsize \
                or blob0 + off + nbytes > len(payload):
            raise TransportError("array segment out of bounds",
                                 transient=False)
        return np.frombuffer(payload, dtype=dtype, count=count,
                             offset=blob0 + off).reshape(shape)

    def dec(v):
        if isinstance(v, dict):
            if _ND in v:
                return dec_arr(v[_ND])
            return {k: dec(x) for k, x in v.items()}
        if isinstance(v, list):
            return [dec(x) for x in v]
        return v

    return dec(head)


def _send_msg(sock: socket.socket, obj) -> None:
    payload = _pack_msg(obj)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly `n` bytes. A half-closed socket (peer died or sent a
    torn frame) raises a TRANSIENT TransportError naming how much of the
    frame arrived — reconnect + retry gets a fresh, resynchronized
    stream, which is exactly what the client's retry loop does."""
    want = n
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise TransportError(
                f"peer closed mid-message: expected {want} bytes, "
                f"got {want - n}", transient=True)
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def _recv_msg(sock: socket.socket):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > _MAX_MSG:
        raise TransportError(f"message of {n} bytes exceeds {_MAX_MSG} "
                             "cap", transient=False)
    return _unpack_msg(_recv_exact(sock, n))


# ----------------------------------------------------------------- clients

class ShardClient:
    """What the table/tier layer codes against — one client per shard.

    All ids are GLOBAL row ids (the shard translates). ``pull`` returns
    packed ``[k, lanes] uint16`` rows; ``push`` scatter-sets whole rows.
    """

    def pull(self, name: str, ids: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def push(self, name: str, ids: np.ndarray, rows: np.ndarray) -> None:
        raise NotImplementedError

    def dump(self, name: str) -> np.ndarray:
        raise NotImplementedError

    def load(self, name: str, rows: np.ndarray) -> None:
        raise NotImplementedError

    def meta(self) -> dict:
        """{table_name: {"lo": int, "hi": int, "lanes": int}}"""
        raise NotImplementedError

    def stats(self) -> dict:
        """{table_name: shard.stats()} — byte/pull/push counters."""
        raise NotImplementedError

    def ping(self) -> bool:
        raise NotImplementedError

    def sweep(self, name: str) -> int:
        """Run one dynamic-vocab eviction pass on table `name`; returns
        rows evicted (0 for a static shard — sweeping is a no-op there,
        not an error, so a mixed static/dynamic fleet sweeps uniformly)."""
        raise NotImplementedError

    def reset_instance_expectation(self) -> None:
        """Forget the remembered server instance id: the next reply's id
        is adopted without raising ShardRestartedError. Recovery calls
        this once the restarted shard has been repopulated."""

    def close(self) -> None:
        pass


class InProcessClient(ShardClient):
    """Direct dispatch onto shard objects living in this process. One
    'shard worker' may host the matching slice of several tables (the
    common case: all sparse tables of a model partitioned the same way)."""

    def __init__(self, shards: Sequence[EmbeddingShard]):
        self._shards: Dict[str, EmbeddingShard] = {}
        for s in shards:
            if s.name in self._shards:
                raise ValueError(f"InProcessClient: duplicate table "
                                 f"{s.name!r}")
            self._shards[s.name] = s

    def _get(self, name: str) -> EmbeddingShard:
        try:
            return self._shards[name]
        except KeyError:
            raise KeyError(f"shard client has no table {name!r}; tables: "
                           f"{sorted(self._shards)}") from None

    def pull(self, name, ids):
        return self._get(name).pull(ids)

    def push(self, name, ids, rows):
        self._get(name).push(ids, rows)

    def dump(self, name):
        return self._get(name).dump()

    def load(self, name, rows):
        self._get(name).load(rows)

    def meta(self):
        return {n: {"lo": s.lo, "hi": s.hi, "lanes": s.rows.shape[1]}
                for n, s in self._shards.items()}

    def stats(self):
        return {n: s.stats() for n, s in self._shards.items()}

    def sweep(self, name):
        sh = self._get(name)
        fn = getattr(sh, "sweep", None)
        return int(fn()) if fn is not None else 0

    def ping(self):
        return True


class SocketClient(ShardClient):
    """Persistent-connection client for a remote ``ShardServer``.

    The connection is LAZY (first RPC connects) and self-healing: any
    transient failure drops the socket, backs off, reconnects, and
    re-sends — safe because every op is idempotent (pull reads, push/load
    scatter-SET absolute rows). Constructor args override the
    ``PDTPU_PS_*`` environment defaults; ``retries=0`` makes a
    single-shot probe client (what ShardMonitor uses)."""

    def __init__(self, endpoint: str, timeout: Optional[float] = None,
                 retries: Optional[int] = None,
                 backoff_ms: Optional[float] = None):
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        self._addr = (host, int(port))
        self._timeout = timeout
        self._retries = retries
        self._backoff_ms = backoff_ms
        self._sock: Optional[socket.socket] = None
        self._inst: Optional[str] = None
        self._lock = threading.Lock()

    # env resolved per call, not per client: tests and operators tune the
    # knobs on a live process
    def _cfg(self) -> Tuple[float, int, float]:
        t = (self._timeout if self._timeout is not None
             else float(os.environ.get("PDTPU_PS_TIMEOUT", "30")))
        r = (self._retries if self._retries is not None
             else int(os.environ.get("PDTPU_PS_RETRIES", "5")))
        b = (self._backoff_ms if self._backoff_ms is not None
             else float(os.environ.get("PDTPU_PS_RETRY_BACKOFF_MS", "50")))
        return t, r, b

    def _ensure_sock(self, timeout: float) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(self._addr, timeout=timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        else:
            self._sock.settimeout(timeout)
        return self._sock

    def _drop_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _call(self, op: str, _retryable: bool = True, **kw):
        msg = {"op": op, **kw}
        timeout, retries, backoff_ms = self._cfg()
        attempt = 0
        tracer = get_tracer()
        with self._lock:
            while True:
                # per-ATTEMPT trace header: same trace_id across a retry
                # but a fresh span_id + retry tag, so a torn-frame re-send
                # is visibly a second RPC in the same trace
                span = None
                ctx = _trace_ctx.current()
                if ctx is not None:
                    rctx = ctx.child()
                    wire = rctx.to_wire()
                    if attempt:
                        wire["retry"] = attempt
                    msg["trace"] = wire
                    if tracer.enabled:
                        sargs = dict(rctx.args(), rpc="client", op=op,
                                     endpoint=self.endpoint)
                        if attempt:
                            sargs["retry"] = attempt
                        span = f"ps/rpc/{op}"
                        tracer.begin(span, sargs)
                try:
                    sock = self._ensure_sock(timeout)
                    _send_msg(sock, msg)
                    rep = _recv_msg(sock)
                    break
                except OSError as e:  # TransportError, timeout, ECONNRESET
                    # a dirty socket cannot be reused: mid-frame state is
                    # unknowable after any failure
                    self._drop_sock()
                    transient = getattr(e, "transient", True)
                    if (not transient or not _retryable
                            or attempt >= retries):
                        raise TransportError(
                            f"{op}: {e}", transient=transient,
                            endpoint=self.endpoint,
                            attempts=attempt + 1) from e
                    _RPC_RETRIES.inc()
                    time.sleep(min(backoff_ms * (2 ** attempt), 5000.0)
                               / 1e3)
                    attempt += 1
                finally:
                    if span is not None:
                        tracer.end(span)
            inst = rep.get("inst")
            if isinstance(inst, str):
                if self._inst is None:
                    self._inst = inst
                elif self._inst != inst:
                    # do NOT adopt: every call keeps failing until
                    # recovery repopulates the shard and calls
                    # reset_instance_expectation() — otherwise the first
                    # raise would "cure" the client and the next read
                    # would silently see a freshly-booted EMPTY shard
                    raise ShardRestartedError(self.endpoint, self._inst,
                                              inst)
        if rep.get("err"):
            raise RuntimeError(f"ps shard {self.endpoint} {op}: "
                               f"{rep['err']}")
        return rep.get("out")

    def pull(self, name, ids):
        return self._call("pull", name=name,
                          ids=np.asarray(ids, dtype=np.int64))

    def push(self, name, ids, rows):
        self._call("push", name=name,
                   ids=np.asarray(ids, dtype=np.int64),
                   rows=np.asarray(rows, dtype=np.uint16))

    def dump(self, name):
        return self._call("dump", name=name)

    def load(self, name, rows):
        self._call("load", name=name,
                   rows=np.asarray(rows, dtype=np.uint16))

    def meta(self):
        return self._call("meta")

    def stats(self):
        return self._call("stats")

    def sweep(self, name):
        return int(self._call("sweep", name=name))

    def metrics(self):
        """The server process's `Registry.series()` — how a pserver
        (no HTTP port, JAX-free) joins metrics federation."""
        return self._call("metrics")

    def trace_export(self):
        """The server process's chrome trace (``{"traceEvents": ...}``)
        — what `tools/timeline.py --fleet` merges by trace_id."""
        return self._call("trace_export")

    def ping(self):
        return bool(self._call("ping"))

    def reset_instance_expectation(self):
        with self._lock:
            self._inst = None

    def shutdown_server(self):
        """Ask the server process to stop (tests / orderly teardown)."""
        try:
            self._call("shutdown", _retryable=False)
        except (ConnectionError, OSError):
            pass  # server may close before replying

    def close(self):
        with self._lock:
            self._drop_sock()


def probe(endpoint: str, timeout: float = 2.0) -> bool:
    """One-shot liveness check: fresh connection, single ping, close.
    Never retries, never touches a persistent client's socket or
    instance expectation — safe to call from a monitor thread at any
    rate. Returns False on ANY failure."""
    c = SocketClient(endpoint, timeout=timeout, retries=0)
    try:
        return c.ping()
    except Exception:
        return False
    finally:
        c.close()


def connect(endpoint_or_shards) -> ShardClient:
    """``"host:port"`` → SocketClient; a shard list → InProcessClient."""
    if isinstance(endpoint_or_shards, str):
        return SocketClient(endpoint_or_shards)
    return InProcessClient(endpoint_or_shards)


# ------------------------------------------------------------------ server

class _Handler(socketserver.BaseRequestHandler):
    def setup(self):
        # registration makes shutdown() able to unblock this thread's
        # recv by closing the socket out from under it
        self.server.ps_server._track(self.request,
                                     threading.current_thread())

    def finish(self):
        self.server.ps_server._untrack(self.request)

    def handle(self):
        srv: "ShardServer" = self.server.ps_server  # type: ignore
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                msg = _recv_msg(sock)
            except (ConnectionError, OSError):
                return
            try:
                fault_point("ps.rpc")
            except InjectedNetworkFault as f:
                if f.kind == "reset":
                    # SO_LINGER 0 → close sends RST, the client sees
                    # ECONNRESET (a crashed pserver); plain close models
                    # a swallowed request (drop)
                    try:
                        sock.setsockopt(
                            socket.SOL_SOCKET, socket.SO_LINGER,
                            struct.pack("ii", 1, 0))
                    except OSError:
                        pass
                return
            op = msg.get("op")
            if op == "shutdown":
                try:
                    _send_msg(sock, {"out": True, "inst": srv.instance_id})
                finally:
                    threading.Thread(target=srv.stop,
                                     daemon=True).start()
                return
            wire = msg.get("trace")
            sargs = {"rpc": "server", "op": str(op)}
            if isinstance(wire, dict) and wire.get("retry"):
                sargs["retry"] = wire["retry"]
            t0 = time.perf_counter()
            try:
                with server_span(f"ps/{op}", wire, **sargs):
                    rep = {"out": srv.dispatch(op, msg)}
            except Exception as e:  # report, keep the connection alive
                rep = {"err": f"{type(e).__name__}: {e}"}
            srv._account(op, (time.perf_counter() - t0) * 1e3)
            rep["inst"] = srv.instance_id
            try:
                _send_msg(sock, rep)
            except (ConnectionError, OSError):
                return


class _TCP(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True
    # ShardServer.stop() does its own BOUNDED join after closing the live
    # connection sockets; the stdlib's unbounded _threads.join() would
    # hang on a handler blocked in recv()
    block_on_close = False


class ShardServer:
    """Serves a set of ``EmbeddingShard`` objects over the socket
    protocol. ``serve_in_thread()`` for tests / co-hosted shards,
    ``serve_forever()`` for a dedicated pserver process
    (``fleet.run_server()``)."""

    def __init__(self, shards: Sequence[EmbeddingShard],
                 host: str = "127.0.0.1", port: int = 0,
                 delay_ms: float = 0.0):
        """delay_ms: simulated per-request network latency on pull/push
        (tests and single-host benches modelling cross-host RTT — a
        loopback server has none, so overlap A/Bs would otherwise be
        measuring pure serialization CPU time)."""
        self.local = InProcessClient(shards)
        self.delay_ms = float(delay_ms)
        # random per-boot token: lets clients detect "this pserver
        # restarted (and lost its rows) between my RPCs"
        self.instance_id = os.urandom(8).hex()
        self._tcp = _TCP((host, port), _Handler)
        self._tcp.ps_server = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None
        self._conn_lock = threading.Lock()
        self._conns: Dict[socket.socket, threading.Thread] = {}
        self._serving = False
        self._stopped = False

    @property
    def endpoint(self) -> str:
        host, port = self._tcp.server_address[:2]
        return f"{host}:{port}"

    def _track(self, sock: socket.socket, thread: threading.Thread):
        with self._conn_lock:
            self._conns[sock] = thread

    def _untrack(self, sock: socket.socket):
        with self._conn_lock:
            self._conns.pop(sock, None)

    def _account(self, op, ms: float) -> None:
        """Server-side per-op request counter + handling-time histogram:
        the federation scraper reads these over the `metrics` op, which
        is how per-SHARD serve time reaches the autoscaler surface."""
        reg = get_registry()
        reg.counter("ps/server_requests", op=str(op)).inc()
        reg.histogram("ps/server_ms", op=str(op)).observe(ms)

    def dispatch(self, op: str, msg: dict):
        if op == "ping":
            return True
        if op == "meta":
            return self.local.meta()
        if op == "stats":
            return self.local.stats()
        if op == "metrics":
            return get_registry().series(deep=True)
        if op == "trace_export":
            return get_tracer().export_chrome_trace()
        name = msg.get("name")
        if op in ("pull", "push") and self.delay_ms:
            time.sleep(self.delay_ms / 1e3)
        if op == "pull":
            return self.local.pull(name, msg["ids"])
        if op == "push":
            self.local.push(name, msg["ids"], msg["rows"])
            return True
        if op == "dump":
            return self.local.dump(name)
        if op == "load":
            self.local.load(name, msg["rows"])
            return True
        if op == "sweep":
            return self.local.sweep(name)
        raise ValueError(f"unknown ps op {op!r}")

    def serve_in_thread(self) -> "ShardServer":
        self._serving = True
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        name=f"ps-server@{self.endpoint}",
                                        daemon=True)
        self._thread.start()
        return self

    def serve_forever(self):
        self._serving = True
        self._tcp.serve_forever()

    def stop(self, join_timeout: float = 5.0):
        """Stop accepting, unblock and join every live per-connection
        handler (bounded): a test teardown or the ``shutdown`` op must
        not leak daemon threads holding the port — or sockets — into the
        next test case. Idempotent."""
        with self._conn_lock:
            if self._stopped:
                return
            self._stopped = True
        if self._serving:
            # BaseServer.shutdown() blocks on serve_forever's exit event;
            # calling it on a never-served server would wait forever
            self._tcp.shutdown()
        self._tcp.server_close()
        with self._conn_lock:
            live = list(self._conns.items())
        for sock, _ in live:
            # recv() in the handler returns immediately once the socket
            # is shut down under it
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        deadline = time.monotonic() + join_timeout
        me = threading.current_thread()
        for _, t in live:
            if t is me or not t.is_alive():
                continue
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        if self._thread is not None and self._thread is not me:
            self._thread.join(timeout=max(0.0,
                                          deadline - time.monotonic()))
            self._thread = None
