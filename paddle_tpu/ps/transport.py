"""Transport for the PS embedding tier: clients and the shard server.

Two transports behind one interface:

* ``InProcessClient`` — the shard object lives in this process; calls are
  direct method dispatch. This is what single-host training and the tier-1
  tests use (no sockets, no pickling, zero copies beyond the pull itself).
* ``SocketClient`` / ``ShardServer`` — a length-prefixed binary protocol
  over TCP so shards can live in other processes or hosts (the reference's
  pserver processes; ``fleet.run_server()`` ends up in
  ``ShardServer.serve_forever``). The server side is numpy + stdlib only —
  a pserver must never import JAX or touch the TPU.

Wire format: every message is ``<u32 length><pickle payload>``; array
payloads ride as ``(dtype-str, shape, bytes)`` triples so unpickling costs
one ``np.frombuffer`` (no object arrays, protocol 4). One request, one
reply; the server is thread-per-connection and a client keeps one
persistent connection per shard (requests on it are serialized by a lock,
concurrency comes from fanning out across shards).
"""
from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .shard import EmbeddingShard

__all__ = ["ShardClient", "InProcessClient", "SocketClient", "ShardServer",
           "connect"]

_LEN = struct.Struct("<I")
_MAX_MSG = 1 << 30  # 1 GiB sanity cap on a single message


# ---------------------------------------------------------------- encoding

def _enc_arr(a: np.ndarray) -> tuple:
    a = np.ascontiguousarray(a)
    return ("__nd__", str(a.dtype), a.shape, a.tobytes())


def _dec_arr(t) -> np.ndarray:
    _, dt, shape, raw = t
    return np.frombuffer(raw, dtype=dt).reshape(shape)


def _maybe_dec(v):
    if isinstance(v, tuple) and len(v) == 4 and v[0] == "__nd__":
        return _dec_arr(v)
    return v


def _send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=4)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError("ps transport: peer closed mid-message")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def _recv_msg(sock: socket.socket):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > _MAX_MSG:
        raise ConnectionError(f"ps transport: message of {n} bytes exceeds "
                              f"{_MAX_MSG} cap")
    return pickle.loads(_recv_exact(sock, n))


# ----------------------------------------------------------------- clients

class ShardClient:
    """What the table/tier layer codes against — one client per shard.

    All ids are GLOBAL row ids (the shard translates). ``pull`` returns
    packed ``[k, lanes] uint16`` rows; ``push`` scatter-sets whole rows.
    """

    def pull(self, name: str, ids: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def push(self, name: str, ids: np.ndarray, rows: np.ndarray) -> None:
        raise NotImplementedError

    def dump(self, name: str) -> np.ndarray:
        raise NotImplementedError

    def load(self, name: str, rows: np.ndarray) -> None:
        raise NotImplementedError

    def meta(self) -> dict:
        """{table_name: {"lo": int, "hi": int, "lanes": int}}"""
        raise NotImplementedError

    def stats(self) -> dict:
        """{table_name: shard.stats()} — byte/pull/push counters."""
        raise NotImplementedError

    def ping(self) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InProcessClient(ShardClient):
    """Direct dispatch onto shard objects living in this process. One
    'shard worker' may host the matching slice of several tables (the
    common case: all sparse tables of a model partitioned the same way)."""

    def __init__(self, shards: Sequence[EmbeddingShard]):
        self._shards: Dict[str, EmbeddingShard] = {}
        for s in shards:
            if s.name in self._shards:
                raise ValueError(f"InProcessClient: duplicate table "
                                 f"{s.name!r}")
            self._shards[s.name] = s

    def _get(self, name: str) -> EmbeddingShard:
        try:
            return self._shards[name]
        except KeyError:
            raise KeyError(f"shard client has no table {name!r}; tables: "
                           f"{sorted(self._shards)}") from None

    def pull(self, name, ids):
        return self._get(name).pull(ids)

    def push(self, name, ids, rows):
        self._get(name).push(ids, rows)

    def dump(self, name):
        return self._get(name).dump()

    def load(self, name, rows):
        self._get(name).load(rows)

    def meta(self):
        return {n: {"lo": s.lo, "hi": s.hi, "lanes": s.rows.shape[1]}
                for n, s in self._shards.items()}

    def stats(self):
        return {n: s.stats() for n, s in self._shards.items()}

    def ping(self):
        return True


class SocketClient(ShardClient):
    """Persistent-connection client for a remote ``ShardServer``."""

    def __init__(self, endpoint: str, timeout: float = 30.0):
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def _call(self, op: str, **kw):
        msg = {"op": op}
        for k, v in kw.items():
            msg[k] = _enc_arr(v) if isinstance(v, np.ndarray) else v
        with self._lock:
            _send_msg(self._sock, msg)
            rep = _recv_msg(self._sock)
        if rep.get("err"):
            raise RuntimeError(f"ps shard {self.endpoint} {op}: "
                               f"{rep['err']}")
        return _maybe_dec(rep.get("out"))

    def pull(self, name, ids):
        return self._call("pull", name=name,
                          ids=np.asarray(ids, dtype=np.int64))

    def push(self, name, ids, rows):
        self._call("push", name=name,
                   ids=np.asarray(ids, dtype=np.int64),
                   rows=np.asarray(rows, dtype=np.uint16))

    def dump(self, name):
        return self._call("dump", name=name)

    def load(self, name, rows):
        self._call("load", name=name,
                   rows=np.asarray(rows, dtype=np.uint16))

    def meta(self):
        return self._call("meta")

    def stats(self):
        return self._call("stats")

    def ping(self):
        return bool(self._call("ping"))

    def shutdown_server(self):
        """Ask the server process to stop (tests / orderly teardown)."""
        try:
            self._call("shutdown")
        except (ConnectionError, OSError):
            pass  # server may close before replying

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


def connect(endpoint_or_shards) -> ShardClient:
    """``"host:port"`` → SocketClient; a shard list → InProcessClient."""
    if isinstance(endpoint_or_shards, str):
        return SocketClient(endpoint_or_shards)
    return InProcessClient(endpoint_or_shards)


# ------------------------------------------------------------------ server

class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        srv: "ShardServer" = self.server.ps_server  # type: ignore
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                msg = _recv_msg(sock)
            except (ConnectionError, OSError):
                return
            op = msg.get("op")
            if op == "shutdown":
                try:
                    _send_msg(sock, {"out": True})
                finally:
                    threading.Thread(target=self.server.shutdown,
                                     daemon=True).start()
                return
            try:
                out = srv.dispatch(op, msg)
                rep = {"out": _enc_arr(out)
                       if isinstance(out, np.ndarray) else out}
            except Exception as e:  # report, keep the connection alive
                rep = {"err": f"{type(e).__name__}: {e}"}
            try:
                _send_msg(sock, rep)
            except (ConnectionError, OSError):
                return


class _TCP(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class ShardServer:
    """Serves a set of ``EmbeddingShard`` objects over the socket
    protocol. ``serve_in_thread()`` for tests / co-hosted shards,
    ``serve_forever()`` for a dedicated pserver process
    (``fleet.run_server()``)."""

    def __init__(self, shards: Sequence[EmbeddingShard],
                 host: str = "127.0.0.1", port: int = 0,
                 delay_ms: float = 0.0):
        """delay_ms: simulated per-request network latency on pull/push
        (tests and single-host benches modelling cross-host RTT — a
        loopback server has none, so overlap A/Bs would otherwise be
        measuring pure serialization CPU time)."""
        self.local = InProcessClient(shards)
        self.delay_ms = float(delay_ms)
        self._tcp = _TCP((host, port), _Handler)
        self._tcp.ps_server = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def endpoint(self) -> str:
        host, port = self._tcp.server_address[:2]
        return f"{host}:{port}"

    def dispatch(self, op: str, msg: dict):
        if op == "ping":
            return True
        if op == "meta":
            return self.local.meta()
        if op == "stats":
            return self.local.stats()
        name = msg.get("name")
        if op in ("pull", "push") and self.delay_ms:
            time.sleep(self.delay_ms / 1e3)
        if op == "pull":
            return self.local.pull(name, _maybe_dec(msg["ids"]))
        if op == "push":
            self.local.push(name, _maybe_dec(msg["ids"]),
                            _maybe_dec(msg["rows"]))
            return True
        if op == "dump":
            return self.local.dump(name)
        if op == "load":
            self.local.load(name, _maybe_dec(msg["rows"]))
            return True
        raise ValueError(f"unknown ps op {op!r}")

    def serve_in_thread(self) -> "ShardServer":
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        name=f"ps-server@{self.endpoint}",
                                        daemon=True)
        self._thread.start()
        return self

    def serve_forever(self):
        self._tcp.serve_forever()

    def stop(self):
        self._tcp.shutdown()
        self._tcp.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
