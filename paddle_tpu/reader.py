"""Reader decorators + PyReader-style prefetching.

Reference analog: ``python/paddle/reader/decorator.py`` (batch/shuffle/
buffered/map_readers/xmap_readers/compose/chain/firstn) and
``python/paddle/fluid/reader.py`` PyReader:47 (background thread feeding a
blocking queue, double-buffered H2D — buffered_reader.cc).

TPU-native: the prefetch queue is the C++ native blocking queue when built
(paddle_tpu/native), else a Python queue; device transfer overlaps with
compute because jax dispatch is async.
"""
from __future__ import annotations

import itertools
import random as _random
import threading
from queue import Queue
from typing import Callable, Iterable, List, Optional

import numpy as np


# ---------------------------------------------------------------------------
# decorators (paddle.reader.* parity)
# ---------------------------------------------------------------------------

def batch(reader: Callable, batch_size: int, drop_last: bool = False):
    def batch_reader():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return batch_reader


def shuffle(reader: Callable, buf_size: int):
    def shuffle_reader():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        _random.shuffle(buf)
        yield from buf
    return shuffle_reader


def buffered(reader: Callable, size: int):
    """Prefetch into a bounded queue on a background thread. Reader errors
    re-raise in the consumer (no silent dataset truncation)."""
    end = object()

    def buffered_reader():
        q: Queue = Queue(maxsize=size)
        error: List[BaseException] = []

        def worker():
            try:
                for item in reader():
                    q.put(item)
            except BaseException as e:  # propagate to consumer
                error.append(e)
            finally:
                q.put(end)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is end:
                if error:
                    raise error[0]
                break
            yield item
    return buffered_reader


def map_readers(func: Callable, *readers):
    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)
    return reader


def xmap_readers(mapper: Callable, reader: Callable, process_num: int,
                 buffer_size: int, order: bool = False):
    """Parallel map via threads (reference uses processes; jax arrays prefer
    threads to avoid fork issues)."""
    end = object()

    def xreader():
        in_q: Queue = Queue(buffer_size)
        out_q: Queue = Queue(buffer_size)

        def feed():
            for i, item in enumerate(reader()):
                in_q.put((i, item))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    break
                i, x = item
                out_q.put((i, mapper(x)))

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True) for _ in range(process_num)]
        for w in workers:
            w.start()
        done = 0
        pending = {}
        next_i = 0
        while done < process_num:
            item = out_q.get()
            if item is end:
                done += 1
                continue
            if not order:
                yield item[1]
            else:
                pending[item[0]] = item[1]
                while next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
        if order:
            for i in sorted(pending):
                yield pending[i]
    return xreader


def compose(*readers):
    def reader():
        for items in zip(*[r() for r in readers]):
            out = []
            for it in items:
                if isinstance(it, tuple):
                    out.extend(it)
                else:
                    out.append(it)
            yield tuple(out)
    return reader


def chain(*readers):
    def reader():
        for r in readers:
            yield from r()
    return reader


def firstn(reader: Callable, n: int):
    def firstn_reader():
        yield from itertools.islice(reader(), n)
    return firstn_reader


def cache(reader: Callable):
    all_data: Optional[List] = None

    def cache_reader():
        nonlocal all_data
        if all_data is None:
            all_data = list(reader())
        yield from all_data
    return cache_reader


# ---------------------------------------------------------------------------
# PyReader (fluid.reader.PyReader:47 parity)
# ---------------------------------------------------------------------------

class PyReader:
    """Iterable prefetching reader bound to feed vars.

    with iterable=True (the only TPU mode): `for data in reader(): exe.run(
    feed=data, ...)`. Decorate with sample/batch generators like the
    reference.

    use_double_buffer=True (the reference default, buffered_reader.cc) is
    REAL here: batches are converted and ``jax.device_put`` on a
    background :class:`~paddle_tpu.dataio.DeviceLoader` thread, so the
    H2D transfer of batch N+1 overlaps step N. use_double_buffer=False
    keeps the host-side `buffered` prefetch only (batches stay numpy).
    """

    def __init__(self, feed_list=None, capacity: int = 64, use_double_buffer=True,
                 iterable: bool = True, shapes=None, dtypes=None,
                 lod_levels=None, name=None):
        self._feed_names = [v.name for v in (feed_list or [])]
        self._capacity = capacity
        self._use_double_buffer = bool(use_double_buffer)
        # shapes/dtypes: the layers.py_reader construction form — feed
        # names come from the decorated generator's dicts (or slot order)
        self._shapes = shapes
        self._dtypes = dtypes
        self._batch_reader = None
        self._places = None
        self._loader = None  # active DeviceLoader (double-buffer mode)

    def decorate_sample_list_generator(self, reader, places=None):
        from .data_feeder import pad_batch_column
        names = self._feed_names

        def gen():
            for samples in reader():
                feed = {}
                arrays = list(zip(*samples))
                for name, col in zip(names, arrays):
                    arr, lens = pad_batch_column(col)
                    feed[name] = arr
                    if lens is not None:
                        feed[name + "_len"] = lens
                yield feed
        self._batch_reader = gen

    def decorate_batch_generator(self, reader, places=None):
        names = self._feed_names

        def gen():
            for b in reader():
                if isinstance(b, dict):
                    yield b
                else:
                    yield {n: np.asarray(v) for n, v in zip(names, b)}
        self._batch_reader = gen

    def __call__(self):
        if self._batch_reader is None:
            raise RuntimeError(
                "PyReader: decorate_sample_list_generator / "
                "decorate_batch_generator must be called before iterating")
        if not self._use_double_buffer:
            return buffered(self._batch_reader, self._capacity)()
        # double-buffer mode: host prefetch (capacity) feeds a device
        # prefetch stage (the classic 2-deep double buffer) — batches
        # arrive as live device arrays, Executor.run skips conversion
        from .dataio import DeviceLoader
        self.reset()
        self._loader = DeviceLoader(
            buffered(self._batch_reader, self._capacity),
            capacity=2, name="py_reader")
        return iter(self._loader)

    def __iter__(self):
        return self()

    def start(self):
        """Non-iterable API compat: spin up the prefetch pipeline now
        (iterable mode does this lazily on iteration)."""
        if self._use_double_buffer and self._batch_reader is not None:
            if self._loader is None or not self._loader.running:
                self()

    def reset(self):
        """Tear down the active prefetch thread/queue. A mid-epoch
        ``break`` otherwise leaks a worker still holding device buffers
        (reference PyReader.reset drained its blocking queue the same
        way)."""
        if self._loader is not None:
            self._loader.close()
            self._loader = None


def bucket_by_sequence_length(reader, bucket_boundaries, batch_sizes,
                              pad_value=0, length_fn=None):
    """Length-bucketing batch reader (SURVEY §7 hard part #1: preserve the
    reference's padding-free LoD efficiency under XLA's static shapes).

    Groups samples into buckets by length, pads every sample in a bucket to
    the bucket's boundary, and yields `(padded_batch, lengths)` once a
    bucket fills. XLA compiles ONE program per bucket shape — the bucket
    count bounds total compilations while padding waste stays
    ≤ (boundary gap / boundary).

    reader: yields samples; a sample is a 1-D sequence (list/np array) or a
    tuple whose first element is the sequence. bucket_boundaries: ascending
    max lengths, e.g. [16, 32, 64]; longer samples are dropped.
    batch_sizes: per-bucket batch size (int = same for all).
    length_fn: custom sample→length (default: len of first element)."""
    import numpy as np

    bounds = list(bucket_boundaries)
    if isinstance(batch_sizes, int):
        batch_sizes = [batch_sizes] * len(bounds)
    if len(batch_sizes) != len(bounds):
        raise ValueError("batch_sizes must match bucket_boundaries")

    def _len(sample):
        if length_fn is not None:
            return length_fn(sample)
        seq = sample[0] if isinstance(sample, (tuple, list)) and not np.isscalar(sample[0]) else sample
        return len(seq)

    def _field_bound(maxlen, bound):
        # pad to the bucketed bound when the field fits it, else to the next
        # boundary up (keeps the shape set small → few XLA compilations)
        if maxlen <= bound:
            return bound
        for b in bounds:
            if maxlen <= b:
                return b
        return maxlen

    def _pad_batch(samples, bound):
        first = samples[0]
        multi = isinstance(first, (tuple, list)) and not np.isscalar(first[0])
        n_fields = len(first) if multi else 1
        fields = []
        for f in range(n_fields):
            rows = [np.asarray(s[f] if multi else s) for s in samples]
            if rows[0].ndim == 0:        # scalar field (e.g. a label)
                fields.append(np.stack(rows))
                continue
            fb = _field_bound(max(len(r) for r in rows), bound)
            padded = np.full((len(rows), fb) + rows[0].shape[1:],
                             pad_value, rows[0].dtype)
            for i, r in enumerate(rows):
                padded[i, :len(r)] = r
            fields.append(padded)
        lengths = np.asarray([_len(s) for s in samples], np.int64)
        return (tuple(fields) if multi else fields[0]), lengths

    def bucketed():
        pending = [[] for _ in bounds]
        for sample in reader():
            L = _len(sample)
            for bi, bound in enumerate(bounds):
                if L <= bound:
                    pending[bi].append(sample)
                    if len(pending[bi]) == batch_sizes[bi]:
                        yield _pad_batch(pending[bi], bound)
                        pending[bi] = []
                    break
            # samples longer than the last boundary are dropped (reference
            # readers truncate or drop equivalently)
        for bi, bucket in enumerate(pending):
            if bucket:
                yield _pad_batch(bucket, bounds[bi])

    return bucketed


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """reader/decorator.py multiprocess_reader: run each reader in its own
    process, interleave results through a queue (order not preserved).

    Workers ALWAYS enqueue a terminal sentinel — `None` on success, an
    error marker on failure — and the consumer polls with a bounded
    timeout while checking worker liveness, so a dead worker can't hang
    the loop. Fork context (readers are usually closures, which spawn
    cannot pickle — same tradeoff as the reference); note Python 3.12
    warns about forking a threaded (JAX) parent, hence the liveness
    guard."""
    import multiprocessing as mp
    import queue as _queue

    ctx = mp.get_context("fork")

    def reader():
        q = ctx.Queue(queue_size)
        procs = [ctx.Process(target=_mp_reader_worker, args=(r, q),
                             daemon=True) for r in readers]
        for p in procs:
            p.start()
        finished = 0
        try:
            while finished < len(readers):
                try:
                    sample = q.get(timeout=5)
                except _queue.Empty:
                    # a worker died without its sentinel (SIGKILL, fork
                    # deadlock): fail loudly instead of hanging forever
                    if all(not p.is_alive() for p in procs) and q.empty():
                        raise RuntimeError(
                            "multiprocess_reader: all workers exited "
                            "without completing")
                    continue
                if sample is None:
                    finished += 1
                elif isinstance(sample, _MpReaderError):
                    raise RuntimeError(
                        f"multiprocess_reader worker failed: {sample.msg}")
                else:
                    yield sample
        finally:
            for p in procs:
                p.join(timeout=5)
                if p.is_alive():
                    p.terminate()

    return reader


class _MpReaderError:
    def __init__(self, msg):
        self.msg = msg


def _mp_reader_worker(r, q):
    try:
        for sample in r():
            q.put(sample)
        q.put(None)
    except BaseException as e:  # sentinel must reach the consumer
        q.put(_MpReaderError(repr(e)))


class Fake:
    """reader/decorator.py Fake: replay the first epoch's samples forever —
    the reference's data-independent throughput-testing reader."""

    def __init__(self):
        self.data = None

    def __call__(self, reader, length):
        def fake_reader():
            if self.data is None:
                self.data = list(reader())
            total = 0
            while total < length:
                for sample in self.data:
                    if total >= length:
                        break
                    total += 1
                    yield sample

        return fake_reader


class _CreatorModule:
    """paddle.reader.creator (reader/creator.py): readers from raw
    sources."""

    @staticmethod
    def np_array(x):
        def reader():
            for row in x:
                yield row

        return reader

    @staticmethod
    def text_file(path):
        def reader():
            with open(path) as f:
                for line in f:
                    yield line.rstrip("\n")

        return reader


creator = _CreatorModule()


def pack_by_tokens(reader, src_budget, tgt_budget, pad_value=0):
    """Sequence packing for NMT-style (src, tgt) pair streams (VERDICT r3
    #2: replace pure bucketing's pad waste with packed rows).

    Packs consecutive sentence pairs into fixed-shape rows of
    ``src_budget``/``tgt_budget`` tokens. Where the reference gets its
    padding-free efficiency from LoD batching
    (/root/reference/paddle/fluid/framework/lod_tensor.h:104), the
    XLA-static-shape equivalent is one compiled shape whose rows are
    nearly pad-free: segment-id masks (see :func:`packed_attention_masks`)
    keep attention block-diagonal so packed sentences never see each
    other, exactly like separate rows.

    Yields dict rows (all 1-D numpy):
      src_ids  [Ts] int32   packed source tokens
      tgt_ids  [Tt] int32   packed decoder INPUT tokens (per-sentence
                            shift: sentence tokens t0..t_{l-2})
      lbl_ids  [Tt] int32   labels (t1..t_{l-1}); 0 = pad/ignore
      src_seg  [Ts] int32   1-based segment id per source token, 0 = pad
      tgt_seg  [Tt] int32   ditto for target positions
      src_pos  [Ts] int32   position WITHIN the segment (restarts at 0)
      tgt_pos  [Tt] int32   ditto

    A pair is added to the current row while both budgets hold; longer
    pairs than a whole row are dropped (bucketing's drop rule)."""
    def gen():
        def new_row():
            return {
                "src_ids": np.full(src_budget, pad_value, "int32"),
                "tgt_ids": np.full(tgt_budget, pad_value, "int32"),
                "lbl_ids": np.full(tgt_budget, pad_value, "int32"),
                "src_seg": np.zeros(src_budget, "int32"),
                "tgt_seg": np.zeros(tgt_budget, "int32"),
                "src_pos": np.zeros(src_budget, "int32"),
                "tgt_pos": np.zeros(tgt_budget, "int32"),
            }

        row, sp, tp, seg = new_row(), 0, 0, 0
        for sample in reader():
            src, tgt = sample[0], sample[1]
            ls, lt = len(src), len(tgt) - 1  # lt decoder positions
            if ls > src_budget or lt > tgt_budget or lt < 1:
                continue  # cannot fit any row
            if sp + ls > src_budget or tp + lt > tgt_budget:
                if seg:
                    yield row
                row, sp, tp, seg = new_row(), 0, 0, 0
            seg += 1
            row["src_ids"][sp:sp + ls] = src
            row["src_seg"][sp:sp + ls] = seg
            row["src_pos"][sp:sp + ls] = np.arange(ls)
            row["tgt_ids"][tp:tp + lt] = tgt[:-1][:lt]
            row["lbl_ids"][tp:tp + lt] = tgt[1:][:lt]
            row["tgt_seg"][tp:tp + lt] = seg
            row["tgt_pos"][tp:tp + lt] = np.arange(lt)
            sp += ls
            tp += lt
        if seg:
            yield row

    return gen


def packed_attention_masks(src_seg, tgt_seg, neg=-1e4):
    """Additive attention masks for a batch of packed rows
    (:func:`pack_by_tokens`): 0 where attention is allowed, ``neg``
    elsewhere. Segment ids gate everything — tokens only see their own
    sentence, so a packed batch computes exactly what separate padded
    rows would.

    src_seg [B,Ts], tgt_seg [B,Tt]  →
      enc_mask   [B,1,Ts,Ts]  block-diagonal self-attention
      self_mask  [B,1,Tt,Tt]  block-diagonal AND causal
      cross_mask [B,1,Tt,Ts]  target segment k ↔ source segment k
    """
    src_seg = np.asarray(src_seg)
    tgt_seg = np.asarray(tgt_seg)
    B, Ts = src_seg.shape
    Tt = tgt_seg.shape[1]
    sv = src_seg[:, :, None]  # [B,Ts,1]
    tv = tgt_seg[:, :, None]  # [B,Tt,1]
    enc = (sv == src_seg[:, None, :]) & (sv > 0)
    causal = np.tril(np.ones((Tt, Tt), bool))
    dec = (tv == tgt_seg[:, None, :]) & (tv > 0) & causal
    cross = (tv == src_seg[:, None, :]) & (tv > 0)
    to_add = lambda m: np.where(m, 0.0, neg).astype("float32")[:, None]
    return to_add(enc), to_add(dec), to_add(cross)
