"""Weight-decay regularizers appended to gradients.

Reference analog: ``python/paddle/fluid/regularizer.py`` — L1/L2 terms are
emitted as ops transforming each param's gradient before the optimizer op.
"""
from __future__ import annotations

from .layer_helper import LayerHelper


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        helper = LayerHelper("l2_decay")
        decay = helper.create_variable_for_type_inference(param.dtype)
        out = helper.create_variable_for_type_inference(param.dtype)
        block.append_op(type="scale", inputs={"X": [param.name]},
                        outputs={"Out": [decay.name]}, attrs={"scale": self._coeff})
        block.append_op(type="sum", inputs={"X": [grad.name, decay.name]},
                        outputs={"Out": [out.name]}, attrs={})
        return out


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        helper = LayerHelper("l1_decay")
        sign = helper.create_variable_for_type_inference(param.dtype)
        decay = helper.create_variable_for_type_inference(param.dtype)
        out = helper.create_variable_for_type_inference(param.dtype)
        block.append_op(type="sign", inputs={"X": [param.name]},
                        outputs={"Out": [sign.name]}, attrs={})
        block.append_op(type="scale", inputs={"X": [sign.name]},
                        outputs={"Out": [decay.name]}, attrs={"scale": self._coeff})
        block.append_op(type="sum", inputs={"X": [grad.name, decay.name]},
                        outputs={"Out": [out.name]}, attrs={})
        return out


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer


def append_regularization_ops(params_grads, regularization=None):
    """optimizer.apply_gradients hook (reference regularizer.py
    append_regularization_ops): per-param regularizer wins over global."""
    out = []
    for param, grad in params_grads:
        reg = getattr(param, "regularizer", None) or regularization
        if reg is None:
            out.append((param, grad))
            continue
        block = param.block.program.global_block()
        new_grad = reg(param, grad, block)
        out.append((param, new_grad))
    return out
