"""paddle_tpu.serving — dynamic-batching TPU inference serving.

Reference analog: the reference framework's serving story was
AnalysisPredictor clones with shared weights behind an RPC pool, each
clone running requests one-by-one through NaiveExecutor
(analysis_predictor.cc:479 clone path). The TPU-native redesign exploits
the opposite strength: one cached XLA executable per padded batch shape
means concurrent requests are cheapest when MERGED, so the serving tier
is a batching scheduler in front of one AOT Predictor:

- `DynamicBatcher` (batcher.py) — groups queued requests by feed
  signature, pads each group to a small fixed set of batch buckets
  (default 1/2/4/8/16/32), dispatches one Predictor call per bucket, and
  slices results back per request.
- `InferenceServer` (server.py) — threaded front end: bounded queue with
  reject-on-full backpressure, `max_batch_delay_ms` straggler window,
  per-request deadlines, graceful drain on stop().
- `warmup` (warmup.py) — compiles every (signature x bucket) executable
  ahead of serving so no user request ever pays an XLA compile.
- `Metrics` (metrics.py) — per-server registry of lock-protected
  counters/histograms (requests, batch-size distribution, queue depth,
  latency percentiles, timeouts, rejections) with a `snapshot()` dict
  and text `report()`. Built on `paddle_tpu.observability.Registry` and
  attached to the process-wide registry, so `InferenceServer.stats()`
  (or `observability.get_registry().snapshot()`) shows serving latency
  next to executor cache-hit/compile-time metrics in one export.

Minimal end-to-end::

    import paddle_tpu as fluid
    from paddle_tpu import inference, serving

    pred = inference.create_predictor(inference.Config(model_dir))
    server = serving.InferenceServer(pred, buckets=(1, 2, 4, 8, 16, 32),
                                     max_batch_delay_ms=2.0,
                                     max_queue_size=256)
    server.warmup()                       # compile all buckets up front
    with server:                          # start(); stop() drains on exit
        out, = server.infer({"x": batch_of_rows})
    print(server.metrics.report())
"""
from .batcher import (DEFAULT_BUCKETS, DynamicBatcher, ServingError,  # noqa: F401
                      bucket_for, item_signature)
from .metrics import Counter, Gauge, Histogram, Metrics  # noqa: F401
from .server import (InferenceServer, QueueFullError, Request,  # noqa: F401
                     ServerClosedError)
from .warmup import warmup  # noqa: F401

__all__ = [
    "DEFAULT_BUCKETS", "DynamicBatcher", "ServingError", "bucket_for",
    "item_signature", "Counter", "Gauge", "Histogram", "Metrics",
    "InferenceServer", "QueueFullError", "Request", "ServerClosedError",
    "fleet", "warmup",
]


def __getattr__(name):
    # lazy subpackage: `serving.fleet` without paying its import (and the
    # ps transport import underneath) on every `import paddle_tpu`
    if name == "fleet":
        import importlib
        mod = importlib.import_module(".fleet", __name__)
        globals()["fleet"] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
