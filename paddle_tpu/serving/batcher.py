"""Dynamic batcher: group requests by feed signature, pad to buckets.

Reference analog: the reference framework served concurrent users with
AnalysisPredictor *clones* — one predictor per worker thread, each running
batch-as-submitted through NaiveExecutor. On TPU the economics invert:
XLA compiles one executable per input shape, and a batch-32 matmul costs
barely more than batch-1, so the win is to MERGE concurrent requests into
one padded dispatch instead of running them on parallel clones.

The padding economics: serving traffic is ragged (any row count per
request), but compiling an executable per distinct total is unbounded
compile debt. So totals are padded up to a small fixed set of bucket
sizes (default powers of two, 1..32) — at most len(buckets) executables
per feed signature, and `warmup.warmup()` can compile ALL of them before
the first real request. Pad waste is bounded by ~2x worst case (power-of
-two buckets) and measured (`serving/padded_rows` counter), not guessed.
"""
from __future__ import annotations

import numpy as np

from typing import Dict, List, Optional, Sequence, Tuple

from .. import profiler
from .metrics import Metrics

__all__ = ["DEFAULT_BUCKETS", "DynamicBatcher", "ServingError",
           "bucket_for", "item_signature"]

DEFAULT_BUCKETS: Tuple[int, ...] = (1, 2, 4, 8, 16, 32)


class ServingError(RuntimeError):
    """Base class for serving-side failures."""


def bucket_for(n: int, buckets: Sequence[int]) -> Optional[int]:
    """Smallest bucket >= n, or None if n exceeds the largest bucket."""
    for b in buckets:
        if n <= b:
            return b
    return None


def item_signature(feed: Dict[str, np.ndarray]) -> tuple:
    """Per-ROW feed signature: (name, shape-without-batch-dim, dtype).

    Two requests batch together iff their item signatures match — then
    padding the concatenated rows to a bucket lands on exactly the
    executable-cache signature `core.executor.feed_signature` computes
    for the padded feed (same keying, batch dim aside)."""
    return tuple(sorted(
        (str(k), tuple(np.asarray(v).shape[1:]), str(np.asarray(v).dtype))
        for k, v in feed.items()))


class _Slot:
    """One request's rows inside an assembled batch."""

    __slots__ = ("request", "offset")

    def __init__(self, request, offset: int):
        self.request = request
        self.offset = offset


class DynamicBatcher:
    """Assemble same-signature requests into padded Predictor dispatches.

    Stateless between calls (the queueing lives in `server.InferenceServer`);
    `dispatch` takes a list of requests that already share an item
    signature, concatenates their rows, runs them through the predictor in
    bucket-padded chunks, and fulfils each request's future with its own
    row slice of every output.
    """

    def __init__(self, predictor, buckets: Sequence[int] = DEFAULT_BUCKETS,
                 metrics: Optional[Metrics] = None):
        buckets = sorted(set(int(b) for b in buckets))
        if not buckets or buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints; got {buckets}")
        self.predictor = predictor
        self.buckets = tuple(buckets)
        self.max_bucket = buckets[-1]
        self.metrics = metrics if metrics is not None else Metrics()

    # -- batch assembly ----------------------------------------------------
    def dispatch(self, requests: List) -> None:
        """Run `requests` (same item signature, each with .feed/.n/.future)
        and fulfil their futures. Never raises on predictor failure — the
        error is delivered through every affected future instead, so one
        bad batch cannot kill the serve loop."""
        reqs = [r for r in requests if not r.future.done()]
        if not reqs:
            return
        try:
            outs = self._run(reqs)
        except Exception as e:  # deliver, don't crash the worker
            self.metrics.counter("serving/errors").inc()
            from ..observability.flight import (get_flight_recorder,
                                                is_oom)
            if is_oom(e):
                # a device OOM answered through futures leaves no trace
                # otherwise — capture the post-mortem before delivering
                get_flight_recorder().record_failure(e, context={
                    "where": "DynamicBatcher.dispatch",
                    "requests": len(reqs),
                    "rows": sum(r.n for r in reqs)})
            for r in reqs:
                if not r.future.done():
                    r.future.set_exception(e)
            return
        off = 0
        for r in reqs:
            res = [o[off:off + r.n] for o in outs]
            off += r.n
            if not r.future.done():
                r.future.set_result(res)

    def _run(self, reqs: List) -> List[np.ndarray]:
        names = sorted(reqs[0].feed)
        total = sum(r.n for r in reqs)
        concat = {k: (np.concatenate([np.asarray(r.feed[k]) for r in reqs])
                      if len(reqs) > 1 else np.asarray(reqs[0].feed[k]))
                  for k in names}
        m = self.metrics
        m.counter("serving/batches").inc()
        m.histogram("serving/batch_rows").observe(total)
        parts: List[List[np.ndarray]] = []
        off = 0
        # a total beyond the largest bucket runs as a chain of full-bucket
        # chunks plus one padded remainder — no signature ever escapes the
        # bucket set
        while off < total:
            take = min(total - off, self.max_bucket)
            bucket = bucket_for(take, self.buckets)
            chunk = {k: v[off:off + take] for k, v in concat.items()}
            m.counter("serving/padded_rows").inc(bucket - take)
            m.histogram("serving/bucket").observe(bucket)
            # the annotation shows up in jax.profiler traces, in the
            # dispatched HLO metadata, AND as a host span in the
            # observability tracer's chrome-trace export — per-bucket
            # serving cost is visible in the same tooling as training
            # steps (profiler.record_event routes to both)
            with profiler.record_event(f"serving/dispatch_b{bucket}",
                                       rows=take, bucket=bucket):
                out = self.predictor.run_padded(chunk, bucket)
            for o in out:
                if not (getattr(o, "ndim", 0) and o.shape[0] == take):
                    raise ServingError(
                        f"serving requires batch-major outputs; fetch "
                        f"shape {getattr(o, 'shape', None)} has no leading "
                        f"batch dim of {take}")
            parts.append(out)
            off += take
        if len(parts) == 1:
            return parts[0]
        return [np.concatenate([p[i] for p in parts])
                for i in range(len(parts[0]))]
