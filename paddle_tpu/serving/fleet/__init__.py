"""paddle_tpu.serving.fleet — replica scale-out for the serving tier.

The fleet half of "millions of users": N shared-nothing
`InferenceServer` replicas (threads or SIGKILL-able subprocesses)
behind a `FleetRouter` that distributes requests (least-outstanding or
round-robin), sheds load off per-replica `/healthz` signals (degraded →
deprioritize, draining → stop sending, failing → eject, re-admit on
recovery) and replays idempotent requests on a different replica when
one dies mid-flight; a `ModelRegistry` of versioned, manifest-verified
model directories; `ServingFleet.rollout()` for zero-downtime weight
swaps (background-warm → atomic flip → drain, one replica at a time)
and `ab_split()` for weighted A/B between two live versions.

Multi-tenant co-hosting: `ServingFleet(..., tenants={...})` partitions
the replica pool by tenant weight (each partition serving its tenant's
model version), routes `infer(feed, tenant=...)` only within the
partition, throttles each tenant at its weighted admission share
(`TenantThrottledError`) and tracks per-tenant p99 against a declared
SLO (`tenant_stats()`).

PS-backed CTR serving plugs in through `predictor_factory`: build each
replica's predictor as an `inference.PsLookupPredictor` and the fleet
serves a big-table model while every replica holds only an LRU row
cache (rows pulled from the live `paddle_tpu.ps.ShardedTable`).

Minimal end-to-end::

    from paddle_tpu.serving import fleet

    reg = fleet.ModelRegistry()
    reg.register("v1", model_dir_v1)
    with fleet.ServingFleet(reg, "v1", replicas=3, mode="process") as f:
        out, = f.infer({"x": rows})
        reg.register("v2", model_dir_v2)
        f.rollout("v2")            # zero requests dropped
"""
from .fleet import ServingFleet  # noqa: F401
from .registry import ModelRegistry, ModelVersion  # noqa: F401
from .replica import (ProcessReplica, ReplicaDeadError,  # noqa: F401
                      ThreadReplica)
from .router import (FleetRouter, NoReplicaAvailableError,  # noqa: F401
                     TenantThrottledError)

__all__ = [
    "FleetRouter", "ModelRegistry", "ModelVersion",
    "NoReplicaAvailableError", "ProcessReplica", "ReplicaDeadError",
    "ServingFleet", "TenantThrottledError", "ThreadReplica",
]
