"""ServingFleet: registry + replicas + router, wired end to end.

The deployment object a serving host runs: build N shared-nothing
replicas (threads or subprocesses) for a registered model version, put
the FleetRouter in front, and drive lifecycle operations against
*versions*, never raw files:

* ``rollout(version)`` — zero-downtime fleet-wide weight swap. One
  replica at a time: background-warm the new version's executables
  (`warmup()` + the persistent compile cache make this cheap), flip
  atomically, drain the old server. The rest of the fleet keeps serving
  throughout, so fleet capacity never drops below N-1 warm replicas and
  no request is dropped.
* ``ab_split(version_b, weight_b)`` — swap a subset of replicas to
  version B and weight the router: weighted A/B between two live
  versions.
* ``submit()/infer()`` — the router's failover-wrapped request path.
"""
from __future__ import annotations

import math
import time

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..batcher import DEFAULT_BUCKETS
from .registry import ModelRegistry
from .replica import ProcessReplica, ThreadReplica
from .router import FleetRouter

__all__ = ["ServingFleet"]


class ServingFleet:
    def __init__(self, registry: ModelRegistry, version: Optional[str] = None,
                 replicas: int = 3, mode: str = "thread",
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 policy: str = "least_outstanding", warm: bool = True,
                 predictor_factory=None, example_feed=None,
                 server_kwargs: Optional[dict] = None,
                 env: Optional[dict] = None,
                 health_interval_s: Optional[float] = None, seed: int = 0):
        if replicas < 1:
            raise ValueError("a fleet needs at least one replica")
        if mode not in ("thread", "process"):
            raise ValueError(f"unknown fleet mode {mode!r}")
        if mode == "process" and predictor_factory is not None:
            raise ValueError("predictor_factory is thread-mode only (a "
                             "subprocess builds its own predictor)")
        self.registry = registry
        version = version if version is not None else registry.latest()
        if version is None:
            raise ValueError("registry is empty — register a version first")
        model = registry.resolve(version)
        self.mode = mode
        self._replicas: List = []
        if mode == "thread":
            for i in range(replicas):
                self._replicas.append(ThreadReplica(
                    f"replica-{i}", model, buckets=buckets,
                    predictor_factory=predictor_factory, warm=warm,
                    example_feed=example_feed, server_kwargs=server_kwargs))
        else:
            # spawn all workers first, then wait: startup cost is one
            # worker's wall time, not N of them
            for i in range(replicas):
                self._replicas.append(ProcessReplica(
                    f"replica-{i}", model, buckets=buckets, warm=warm,
                    env=env, server_kwargs=server_kwargs))
            for r in self._replicas:
                r.wait_ready()
        self.router = FleetRouter(self._replicas, policy=policy,
                                  health_interval_s=health_interval_s,
                                  seed=seed)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ServingFleet":
        self.router.start()
        return self

    def stop(self) -> dict:
        self.router.close()
        reports = {}
        for r in self._replicas:
            try:
                reports[r.name] = r.stop()
            except Exception as e:
                reports[r.name] = {"error": str(e)[:200]}
        return reports

    def __enter__(self) -> "ServingFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- request path -------------------------------------------------------
    def submit(self, feed: Dict[str, np.ndarray],
               timeout_ms: Optional[float] = None):
        return self.router.submit(feed, timeout_ms=timeout_ms)

    def infer(self, feed: Dict[str, np.ndarray],
              timeout_ms: Optional[float] = None) -> List[np.ndarray]:
        return self.router.infer(feed, timeout_ms=timeout_ms)

    # -- version management -------------------------------------------------
    @property
    def replicas(self) -> List:
        return list(self._replicas)

    def versions_live(self) -> Dict[str, int]:
        live: Dict[str, int] = {}
        for r in self._replicas:
            if r.alive:
                live[r.version] = live.get(r.version, 0) + 1
        return live

    def rollout(self, version: str,
                only: Optional[Sequence[str]] = None) -> dict:
        """Swap every live replica (or the named subset) to `version`,
        one at a time, each swap warm-then-flip-then-drain. Returns the
        per-replica swap reports; a replica that died mid-rollout is
        reported, not fatal (the rest of the fleet still converges)."""
        model = self.registry.resolve(version)
        t0 = time.monotonic()
        reports = {}
        names = set(only) if only is not None else None
        for r in self._replicas:
            if names is not None and r.name not in names:
                continue
            if not r.alive:
                reports[r.name] = {"skipped": "replica dead"}
                continue
            try:
                reports[r.name] = r.swap(model)
            except Exception as e:
                reports[r.name] = {"error": f"{type(e).__name__}: "
                                            f"{str(e)[:200]}"}
        # re-sweep now: replicas that looked draining mid-swap are
        # eligible again the moment their new server answers healthy
        self.router.sweep()
        return {"version": version, "wall_ms": (time.monotonic() - t0) * 1e3,
                "replicas": reports}

    def ab_split(self, version_b: str, weight_b: float = 0.5,
                 count: Optional[int] = None) -> dict:
        """Weighted A/B: swap `count` replicas (default: the weighted
        share, at least 1) to `version_b` and set router weights so
        traffic splits `1-weight_b` / `weight_b` between the versions."""
        if not 0.0 < weight_b < 1.0:
            raise ValueError("weight_b must be in (0, 1)")
        live = [r for r in self._replicas if r.alive]
        if len(live) < 2:
            raise ValueError("A/B needs at least 2 live replicas")
        if count is None:
            count = max(1, min(len(live) - 1,
                               int(math.floor(weight_b * len(live) + 0.5))))
        version_a = live[0].version
        report = self.rollout(version_b,
                              only=[r.name for r in live[-count:]])
        self.router.set_version_weights(
            {version_a: 1.0 - weight_b, version_b: weight_b})
        return report

    def stats(self) -> dict:
        return {"mode": self.mode, "versions_live": self.versions_live(),
                "router": self.router.stats()}
