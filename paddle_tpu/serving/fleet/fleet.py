"""ServingFleet: registry + replicas + router, wired end to end.

The deployment object a serving host runs: build N shared-nothing
replicas (threads or subprocesses) for a registered model version, put
the FleetRouter in front, and drive lifecycle operations against
*versions*, never raw files:

* ``rollout(version)`` — zero-downtime fleet-wide weight swap. One
  replica at a time: background-warm the new version's executables
  (`warmup()` + the persistent compile cache make this cheap), flip
  atomically, drain the old server. The rest of the fleet keeps serving
  throughout, so fleet capacity never drops below N-1 warm replicas and
  no request is dropped.
* ``ab_split(version_b, weight_b)`` — swap a subset of replicas to
  version B and weight the router: weighted A/B between two live
  versions.
* ``submit()/infer()`` — the router's failover-wrapped request path.

Multi-tenant co-hosting: pass ``tenants={name: {"version": v,
"weight": w, "slo_p99_ms": ms}}`` and the replica pool is partitioned
by weight (largest remainder, every tenant keeps at least one replica),
each partition serving its tenant's model version. Requests then carry
``tenant=``; the router enforces the weighted admission share and
tracks per-tenant p99 against the declared SLO (``tenant_stats()``).
``rollout(version, tenant=...)`` swaps one tenant's partition without
touching the others.
"""
from __future__ import annotations

import math
import time

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..batcher import DEFAULT_BUCKETS
from .registry import ModelRegistry
from .replica import ProcessReplica, ThreadReplica
from .router import FleetRouter

__all__ = ["ServingFleet"]


def _partition_by_weight(total: int, weights: Dict[str, float]) -> Dict[str, int]:
    """Split `total` replica slots across tenants proportional to weight:
    floor of the proportional quota on top of a guaranteed 1 each, then
    largest-remainder for what's left."""
    names = list(weights)
    if total < len(names):
        raise ValueError(f"{len(names)} tenants need at least "
                         f"{len(names)} replicas (got {total})")
    wsum = sum(weights.values())
    rest = total - len(names)
    quota = {n: weights[n] / wsum * rest for n in names}
    alloc = {n: 1 + int(math.floor(quota[n])) for n in names}
    leftover = total - sum(alloc.values())
    for n in sorted(names, key=lambda n: quota[n] - math.floor(quota[n]),
                    reverse=True)[:leftover]:
        alloc[n] += 1
    return alloc


class ServingFleet:
    def __init__(self, registry: ModelRegistry, version: Optional[str] = None,
                 replicas: int = 3, mode: str = "thread",
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 policy: str = "least_outstanding", warm: bool = True,
                 predictor_factory=None, example_feed=None,
                 server_kwargs: Optional[dict] = None,
                 env: Optional[dict] = None,
                 health_interval_s: Optional[float] = None, seed: int = 0,
                 tenants: Optional[Dict[str, dict]] = None,
                 tenant_capacity: Optional[int] = None):
        if replicas < 1:
            raise ValueError("a fleet needs at least one replica")
        if mode not in ("thread", "process"):
            raise ValueError(f"unknown fleet mode {mode!r}")
        if mode == "process" and predictor_factory is not None:
            raise ValueError("predictor_factory is thread-mode only (a "
                             "subprocess builds its own predictor)")
        self.registry = registry
        self.mode = mode
        self._tenants = tenants
        self._replicas: List = []

        def build(name, model, tenant=None):
            if mode == "thread":
                r = ThreadReplica(
                    name, model, buckets=buckets,
                    predictor_factory=predictor_factory, warm=warm,
                    example_feed=example_feed, server_kwargs=server_kwargs)
            else:
                r = ProcessReplica(name, model, buckets=buckets, warm=warm,
                                   env=env, server_kwargs=server_kwargs)
            r.tenant = tenant
            self._replicas.append(r)
            return r

        if tenants:
            # tenant partitions: each tenant's replicas serve its own
            # version; the int `replicas` is the total pool being split
            alloc = _partition_by_weight(
                replicas,
                {n: float(s.get("weight", 1.0)) for n, s in tenants.items()})
            for tname, spec in tenants.items():
                v = spec.get("version") or version or registry.latest()
                if v is None:
                    raise ValueError(f"tenant {tname!r} names no version "
                                     "and the registry is empty")
                model = registry.resolve(v)
                for i in range(alloc[tname]):
                    build(f"{tname}/replica-{i}", model, tenant=tname)
        else:
            version = version if version is not None else registry.latest()
            if version is None:
                raise ValueError(
                    "registry is empty — register a version first")
            model = registry.resolve(version)
            for i in range(replicas):
                build(f"replica-{i}", model)
        if mode == "process":
            # spawned all workers above; wait after, so startup cost is
            # one worker's wall time, not N of them
            for r in self._replicas:
                r.wait_ready()
        self.router = FleetRouter(self._replicas, policy=policy,
                                  health_interval_s=health_interval_s,
                                  seed=seed)
        if tenants:
            self.router.set_tenants(
                {n: {"weight": s.get("weight", 1.0),
                     "slo_p99_ms": s.get("slo_p99_ms")}
                 for n, s in tenants.items()},
                capacity=tenant_capacity)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ServingFleet":
        self.router.start()
        return self

    def stop(self) -> dict:
        self.router.close()
        reports = {}
        for r in self._replicas:
            try:
                reports[r.name] = r.stop()
            except Exception as e:
                reports[r.name] = {"error": str(e)[:200]}
        return reports

    def __enter__(self) -> "ServingFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- request path -------------------------------------------------------
    def submit(self, feed: Dict[str, np.ndarray],
               timeout_ms: Optional[float] = None,
               tenant: Optional[str] = None):
        return self.router.submit(feed, timeout_ms=timeout_ms, tenant=tenant)

    def infer(self, feed: Dict[str, np.ndarray],
              timeout_ms: Optional[float] = None,
              tenant: Optional[str] = None) -> List[np.ndarray]:
        return self.router.infer(feed, timeout_ms=timeout_ms, tenant=tenant)

    def tenant_stats(self) -> Optional[dict]:
        return self.router.tenant_stats()

    # -- version management -------------------------------------------------
    @property
    def replicas(self) -> List:
        return list(self._replicas)

    def versions_live(self) -> Dict[str, int]:
        live: Dict[str, int] = {}
        for r in self._replicas:
            if r.alive:
                live[r.version] = live.get(r.version, 0) + 1
        return live

    def rollout(self, version: str,
                only: Optional[Sequence[str]] = None,
                tenant: Optional[str] = None) -> dict:
        """Swap every live replica (or the named subset, or one tenant's
        partition) to `version`, one at a time, each swap
        warm-then-flip-then-drain. Returns the per-replica swap reports;
        a replica that died mid-rollout is reported, not fatal (the rest
        of the fleet still converges)."""
        model = self.registry.resolve(version)
        t0 = time.monotonic()
        reports = {}
        names = set(only) if only is not None else None
        for r in self._replicas:
            if tenant is not None and getattr(r, "tenant", None) != tenant:
                continue
            if names is not None and r.name not in names:
                continue
            if not r.alive:
                reports[r.name] = {"skipped": "replica dead"}
                continue
            try:
                reports[r.name] = r.swap(model)
            except Exception as e:
                reports[r.name] = {"error": f"{type(e).__name__}: "
                                            f"{str(e)[:200]}"}
        # re-sweep now: replicas that looked draining mid-swap are
        # eligible again the moment their new server answers healthy
        self.router.sweep()
        return {"version": version, "wall_ms": (time.monotonic() - t0) * 1e3,
                "replicas": reports}

    def ab_split(self, version_b: str, weight_b: float = 0.5,
                 count: Optional[int] = None) -> dict:
        """Weighted A/B: swap `count` replicas (default: the weighted
        share, at least 1) to `version_b` and set router weights so
        traffic splits `1-weight_b` / `weight_b` between the versions."""
        if not 0.0 < weight_b < 1.0:
            raise ValueError("weight_b must be in (0, 1)")
        live = [r for r in self._replicas if r.alive]
        if len(live) < 2:
            raise ValueError("A/B needs at least 2 live replicas")
        if count is None:
            count = max(1, min(len(live) - 1,
                               int(math.floor(weight_b * len(live) + 0.5))))
        version_a = live[0].version
        report = self.rollout(version_b,
                              only=[r.name for r in live[-count:]])
        self.router.set_version_weights(
            {version_a: 1.0 - weight_b, version_b: weight_b})
        return report

    def stats(self) -> dict:
        return {"mode": self.mode, "versions_live": self.versions_live(),
                "tenants": self.tenant_stats(),
                "router": self.router.stats()}
