"""Versioned model registry: which model bytes may a replica serve?

Reference analog: the reference stack's serving deployments pushed
versioned model directories to replicas and flipped a `fluid_model`
symlink; here the registry is the explicit object — every version is a
validated inference-model directory plus metadata (serving precision,
the training checkpoint step it was exported from), and the fleet's
rollout/A-B machinery only ever speaks version names.

Checkpoint lineage: pass ``checkpointer=``/``step=`` at register time
and the registry reads the checkpoint's SHA-256 manifest via
``Checkpointer.verified_steps()`` — a version can only claim lineage
from a step whose on-disk bytes actually verify, so a torn or corrupt
training checkpoint can never be promoted to serving.

Quantization promotion gate: registering ``precision="int8"`` requires
``calibration={"accuracy_delta": ..., "samples": ...}`` metadata (the
measurement `inference.quant.quantize_predictor_inplace` produces), and
the recorded delta must sit inside the accuracy budget — an
uncalibrated or out-of-budget int8 export can never be promoted to a
servable version.
"""
from __future__ import annotations

import os
import threading

from collections import OrderedDict
from typing import List, Optional

__all__ = ["ModelRegistry", "ModelVersion"]


class ModelVersion:
    """One registered serving model: name → validated model dir."""

    __slots__ = ("version", "model_dir", "precision", "meta")

    def __init__(self, version: str, model_dir: str,
                 precision: Optional[str], meta: dict):
        self.version = version
        self.model_dir = model_dir
        self.precision = precision
        self.meta = meta

    def __repr__(self):
        return (f"ModelVersion({self.version!r}, {self.model_dir!r}, "
                f"precision={self.precision!r})")


class ModelRegistry:
    """Thread-safe version-name → ModelVersion map (insertion ordered:
    `latest()` is the most recently registered version)."""

    def __init__(self):
        self._versions: "OrderedDict[str, ModelVersion]" = OrderedDict()
        self._lock = threading.Lock()

    def register(self, version: str, model_dir: str,
                 precision: Optional[str] = None,
                 model_filename: Optional[str] = None,
                 checkpointer=None, step: Optional[int] = None,
                 **meta) -> ModelVersion:
        """Validate and record a version. The model dir must exist and
        contain the model file; with `checkpointer` the claimed training
        `step` (default: its newest verified step) must pass manifest
        verification and is recorded as ``meta["checkpoint_step"]``."""
        if not os.path.isdir(model_dir):
            raise ValueError(
                f"registry: model dir {model_dir!r} does not exist")
        model_path = os.path.join(model_dir, model_filename or "__model__")
        if not os.path.isfile(model_path):
            raise ValueError(
                f"registry: {model_path!r} missing — not an inference "
                f"model dir (io.save_inference_model writes __model__)")
        if precision is not None and str(precision).lower() in ("int8", "i8"):
            from ...inference.quant import default_budget
            calib = meta.get("calibration")
            if not isinstance(calib, dict) or "accuracy_delta" not in calib:
                raise ValueError(
                    f"registry: version {version!r} claims int8 but has no "
                    "calibration metadata — pass calibration={'accuracy_"
                    "delta': ..., 'samples': ...} (quantize_predictor_"
                    "inplace measures it); refusing to promote an "
                    "uncalibrated quantized model")
            budget = float(calib.get("accuracy_budget", default_budget()))
            delta = float(calib["accuracy_delta"])
            if delta > budget:
                raise ValueError(
                    f"registry: version {version!r} int8 accuracy delta "
                    f"{delta:.6f} exceeds budget {budget:.6f} — refusing "
                    "to promote; recalibrate with more samples or raise "
                    "the budget explicitly")
        if checkpointer is not None:
            verified = checkpointer.verified_steps()
            if step is None:
                if not verified:
                    raise ValueError(
                        "registry: checkpointer has no verified steps to "
                        "claim lineage from")
                step = verified[0]
            elif step not in verified:
                raise ValueError(
                    f"registry: checkpoint step {step} is not verified "
                    f"(verified steps: {verified}) — refusing to promote "
                    f"unverifiable training bytes to serving")
            meta = dict(meta, checkpoint_step=int(step))
        mv = ModelVersion(version, model_dir, precision, dict(meta))
        with self._lock:
            if version in self._versions:
                raise ValueError(
                    f"registry: version {version!r} already registered "
                    f"(at {self._versions[version].model_dir!r}); "
                    f"versions are immutable — pick a new name")
            self._versions[version] = mv
        return mv

    def resolve(self, version: str) -> ModelVersion:
        with self._lock:
            try:
                return self._versions[version]
            except KeyError:
                raise KeyError(
                    f"registry: unknown version {version!r}; registered: "
                    f"{list(self._versions)}") from None

    def versions(self) -> List[str]:
        with self._lock:
            return list(self._versions)

    def latest(self) -> Optional[str]:
        with self._lock:
            return next(reversed(self._versions), None)

    def __contains__(self, version: str) -> bool:
        with self._lock:
            return version in self._versions

    def __len__(self) -> int:
        with self._lock:
            return len(self._versions)
