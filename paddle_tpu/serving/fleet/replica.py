"""Fleet replicas: one Predictor + InferenceServer per replica.

Two isolation levels behind one interface:

* ``ThreadReplica`` — the replica's `InferenceServer` lives in this
  process (shared-nothing by discipline: its own predictor, queue,
  metrics). What single-host fleets and most tests use — a "replica
  death" is a stopped server, failover is exercised without process
  machinery.
* ``ProcessReplica`` — a real subprocess running
  ``python -m paddle_tpu.serving.fleet.worker``, speaking the PS tier's
  length-prefixed JSON+blob frames (paddle_tpu.ps.transport — already
  pickle-free and hardened) over a loopback socket. SIGKILL-able: an
  in-flight request on a killed worker surfaces as a *transient*
  ``TransportError``, which is exactly what the router retries on
  another replica.

Both expose: ``submit() -> Future``, ``outstanding`` (the router's
least-outstanding signal), ``health()`` (the server's /healthz view —
state 'draining' tells the router to stop sending before admission
closes), ``swap(model)`` (background-warm the new version, then an
atomic flip + drain of the old server — zero dropped requests), and
``stop()`` / ``kill()``.
"""
from __future__ import annotations

import os
import queue
import socket
import subprocess
import sys
import threading
import time

from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Optional, Sequence

import numpy as np

from ..batcher import DEFAULT_BUCKETS, ServingError
from ..metrics import Metrics
from ..server import InferenceServer, QueueFullError, ServerClosedError
from ...observability import context as _trace_ctx
from ...observability.tracer import get_tracer
from ...ps.transport import TransportError, _recv_msg, _send_msg
from .registry import ModelVersion

__all__ = ["ProcessReplica", "ReplicaDeadError", "ThreadReplica"]


class ReplicaDeadError(ServingError):
    """The replica's process/server is gone; route elsewhere."""


def _default_factory(model: ModelVersion):
    from ...inference import Config, create_predictor
    return create_predictor(Config(model.model_dir),
                            precision=model.precision)


class ThreadReplica:
    """In-process replica: its own InferenceServer over its own
    predictor. `predictor_factory(model: ModelVersion)` customizes how a
    version's bytes become a predictor (e.g. wrap in a
    PsLookupPredictor for PS-backed serving)."""

    kind = "thread"

    def __init__(self, name: str, model: ModelVersion,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 predictor_factory=None, warm: bool = True,
                 example_feed: Optional[Dict[str, np.ndarray]] = None,
                 server_kwargs: Optional[dict] = None):
        self.name = name
        self._factory = predictor_factory or _default_factory
        self._buckets = tuple(buckets)
        self._warm = warm
        self._example_feed = example_feed
        self._server_kwargs = dict(server_kwargs or {})
        self._lock = threading.Lock()
        self._olock = threading.Lock()
        self._outstanding = 0
        self._killed = False
        self._model = model
        self._server = self._build_server(model)

    def _build_server(self, model: ModelVersion) -> InferenceServer:
        pred = self._factory(model)
        kw = dict(self._server_kwargs)
        # isolated metrics per replica server: N replicas (and their
        # swapped-out predecessors) must not fight over one metric name
        # space in the global registry
        kw.setdefault("metrics", Metrics(attach=False))
        srv = InferenceServer(pred, buckets=self._buckets, **kw)
        if self._warm:
            srv.warmup(example_feed=self._example_feed)
        srv.start()
        return srv

    # -- request path -------------------------------------------------------
    @property
    def outstanding(self) -> int:
        with self._olock:
            return self._outstanding

    def _track(self, fut: Future) -> Future:
        with self._olock:
            self._outstanding += 1

        def done(_):
            with self._olock:
                self._outstanding -= 1

        fut.add_done_callback(done)
        return fut

    def submit(self, feed: Dict[str, np.ndarray],
               timeout_ms: Optional[float] = None) -> Future:
        last: Optional[Exception] = None
        for _ in range(2):  # one retry: a swap may flip the server mid-call
            with self._lock:
                srv, killed = self._server, self._killed
            if srv is None or killed:
                raise ReplicaDeadError(f"replica {self.name} is dead")
            try:
                return self._track(srv.submit(feed, timeout_ms=timeout_ms))
            except ServerClosedError as e:
                last = e
        raise last

    def infer(self, feed, timeout_ms=None):
        return self.submit(feed, timeout_ms=timeout_ms).result()

    # -- observability ------------------------------------------------------
    def metrics(self) -> list:
        """This replica's serving metrics as a structured series list
        (`Registry.series` shape) — the federation scrape surface."""
        with self._lock:
            srv = self._server
        if srv is None:
            return []
        return srv.metrics.series(deep=True)

    def trace_export(self) -> dict:
        """Chrome-trace events from this replica's process — which for a
        thread replica is the host process tracer."""
        return get_tracer().export_chrome_trace()

    # -- lifecycle ----------------------------------------------------------
    @property
    def version(self) -> str:
        return self._model.version

    @property
    def model_dir(self) -> str:
        return self._model.model_dir

    @property
    def alive(self) -> bool:
        with self._lock:
            return self._server is not None and not self._killed

    def health(self) -> dict:
        with self._lock:
            srv, killed = self._server, self._killed
        if srv is None or killed:
            return {"status": "failing", "state": "dead",
                    "checks": {"replica": {"status": "failing",
                                           "detail": "replica stopped"}}}
        h = srv.health()
        h["version"] = self._model.version
        return h

    def swap(self, model: ModelVersion) -> dict:
        """Zero-downtime version swap: warm the new server while the old
        one keeps serving, flip atomically, then drain the old server so
        every admitted request completes. Returns
        {"version", "warm_ms", "drained": stop-report}."""
        t0 = time.monotonic()
        new_srv = self._build_server(model)
        warm_ms = (time.monotonic() - t0) * 1e3
        with self._lock:
            if self._killed or self._server is None:
                new_srv.stop(drain=False)
                raise ReplicaDeadError(
                    f"replica {self.name} died during swap warmup")
            old, self._server = self._server, new_srv
            self._model = model
        report = old.stop(drain=True)
        return {"version": model.version, "warm_ms": warm_ms,
                "drained": report}

    def stop(self) -> dict:
        with self._lock:
            srv, self._server = self._server, None
        if srv is None:
            return {"pending": 0, "completed": 0, "rejected": 0}
        return srv.stop(drain=True)

    def kill(self) -> None:
        """Abrupt death for failover tests: pending work fails, the
        replica reports dead, nothing is drained."""
        with self._lock:
            srv, self._server = self._server, None
            self._killed = True
        if srv is not None:
            srv.stop(drain=False)


def _map_worker_error(reply: dict) -> Exception:
    kind = reply.get("kind", "")
    msg = reply.get("err", "worker error")
    return {
        "QueueFullError": QueueFullError,
        "ServerClosedError": ServerClosedError,
        "TimeoutError": TimeoutError,
        "ValueError": ValueError,
    }.get(kind, ServingError)(msg)


class ProcessReplica:
    """Subprocess replica: a `fleet.worker` process serving the PS-tier
    frame protocol on loopback. The parent keeps a small socket pool
    (concurrent in-flight requests ride separate connections — the
    worker is thread-per-connection, so its InferenceServer still
    batches across them) and a thread pool that turns RPCs into
    Futures."""

    kind = "process"

    def __init__(self, name: str, model: ModelVersion,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 warm: bool = True, python: Optional[str] = None,
                 env: Optional[dict] = None, max_inflight: int = 8,
                 extra_args: Sequence[str] = (),
                 server_kwargs: Optional[dict] = None):
        self.name = name
        self._model = model
        self._buckets = tuple(buckets)
        self._rpc_timeout = float(
            os.environ.get("PDTPU_FLEET_RPC_TIMEOUT", "120"))
        self._swap_timeout = float(
            os.environ.get("PDTPU_FLEET_SWAP_TIMEOUT", "600"))
        self._olock = threading.Lock()
        self._outstanding = 0
        self._idle: "queue.SimpleQueue[socket.socket]" = queue.SimpleQueue()
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, int(max_inflight)),
            thread_name_prefix=f"fleet-{name}")
        self._port: Optional[int] = None
        self._ready = threading.Event()
        self._spawn_error: Optional[str] = None

        cmd = [python or sys.executable, "-m",
               "paddle_tpu.serving.fleet.worker",
               "--model-dir", model.model_dir,
               "--buckets", ",".join(str(b) for b in self._buckets)]
        if model.precision:
            cmd += ["--precision", model.precision]
        if not warm:
            cmd += ["--no-warm"]
        for k, v in (server_kwargs or {}).items():
            cmd += [f"--{k.replace('_', '-')}", str(v)]
        cmd += list(extra_args)
        env = dict(os.environ if env is None else env)
        # make `python -m paddle_tpu...` work from any cwd
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else pkg_root)
        self._proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, env=env)
        threading.Thread(target=self._read_stdout, daemon=True,
                         name=f"fleet-{name}-stdout").start()

    def _read_stdout(self) -> None:
        for line in self._proc.stdout:
            line = line.decode("utf-8", "replace").strip()
            if line.startswith("PDTPU_FLEET_WORKER_READY"):
                try:
                    self._port = int(line.rsplit("=", 1)[1])
                except ValueError:
                    self._spawn_error = f"bad ready line: {line!r}"
                self._ready.set()
            # keep draining so the worker never blocks on a full pipe
        self._ready.set()  # EOF: the worker exited

    def wait_ready(self, timeout: float = 300.0) -> "ProcessReplica":
        if not self._ready.wait(timeout):
            raise TransportError(
                f"replica {self.name}: worker not ready after {timeout}s",
                transient=False)
        if self._port is None:
            rc = self._proc.poll()
            raise TransportError(
                f"replica {self.name}: worker exited before ready "
                f"(rc={rc}, {self._spawn_error or 'no port line'})",
                transient=False)
        return self

    # -- RPC plumbing -------------------------------------------------------
    def _conn(self) -> socket.socket:
        try:
            return self._idle.get_nowait()
        except queue.Empty:
            pass
        s = socket.create_connection(("127.0.0.1", self._port),
                                     timeout=self._rpc_timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _rpc(self, op: str, timeout: Optional[float] = None, **kw):
        if self._port is None:
            self.wait_ready()
        if self._proc.poll() is not None:
            raise ReplicaDeadError(
                f"replica {self.name}: worker exited "
                f"rc={self._proc.returncode}")
        msg = {"op": op, **kw}
        # propagate the caller's trace into the worker process: fresh
        # client span, trace dict in the frame header (same carrier the
        # PS wire protocol uses)
        span = None
        tracer = get_tracer()
        ctx = _trace_ctx.current()
        if ctx is not None:
            rctx = ctx.child()
            msg["trace"] = rctx.to_wire()
            if tracer.enabled:
                span = f"fleet/rpc/{op}"
                tracer.begin(span, dict(rctx.args(), rpc="client", op=op,
                                        endpoint=f"127.0.0.1:{self._port}",
                                        replica=self.name))
        s = self._conn()
        try:
            s.settimeout(timeout if timeout is not None
                         else self._rpc_timeout)
            _send_msg(s, msg)
            reply = _recv_msg(s)
        except TransportError:
            s.close()
            raise
        except OSError as e:
            s.close()
            raise TransportError(f"{op}: {e}", transient=True,
                                 endpoint=f"127.0.0.1:{self._port}") from e
        finally:
            if span is not None:
                tracer.end(span)
        self._idle.put(s)
        if isinstance(reply, dict) and reply.get("err"):
            raise _map_worker_error(reply)
        return reply

    # -- request path -------------------------------------------------------
    @property
    def outstanding(self) -> int:
        with self._olock:
            return self._outstanding

    def _infer_rpc(self, feed, timeout_ms, ctx=None):
        feed = {k: np.asarray(v) for k, v in feed.items()}
        sock_timeout = (self._rpc_timeout if timeout_ms is None
                        else self._rpc_timeout + timeout_ms / 1e3)
        with _trace_ctx.use(ctx):
            reply = self._rpc("infer", feed=feed, timeout_ms=timeout_ms,
                              timeout=sock_timeout)
        return [np.asarray(o) for o in reply["out"]]

    def submit(self, feed: Dict[str, np.ndarray],
               timeout_ms: Optional[float] = None) -> Future:
        if self._proc.poll() is not None:
            raise ReplicaDeadError(
                f"replica {self.name}: worker exited "
                f"rc={self._proc.returncode}")
        with self._olock:
            self._outstanding += 1
        # the RPC runs on a pool thread; carry the submitter's trace over
        fut = self._pool.submit(self._infer_rpc, dict(feed), timeout_ms,
                                _trace_ctx.current())

        def done(_):
            with self._olock:
                self._outstanding -= 1

        fut.add_done_callback(done)
        return fut

    def infer(self, feed, timeout_ms=None):
        return self.submit(feed, timeout_ms=timeout_ms).result()

    # -- observability ------------------------------------------------------
    def metrics(self) -> list:
        """The worker process's full registry as a structured series
        list (serving + executor + PS-client metrics live there)."""
        return self._rpc("metrics", timeout=10.0)["series"]

    def trace_export(self) -> dict:
        """Chrome-trace events recorded inside the worker process —
        merged across processes by ``tools/timeline.py --fleet``."""
        return self._rpc("trace_export", timeout=30.0)["trace"]

    # -- lifecycle ----------------------------------------------------------
    @property
    def version(self) -> str:
        return self._model.version

    @property
    def model_dir(self) -> str:
        return self._model.model_dir

    @property
    def pid(self) -> int:
        return self._proc.pid

    @property
    def alive(self) -> bool:
        return self._proc.poll() is None

    def health(self) -> dict:
        if not self.alive:
            return {"status": "failing", "state": "dead",
                    "checks": {"process": {
                        "status": "failing",
                        "detail": f"worker exited "
                                  f"rc={self._proc.returncode}"}}}
        try:
            h = self._rpc("health", timeout=5.0)
        except Exception as e:
            return {"status": "failing", "state": "unreachable",
                    "checks": {"rpc": {"status": "failing",
                                       "detail": str(e)[:200]}}}
        h.setdefault("version", self._model.version)
        return h

    def swap(self, model: ModelVersion) -> dict:
        report = self._rpc("swap", model_dir=model.model_dir,
                           version=model.version,
                           precision=model.precision,
                           timeout=self._swap_timeout)
        self._model = model
        return report

    def stop(self) -> dict:
        report = {"pending": 0, "completed": 0, "rejected": 0}
        if self.alive:
            try:
                report = self._rpc("stop", timeout=30.0).get("report", report)
            except Exception:
                pass
        try:
            self._proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            self._proc.kill()
            self._proc.wait()
        self._close_io()
        return report

    def kill(self) -> None:
        """SIGKILL the worker — the failover drill. In-flight RPCs fail
        with transient TransportError; the router retries them on a
        different replica."""
        self._proc.kill()
        self._proc.wait()

    def _close_io(self) -> None:
        self._pool.shutdown(wait=False)
        while True:
            try:
                self._idle.get_nowait().close()
            except queue.Empty:
                break
            except OSError:
                pass
