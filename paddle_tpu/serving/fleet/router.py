"""FleetRouter: the connection-distributing frontend of the replica set.

Routing policy: ``least_outstanding`` (default — send to the eligible
replica with the fewest unresolved requests; a slow or swap-warming
replica naturally sheds load) or ``round_robin``.

Health-driven shedding, off the same signals `/healthz` serves:

* **degraded** replicas (full queue, draining grace, deadline misses)
  are *deprioritized* — chosen only when no healthy replica is eligible;
* a replica whose server state is **draining** is removed from rotation
  immediately (new work stops before its admission closes — the
  `stop(drain=True)` contract);
* **failing**/dead replicas are *ejected* and re-admitted automatically
  when a later health sweep sees them healthy again (a replica that was
  merely overloaded or mid-swap comes back; a SIGKILLed process does
  not).

Failover: a request whose replica dies mid-flight (transient
`TransportError`, `ServerClosedError`, `ReplicaDeadError`) is retried
on a different replica — inference is idempotent, so replay is safe.
Each replica is tried at most once per request; non-replica errors
(`TimeoutError`, `ValueError` from a bad feed) surface to the caller
unchanged. `QueueFullError` also fails over (another replica may have
room) but surfaces when every replica is full — backpressure stays
explicit at the fleet boundary.

Multi-tenant co-hosting (generalizing the A/B weight split): replicas
carry a ``tenant`` tag and ``set_tenants`` declares the tenant table —
relative capacity weight plus an optional p99 SLO per tenant. A request
submitted with ``tenant=`` routes only to that tenant's replicas, and
admission is capped at the tenant's weighted share of fleet capacity
(`TenantThrottledError` — a bursting tenant is throttled at the door
instead of queuing behind everyone else's work, which is what keeps the
*other* tenants' p99 flat). Per-tenant latency lands in a labelled
histogram; ``tenant_stats`` reports p99-vs-SLO per tenant.
"""
from __future__ import annotations

import os
import random
import threading
import time

from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..batcher import ServingError
from ..metrics import Metrics
from ..server import QueueFullError, ServerClosedError
from ...observability import context as _trace_ctx
from ...observability.tracer import trace_span
from ...ps.transport import TransportError
from .replica import ReplicaDeadError

__all__ = ["FleetRouter", "NoReplicaAvailableError", "TenantThrottledError"]

# a replica died under the request — replay it elsewhere
_FAILOVER_ERRORS = (TransportError, ServerClosedError, ReplicaDeadError,
                    ConnectionError, EOFError)


class NoReplicaAvailableError(ServingError):
    """Every replica is ejected, draining, or already tried."""


class TenantThrottledError(ServingError):
    """The tenant is at its weighted capacity share — back off and retry.

    Raised at admission, before any replica queue is touched: one
    tenant's burst must not consume fleet headroom another tenant's SLO
    depends on."""


class _ReplicaSlot:
    __slots__ = ("replica", "eligible", "degraded", "ejected")

    def __init__(self, replica):
        self.replica = replica
        self.eligible = True
        self.degraded = False
        self.ejected = False


class FleetRouter:
    def __init__(self, replicas: Sequence, policy: str = "least_outstanding",
                 health_interval_s: Optional[float] = None,
                 metrics: Optional[Metrics] = None, seed: int = 0):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        if policy not in ("least_outstanding", "round_robin"):
            raise ValueError(f"unknown policy {policy!r}")
        self._slots = [_ReplicaSlot(r) for r in replicas]
        self._by_name = {s.replica.name: s for s in self._slots}
        if len(self._by_name) != len(self._slots):
            raise ValueError("replica names must be unique")
        self.policy = policy
        self.metrics = metrics if metrics is not None else Metrics()
        self._lock = threading.Lock()
        self._rr = 0
        self._rng = random.Random(seed)
        self._weights: Optional[Dict[str, float]] = None
        # tenancy: {tenant: {"weight": normalized, "slo_p99_ms": float|None,
        #                    "share": max in-flight}} — None = single-tenant
        self._tenants: Optional[Dict[str, dict]] = None
        self._tenant_out: Dict[str, int] = {}
        self._interval = (health_interval_s if health_interval_s is not None
                          else float(os.environ.get(
                              "PDTPU_FLEET_HEALTH_INTERVAL", "0.5")))
        self._stop_evt = threading.Event()
        self._health_thread: Optional[threading.Thread] = None

    # -- health sweep -------------------------------------------------------
    def start(self) -> "FleetRouter":
        if self._health_thread is None:
            self.sweep()
            self._stop_evt.clear()
            t = threading.Thread(target=self._health_loop, daemon=True,
                                 name="fleet-health")
            self._health_thread = t
            t.start()
        return self

    def close(self) -> None:
        self._stop_evt.set()
        t, self._health_thread = self._health_thread, None
        if t is not None:
            t.join(timeout=5.0)

    def _health_loop(self) -> None:
        while not self._stop_evt.wait(self._interval):
            try:
                self.sweep()
            except Exception:
                pass  # a broken sweep must never kill routing

    def sweep(self) -> dict:
        """One health pass over every replica; returns the fleet view."""
        view = {}
        for slot in self._slots:
            r = slot.replica
            try:
                h = r.health() if r.alive else {"status": "failing",
                                                "state": "dead"}
            except Exception as e:
                h = {"status": "failing", "state": "unreachable",
                     "error": str(e)[:200]}
            status = h.get("status", "failing")
            state = h.get("state", "")
            with self._lock:
                if status == "failing" or state in ("dead", "stopped"):
                    if not slot.ejected:
                        slot.ejected = True
                        self.metrics.counter("fleet/ejections").inc()
                    slot.eligible = False
                elif state == "draining":
                    # cooperative drain: not dead, but take no new work
                    slot.eligible = False
                else:
                    if slot.ejected:
                        slot.ejected = False
                        self.metrics.counter("fleet/readmissions").inc()
                    slot.eligible = True
                    slot.degraded = (status == "degraded")
            view[r.name] = h
        with self._lock:
            live = sum(1 for s in self._slots if s.eligible)
        self.metrics.gauge("fleet/replicas_eligible").set(live)
        return view

    def _suspect(self, name: str) -> None:
        """Immediate demotion on an observed failure — don't keep routing
        to a corpse until the next sweep re-confirms it."""
        with self._lock:
            slot = self._by_name.get(name)
            if slot is not None and slot.eligible:
                slot.eligible = False

    # -- A/B ----------------------------------------------------------------
    def set_version_weights(self,
                            weights: Optional[Dict[str, float]]) -> None:
        """Weighted A/B routing across the versions currently served by
        the fleet (None restores version-blind routing). Weights are
        relative; versions with no eligible replica fall through to the
        rest of the fleet."""
        if weights is not None:
            total = sum(float(w) for w in weights.values())
            if total <= 0:
                raise ValueError("version weights must sum to > 0")
            weights = {v: float(w) / total for v, w in weights.items()}
        with self._lock:
            self._weights = weights

    # -- tenancy ------------------------------------------------------------
    def set_tenants(self, tenants: Optional[Dict[str, dict]],
                    capacity: Optional[int] = None) -> None:
        """Declare the tenant table: ``{name: {"weight": w,
        "slo_p99_ms": ms}}`` (None returns to single-tenant routing).
        ``capacity`` is the fleet-wide in-flight budget the weights
        divide (default: 8 × replica count); every tenant gets at least
        one admission slot."""
        if tenants is None:
            with self._lock:
                self._tenants = None
                self._tenant_out = {}
            return
        total = sum(float(t.get("weight", 1.0)) for t in tenants.values())
        if total <= 0:
            raise ValueError("tenant weights must sum to > 0")
        cap = int(capacity) if capacity is not None else 8 * len(self._slots)
        table = {}
        for name, spec in tenants.items():
            w = float(spec.get("weight", 1.0)) / total
            slo = spec.get("slo_p99_ms")
            table[name] = {"weight": w,
                           "slo_p99_ms": None if slo is None else float(slo),
                           "share": max(1, int(round(w * cap)))}
        with self._lock:
            self._tenants = table
            self._tenant_out = {name: 0 for name in table}

    def _admit(self, tenant: str) -> None:
        """Count the request against the tenant's capacity share."""
        with self._lock:
            table = self._tenants
            if table is None:
                return
            spec = table.get(tenant)
            if spec is None:
                raise ValueError(f"unknown tenant {tenant!r}; declared: "
                                 f"{sorted(table)}")
            if self._tenant_out[tenant] >= spec["share"]:
                self.metrics.counter("fleet/tenant_throttled",
                                     tenant=tenant).inc()
                raise TenantThrottledError(
                    f"tenant {tenant!r} at capacity share "
                    f"({spec['share']} in flight)")
            self._tenant_out[tenant] += 1

    def _release(self, tenant: str, t0: float, ok: bool) -> None:
        with self._lock:
            if self._tenants is not None and tenant in self._tenant_out:
                self._tenant_out[tenant] = max(
                    0, self._tenant_out[tenant] - 1)
        if ok:
            self.metrics.histogram("fleet/tenant_latency_ms",
                                   tenant=tenant).observe(
                (time.monotonic() - t0) * 1e3)

    def tenant_stats(self) -> Optional[dict]:
        """Per-tenant view: share, in-flight, request/throttle counts,
        observed p99 against the declared SLO (``slo_ok`` is None until
        latency samples exist)."""
        with self._lock:
            table = self._tenants
            if table is None:
                return None
            out = dict(self._tenant_out)
            table = {k: dict(v) for k, v in table.items()}
        stats = {}
        for name, spec in table.items():
            p99 = self.metrics.histogram(
                "fleet/tenant_latency_ms", tenant=name).percentile(99)
            slo = spec["slo_p99_ms"]
            stats[name] = {
                "weight": spec["weight"], "share": spec["share"],
                "outstanding": out.get(name, 0),
                "requests": self.metrics.counter(
                    "fleet/tenant_requests", tenant=name).value,
                "throttled": self.metrics.counter(
                    "fleet/tenant_throttled", tenant=name).value,
                "p99_ms": p99, "slo_p99_ms": slo,
                "slo_ok": (None if p99 is None or slo is None
                           else bool(p99 <= slo)),
            }
        return stats

    # -- replica choice -----------------------------------------------------
    def _pick(self, exclude: set, tenant: Optional[str] = None):
        with self._lock:
            cands = [s for s in self._slots
                     if s.eligible and s.replica.name not in exclude
                     and s.replica.alive]
            if tenant is not None:
                cands = [s for s in cands
                         if getattr(s.replica, "tenant", None) == tenant]
            if not cands:
                return None
            weights = self._weights
            if weights:
                present = [v for v in weights
                           if any(s.replica.version == v for s in cands)]
                if present:
                    r = self._rng.random() * sum(weights[v] for v in present)
                    acc = 0.0
                    chosen = present[-1]
                    for v in present:
                        acc += weights[v]
                        if r < acc:
                            chosen = v
                            break
                    cands = [s for s in cands
                             if s.replica.version == chosen]
            healthy = [s for s in cands if not s.degraded]
            pool = healthy or cands  # degraded → deprioritized, not dead
            if self.policy == "round_robin":
                self._rr += 1
                return pool[self._rr % len(pool)].replica
            return min(pool, key=lambda s: s.replica.outstanding).replica

    # -- request path -------------------------------------------------------
    def submit(self, feed: Dict[str, np.ndarray],
               timeout_ms: Optional[float] = None,
               tenant: Optional[str] = None) -> Future:
        """Route one request; the returned Future resolves to the output
        slices. Failover happens inside — the caller only ever sees a
        non-replica error or the final result. With ``tenant=`` the
        request is admission-checked against the tenant's capacity share
        (raising :class:`TenantThrottledError` synchronously) and routed
        only to that tenant's replicas."""
        outer: Future = Future()
        attempted: set = set()
        if tenant is not None:
            self._admit(tenant)  # raises before any queue is touched
            self.metrics.counter("fleet/tenant_requests",
                                 tenant=tenant).inc()
            t0 = time.monotonic()
            outer.add_done_callback(
                lambda f: self._release(tenant, t0, f.exception() is None))
        self.metrics.counter("fleet/requests").inc()
        # every routed request is one distributed trace: adopt the
        # caller's context or root a fresh one here — try_next may run
        # on a callback thread (failover), so the root is re-activated
        # explicitly at every attempt
        root = _trace_ctx.current() or _trace_ctx.new_trace()

        def try_next(last_error: Optional[Exception]) -> None:
            replica = self._pick(attempted, tenant)
            if replica is None:
                outer.set_exception(last_error or NoReplicaAvailableError(
                    f"no eligible replica"
                    + (f" for tenant {tenant!r}" if tenant else "")
                    + f" (tried {sorted(attempted)})"))
                return
            attempted.add(replica.name)
            try:
                with _trace_ctx.use(root), \
                        trace_span("fleet/route", replica=replica.name,
                                   attempt=len(attempted)):
                    inner = replica.submit(feed, timeout_ms=timeout_ms)
            except _FAILOVER_ERRORS as e:
                self._suspect(replica.name)
                self.metrics.counter("fleet/retries").inc()
                try_next(e)
                return
            except QueueFullError as e:
                self.metrics.counter("fleet/retries").inc()
                try_next(e)  # replica stays eligible — it is just full
                return
            except Exception as e:
                outer.set_exception(e)
                return

            def done(f: Future) -> None:
                exc = f.exception()
                if exc is None:
                    outer.set_result(f.result())
                elif isinstance(exc, _FAILOVER_ERRORS):
                    self._suspect(replica.name)
                    self.metrics.counter("fleet/retries").inc()
                    try_next(exc)
                elif isinstance(exc, QueueFullError):
                    self.metrics.counter("fleet/retries").inc()
                    try_next(exc)
                else:
                    outer.set_exception(exc)

            inner.add_done_callback(done)

        try_next(None)
        return outer

    def infer(self, feed: Dict[str, np.ndarray],
              timeout_ms: Optional[float] = None,
              tenant: Optional[str] = None) -> List[np.ndarray]:
        return self.submit(feed, timeout_ms=timeout_ms,
                           tenant=tenant).result()

    # -- introspection ------------------------------------------------------
    @property
    def replicas(self) -> List:
        return [s.replica for s in self._slots]

    def stats(self) -> dict:
        with self._lock:
            per = {s.replica.name: {
                "eligible": s.eligible, "degraded": s.degraded,
                "ejected": s.ejected, "alive": s.replica.alive,
                "version": s.replica.version,
                "tenant": getattr(s.replica, "tenant", None),
                "outstanding": s.replica.outstanding}
                for s in self._slots}
            weights = dict(self._weights) if self._weights else None
        return {"policy": self.policy, "replicas": per,
                "version_weights": weights,
                "tenants": self.tenant_stats(),
                "metrics": self.metrics.snapshot()}
