"""Fleet worker: one replica process = one InferenceServer + RPC loop.

``python -m paddle_tpu.serving.fleet.worker --model-dir DIR`` builds the
predictor, warms every (signature × bucket) executable, starts the
InferenceServer, then serves the PS tier's length-prefixed JSON+blob
frame protocol (paddle_tpu.ps.transport — pickle-free by construction)
on a loopback port. It prints ``PDTPU_FLEET_WORKER_READY port=<p>`` on
stdout once — and only once — traffic is safe, so the parent
(`ProcessReplica`) never routes to a cold replica.

Ops: ``infer`` (feed arrays → output arrays; user errors travel back as
``{"err", "kind"}`` and are re-raised client-side), ``health`` (the
server's /healthz view + state), ``swap`` (warm the new version in this
process, atomic flip, drain the old server — the in-process half of
zero-downtime rollout), ``ping``, ``metrics`` (this process's registry
as a structured series list — the federation scrape surface),
``trace_export`` (this process's chrome-trace events, merged across the
fleet by ``tools/timeline.py --fleet``), ``stop`` (drain, reply with
the drain report, exit).

Every frame may carry a ``trace`` header dict; the handler opens a
server-side span parented to the sender's span, so one routed request
is one trace across router → worker → pserver.

PS-backed serving: ``--ps-endpoints host:p,host:p --ps-table
PARAM=TABLE:VOCAB[:LANES] --ps-id-feeds ids`` wraps the predictor in a
`PsLookupPredictor` whose embedding rows live on pserver shards — the
subprocess equivalent of handing `PsLookupBinding`s to a ThreadReplica
factory (``--ps-cache-rows`` sizes the device-resident hot-row cache).

Thread-per-connection: concurrent parent connections land in the same
InferenceServer queue, so dynamic batching still merges them.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import sys
import threading

import numpy as np


def _handle_op(op, msg, replica, stop_evt, conn):
    """Dispatch one op; returns the reply dict, or None when the op
    already sent its reply (stop)."""
    from ...observability.registry import get_registry
    from ...observability.tracer import get_tracer
    from ...ps.transport import _send_msg
    if op == "ping":
        return {"ok": True, "pid": os.getpid()}
    if op == "infer":
        feed = {k: np.asarray(v)
                for k, v in (msg.get("feed") or {}).items()}
        outs = replica.infer(feed, timeout_ms=msg.get("timeout_ms"))
        return {"out": [np.asarray(o) for o in outs]}
    if op == "health":
        return replica.health()
    if op == "metrics":
        return {"series": get_registry().series(deep=True)}
    if op == "trace_export":
        return {"trace": get_tracer().export_chrome_trace()}
    if op == "swap":
        from .registry import ModelVersion
        mv = ModelVersion(msg["version"], msg["model_dir"],
                          msg.get("precision"), {})
        return replica.swap(mv)
    if op == "stop":
        report = replica.stop()
        _send_msg(conn, {"ok": True, "report": report})
        stop_evt.set()
        return None
    return {"err": f"unknown op {op!r}", "kind": "ValueError"}


def _serve_conn(conn, replica, stop_evt):
    from ...observability.tracer import server_span
    from ...ps.transport import TransportError, _recv_msg, _send_msg
    try:
        while not stop_evt.is_set():
            try:
                msg = _recv_msg(conn)
            except TransportError:
                return  # peer went away / torn frame: drop the connection
            op = msg.get("op") if isinstance(msg, dict) else None
            wire = msg.get("trace") if isinstance(msg, dict) else None
            try:
                # server half of the RPC span pair: adopts the parent's
                # trace_id so a routed request is one trace end to end
                with server_span(f"serve/{op}", wire, rpc="server",
                                 op=str(op)):
                    reply = _handle_op(op, msg, replica, stop_evt, conn)
                if reply is None:
                    return  # stop already replied
            except Exception as e:
                reply = {"err": str(e)[:500], "kind": type(e).__name__}
            _send_msg(conn, reply)
    except OSError:
        pass
    finally:
        try:
            conn.close()
        except OSError:
            pass


def _ps_predictor_factory(args):
    """Build a `predictor_factory` closing over the --ps-* flags: base
    predictor wrapped in a PsLookupPredictor over socket shard clients.
    Table spec grammar: ``PARAM=TABLE:VOCAB[:LANES]`` (repeatable)."""
    from ...inference import Config, create_predictor
    from ...inference.ps_lookup import PsLookupBinding, PsLookupPredictor
    from ...ps.shard import RangeSpec
    from ...ps.table import ShardedTable
    from ...ps.transport import SocketClient

    endpoints = [e.strip() for e in args.ps_endpoints.split(",")
                 if e.strip()]
    if not endpoints:
        raise SystemExit("--ps-endpoints: no endpoints given")
    specs = []
    for spec in args.ps_table:
        try:
            param, rest = spec.split("=", 1)
            parts = rest.split(":")
            table, vocab = parts[0], int(parts[1])
            lanes = int(parts[2]) if len(parts) > 2 else 128
        except (ValueError, IndexError):
            raise SystemExit(
                f"--ps-table {spec!r}: want PARAM=TABLE:VOCAB[:LANES]")
        specs.append((param, table, vocab, lanes))
    id_feeds = [f.strip() for f in (args.ps_id_feeds or "ids").split(",")
                if f.strip()]

    def factory(model):
        base = create_predictor(Config(model.model_dir),
                                precision=model.precision)
        bindings = []
        for param, table, vocab, lanes in specs:
            # each table gets its own client set: one connection per
            # shard per table keeps the fan-outs independent
            clients = [SocketClient(ep) for ep in endpoints]
            st = ShardedTable(table, RangeSpec.even(vocab, len(endpoints)),
                              clients, lanes=lanes)
            bindings.append(PsLookupBinding(param, st, id_feeds))
        return PsLookupPredictor(base, bindings,
                                 cache_rows_per_table=args.ps_cache_rows)

    return factory


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model-dir", required=True)
    ap.add_argument("--version", default="v0")
    ap.add_argument("--precision", default=None)
    ap.add_argument("--buckets", default="1,2,4,8,16,32")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--max-queue-size", type=int, default=256)
    ap.add_argument("--max-batch-delay-ms", type=float, default=2.0)
    ap.add_argument("--num-workers", type=int, default=1)
    ap.add_argument("--no-warm", action="store_true")
    ap.add_argument("--ps-endpoints", default=None,
                    help="host:port,host:port — pserver shards backing "
                         "the model's embedding tables")
    ap.add_argument("--ps-table", action="append", default=[],
                    help="PARAM=TABLE:VOCAB[:LANES] (repeatable)")
    ap.add_argument("--ps-id-feeds", default=None,
                    help="comma-separated id feed names (default: ids)")
    ap.add_argument("--ps-cache-rows", type=int, default=None,
                    help="device-resident hot-row cache size per table")
    args = ap.parse_args(argv)

    from ...observability.tracer import get_tracer
    from ..metrics import Metrics
    from .registry import ModelVersion
    from .replica import ThreadReplica

    # this process IS the replica: its serving metrics belong in the
    # process registry (the `metrics` op scrapes it), and its trace
    # events need a role-identifying process name for the fleet merge
    get_tracer().process_name = f"fleet-worker:{os.getpid()}"
    factory = _ps_predictor_factory(args) if args.ps_endpoints else None

    model = ModelVersion(args.version, args.model_dir, args.precision, {})
    replica = ThreadReplica(
        f"worker-{os.getpid()}", model,
        buckets=tuple(int(b) for b in args.buckets.split(",")),
        warm=not args.no_warm,
        predictor_factory=factory,
        server_kwargs={"max_queue_size": args.max_queue_size,
                       "max_batch_delay_ms": args.max_batch_delay_ms,
                       "num_workers": args.num_workers,
                       "metrics": Metrics()})

    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind((args.host, args.port))
    lsock.listen(64)
    lsock.settimeout(0.25)
    stop_evt = threading.Event()

    def on_term(signum, frame):
        stop_evt.set()

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)

    # the readiness line the parent blocks on — executables are compiled,
    # the server is started, the port is bound
    print(f"PDTPU_FLEET_WORKER_READY port={lsock.getsockname()[1]}",
          flush=True)

    conns = []
    try:
        while not stop_evt.is_set():
            try:
                conn, _ = lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=_serve_conn,
                                 args=(conn, replica, stop_evt), daemon=True)
            t.start()
            conns.append(t)
    finally:
        lsock.close()
        if replica.alive:
            replica.stop()  # SIGTERM path: drain before exiting
    return 0


if __name__ == "__main__":
    sys.exit(main())
