"""Fleet worker: one replica process = one InferenceServer + RPC loop.

``python -m paddle_tpu.serving.fleet.worker --model-dir DIR`` builds the
predictor, warms every (signature × bucket) executable, starts the
InferenceServer, then serves the PS tier's length-prefixed JSON+blob
frame protocol (paddle_tpu.ps.transport — pickle-free by construction)
on a loopback port. It prints ``PDTPU_FLEET_WORKER_READY port=<p>`` on
stdout once — and only once — traffic is safe, so the parent
(`ProcessReplica`) never routes to a cold replica.

Ops: ``infer`` (feed arrays → output arrays; user errors travel back as
``{"err", "kind"}`` and are re-raised client-side), ``health`` (the
server's /healthz view + state), ``swap`` (warm the new version in this
process, atomic flip, drain the old server — the in-process half of
zero-downtime rollout), ``ping``, ``stop`` (drain, reply with the drain
report, exit).

Thread-per-connection: concurrent parent connections land in the same
InferenceServer queue, so dynamic batching still merges them.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import sys
import threading

import numpy as np


def _serve_conn(conn, replica, stop_evt):
    from ...ps.transport import TransportError, _recv_msg, _send_msg
    try:
        while not stop_evt.is_set():
            try:
                msg = _recv_msg(conn)
            except TransportError:
                return  # peer went away / torn frame: drop the connection
            op = msg.get("op") if isinstance(msg, dict) else None
            try:
                if op == "ping":
                    reply = {"ok": True, "pid": os.getpid()}
                elif op == "infer":
                    feed = {k: np.asarray(v)
                            for k, v in (msg.get("feed") or {}).items()}
                    outs = replica.infer(feed,
                                         timeout_ms=msg.get("timeout_ms"))
                    reply = {"out": [np.asarray(o) for o in outs]}
                elif op == "health":
                    reply = replica.health()
                elif op == "swap":
                    from .registry import ModelVersion
                    mv = ModelVersion(msg["version"], msg["model_dir"],
                                      msg.get("precision"), {})
                    reply = replica.swap(mv)
                elif op == "stop":
                    report = replica.stop()
                    _send_msg(conn, {"ok": True, "report": report})
                    stop_evt.set()
                    return
                else:
                    reply = {"err": f"unknown op {op!r}", "kind": "ValueError"}
            except Exception as e:
                reply = {"err": str(e)[:500], "kind": type(e).__name__}
            _send_msg(conn, reply)
    except OSError:
        pass
    finally:
        try:
            conn.close()
        except OSError:
            pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model-dir", required=True)
    ap.add_argument("--version", default="v0")
    ap.add_argument("--precision", default=None)
    ap.add_argument("--buckets", default="1,2,4,8,16,32")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--max-queue-size", type=int, default=256)
    ap.add_argument("--max-batch-delay-ms", type=float, default=2.0)
    ap.add_argument("--num-workers", type=int, default=1)
    ap.add_argument("--no-warm", action="store_true")
    args = ap.parse_args(argv)

    from .registry import ModelVersion
    from .replica import ThreadReplica

    model = ModelVersion(args.version, args.model_dir, args.precision, {})
    replica = ThreadReplica(
        f"worker-{os.getpid()}", model,
        buckets=tuple(int(b) for b in args.buckets.split(",")),
        warm=not args.no_warm,
        server_kwargs={"max_queue_size": args.max_queue_size,
                       "max_batch_delay_ms": args.max_batch_delay_ms,
                       "num_workers": args.num_workers})

    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind((args.host, args.port))
    lsock.listen(64)
    lsock.settimeout(0.25)
    stop_evt = threading.Event()

    def on_term(signum, frame):
        stop_evt.set()

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)

    # the readiness line the parent blocks on — executables are compiled,
    # the server is started, the port is bound
    print(f"PDTPU_FLEET_WORKER_READY port={lsock.getsockname()[1]}",
          flush=True)

    conns = []
    try:
        while not stop_evt.is_set():
            try:
                conn, _ = lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=_serve_conn,
                                 args=(conn, replica, stop_evt), daemon=True)
            t.start()
            conns.append(t)
    finally:
        lsock.close()
        if replica.alive:
            replica.stop()  # SIGTERM path: drain before exiting
    return 0


if __name__ == "__main__":
    sys.exit(main())
