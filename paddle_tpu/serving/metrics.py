"""Serving metrics: lock-protected counters / gauges / histograms.

Reference analog: the reference framework's serving deployments counted
QPS and latency outside the framework (Paddle Serving's grpc metrics);
here the registry is in-process so the batcher/server can account every
request, batch, rejection, and timeout at the exact point it happens.

Design: tiny and allocation-light — a serving hot path touches these on
every request, so each metric holds one small lock (contention is
per-metric, not registry-wide) and `Histogram` keeps a fixed-size ring
of recent observations rather than an unbounded list: percentiles are
over the last `cap` samples, which is what a serving dashboard wants
anyway (recent tail, not all-time tail).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

__all__ = ["Counter", "Gauge", "Histogram", "Metrics"]


class Counter:
    """Monotonic counter (requests, batches, rejections, timeouts)."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (queue depth)."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, d: float) -> None:
        with self._lock:
            self._value += float(d)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Observation stream with all-time count/sum/min/max and percentiles
    over a fixed ring of the most recent `cap` observations."""

    def __init__(self, name: str, cap: int = 8192):
        self.name = name
        self._lock = threading.Lock()
        self._ring: List[float] = []
        self._cap = int(cap)
        self._idx = 0
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)
            if len(self._ring) < self._cap:
                self._ring.append(v)
            else:
                self._ring[self._idx] = v
                self._idx = (self._idx + 1) % self._cap

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def percentile(self, p: float) -> Optional[float]:
        """Nearest-rank percentile (p in [0, 100]) over the retained ring."""
        with self._lock:
            data = sorted(self._ring)
        if not data:
            return None
        rank = max(0, min(len(data) - 1,
                          int(round(p / 100.0 * (len(data) - 1)))))
        return data[rank]

    def snapshot(self) -> dict:
        with self._lock:
            n, s = self._count, self._sum
            lo, hi = self._min, self._max
            data = sorted(self._ring)

        def pct(p):
            if not data:
                return None
            return data[max(0, min(len(data) - 1,
                                   int(round(p / 100.0 * (len(data) - 1)))))]

        return {"count": n, "mean": (s / n) if n else None,
                "min": lo, "max": hi,
                "p50": pct(50), "p95": pct(95), "p99": pct(99)}


class Metrics:
    """Named registry; metrics are created on first use so the batcher and
    server never need None-checks on the hot path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            m = self._counters.get(name)
            if m is None:
                m = self._counters[name] = Counter(name)
            return m

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            m = self._gauges.get(name)
            if m is None:
                m = self._gauges[name] = Gauge(name)
            return m

    def histogram(self, name: str, cap: int = 8192) -> Histogram:
        with self._lock:
            m = self._histograms.get(name)
            if m is None:
                m = self._histograms[name] = Histogram(name, cap)
            return m

    def snapshot(self) -> dict:
        """One plain dict of everything — counters/gauges as numbers,
        histograms as their summary dicts."""
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            hists = list(self._histograms.values())
        out: dict = {}
        for c in counters:
            out[c.name] = c.value
        for g in gauges:
            out[g.name] = g.value
        for h in hists:
            out[h.name] = h.snapshot()
        return out

    def report(self) -> str:
        """Human-readable text table of the snapshot."""
        snap = self.snapshot()
        lines = [f"{'metric':<36}{'value':>44}"]
        for name in sorted(snap):
            v = snap[name]
            if isinstance(v, dict):
                parts = []
                for k in ("count", "mean", "p50", "p95", "p99", "max"):
                    x = v.get(k)
                    if x is None:
                        continue
                    parts.append(f"{k}={x:.3f}" if isinstance(x, float)
                                 else f"{k}={x}")
                v = " ".join(parts) or "-"
            lines.append(f"{name:<36}{str(v):>44}")
        return "\n".join(lines)
