"""Serving metrics: a per-server view onto the shared telemetry Registry.

Reference analog: the reference framework's serving deployments counted
QPS and latency outside the framework (Paddle Serving's grpc metrics);
here the registry is in-process so the batcher/server can account every
request, batch, rejection, and timeout at the exact point it happens.

Since the observability subsystem landed, the metric primitives
(`Counter`/`Gauge`/`Histogram`) and the registry machinery live in
``paddle_tpu.observability.registry`` — one implementation shared by the
executor, the serving tier, and user code. `Metrics` stays the serving
public API: an instance-scoped registry (two servers in one process keep
separate request counts) that ATTACHES itself to the process-wide
registry, so ``observability.get_registry().snapshot()`` shows serving
latency next to executor cache/compile metrics in one export, and
`InferenceServer.stats()` can surface the unified view.

Histogram snapshot/percentile reads are copy-on-read under the metric's
lock (the ring is copied before any sorting), so concurrent `observe()`
calls from serve workers can never corrupt a dashboard read — see the
threaded regression test in tests/test_observability.py.
"""
from __future__ import annotations

from ..observability.registry import (Counter, Gauge, Histogram,  # noqa: F401
                                      Registry, get_registry)

__all__ = ["Counter", "Gauge", "Histogram", "Metrics"]


class Metrics(Registry):
    """Instance-scoped metric registry for one server/batcher.

    Metrics are created on first use so the hot path never needs
    None-checks. By default the instance attaches to the process-wide
    registry (`observability.get_registry()`) as a child — weakly held,
    so a dropped server's metrics leave the global export automatically.
    Pass ``attach=False`` for a fully isolated registry.
    """

    def __init__(self, attach: bool = True):
        super().__init__()
        if attach:
            get_registry().attach(self)
