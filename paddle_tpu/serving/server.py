"""Threaded inference server: bounded queue + dynamic batching + deadlines.

Reference analog: a reference-framework serving deployment ran an RPC
front end over a pool of AnalysisPredictor clones (shared weights, one
NaiveExecutor loop each). Here the front end is in-process: callers
`submit()` feeds from any thread, a serve worker drains the queue,
merges same-signature requests up to the largest batch bucket (waiting
at most `max_batch_delay_ms` for stragglers), and one padded XLA
dispatch serves the whole group (`batcher.DynamicBatcher`).

Overload behavior is explicit, not emergent: the queue is bounded and
`submit()` raises `QueueFullError` immediately when it is full
(reject-with-error backpressure — a serving tier should shed load at
admission, not time out deep in the queue); each request can carry a
deadline after which it is answered with `TimeoutError` instead of
occupying a batch slot; `stop()` refuses new work and drains what was
already admitted.
"""
from __future__ import annotations

import os
import threading
import time

from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence

import numpy as np

from .batcher import (DEFAULT_BUCKETS, DynamicBatcher, ServingError,
                      item_signature)
from .metrics import Metrics
from ..observability import context as _trace_ctx
from ..observability.http import (maybe_serve_from_env,
                                  register_health_check,
                                  unregister_health_check)
from ..observability.tracer import trace_span

__all__ = ["InferenceServer", "QueueFullError", "Request", "ServerClosedError",
           "ServingError"]

# distinguishes health-check names when several servers live in one
# process ("serving/queue", then "serving#2/queue", ...)
_server_seq_lock = threading.Lock()
_server_seq = [0]


class QueueFullError(ServingError):
    """Backpressure: the bounded request queue is at max_queue_size."""


class ServerClosedError(ServingError):
    """submit() after stop()."""


class Request:
    """One admitted inference request: `feed` arrays all carry a leading
    batch dim of `n` rows; `future` resolves to the per-request output
    slices (list of np arrays, one per fetch)."""

    __slots__ = ("feed", "n", "sig", "future", "deadline", "enqueued_at",
                 "ctx")

    def __init__(self, feed: Dict[str, np.ndarray], n: int, sig: tuple,
                 deadline: Optional[float], enqueued_at: float,
                 ctx=None):
        self.feed = feed
        self.n = n
        self.sig = sig
        self.future: Future = Future()
        self.deadline = deadline
        self.enqueued_at = enqueued_at
        # trace context captured at submit() so the dispatch (and any PS
        # pulls under it) joins the submitter's distributed trace
        self.ctx = ctx

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline


class InferenceServer:
    """Dynamic-batching serve loop over an AOT Predictor.

    Usage::

        server = serving.InferenceServer(predictor, buckets=(1, 2, 4, 8),
                                         max_batch_delay_ms=2.0)
        server.warmup(example_feed={"x": np.zeros((1, 8), np.float32)})
        server.start()
        out, = server.infer({"x": x_row})          # blocking convenience
        fut = server.submit({"x": x_row})          # or async
        server.stop()                              # drains, then joins

    `num_workers` > 1 runs several serve workers over predictor clones
    (shared weights — the reference's clone optimization); useful when
    per-dispatch host work (padding, slicing) limits throughput, since
    XLA dispatches already overlap.
    """

    def __init__(self, predictor, buckets: Sequence[int] = DEFAULT_BUCKETS,
                 max_batch_delay_ms: float = 2.0, max_queue_size: int = 256,
                 default_timeout_ms: Optional[float] = None,
                 num_workers: int = 1, metrics: Optional[Metrics] = None):
        if max_queue_size < 1:
            raise ValueError("max_queue_size must be >= 1")
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.metrics = metrics if metrics is not None else Metrics()
        self._predictor = predictor
        self._batchers = [DynamicBatcher(predictor, buckets, self.metrics)]
        for _ in range(num_workers - 1):
            self._batchers.append(
                DynamicBatcher(predictor.clone(), buckets, self.metrics))
        self.buckets = self._batchers[0].buckets
        self.max_batch_delay = max(0.0, float(max_batch_delay_ms)) / 1e3
        self.max_queue_size = int(max_queue_size)
        self.default_timeout = (None if default_timeout_ms is None
                                else float(default_timeout_ms) / 1e3)
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._workers: List[threading.Thread] = []
        self._started = False
        self._closed = False
        self._draining = False
        self._inflight: set = set()   # popped from queue, future unresolved
        self._stop_lock = threading.Lock()
        self._stop_report: Optional[dict] = None
        self._health_names: List[str] = []
        self._health_fns = [("queue", self._check_queue),
                            ("deadlines", self._check_deadlines),
                            ("workers", self._check_workers)]

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "InferenceServer":
        with self._cond:
            if self._closed:
                raise ServerClosedError("server already stopped")
            if self._started:
                return self
            self._started = True
        for i in range(len(self._batchers)):
            t = threading.Thread(target=self._serve_loop,
                                 args=(self._batchers[i],),
                                 name=f"paddle_tpu-serve-{i}", daemon=True)
            self._workers.append(t)
            t.start()
        # k8s-probe readiness: queue/deadline/worker checks on /healthz,
        # and PDTPU_INTROSPECT_PORT alone brings the endpoints up
        self._register_health_checks()
        maybe_serve_from_env()
        return self

    def stop(self, drain: bool = True,
             grace_ms: Optional[float] = None) -> dict:
        """Refuse new submissions; with drain=True (default) every already
        admitted request is still served before the workers exit, with
        drain=False pending requests are failed with ServerClosedError.

        `grace_ms` (default `PDTPU_SERVE_DRAIN_GRACE_MS`, 0) keeps the
        queue OPEN for that long while `/healthz` already reports the
        degraded `draining` state — a router polling health stops sending
        new work before admission actually closes, so a cooperative fleet
        drains without a single rejected submit.

        Returns the drain report for the requests that were in flight
        (admitted but unresolved) at stop time:
        ``{"pending": n, "completed": served_ok, "rejected": failed}``.
        Idempotent — a second stop() returns the first report.
        """
        with self._stop_lock:
            if self._stop_report is not None:
                return dict(self._stop_report)
            with self._cond:
                self._draining = True
                self._cond.notify_all()
            if grace_ms is None:
                grace_ms = float(
                    os.environ.get("PDTPU_SERVE_DRAIN_GRACE_MS", "0"))
            if grace_ms > 0:
                time.sleep(grace_ms / 1e3)
            with self._cond:
                self._closed = True
                pending = list(self._queue) + list(self._inflight)
                # a never-started server has no workers to drain the queue
                if not drain or not self._started:
                    while self._queue:
                        r = self._queue.popleft()
                        if not r.future.done():
                            r.future.set_exception(ServerClosedError(
                                "server stopped without drain"))
                self.metrics.gauge("serving/queue_depth").set(len(self._queue))
                self._cond.notify_all()
            for t in self._workers:
                t.join()
            self._workers = []
            completed = sum(1 for r in pending
                            if r.future.done() and r.future.exception() is None)
            report = {"pending": len(pending), "completed": completed,
                      "rejected": len(pending) - completed}
            with self._cond:
                self._draining = False
                self._stop_report = report
            for name in self._health_names:
                unregister_health_check(name)
            self._health_names = []
            return dict(report)

    @property
    def state(self) -> str:
        """'idle' | 'serving' | 'draining' | 'stopped' — routers key on
        'draining' to stop sending new work before the queue closes."""
        with self._cond:
            if self._stop_report is not None:
                return "stopped"
            if self._draining:
                return "draining"
            if self._closed:
                return "stopped"
            return "serving" if self._started else "idle"

    # -- health checks (served at /healthz) --------------------------------
    def _check_queue(self):
        with self._cond:
            depth, cap = len(self._queue), self.max_queue_size
        if depth >= cap:
            return ("degraded",
                    f"queue full ({depth}/{cap}) — shedding load")
        if depth >= 0.8 * cap:
            return ("degraded", f"queue {depth}/{cap} (>= 80% full)")
        return ("ok", f"queue {depth}/{cap}")

    def _check_deadlines(self):
        req = self.metrics.counter("serving/requests").value
        missed = self.metrics.counter("serving/timeouts").value
        rate = missed / req if req else 0.0
        detail = f"{missed}/{req} requests missed their deadline"
        if rate > 0.5:
            return ("failing", detail)
        if rate > 0.05:
            return ("degraded", detail)
        return ("ok", detail)

    def _check_workers(self):
        with self._cond:
            started, closed, draining = (self._started, self._closed,
                                         self._draining)
        workers = list(self._workers)
        if draining:
            # degraded, not failing: admitted work is still being served —
            # a router should deprioritize, not declare the replica dead
            return ("degraded",
                    "draining — serving admitted requests, "
                    + ("admission closing soon" if not closed
                       else "admission closed"))
        if closed:
            return ("degraded", "server stopped")
        if not started:
            return ("degraded", "server not started")
        dead = sum(1 for t in workers if not t.is_alive())
        if dead:
            return ("failing",
                    f"{dead}/{len(workers)} serve workers dead — "
                    f"dispatch is stalled")
        return ("ok", f"{len(workers)} serve workers alive")

    def _register_health_checks(self) -> None:
        with _server_seq_lock:
            _server_seq[0] += 1
            seq = _server_seq[0]
        prefix = "serving" if seq == 1 else f"serving#{seq}"
        for short, fn in self._health_fns:
            name = f"{prefix}/{short}"
            register_health_check(name, fn)
            self._health_names.append(name)

    def health(self) -> dict:
        """This server's own /healthz view (no global registry involved):
        ``{"status": worst, "state": ..., "checks": {name: {status,
        detail}}}`` — what a fleet router polls per replica."""
        order = {"ok": 0, "degraded": 1, "failing": 2}
        checks = {}
        worst = "ok"
        for short, fn in self._health_fns:
            try:
                status, detail = fn()
            except Exception as e:  # a broken check is itself a failure
                status, detail = "failing", f"check raised: {e!r}"
            checks[short] = {"status": status, "detail": detail}
            if order.get(status, 2) > order[worst]:
                worst = status
        return {"status": worst, "state": self.state, "checks": checks}

    def __enter__(self) -> "InferenceServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def stats(self) -> dict:
        """Unified runtime snapshot: this server's serving metrics merged
        with the process-wide observability registry — executor
        executable-cache hits/misses, per-signature compile time, queue
        depth, and latency percentiles in ONE dict (the server's
        `Metrics` attaches itself to `observability.get_registry()` at
        construction). For only this server's metrics use
        ``server.metrics.snapshot()``."""
        from ..observability import get_registry

        snap = get_registry().snapshot(deep=True)
        # a detached Metrics (Metrics(attach=False)) must still show up
        # in its own server's stats
        for k, v in self.metrics.snapshot().items():
            snap.setdefault(k, v)
        return snap

    def warmup(self, example_feed: Optional[Dict[str, np.ndarray]] = None):
        """Compile every (signature x bucket) executable before serving
        (see serving.warmup.warmup)."""
        from .warmup import warmup as _warmup
        reports = [_warmup(b.predictor, self.buckets, example_feed)
                   for b in self._batchers]
        return reports[0] if len(reports) == 1 else reports

    # -- admission ---------------------------------------------------------
    def submit(self, feed: Dict[str, np.ndarray],
               timeout_ms: Optional[float] = None) -> Future:
        """Admit one request; returns a Future of its output slices.
        Raises QueueFullError (backpressure) or ServerClosedError
        immediately instead of blocking the caller."""
        feed = {k: np.asarray(v) for k, v in feed.items()}
        if not feed:
            raise ValueError("submit: empty feed")
        ns = {k: (v.shape[0] if v.ndim else -1) for k, v in feed.items()}
        n = next(iter(ns.values()))
        if n <= 0 or any(m != n for m in ns.values()):
            raise ValueError(
                f"submit: feeds must share one positive leading batch dim "
                f"(add [None] for single rows); got {ns}")
        now = time.monotonic()
        timeout = (self.default_timeout if timeout_ms is None
                   else float(timeout_ms) / 1e3)
        req = Request(feed, n, item_signature(feed),
                      None if timeout is None else now + timeout, now,
                      ctx=_trace_ctx.current())
        with self._cond:
            if self._closed:
                raise ServerClosedError("server is stopped")
            if len(self._queue) >= self.max_queue_size:
                self.metrics.counter("serving/rejected").inc()
                raise QueueFullError(
                    f"request queue full ({self.max_queue_size}); retry "
                    f"later or raise max_queue_size")
            self._queue.append(req)
            self.metrics.counter("serving/requests").inc()
            self.metrics.gauge("serving/queue_depth").set(len(self._queue))
            self._cond.notify()
        return req.future

    def infer(self, feed: Dict[str, np.ndarray],
              timeout_ms: Optional[float] = None) -> List[np.ndarray]:
        """Blocking convenience wrapper around submit()."""
        return self.submit(feed, timeout_ms=timeout_ms).result()

    # -- serve loop --------------------------------------------------------
    def _pop_group(self) -> Optional[List[Request]]:
        """Take the queue head plus every queued same-signature request up
        to the largest bucket; wait up to max_batch_delay for stragglers
        once a group is open. Returns None only at shutdown with an empty
        queue. Holds the lock except while sleeping on the condition."""
        max_rows = self._batchers[0].max_bucket
        with self._cond:
            while not self._queue:
                if self._closed:
                    return None
                self._cond.wait(0.05)
            group = [self._queue.popleft()]
            sig = group[0].sig
            rows = group[0].n

            def scoop():
                nonlocal rows
                i = 0
                while i < len(self._queue) and rows < max_rows:
                    if self._queue[i].sig == sig:
                        r = self._queue[i]
                        del self._queue[i]
                        group.append(r)
                        rows += r.n
                    else:
                        i += 1

            scoop()
            deadline = time.monotonic() + self.max_batch_delay
            # batch-delay gamble: trade a bounded sliver of latency for a
            # fuller bucket — but never wait once the bucket is full or the
            # server is draining
            while (rows < max_rows and not self._closed):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
                scoop()
            self.metrics.gauge("serving/queue_depth").set(len(self._queue))
        return group

    def _serve_loop(self, batcher: DynamicBatcher) -> None:
        while True:
            group = self._pop_group()
            if group is None:
                return
            now = time.monotonic()
            live: List[Request] = []
            for r in group:
                if r.expired(now):
                    self.metrics.counter("serving/timeouts").inc()
                    if not r.future.done():
                        r.future.set_exception(TimeoutError(
                            f"request missed its deadline after "
                            f"{(now - r.enqueued_at) * 1e3:.1f}ms in queue"))
                else:
                    live.append(r)
            if not live:
                continue
            with self._cond:
                self._inflight.update(live)
            t0 = time.monotonic()
            # adopt one request's trace for the batch dispatch — a batch
            # serves many requests but a span tree needs one parent; the
            # group-opener's context wins, and every PS pull under the
            # dispatch inherits it across the socket
            ctx = next((r.ctx for r in live if r.ctx is not None), None)
            try:
                with _trace_ctx.use(ctx), \
                        trace_span("serving/dispatch",
                                   batch=sum(r.n for r in live),
                                   requests=len(live)):
                    batcher.dispatch(live)
            finally:
                with self._cond:
                    self._inflight.difference_update(live)
            done = time.monotonic()
            lat = self.metrics.histogram("serving/latency_ms")
            wait = self.metrics.histogram("serving/queue_wait_ms")
            for r in live:
                lat.observe((done - r.enqueued_at) * 1e3)
                wait.observe((t0 - r.enqueued_at) * 1e3)
