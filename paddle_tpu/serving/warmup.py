"""Ahead-of-serve compilation: every (feed signature x bucket) executable.

Reference analog: serving deployments of the reference framework warmed
AnalysisPredictor by replaying recorded requests before opening the RPC
port. TPU serving makes this non-optional in spirit: the first request
at a never-seen padded shape pays an XLA compile (seconds), which is a
tail-latency cliff no production deployment should leak to users. Since
the batcher confines every dispatch to a fixed bucket set, the whole
executable space is finite and enumerable — so compile ALL of it before
taking traffic.
"""
from __future__ import annotations

import numpy as np

from typing import Dict, Optional, Sequence

from ..core.dtypes import convert_dtype
from .batcher import DEFAULT_BUCKETS

__all__ = ["warmup"]


def _example_rows(predictor, example_feed):
    """One example row (no batch dim) per feed name: taken from
    `example_feed` when given, else derived from the program's feed var
    shapes with dynamic dims defaulted to 1."""
    rows: Dict[str, np.ndarray] = {}
    blk = predictor._program.global_block()
    for name in predictor.get_input_names():
        if example_feed is not None and name in example_feed:
            ex = np.asarray(example_feed[name])
            rows[name] = ex[0] if ex.ndim else ex
            continue
        var = blk._find_var_recursive(name)
        if var is None:
            raise ValueError(f"warmup: feed var {name!r} not in program and "
                             f"no example_feed row given")
        shape = [1 if int(d) < 0 else int(d) for d in var.shape[1:]]
        rows[name] = np.zeros(shape, np.dtype(convert_dtype(var.dtype)))
    return rows

def warmup(predictor, buckets: Sequence[int] = DEFAULT_BUCKETS,
           example_feed: Optional[Dict[str, np.ndarray]] = None) -> dict:
    """Compile the executable for every bucket of the feed signature.

    `example_feed` (optional) supplies per-example shapes/dtypes for feeds
    with dynamic non-batch dims — pass one real request's feed (leading
    batch dim included); only row 0 is used. Feeds absent from it fall
    back to the program's declared var shapes.

    Returns {"buckets", "compiled", "cached", "signature"}: `compiled`
    counts fresh XLA compiles, `cached` the buckets that were already in
    the predictor's executable cache (warmup is idempotent).
    """
    rows = _example_rows(predictor, example_feed)
    compiled = 0
    cached = 0
    sig = None
    for b in sorted(set(int(x) for x in buckets)):
        feed = {k: np.broadcast_to(v, (b,) + v.shape).copy()
                for k, v in rows.items()}
        before = len(predictor._cache)
        predictor.run_padded(feed, b)
        sig = sig or tuple(sorted(
            (k, tuple(v.shape[1:]), str(v.dtype)) for k, v in feed.items()))
        if len(predictor._cache) > before:
            compiled += 1
        else:
            cached += 1
    return {"buckets": tuple(sorted(set(int(x) for x in buckets))),
            "compiled": compiled, "cached": cached, "signature": sig}
