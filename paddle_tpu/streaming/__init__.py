"""Streaming online learning — close the train→serve loop in seconds.

Reference analog: the reference's online-learning deployments run
``QueueDataset`` over a fleet data pipe (``train_from_dataset`` forever),
grow sparse tables on demand inside pslib (DownpourSparseTable's
accessors materialize unseen feasigns and decay/shrink cold ones), save
``delta`` checkpoints (``fleet.save_persistables(mode=delta)``) and push
fresh rows to Cube/serving on a cadence. This package is that loop,
TPU-native, over the PR 9–13 PS tier:

- ``StreamingDataset`` — unbounded ingestion: a generator/pipe source
  feeds ``train_from_dataset``/``PsEmbeddingTier.steps`` continuously,
  with a windowed held-out split peeled off the same stream for eval;
- ``ps.DynamicEmbeddingShard`` — the vocab is no longer provisioned
  up front: rows materialize on first pull and cold ids are swept out
  (TTL + watermark LFU), see ``paddle_tpu/ps/dynamic.py``;
- ``Checkpointer.save_delta`` — incremental checkpoints persist only
  rows touched since the chain head (the push journal IS the delta),
  see ``paddle_tpu/parallel/checkpoint.py``;
- ``DeltaPublisher`` — touched rows stream to serving replicas
  (``PsLookupPredictor.apply_delta``) within a bounded staleness budget;
- ``OnlineTrainer`` — the loop that wires all four together.
"""
from .dataset import StreamingDataset
from .delta_push import DeltaPublisher
from .trainer import OnlineTrainer, auc, eval_auc

__all__ = ["StreamingDataset", "DeltaPublisher", "OnlineTrainer", "auc",
           "eval_auc"]
