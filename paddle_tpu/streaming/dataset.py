"""StreamingDataset — unbounded ingestion for online learning.

Reference analog: QueueDataset over a data pipe (dataset.py:613 +
data_feed.cc MultiSlotDataFeed): the trainer never sees "an epoch", it
sees a socket/pipe that keeps producing MultiSlot records. Here the
source is any Python iterable/callable — a kafka consumer wrapper, a log
tailer, ``DataGenerator.iter_samples`` over raw lines, or MultiSlot text
lines — normalized into per-sample slot dicts and collated into the same
padded feed-dict batches ``QueueDataset.batches()`` emits, so
``Executor.train_from_dataset`` and ``PsEmbeddingTier.steps`` consume it
unchanged (it speaks the full DatasetBase protocol: ``set_batch_size`` /
``set_thread`` / ``set_use_var`` / ``batches()``).

Held-out eval WITHOUT a second pipeline: every ``held_out_every``-th
sample is diverted into a bounded window (``eval_window`` newest held-out
samples) instead of the training batch. ``eval_batches()`` snapshots the
window — a rolling, time-local validation set, which is what online AUC
must be measured on (yesterday's eval set tells you nothing about drift).
"""
from __future__ import annotations

import collections
import threading
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..data_feeder import pad_batch_column
from ..observability import get_registry

__all__ = ["StreamingDataset", "parse_multislot_line"]


def parse_multislot_line(line: str, slots: Sequence[str],
                         slot_types: str = "") -> List[tuple]:
    """One MultiSlot text line → ``[(slot, values), ...]`` (the inverse of
    ``MultiSlotDataGenerator._gen_str``, same framing as the native C++
    parser): for each slot in order, a length then that many values."""
    toks = line.split()
    out = []
    pos = 0
    for i, name in enumerate(slots):
        if pos >= len(toks):
            raise ValueError(
                f"MultiSlot line ends before slot {name!r}: {line!r}")
        n = int(toks[pos])
        pos += 1
        if n < 1 or pos + n > len(toks):
            raise ValueError(
                f"slot {name!r} claims {n} values but the line has "
                f"{len(toks) - pos} left: {line!r}")
        kind = slot_types[i] if i < len(slot_types) else "i"
        conv = int if kind == "i" else float
        out.append((name, [conv(t) for t in toks[pos:pos + n]]))
        pos += n
    if pos != len(toks):
        raise ValueError(
            f"{len(toks) - pos} trailing tokens after the declared slots "
            f"({list(slots)}): {line!r}")
    return out


class StreamingDataset:
    """An unbounded sample stream with the Dataset batching protocol.

    ``source`` is a callable returning an iterator (re-invoked by every
    ``batches()`` call — a live tail), or a plain iterable (consumed
    once). Each item is one SAMPLE in any of three shapes:

    - a dict ``{slot: values}``,
    - a ``[(slot, values), ...]`` pair list (the ``DataGenerator``
      protocol — wire a reference generator via
      ``StreamingDataset(source=lambda: gen.iter_samples(lines))``),
    - a MultiSlot text line (requires ``slots=[...]``; parsed with the
      exact native framing).

    ``max_batches`` bounds one ``batches()`` drain (an online trainer
    alternates: drain a bounded slice, sweep/checkpoint/eval, drain
    again) — ``None`` streams until the source ends.
    """

    def __init__(self, source, *, slots: Optional[Sequence[str]] = None,
                 slot_types: str = "", batch_size: int = 1,
                 held_out_every: int = 0, eval_window: int = 1024,
                 max_batches: Optional[int] = None, drop_last: bool = True):
        self._source = source
        self._slots = list(slots) if slots else None
        self._slot_types = slot_types
        self._batch_size = int(batch_size)
        if held_out_every < 0:
            raise ValueError(f"held_out_every must be >= 0 (0 = no "
                             f"held-out split), got {held_out_every}")
        self._held_out_every = int(held_out_every)
        self._eval_win: "collections.deque" = collections.deque(
            maxlen=int(eval_window))
        self._eval_lock = threading.Lock()
        self._seen = 0
        self.max_batches = max_batches
        # online streams drop ragged tails by default: a one-off batch
        # shape costs a full XLA recompile mid-serving
        self._drop_last = bool(drop_last)
        self._use_var_names: List[str] = []
        self._thread_num = 1
        reg = get_registry()
        self._c_samples = reg.counter("stream/samples")
        self._c_held = reg.counter("stream/held_out_samples")
        self._c_batches = reg.counter("stream/batches")

    # -- DatasetBase protocol (train_from_dataset compatibility) ------------
    def set_batch_size(self, batch_size: int):
        if int(batch_size) < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self._batch_size = int(batch_size)

    def set_drop_last(self, drop_last: bool):
        self._drop_last = bool(drop_last)

    def set_thread(self, thread_num: int):
        # parse threading belongs to the upstream source here (the pipe /
        # consumer is the parallel part); recorded for protocol parity
        self._thread_num = int(thread_num)

    def set_use_var(self, var_list):
        self._use_var_names = [v.name for v in var_list]

    # -- normalization -------------------------------------------------------
    def _as_pairs(self, sample) -> List[tuple]:
        if isinstance(sample, str):
            if not self._slots:
                raise ValueError(
                    "StreamingDataset got a text line but no slots=[...] "
                    "schema to parse it with")
            return parse_multislot_line(sample, self._slots,
                                        self._slot_types)
        if isinstance(sample, dict):
            return list(sample.items())
        if isinstance(sample, (list, tuple)):
            return list(sample)
        raise ValueError(
            f"StreamingDataset sample must be a dict, a (slot, values) "
            f"pair list, or a MultiSlot text line; got {type(sample)}")

    def _samples(self):
        src = self._source() if callable(self._source) else self._source
        for sample in src:
            pairs = self._as_pairs(sample)
            self._seen += 1
            self._c_samples.inc()
            if (self._held_out_every
                    and self._seen % self._held_out_every == 0):
                with self._eval_lock:
                    self._eval_win.append(pairs)
                self._c_held.inc()
                continue
            yield pairs

    def _collate(self, batch: List[List[tuple]]) -> Dict[str, np.ndarray]:
        cols: Dict[str, list] = {}
        for pairs in batch:
            for name, values in pairs:
                cols.setdefault(name, []).append(np.asarray(values))
        want = self._use_var_names or list(cols)
        out: Dict[str, np.ndarray] = {}
        for name in want:
            if name not in cols:
                raise ValueError(
                    f"slot {name!r} (from set_use_var) missing from the "
                    f"stream; sample slots: {sorted(cols)}")
            if len(cols[name]) != len(batch):
                raise ValueError(
                    f"slot {name!r} present in only {len(cols[name])}/"
                    f"{len(batch)} samples — every sample must carry "
                    "every slot")
            arr, lens = pad_batch_column(cols[name])
            out[name] = arr
            if lens is not None:
                out[name + "_len"] = lens
        return out

    # -- the two taps --------------------------------------------------------
    def batches(self):
        """Training batches (held-out samples already diverted). Bounded
        by ``max_batches`` per call when set; the NEXT call resumes the
        same callable-source stream where this one left off only if the
        source itself is stateful (a generator object is; re-invoking a
        fresh list comprehension is not)."""
        it = self._samples()
        n = 0
        batch: List[List[tuple]] = []
        for pairs in it:
            batch.append(pairs)
            if len(batch) == self._batch_size:
                yield self._collate(batch)
                self._c_batches.inc()
                batch = []
                n += 1
                if self.max_batches is not None and n >= self.max_batches:
                    return
        if batch and not self._drop_last:
            yield self._collate(batch)
            self._c_batches.inc()

    def reader(self) -> Callable:
        """``PsEmbeddingTier.steps(dataset.reader())`` adapter."""
        return self.batches

    def eval_batches(self, batch_size: Optional[int] = None):
        """Collated batches over a SNAPSHOT of the held-out window (the
        stream keeps appending while eval runs; the snapshot keeps one
        eval internally consistent). Ragged tail kept — eval wants every
        sample, and it runs off the hot path."""
        with self._eval_lock:
            window = list(self._eval_win)
        bs = int(batch_size or self._batch_size)
        for i in range(0, len(window), bs):
            yield self._collate(window[i:i + bs])

    @property
    def eval_size(self) -> int:
        with self._eval_lock:
            return len(self._eval_win)

    def stats(self) -> dict:
        return {"samples": self._seen, "eval_window": self.eval_size,
                "batch_size": self._batch_size,
                "held_out_every": self._held_out_every}
