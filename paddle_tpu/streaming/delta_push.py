"""DeltaPublisher — stream freshly-trained rows to serving at bounded
staleness.

Reference analog: the online-learning deployments around the reference
push sparse-table deltas from trainers to the serving cache (Cube) on a
seconds cadence, instead of shipping whole-model checkpoints. Here the
trainer side already has a precise "what changed" signal: every update
the tier makes lands as a ``ShardedTable.push`` (the async pusher, hot-
slab write-backs, flush — all of them). The publisher taps that stream
with ``add_push_listener``, coalesces per-uid (last write wins — a hot id
pushed 50 times in a window ships once, with its newest bytes), and a
background thread flushes the pending set to subscribers every
``staleness_s/2`` seconds, so a row a serving replica already holds is
refreshed within ~``staleness_s`` of the trainer computing it.

Subscribers are callables ``fn(table_name, sorted_uids, rows)``:

- ``attach_predictor`` wires ``PsLookupPredictor.apply_delta`` — resident
  cache rows are overwritten in place, absent rows fault in from the PS
  shards (which applied the push before the listener ever fired, so the
  pull is coherent);
- ``attach_hot_cache`` wires ``HotRowCache.drop_rows`` for a device slab
  owned by ANOTHER process's tier (drop clean residents so the next
  touch re-pulls) — never attach a tier's publisher to its own slab.

The staleness CONTRACT (docs/migration.md "Online learning"): a pushed
row is visible to every subscriber within ``staleness_s`` plus one
subscriber-callback time, env-tunable via ``PDTPU_STREAM_STALENESS_S``
(seconds, default 2.0). Observed per-row staleness (flush time − push
time) feeds the ``stream/staleness_ms`` histogram and the local p50/p99
sample window the bench and soak assertions read. That number is the
publisher's HALF of the story; meta-aware subscribers (``subscribe(fn,
meta=True)``, which `attach_predictor` uses) additionally receive the
per-row enqueue stamps and record their own visibility time, closing
the TRUE train→serve audit as ``staleness/e2e_ms`` (push → visible in
the serving cache) — the histogram the ``DeltaStaleness`` SLO reads.
"""
from __future__ import annotations

import collections
import os
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..observability import get_registry

__all__ = ["DeltaPublisher"]


class DeltaPublisher:
    """Coalescing push-stream fan-out for one ``ShardedTable``."""

    def __init__(self, table, staleness_s: Optional[float] = None,
                 start: bool = True):
        if staleness_s is None:
            staleness_s = float(
                os.environ.get("PDTPU_STREAM_STALENESS_S", "2.0"))
        if staleness_s <= 0:
            raise ValueError(
                f"staleness_s must be > 0, got {staleness_s}")
        self.table = table
        self.staleness_s = float(staleness_s)
        self._subs: List[tuple] = []  # (fn, wants_meta)
        self._seq = 0
        # uid -> (row copy, enqueue time): last write wins, age is the
        # FIRST unflushed write's (the staleness bound is on the oldest
        # pending byte, not the newest)
        self._pending: Dict[int, tuple] = {}
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.staleness_samples: "collections.deque" = collections.deque(
            maxlen=4096)
        reg = get_registry()
        lbl = {"table": getattr(table, "name", "?")}
        self._c_rows = reg.counter("stream/delta_rows", **lbl)
        self._c_bytes = reg.counter("stream/delta_bytes", **lbl)
        self._c_flushes = reg.counter("stream/delta_flushes", **lbl)
        self._c_errors = reg.counter("stream/subscriber_errors", **lbl)
        self._h_staleness = reg.histogram("stream/staleness_ms", **lbl)
        table.add_push_listener(self._on_push)
        if start:
            self._thread = threading.Thread(
                target=self._run, name="delta_publisher", daemon=True)
            self._thread.start()

    # -- the tap (runs on whatever thread pushed) ---------------------------
    def _on_push(self, ids: np.ndarray, rows: np.ndarray) -> None:
        now = time.monotonic()
        rows = np.array(rows, np.uint16, copy=True)  # caller may reuse
        with self._lock:
            for j, u in enumerate(np.asarray(ids).tolist()):
                prev = self._pending.get(u)
                # newest bytes, oldest timestamp
                self._pending[u] = (rows[j],
                                    prev[1] if prev is not None else now)

    # -- fan-out -------------------------------------------------------------
    def subscribe(self, fn: Callable, meta: bool = False) -> None:
        """``fn(table_name, sorted_uids, rows)`` on every flush. Runs on
        the publisher thread — keep it bounded (a cache refresh, not a
        network round-trip per row).

        With ``meta=True`` the subscriber instead gets
        ``fn(table_name, sorted_uids, rows, meta=meta_dict)`` where the
        dict carries the staleness-auditor stamps: ``seq`` (flush
        number), ``published_t`` (monotonic flush time) and
        ``enqueue_t`` (float64 array aligned with `uids`: each row's
        FIRST unflushed push time). A meta-aware consumer records its
        own visibility time against these stamps, producing a true
        train→serve end-to-end freshness histogram instead of the
        publisher-half number `staleness_percentiles` sees."""
        self._subs.append((fn, bool(meta)))

    def attach_predictor(self, predictor) -> None:
        # meta-aware: the predictor stamps visibility per delta batch,
        # closing the e2e staleness audit (staleness/e2e_ms)
        self.subscribe(predictor.apply_delta, meta=True)

    def attach_hot_cache(self, hot_cache) -> None:
        self.subscribe(lambda name, uids, rows: hot_cache.drop_rows(uids))

    def flush(self) -> int:
        """Publish the pending set now (also the cadence thread's body).
        Returns #rows shipped."""
        now = time.monotonic()
        with self._lock:
            if not self._pending:
                return 0
            pending, self._pending = self._pending, {}
        uids = np.asarray(sorted(pending), np.int64)
        rows = np.stack([pending[int(u)][0] for u in uids])
        enqueue_t = np.asarray([pending[int(u)][1] for u in uids.tolist()],
                               np.float64)
        ages_ms = ((now - enqueue_t) * 1e3).tolist()
        for a in ages_ms:
            self._h_staleness.observe(a)
        self.staleness_samples.extend(ages_ms)
        name = getattr(self.table, "name", "?")
        self._seq += 1
        meta = {"seq": self._seq, "published_t": now,
                "enqueue_t": enqueue_t}
        for fn, wants_meta in list(self._subs):
            try:
                if wants_meta:
                    fn(name, uids, rows, meta=meta)
                else:
                    fn(name, uids, rows)
            except Exception:
                # one sick replica must not stall the stream (or lose the
                # flush for its siblings); it re-converges on its next
                # cache miss because the shards already hold these bytes
                self._c_errors.inc()
        self._c_rows.inc(int(uids.size))
        self._c_bytes.inc(int(rows.nbytes))
        self._c_flushes.inc()
        return int(uids.size)

    def _run(self) -> None:
        # half the budget per tick: a row enqueued right after a flush
        # still ships within ~staleness_s
        tick = max(0.01, self.staleness_s / 2.0)
        while not self._stop:
            self._wake.wait(tick)
            self._wake.clear()
            if self._stop:
                break
            try:
                self.flush()
            except Exception:
                self._c_errors.inc()

    def staleness_percentiles(self) -> dict:
        """{p50, p99, max} over the recent per-row staleness samples
        (ms) — the numbers the soak asserts against the budget."""
        s = list(self.staleness_samples)
        if not s:
            return {"p50": None, "p99": None, "max": None}
        arr = np.asarray(s, np.float64)
        return {"p50": float(np.percentile(arr, 50)),
                "p99": float(np.percentile(arr, 99)),
                "max": float(arr.max())}

    def close(self) -> None:
        """Detach from the table, stop the cadence thread, final flush."""
        try:
            self.table.remove_push_listener(self._on_push)
        except Exception:
            pass
        self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.flush()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
