"""OnlineTrainer — the loop that wires the four streaming pillars.

Reference analog: an online CTR job around the reference is a forever
loop of ``train_from_dataset`` over a data pipe, with pslib shrink/decay
on a timer, delta saves, and Cube pushes. Here:

    per step        tier.run_step over StreamingDataset batches
    sweep_every     table.sweep() — dynamic-vocab TTL/watermark eviction
    delta_every     checkpointer.save_delta — rows touched since chain head
    compact_every   every Nth delta becomes a FULL save (chain restart)
    eval_every      publisher flush + eval_fn over the held-out window

All cadences are in steps (an online "step" is the natural clock — wall
time cadences belong to the publisher, which already has one). The loop
is resumable: ``run(max_steps=k)`` drains k steps and returns, so a soak
interleaves training with serving assertions in the same process.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..observability import get_registry

__all__ = ["OnlineTrainer", "auc", "eval_auc"]


def auc(scores, labels) -> float:
    """Rank-based (Mann-Whitney) AUC with tied-score averaging — plain
    numpy, no sklearn in the container. NaN when the window is one-class
    (early stream): callers treat that as "no reading yet"."""
    scores = np.asarray(scores, np.float64).ravel()
    labels = np.asarray(labels, np.float64).ravel() > 0.5
    npos = int(labels.sum())
    nneg = int(labels.size) - npos
    if npos == 0 or nneg == 0:
        return float("nan")
    _, inv, counts = np.unique(scores, return_inverse=True,
                               return_counts=True)
    first_rank = np.cumsum(counts) - counts + 1  # 1-based
    avg_rank = first_rank + (counts - 1) / 2.0
    ranks = avg_rank[inv]
    return float((ranks[labels].sum() - npos * (npos + 1) / 2.0)
                 / (npos * nneg))


def eval_auc(dataset, score_fn: Callable, label_slot: str) -> float:
    """AUC of ``score_fn(feed) -> scores`` over the dataset's held-out
    window (``StreamingDataset.eval_batches``). Scoring through a
    ``PsLookupPredictor`` here is deliberate: the reading then measures
    exactly what serving would return, post-delta-push bytes included."""
    scores: List[np.ndarray] = []
    labels: List[np.ndarray] = []
    for feed in dataset.eval_batches():
        lbl = np.asarray(feed[label_slot]).ravel()
        s = np.asarray(score_fn(feed)).ravel()
        if s.size != lbl.size:
            raise ValueError(
                f"eval_auc: score_fn returned {s.size} scores for "
                f"{lbl.size} labels")
        scores.append(s)
        labels.append(lbl)
    if not scores:
        return float("nan")
    return auc(np.concatenate(scores), np.concatenate(labels))


class OnlineTrainer:
    """Drive a ``PsEmbeddingTier`` over a ``StreamingDataset`` with the
    online-learning cadences. All collaborators are optional — a bare
    (exe, program, tier, dataset) runs forever with no sweeps, no
    checkpoints, no eval; each cadence activates when its knob is > 0
    AND its collaborator is present."""

    def __init__(self, exe, program, tier, dataset, *,
                 fetch_list=None, scope=None,
                 ps_tables: Optional[Dict[str, object]] = None,
                 checkpointer=None,
                 publishers: Sequence = (),
                 sweep_every: int = 0, delta_every: int = 0,
                 compact_every: int = 0,
                 eval_every: int = 0,
                 eval_fn: Optional[Callable[[], float]] = None):
        self.exe = exe
        self.program = program
        self.tier = tier
        self.dataset = dataset
        self.fetch_list = list(fetch_list or [])
        self.scope = scope
        self.ps_tables = dict(ps_tables or {})
        self.ck = checkpointer
        self.publishers = list(publishers)
        if (delta_every or compact_every) and (
                checkpointer is None or not self.ps_tables):
            raise ValueError(
                "delta_every/compact_every need checkpointer= and "
                "ps_tables= (a delta checkpoint IS the PS increment)")
        if sweep_every and not self.ps_tables:
            raise ValueError("sweep_every needs ps_tables= (the tables "
                             "whose dynamic shards get swept)")
        self.sweep_every = int(sweep_every)
        self.delta_every = int(delta_every)
        self.compact_every = int(compact_every)
        self.eval_every = int(eval_every)
        self.eval_fn = eval_fn
        self.step = 0
        self._deltas_since_full = 0
        self.history: Dict[str, list] = {"loss": [], "eval": [],
                                         "evicted": []}
        reg = get_registry()
        self._c_steps = reg.counter("stream/steps")
        self._c_sweeps = reg.counter("stream/sweeps")
        self._c_deltas = reg.counter("stream/delta_saves")
        self._c_fulls = reg.counter("stream/full_saves")
        self._c_evals = reg.counter("stream/evals")

    # -- cadence bodies ------------------------------------------------------
    def _sweep(self) -> int:
        evicted = 0
        for t in self.ps_tables.values():
            evicted += int(t.sweep())
        self._c_sweeps.inc()
        self.history["evicted"].append((self.step, evicted))
        return evicted

    def _checkpoint(self) -> None:
        self._deltas_since_full += 1
        if (self.compact_every
                and self._deltas_since_full >= self.compact_every):
            # compaction: a full save rewrites the table and re-anchors
            # the delta chain, bounding restore replay length
            self.ck.save(self.step, program=self.program, scope=self.scope,
                         ps_tables=self.ps_tables)
            self._deltas_since_full = 0
            self._c_fulls.inc()
        else:
            self.ck.save_delta(self.step, self.ps_tables)
            self._c_deltas.inc()

    def _eval(self) -> Optional[float]:
        for p in self.publishers:
            p.flush()  # eval must see the newest published bytes
        if self.eval_fn is None:
            return None
        v = float(self.eval_fn())
        self.history["eval"].append((self.step, v))
        self._c_evals.inc()
        return v

    # -- the loop ------------------------------------------------------------
    def run(self, max_steps: Optional[int] = None) -> int:
        """Drain up to ``max_steps`` training steps from the stream (None
        = until the source ends). Returns #steps run this call; the
        trainer's cadences and ``self.step`` carry across calls."""
        n = 0
        it = self.tier.steps(self.dataset.reader(), scope=self.scope)
        try:
            for prepared in it:
                fetched = self.tier.run_step(
                    self.exe, prepared, fetch_list=self.fetch_list,
                    scope=self.scope)
                self.step += 1
                n += 1
                self._c_steps.inc()
                if self.fetch_list:
                    self.history["loss"].append(
                        float(np.mean(np.asarray(fetched[0]))))
                if self.sweep_every and self.step % self.sweep_every == 0:
                    self._sweep()
                if (self.delta_every
                        and self.step % self.delta_every == 0):
                    self._checkpoint()
                if self.eval_every and self.step % self.eval_every == 0:
                    self._eval()
                if max_steps is not None and n >= max_steps:
                    break
        finally:
            it.close()  # deterministic prefetch-loader shutdown
        return n

    def finish(self) -> None:
        """End-of-run barrier: drain the tier's pushers, final publisher
        flush, and join any in-flight checkpoint write."""
        self.tier.flush()
        for p in self.publishers:
            p.flush()
        if self.ck is not None:
            self.ck.wait()
