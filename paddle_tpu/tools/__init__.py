"""Developer tooling (reference: tools/ + operators/benchmark/)."""
