"""Offline HBM budget planner CLI.

Answers "will this model fit, and under which (sharding stage, remat
policy, microbatch) config?" WITHOUT executing a training step: each
candidate on the planner ladder is lowered + compiled against shape
structs only, and XLA's ``memory_analysis()`` supplies the per-device
estimate. Prints the candidate table and the chosen plan as one JSON
line; exits 2 with the best-found plan when nothing fits.

CLI::

    python -m paddle_tpu.tools.hbm_plan --model nmt --batch 8 --seq 64
    python -m paddle_tpu.tools.hbm_plan --model bert --budget 4e9
    python -m paddle_tpu.tools.hbm_plan --model mlp --budget 16384 --json

``--budget`` accepts bytes (float ok: 4e9); without it the device's
``bytes_limit`` decides (CPU: unconstrained — every estimate is still
printed, the baseline plan wins).
"""
from __future__ import annotations

import argparse
import json
import sys

import numpy as np

from .. import planner


def _build_model(name: str, batch: int, seq: int):
    """(program, feed, loss_name) for a named bench model at the given
    shape — build only, nothing is initialized or run."""
    import paddle_tpu as fluid

    if name == "bert":
        from ..models import bert
        cfg = bert.BertConfig(num_layers=2, hidden_size=128, num_heads=4,
                              ffn_size=256, vocab_size=1000)
        program, _startup, _feeds, loss = bert.build_pretrain_program(
            cfg, batch, seq)
        rng = np.random.RandomState(0)
        feed = {
            "src_ids": rng.randint(0, 1000, (batch, seq)).astype("int32"),
            "pos_ids": np.tile(np.arange(seq), (batch, 1)).astype("int32"),
            "sent_ids": np.zeros((batch, seq), dtype="int32"),
            "input_mask": np.ones((batch, seq), dtype="float32"),
            "mlm_labels": rng.randint(0, 1000,
                                      (batch, seq, 1)).astype("int32"),
        }
        return program, feed, loss.name
    if name == "nmt":
        from ..models import transformer_nmt as nmt
        cfg = nmt.TransformerConfig(d_model=64, n_heads=4, d_ff=128,
                                    n_enc=2, n_dec=2, src_vocab=1000,
                                    tgt_vocab=1000)
        program, _startup, _feeds, loss = nmt.build_train_program(
            cfg, seq, seq)
        rng = np.random.RandomState(0)
        causal = np.triu(np.full((seq, seq), -1e4, "float32"), 1)
        feed = {
            "src_ids": rng.randint(1, 1000, (batch, seq)).astype("int32"),
            "tgt_ids": rng.randint(1, 1000, (batch, seq)).astype("int32"),
            "lbl_ids": rng.randint(1, 1000, (batch, seq, 1)).astype("int32"),
            "src_mask": np.zeros((batch, 1, 1, seq), "float32"),
            "tgt_mask": np.broadcast_to(causal, (batch, 1, seq, seq)).copy(),
        }
        return program, feed, loss.name
    if name == "mlp":
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", [64], dtype="float32")
            y = fluid.layers.data("y", [1], dtype="float32")
            h = fluid.layers.fc(x, 256, act="relu")
            h = fluid.layers.fc(h, 256, act="relu")
            out = fluid.layers.fc(h, 1)
            loss = fluid.layers.mean(fluid.layers.square(out - y))
            fluid.optimizer.Adam(1e-3).minimize(loss)
        rng = np.random.RandomState(0)
        feed = {"x": rng.rand(batch, 64).astype("float32"),
                "y": rng.rand(batch, 1).astype("float32")}
        return main, feed, loss.name
    raise SystemExit(f"unknown --model {name!r} (bert | nmt | mlp)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="hbm_plan",
        description="pre-compile HBM budget planning for a bench model")
    ap.add_argument("--model", default="mlp", help="bert | nmt | mlp")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--budget", type=float, default=None,
                    help="HBM budget in bytes/device (default: device "
                         "bytes_limit, unconstrained on CPU)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output only")
    args = ap.parse_args(argv)

    program, feed, loss_name = _build_model(args.model, args.batch, args.seq)
    budget = int(args.budget) if args.budget is not None else None

    try:
        plan = planner.plan_for(program, feed, loss_name,
                                budget_bytes=budget,
                                where=f"hbm_plan/{args.model}")
        chosen, candidates, code = plan, planner._last_candidates, 0
    except planner.HbmBudgetError as e:
        chosen, candidates, code = e.plan, e.candidates, 2

    out = {"model": args.model, "batch": args.batch, "seq": args.seq,
           "budget_bytes": budget,
           "fits": code == 0,
           "chosen": chosen.to_dict() if chosen else None,
           "candidates": [p.to_dict() for p in candidates]}
    if args.json:
        print(json.dumps(out))
        return code
    for p in candidates:
        mark = "*" if (chosen is not None
                       and (p.stage, p.remat, p.microbatch)
                       == (chosen.stage, chosen.remat, chosen.microbatch)) \
            else " "
        fit = {True: "fits", False: "over", None: "?"}[p.fits]
        print(f" {mark} {p.describe():<60} {fit}")
    if code == 0:
        print(f"chosen: {chosen.describe()}")
    else:
        print(f"NO FIT under {budget} bytes/device — best: "
              f"{chosen.describe() if chosen else 'none'}")
    print(json.dumps(out))
    return code


if __name__ == "__main__":
    sys.exit(main())
