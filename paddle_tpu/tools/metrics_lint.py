"""Static lint for metric names: catch drift before a scraper does.

The registry sanitizes names at export time (``/`` → ``_`` etc.), which
keeps hostile values scrapeable but also means two DIFFERENT raw names
can silently collide post-sanitization, and a typo'd name simply
becomes a new, empty series. This linter scans the package source for
``counter("..."``/``gauge("..."``/``histogram("..."`` string literals
and fails on:

* exposition-illegal raw names — anything outside
  ``[a-zA-Z_][a-zA-Z0-9_/]*`` (the repo convention: ``/`` namespacing,
  folded to ``_`` at export). A dash or colon would fold silently and
  is exactly the drift this lint exists to catch;
* the same raw name registered with conflicting metric types (a
  ``counter("x")`` here and a ``gauge("x")`` there renders two ``# TYPE``
  claims for one series — Prometheus rejects the page);
* two distinct raw names that sanitize to the same exposition name
  (post-fold collision).

It also lints the metrics-history JSONL spill (``PDTPU_HISTORY_DIR``
segments written by `observability.history.MetricsHistory`): every line
must be valid JSON with a numeric ``t`` and a ``series`` list whose
entries carry a legal ``name``, a string-valued ``labels`` dict, a
known ``field``, and a numeric ``v`` — the contract
`tools/postmortem.py --history-dir` replays offline. A torn FINAL line
of the NEWEST segment is tolerated (the process may have died
mid-write; that is the segment's whole purpose).

Wired as a plain pytest (tests/test_metrics_lint.py) so CI catches
metric-name drift on every run, and as a CLI::

    python -m paddle_tpu.tools.metrics_lint [root]
    python -m paddle_tpu.tools.metrics_lint --history /path/to/segments

Exit 0 when clean, 1 with one line per problem otherwise.
"""
from __future__ import annotations

import json
import os
import re
import sys
from typing import Dict, List, Tuple

__all__ = ["scan_file", "lint_source_tree", "lint_history_segments",
           "main"]

# the summary fields history.py extracts, plus plain value
_HISTORY_FIELDS = ("value", "p50", "p99", "count")

# reg.counter("name" / .gauge('name' / histogram("name" — a quote must
# immediately follow the paren, so definitions (`def counter(self, ...`)
# and f-strings (dynamic names are the caller's problem) don't match
_CALL_RE = re.compile(
    r"\b(counter|gauge|histogram)\(\s*(['\"])((?:[^'\"\\]|\\.)*)\2")

_LEGAL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_/]*$")


def _sanitized(name: str) -> str:
    from ..observability.registry import _prom_metric_name
    return _prom_metric_name(name)


def scan_file(path: str) -> List[Tuple[str, str, int]]:
    """(metric_type, raw_name, line_number) for every literal metric
    registration in `path`."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    out = []
    for m in _CALL_RE.finditer(src):
        line = src.count("\n", 0, m.start()) + 1
        out.append((m.group(1), m.group(3), line))
    return out


def lint_source_tree(root: str) -> List[str]:
    """One human-readable line per problem found under `root`
    (recursively, ``*.py``); empty list means clean."""
    sites: Dict[str, List[Tuple[str, str, int]]] = {}  # name -> uses
    problems: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for fn in sorted(filenames):
            # the linter's own docstring is full of deliberately-bad
            # example registrations
            if not fn.endswith(".py") or fn == "metrics_lint.py":
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            for mtype, name, line in scan_file(path):
                sites.setdefault(name, []).append((mtype, rel, line))
                if not _LEGAL_RE.match(name):
                    problems.append(
                        f"{rel}:{line}: illegal metric name {name!r} "
                        f"(must match [a-zA-Z_][a-zA-Z0-9_/]*)")
    # type conflicts: one raw name, more than one metric type
    for name in sorted(sites):
        types = sorted({t for t, _, _ in sites[name]})
        if len(types) > 1:
            where = ", ".join(f"{t} at {r}:{ln}"
                              for t, r, ln in sites[name])
            problems.append(
                f"metric {name!r} registered with conflicting types "
                f"{types}: {where}")
    # post-sanitization collisions between distinct raw names
    by_exposed: Dict[str, set] = {}
    for name in sites:
        by_exposed.setdefault(_sanitized(name), set()).add(name)
    for exposed, names in sorted(by_exposed.items()):
        if len(names) > 1:
            problems.append(
                f"raw names {sorted(names)} all sanitize to {exposed!r} "
                f"— they would merge into one exposition series")
    return problems


def lint_history_segments(history_dir: str) -> List[str]:
    """One line per problem in the ``history_*.jsonl`` spill segments
    under `history_dir`; empty list means clean (or no segments)."""
    problems: List[str] = []
    segs = sorted(f for f in os.listdir(history_dir)
                  if f.startswith("history_") and f.endswith(".jsonl"))
    for si, seg in enumerate(segs):
        path = os.path.join(history_dir, seg)
        with open(path, encoding="utf-8") as f:
            lines = f.read().split("\n")
        if lines and lines[-1] == "":
            lines.pop()
        last_t = None
        for ln, raw in enumerate(lines, 1):
            try:
                doc = json.loads(raw)
            except ValueError:
                if si == len(segs) - 1 and ln == len(lines):
                    continue  # torn final write of the live segment
                problems.append(f"{seg}:{ln}: not valid JSON")
                continue
            t = doc.get("t")
            if not isinstance(t, (int, float)):
                problems.append(f"{seg}:{ln}: missing numeric 't'")
            elif last_t is not None and t < last_t:
                problems.append(
                    f"{seg}:{ln}: timestamp moved backwards "
                    f"({t} < {last_t})")
            else:
                last_t = t
            series = doc.get("series")
            if not isinstance(series, list):
                problems.append(f"{seg}:{ln}: 'series' is not a list")
                continue
            for i, s in enumerate(series):
                where = f"{seg}:{ln} series[{i}]"
                name = s.get("name") if isinstance(s, dict) else None
                if not (isinstance(name, str) and _LEGAL_RE.match(name)):
                    problems.append(f"{where}: illegal name {name!r}")
                    continue
                if s.get("field") not in _HISTORY_FIELDS:
                    problems.append(
                        f"{where}: unknown field {s.get('field')!r} "
                        f"(one of {_HISTORY_FIELDS})")
                if not isinstance(s.get("v"), (int, float)):
                    problems.append(
                        f"{where}: non-numeric value {s.get('v')!r}")
                labels = s.get("labels")
                if labels is not None and not (
                        isinstance(labels, dict)
                        and all(isinstance(k, str) and isinstance(v, str)
                                for k, v in labels.items())):
                    problems.append(
                        f"{where}: labels must be a str->str dict")
    return problems


def default_root() -> str:
    """The paddle_tpu package directory (what CI lints)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    history_dirs = []
    while "--history" in args:
        i = args.index("--history")
        try:
            history_dirs.append(args[i + 1])
        except IndexError:
            print("metrics_lint: --history needs a directory",
                  file=sys.stderr)
            return 2
        del args[i:i + 2]
    if history_dirs:
        problems = []
        for d in history_dirs:
            problems += [f"{d}: {p}" for p in lint_history_segments(d)]
        for p in problems:
            print(p)
        if problems:
            print(f"metrics_lint: {len(problems)} problem(s) in "
                  f"history segments")
            return 1
        print(f"metrics_lint: history segments clean "
              f"({', '.join(history_dirs)})")
        return 0
    root = args[0] if args else default_root()
    problems = lint_source_tree(root)
    for p in problems:
        print(p)
    if problems:
        print(f"metrics_lint: {len(problems)} problem(s) under {root}")
        return 1
    print(f"metrics_lint: clean ({root})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
