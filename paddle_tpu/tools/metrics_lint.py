"""Static lint for metric names: catch drift before a scraper does.

The registry sanitizes names at export time (``/`` → ``_`` etc.), which
keeps hostile values scrapeable but also means two DIFFERENT raw names
can silently collide post-sanitization, and a typo'd name simply
becomes a new, empty series. This linter scans the package source for
``counter("..."``/``gauge("..."``/``histogram("..."`` string literals
and fails on:

* exposition-illegal raw names — anything outside
  ``[a-zA-Z_][a-zA-Z0-9_/]*`` (the repo convention: ``/`` namespacing,
  folded to ``_`` at export). A dash or colon would fold silently and
  is exactly the drift this lint exists to catch;
* the same raw name registered with conflicting metric types (a
  ``counter("x")`` here and a ``gauge("x")`` there renders two ``# TYPE``
  claims for one series — Prometheus rejects the page);
* two distinct raw names that sanitize to the same exposition name
  (post-fold collision).

Wired as a plain pytest (tests/test_metrics_lint.py) so CI catches
metric-name drift on every run, and as a CLI::

    python -m paddle_tpu.tools.metrics_lint [root]

Exit 0 when clean, 1 with one line per problem otherwise.
"""
from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Tuple

__all__ = ["scan_file", "lint_source_tree", "main"]

# reg.counter("name" / .gauge('name' / histogram("name" — a quote must
# immediately follow the paren, so definitions (`def counter(self, ...`)
# and f-strings (dynamic names are the caller's problem) don't match
_CALL_RE = re.compile(
    r"\b(counter|gauge|histogram)\(\s*(['\"])((?:[^'\"\\]|\\.)*)\2")

_LEGAL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_/]*$")


def _sanitized(name: str) -> str:
    from ..observability.registry import _prom_metric_name
    return _prom_metric_name(name)


def scan_file(path: str) -> List[Tuple[str, str, int]]:
    """(metric_type, raw_name, line_number) for every literal metric
    registration in `path`."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    out = []
    for m in _CALL_RE.finditer(src):
        line = src.count("\n", 0, m.start()) + 1
        out.append((m.group(1), m.group(3), line))
    return out


def lint_source_tree(root: str) -> List[str]:
    """One human-readable line per problem found under `root`
    (recursively, ``*.py``); empty list means clean."""
    sites: Dict[str, List[Tuple[str, str, int]]] = {}  # name -> uses
    problems: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for fn in sorted(filenames):
            # the linter's own docstring is full of deliberately-bad
            # example registrations
            if not fn.endswith(".py") or fn == "metrics_lint.py":
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            for mtype, name, line in scan_file(path):
                sites.setdefault(name, []).append((mtype, rel, line))
                if not _LEGAL_RE.match(name):
                    problems.append(
                        f"{rel}:{line}: illegal metric name {name!r} "
                        f"(must match [a-zA-Z_][a-zA-Z0-9_/]*)")
    # type conflicts: one raw name, more than one metric type
    for name in sorted(sites):
        types = sorted({t for t, _, _ in sites[name]})
        if len(types) > 1:
            where = ", ".join(f"{t} at {r}:{ln}"
                              for t, r, ln in sites[name])
            problems.append(
                f"metric {name!r} registered with conflicting types "
                f"{types}: {where}")
    # post-sanitization collisions between distinct raw names
    by_exposed: Dict[str, set] = {}
    for name in sites:
        by_exposed.setdefault(_sanitized(name), set()).add(name)
    for exposed, names in sorted(by_exposed.items()):
        if len(names) > 1:
            problems.append(
                f"raw names {sorted(names)} all sanitize to {exposed!r} "
                f"— they would merge into one exposition series")
    return problems


def default_root() -> str:
    """The paddle_tpu package directory (what CI lints)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    root = args[0] if args else default_root()
    problems = lint_source_tree(root)
    for p in problems:
        print(p)
    if problems:
        print(f"metrics_lint: {len(problems)} problem(s) under {root}")
        return 1
    print(f"metrics_lint: clean ({root})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
