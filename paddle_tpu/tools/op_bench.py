"""Per-op micro-benchmark harness.

Reference analog: ``paddle/fluid/operators/benchmark/op_tester.cc`` — a
config-driven runner that builds one op, feeds synthetic tensors, and
reports per-op latency (op_tester.cc:1; config format op_tester_config.cc).
BASELINE.md requires this harness to exist "from day one" since all speedup
claims are measured, not quoted.

TPU-native redesign: the op executes through the same Program→Executor→XLA
path as production (so the measurement includes our lowering, XLA fusion,
and dispatch), with an explicit compile warmup so steady-state latency is
reported separately from compile time.

CLI::

    python -m paddle_tpu.tools.op_bench --op matmul \
        --input X=256x256 --input Y=256x256 --repeat 200
    python -m paddle_tpu.tools.op_bench --config bench_ops.json

Config file: a JSON list of {"op", "inputs": {slot: {"shape", "dtype"}},
"attrs", "outputs", "repeat"}. Output: one JSON line per config with
{op, mean_us, min_us, p50_us, compile_ms, repeat}.
"""
from __future__ import annotations

import argparse
import json
import time
import zlib
from typing import Dict, List, Optional

import numpy as np


def _make_input(shape, dtype, seed=0):
    rng = np.random.RandomState(seed)
    if np.issubdtype(np.dtype(dtype), np.integer):
        return rng.randint(0, 8, size=shape).astype(dtype)
    return rng.rand(*shape).astype(dtype)


def bench_op(op_type: str, inputs: Dict[str, Dict], attrs: Optional[dict] = None,
             outputs: Optional[Dict[str, int]] = None, repeat: int = 100,
             warmup: int = 2) -> dict:
    """Build a one-op program, execute through the real Executor, time it.

    inputs: slot -> {"shape": [..], "dtype": "float32"} (or a list of such
    for multi-value slots). outputs: slot -> count (default {"Out": 1}).
    """
    import paddle_tpu as fluid

    attrs = attrs or {}
    outputs = outputs or {"Out": 1}
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        block = main.global_block()
        in_map, feed = {}, {}
        for slot, specs in inputs.items():
            specs = specs if isinstance(specs, list) else [specs]
            names = []
            for i, sp in enumerate(specs):
                a = _make_input(sp["shape"], sp.get("dtype", "float32"),
                                seed=(zlib.crc32(slot.encode()) + i) % 2 ** 31)
                name = f"{slot.lower()}_{i}"
                block.create_var(name=name, shape=a.shape, dtype=str(a.dtype),
                                 is_data=True)
                feed[name] = a
                names.append(name)
            in_map[slot] = names
        out_map = {}
        for slot, n in outputs.items():
            out_map[slot] = [f"out_{slot.lower()}_{i}" for i in range(n)]
            for nm in out_map[slot]:
                block.create_var(name=nm, dtype="float32")
        block.append_op(op_type, in_map, out_map, attrs)
        fetch = [nm for slot in sorted(out_map) for nm in out_map[slot]]

        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.TPUPlace())
            exe.run(startup)
            t0 = time.perf_counter()
            exe.run(main, feed=feed, fetch_list=fetch)
            compile_ms = (time.perf_counter() - t0) * 1e3
            for _ in range(warmup):
                exe.run(main, feed=feed, fetch_list=fetch)
            times = []
            for _ in range(repeat):
                t0 = time.perf_counter()
                res = exe.run(main, feed=feed, fetch_list=fetch,
                              return_numpy=False)
                np.asarray(res[0])  # sync
                times.append(time.perf_counter() - t0)
    times = np.array(times) * 1e6
    return {"op": op_type,
            "mean_us": round(float(times.mean()), 2),
            "min_us": round(float(times.min()), 2),
            "p50_us": round(float(np.percentile(times, 50)), 2),
            "compile_ms": round(compile_ms, 2),
            "repeat": repeat}


def _parse_input_flag(s: str):
    # "X=256x256" or "X=256x256:int64"
    slot, rest = s.split("=", 1)
    parts = rest.split(":")
    shape = [int(d) for d in parts[0].split("x")]
    dtype = parts[1] if len(parts) > 1 else "float32"
    return slot, {"shape": shape, "dtype": dtype}


def bench_dygraph_mlp(steps: int = 50, batch: int = 64, width: int = 256,
                      depth: int = 4):
    """Dygraph transformer-style MLP train-step micro-bench (VERDICT r3
    #9): linear → layer_norm → gelu blocks, the realistic dygraph op mix
    (multi-primitive ops are where per-op jit caching pays — a bare
    single-primitive relu MLP measures launch count, not fusion). Eager
    per-op jit cache (ops/eager.py _prepare — the PreparedOp analog,
    imperative/prepared_operator.h) vs raw per-primitive dispatch
    (PDTPU_EAGER_JIT=0). The two arms run as INTERLEAVED 10-step
    segments and report per-arm medians — the tunnel runtime's dispatch
    latency drifts by multiples over minutes, so back-to-back A/B runs
    are meaningless. Returns {cached_ms, uncached_ms, speedup}."""
    import os
    import statistics
    import time

    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import dygraph
    from paddle_tpu.ops import eager as _eager

    rng = np.random.RandomState(0)
    X = rng.rand(batch, width).astype("float32")
    Y = rng.rand(batch, 1).astype("float32")
    seg = 10
    n_seg = max(2, steps // seg)

    old = os.environ.get("PDTPU_EAGER_JIT")
    os.environ.pop("PDTPU_EAGER_JIT", None)
    try:
        with dygraph.guard(seed=7):
            layers_ = [dygraph.nn.Linear(width, width)
                       for i in range(depth)] + [dygraph.nn.Linear(width, 1)]
            lns = [dygraph.nn.LayerNorm(width) for _ in range(depth)]
            opt = fluid.optimizer.SGD(0.01)
            xv = dygraph.to_variable(X)
            yv = dygraph.to_variable(Y)
            from paddle_tpu.dygraph.tracer import trace_op
            params = [q for ly in layers_ + lns for q in ly.parameters()]

            def step():
                h = xv
                for i, ly in enumerate(layers_[:-1]):
                    h = ly(h)
                    h = lns[i](h)
                    h = trace_op("gelu", {"X": [h]}, {})["Out"][0]
                h = layers_[-1](h)
                diff = trace_op("elementwise_sub", {"X": [h], "Y": [yv]},
                                {"axis": -1})["Out"][0]
                sq = trace_op("square", {"X": [diff]}, {})["Out"][0]
                loss = trace_op("mean", {"X": [sq]}, {})["Out"][0]
                loss.backward()
                opt.minimize(loss, parameter_list=params)
                for ly in layers_ + lns:
                    ly.clear_gradients()
                return loss

            def segment(cached: bool):
                if cached:
                    os.environ.pop("PDTPU_EAGER_JIT", None)
                else:
                    os.environ["PDTPU_EAGER_JIT"] = "0"
                step()  # warmup/compile for this arm
                t0 = time.time()
                for _ in range(seg):
                    loss = step()
                np.asarray(loss.value)
                return (time.time() - t0) / seg * 1e3

            cached_t, uncached_t = [], []
            for _ in range(n_seg):
                cached_t.append(segment(True))
                uncached_t.append(segment(False))
    finally:
        if old is not None:
            os.environ["PDTPU_EAGER_JIT"] = old
        else:
            os.environ.pop("PDTPU_EAGER_JIT", None)
    def _iqr(xs):
        qs = statistics.quantiles(xs, n=4) if len(xs) >= 2 else [0, 0, 0]
        return round(qs[2] - qs[0], 3)

    cached = statistics.median(cached_t)
    uncached = statistics.median(uncached_t)
    return {"bench": "dygraph_mlp_step", "steps": steps,
            "cached_ms": round(cached, 3), "uncached_ms": round(uncached, 3),
            "cached_iqr_ms": _iqr(cached_t),
            "uncached_iqr_ms": _iqr(uncached_t),
            "n_segments": n_seg,
            "speedup": round(uncached / cached, 2)}


def _interleaved_ab(arms: Dict[str, callable], n_seg: int = 5,
                    seg_iters: int = 5) -> dict:
    """Shared A/B protocol: all arms pre-compiled, then INTERLEAVED
    timed segments with per-arm medians + IQR — back-to-back A/B runs
    are meaningless under drifting dispatch latency."""
    import statistics

    import jax

    for f in arms.values():  # compile off the clock
        np.asarray(jax.tree_util.tree_leaves(f())[0]).ravel()[:1]

    def _seg(f):
        t0 = time.perf_counter()
        for _ in range(seg_iters):
            o = f()
        np.asarray(jax.tree_util.tree_leaves(o)[0]).ravel()[:1]
        return (time.perf_counter() - t0) / seg_iters * 1e3

    times = {k: [] for k in arms}
    for _ in range(n_seg):
        for k, f in arms.items():
            times[k].append(_seg(f))

    def _iqr(xs):
        qs = statistics.quantiles(xs, n=4) if len(xs) >= 2 else [0, 0, 0]
        return round(qs[2] - qs[0], 3)

    return {k: {"median_ms": round(statistics.median(v), 3),
                "iqr_ms": _iqr(v), "n_segments": n_seg}
            for k, v in times.items()}


def bench_fused_conv_bn(batch: int = 8, ci: int = 64, co: int = 256,
                        hw: int = 32, stride: int = 1, n_seg: int = 5):
    """Standalone A/B cell for the fused 1×1-conv+BN(+relu+residual)
    Pallas kernel vs the exact XLA composition it replaces
    (ops/pallas_kernels/fused_bn.py): fwd and fwd+bwd arms, interleaved
    segments. On CPU the Pallas arm runs the interpreter (parity, not
    speed); the TPU numbers are the campaign evidence."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas_kernels import fused_bn

    on_tpu = fused_bn._on_tpu()
    old_force = fused_bn.FORCE_PALLAS_INTERPRET
    if not on_tpu:  # CPU: run the Pallas arm through the interpreter
        fused_bn.FORCE_PALLAS_INTERPRET = True

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, ci, hw, hw), jnp.float32)
    w = jnp.asarray(rng.randn(co, ci, 1, 1) * 0.1, jnp.float32)
    scale = jnp.asarray(rng.rand(co) + 0.5, jnp.float32)
    bias = jnp.asarray(rng.randn(co) * 0.1, jnp.float32)
    eps = 1e-5

    def fused(x, w, scale, bias):
        y, _, _ = fused_bn.fused_conv_bn_act(x, w, scale, bias, eps, "relu",
                                             stride, False, None)
        return y

    def unfused(x, w, scale, bias):
        y, _, _ = fused_bn.conv_bn_xla(x, w, scale, bias, eps, "relu",
                                       stride, None)
        return y

    f_p = jax.jit(fused)
    f_x = jax.jit(unfused)
    g_p = jax.jit(jax.grad(lambda *a: jnp.sum(fused(*a) ** 2), (0, 1, 2, 3)))
    g_x = jax.jit(jax.grad(lambda *a: jnp.sum(unfused(*a) ** 2),
                           (0, 1, 2, 3)))
    args = (x, w, scale, bias)
    try:
        res = _interleaved_ab({
            "pallas_fwd": lambda: f_p(*args), "xla_fwd": lambda: f_x(*args),
            "pallas_bwd": lambda: g_p(*args), "xla_bwd": lambda: g_x(*args),
        }, n_seg=n_seg)
    finally:
        fused_bn.FORCE_PALLAS_INTERPRET = old_force
    return {"bench": "fused_conv_bn",
            "shape": [batch, ci, hw, hw], "co": co, "stride": stride,
            "interpret": not on_tpu,
            "arms": res,
            "fwd_speedup": round(res["xla_fwd"]["median_ms"]
                                 / res["pallas_fwd"]["median_ms"], 2),
            "bwd_speedup": round(res["xla_bwd"]["median_ms"]
                                 / res["pallas_bwd"]["median_ms"], 2)}


def bench_block_sparse_attn(batch: int = 2, t: int = 512, hidden: int = 256,
                            num_heads: int = 4, avg_sent: int = 48,
                            n_seg: int = 5):
    """Standalone A/B cell for block-sparse packed-segment attention vs
    the dense-additive-mask flash path on the same packed batch
    (ops/pallas_kernels/flash_attention.py): fwd and fwd+bwd arms,
    interleaved segments. The dense arm pays every K block; the sparse
    arm skips fully-masked ones, so the gap scales with pad/pack waste."""
    import importlib

    import jax
    import jax.numpy as jnp

    _fa = importlib.import_module(
        "paddle_tpu.ops.pallas_kernels.flash_attention")

    on_tpu = _fa._on_tpu()
    old_force = _fa.FORCE_PALLAS_INTERPRET
    if not on_tpu:  # CPU: run the Pallas arms through the interpreter
        _fa.FORCE_PALLAS_INTERPRET = True

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(batch, t, hidden), jnp.float32)
    k = jnp.asarray(rng.randn(batch, t, hidden), jnp.float32)
    v = jnp.asarray(rng.randn(batch, t, hidden), jnp.float32)
    seg_np = np.zeros((batch, t), "int32")
    for b in range(batch):
        p, sid = 0, 1
        while p < t - 4:
            ln = min(int(rng.randint(avg_sent // 2, avg_sent * 2)), t - p)
            seg_np[b, p:p + ln] = sid
            p += ln
            sid += 1
            if rng.rand() < 0.3:  # leave a pad tail on some rows
                break
    seg = jnp.asarray(seg_np)
    neg = jnp.where((seg[:, :, None] == seg[:, None, :])
                    & (seg[:, :, None] > 0), 0.0, -1e30).astype(jnp.float32)

    def sparse(q, k, v):
        return _fa.flash_attention_packed_sparse(q, k, v, num_heads, seg,
                                                 seg)

    def dense(q, k, v):
        # the dense [B, 1, Tq, Tk] additive segment mask through the
        # 4D bias path — what the packed NMT model fed before the
        # descriptor existed
        d = hidden // num_heads

        def heads(x):
            return x.reshape(batch, t, num_heads, d).transpose(0, 2, 1, 3)

        o = _fa.flash_attention(heads(q), heads(k), heads(v),
                                bias=neg[:, None])
        return o.transpose(0, 2, 1, 3).reshape(batch, t, hidden)

    f_s = jax.jit(sparse)
    f_d = jax.jit(dense)
    g_s = jax.jit(jax.grad(lambda *a: jnp.sum(sparse(*a) ** 2), (0, 1, 2)))
    g_d = jax.jit(jax.grad(lambda *a: jnp.sum(dense(*a) ** 2), (0, 1, 2)))
    args = (q, k, v)
    try:
        res = _interleaved_ab({
            "sparse_fwd": lambda: f_s(*args), "dense_fwd": lambda: f_d(*args),
            "sparse_bwd": lambda: g_s(*args), "dense_bwd": lambda: g_d(*args),
        }, n_seg=n_seg)
    finally:
        _fa.FORCE_PALLAS_INTERPRET = old_force
    fill = float((seg_np > 0).mean())
    return {"bench": "block_sparse_attn",
            "shape": [batch, t, hidden], "num_heads": num_heads,
            "fill_rate": round(fill, 4),
            "interpret": not on_tpu,
            "arms": res,
            "fwd_speedup": round(res["dense_fwd"]["median_ms"]
                                 / res["sparse_fwd"]["median_ms"], 2),
            "bwd_speedup": round(res["dense_bwd"]["median_ms"]
                                 / res["sparse_bwd"]["median_ms"], 2)}


def main(argv: Optional[List[str]] = None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dygraph", action="store_true",
                    help="run the dygraph MLP step bench (eager jit cache "
                         "on vs off)")
    ap.add_argument("--fused-conv-bn", action="store_true",
                    help="A/B the fused conv+BN Pallas kernel vs its XLA "
                         "composition")
    ap.add_argument("--block-sparse-attn", action="store_true",
                    help="A/B block-sparse packed-segment attention vs the "
                         "dense-mask flash path")
    ap.add_argument("--op")
    ap.add_argument("--input", action="append", default=[],
                    help="SLOT=shape[:dtype], e.g. X=256x256:float32")
    ap.add_argument("--attrs", default="{}", help="JSON attr dict")
    ap.add_argument("--out", action="append", default=[],
                    help="output slot[:count], default Out:1")
    ap.add_argument("--repeat", type=int, default=100)
    ap.add_argument("--config", help="JSON list of bench specs")
    args = ap.parse_args(argv)

    specs = []
    if args.config:
        with open(args.config) as f:
            specs = json.load(f)
    if args.op:
        inputs = {}
        for s in args.input:
            slot, sp = _parse_input_flag(s)
            inputs.setdefault(slot, []).append(sp)
        outputs = {}
        for o in args.out:
            slot, _, n = o.partition(":")
            outputs[slot] = int(n or 1)
        specs.append({"op": args.op, "inputs": inputs,
                      "attrs": json.loads(args.attrs),
                      "outputs": outputs or None, "repeat": args.repeat})
    ran_cell = False
    if args.dygraph:
        print(json.dumps(bench_dygraph_mlp()))
        ran_cell = True
    if args.fused_conv_bn:
        print(json.dumps(bench_fused_conv_bn()))
        ran_cell = True
    if args.block_sparse_attn:
        print(json.dumps(bench_block_sparse_attn()))
        ran_cell = True
    if ran_cell and not specs:
        return
    if not specs:
        ap.error("need --op or --config")

    for sp in specs:
        res = bench_op(sp["op"], sp["inputs"], sp.get("attrs"),
                       sp.get("outputs"), sp.get("repeat", 100))
        print(json.dumps(res))


if __name__ == "__main__":
    main()
