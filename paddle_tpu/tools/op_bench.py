"""Per-op micro-benchmark harness.

Reference analog: ``paddle/fluid/operators/benchmark/op_tester.cc`` — a
config-driven runner that builds one op, feeds synthetic tensors, and
reports per-op latency (op_tester.cc:1; config format op_tester_config.cc).
BASELINE.md requires this harness to exist "from day one" since all speedup
claims are measured, not quoted.

TPU-native redesign: the op executes through the same Program→Executor→XLA
path as production (so the measurement includes our lowering, XLA fusion,
and dispatch), with an explicit compile warmup so steady-state latency is
reported separately from compile time.

CLI::

    python -m paddle_tpu.tools.op_bench --op matmul \
        --input X=256x256 --input Y=256x256 --repeat 200
    python -m paddle_tpu.tools.op_bench --config bench_ops.json

Config file: a JSON list of {"op", "inputs": {slot: {"shape", "dtype"}},
"attrs", "outputs", "repeat"}. Output: one JSON line per config with
{op, mean_us, min_us, p50_us, compile_ms, repeat}.
"""
from __future__ import annotations

import argparse
import json
import time
import zlib
from typing import Dict, List, Optional

import numpy as np


def _make_input(shape, dtype, seed=0):
    rng = np.random.RandomState(seed)
    if np.issubdtype(np.dtype(dtype), np.integer):
        return rng.randint(0, 8, size=shape).astype(dtype)
    return rng.rand(*shape).astype(dtype)


def bench_op(op_type: str, inputs: Dict[str, Dict], attrs: Optional[dict] = None,
             outputs: Optional[Dict[str, int]] = None, repeat: int = 100,
             warmup: int = 2) -> dict:
    """Build a one-op program, execute through the real Executor, time it.

    inputs: slot -> {"shape": [..], "dtype": "float32"} (or a list of such
    for multi-value slots). outputs: slot -> count (default {"Out": 1}).
    """
    import paddle_tpu as fluid

    attrs = attrs or {}
    outputs = outputs or {"Out": 1}
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        block = main.global_block()
        in_map, feed = {}, {}
        for slot, specs in inputs.items():
            specs = specs if isinstance(specs, list) else [specs]
            names = []
            for i, sp in enumerate(specs):
                a = _make_input(sp["shape"], sp.get("dtype", "float32"),
                                seed=(zlib.crc32(slot.encode()) + i) % 2 ** 31)
                name = f"{slot.lower()}_{i}"
                block.create_var(name=name, shape=a.shape, dtype=str(a.dtype),
                                 is_data=True)
                feed[name] = a
                names.append(name)
            in_map[slot] = names
        out_map = {}
        for slot, n in outputs.items():
            out_map[slot] = [f"out_{slot.lower()}_{i}" for i in range(n)]
            for nm in out_map[slot]:
                block.create_var(name=nm, dtype="float32")
        block.append_op(op_type, in_map, out_map, attrs)
        fetch = [nm for slot in sorted(out_map) for nm in out_map[slot]]

        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.TPUPlace())
            exe.run(startup)
            t0 = time.perf_counter()
            exe.run(main, feed=feed, fetch_list=fetch)
            compile_ms = (time.perf_counter() - t0) * 1e3
            for _ in range(warmup):
                exe.run(main, feed=feed, fetch_list=fetch)
            times = []
            for _ in range(repeat):
                t0 = time.perf_counter()
                res = exe.run(main, feed=feed, fetch_list=fetch,
                              return_numpy=False)
                np.asarray(res[0])  # sync
                times.append(time.perf_counter() - t0)
    times = np.array(times) * 1e6
    return {"op": op_type,
            "mean_us": round(float(times.mean()), 2),
            "min_us": round(float(times.min()), 2),
            "p50_us": round(float(np.percentile(times, 50)), 2),
            "compile_ms": round(compile_ms, 2),
            "repeat": repeat}


def _parse_input_flag(s: str):
    # "X=256x256" or "X=256x256:int64"
    slot, rest = s.split("=", 1)
    parts = rest.split(":")
    shape = [int(d) for d in parts[0].split("x")]
    dtype = parts[1] if len(parts) > 1 else "float32"
    return slot, {"shape": shape, "dtype": dtype}


def bench_dygraph_mlp(steps: int = 50, batch: int = 64, width: int = 256,
                      depth: int = 4):
    """Dygraph transformer-style MLP train-step micro-bench (VERDICT r3
    #9): linear → layer_norm → gelu blocks, the realistic dygraph op mix
    (multi-primitive ops are where per-op jit caching pays — a bare
    single-primitive relu MLP measures launch count, not fusion). Eager
    per-op jit cache (ops/eager.py _prepare — the PreparedOp analog,
    imperative/prepared_operator.h) vs raw per-primitive dispatch
    (PDTPU_EAGER_JIT=0). The two arms run as INTERLEAVED 10-step
    segments and report per-arm medians — the tunnel runtime's dispatch
    latency drifts by multiples over minutes, so back-to-back A/B runs
    are meaningless. Returns {cached_ms, uncached_ms, speedup}."""
    import os
    import statistics
    import time

    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import dygraph
    from paddle_tpu.ops import eager as _eager

    rng = np.random.RandomState(0)
    X = rng.rand(batch, width).astype("float32")
    Y = rng.rand(batch, 1).astype("float32")
    seg = 10
    n_seg = max(2, steps // seg)

    old = os.environ.get("PDTPU_EAGER_JIT")
    os.environ.pop("PDTPU_EAGER_JIT", None)
    try:
        with dygraph.guard(seed=7):
            layers_ = [dygraph.nn.Linear(width, width)
                       for i in range(depth)] + [dygraph.nn.Linear(width, 1)]
            lns = [dygraph.nn.LayerNorm(width) for _ in range(depth)]
            opt = fluid.optimizer.SGD(0.01)
            xv = dygraph.to_variable(X)
            yv = dygraph.to_variable(Y)
            from paddle_tpu.dygraph.tracer import trace_op
            params = [q for ly in layers_ + lns for q in ly.parameters()]

            def step():
                h = xv
                for i, ly in enumerate(layers_[:-1]):
                    h = ly(h)
                    h = lns[i](h)
                    h = trace_op("gelu", {"X": [h]}, {})["Out"][0]
                h = layers_[-1](h)
                diff = trace_op("elementwise_sub", {"X": [h], "Y": [yv]},
                                {"axis": -1})["Out"][0]
                sq = trace_op("square", {"X": [diff]}, {})["Out"][0]
                loss = trace_op("mean", {"X": [sq]}, {})["Out"][0]
                loss.backward()
                opt.minimize(loss, parameter_list=params)
                for ly in layers_ + lns:
                    ly.clear_gradients()
                return loss

            def segment(cached: bool):
                if cached:
                    os.environ.pop("PDTPU_EAGER_JIT", None)
                else:
                    os.environ["PDTPU_EAGER_JIT"] = "0"
                step()  # warmup/compile for this arm
                t0 = time.time()
                for _ in range(seg):
                    loss = step()
                np.asarray(loss.value)
                return (time.time() - t0) / seg * 1e3

            cached_t, uncached_t = [], []
            for _ in range(n_seg):
                cached_t.append(segment(True))
                uncached_t.append(segment(False))
    finally:
        if old is not None:
            os.environ["PDTPU_EAGER_JIT"] = old
        else:
            os.environ.pop("PDTPU_EAGER_JIT", None)
    def _iqr(xs):
        qs = statistics.quantiles(xs, n=4) if len(xs) >= 2 else [0, 0, 0]
        return round(qs[2] - qs[0], 3)

    cached = statistics.median(cached_t)
    uncached = statistics.median(uncached_t)
    return {"bench": "dygraph_mlp_step", "steps": steps,
            "cached_ms": round(cached, 3), "uncached_ms": round(uncached, 3),
            "cached_iqr_ms": _iqr(cached_t),
            "uncached_iqr_ms": _iqr(uncached_t),
            "n_segments": n_seg,
            "speedup": round(uncached / cached, 2)}


def main(argv: Optional[List[str]] = None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dygraph", action="store_true",
                    help="run the dygraph MLP step bench (eager jit cache "
                         "on vs off)")
    ap.add_argument("--op")
    ap.add_argument("--input", action="append", default=[],
                    help="SLOT=shape[:dtype], e.g. X=256x256:float32")
    ap.add_argument("--attrs", default="{}", help="JSON attr dict")
    ap.add_argument("--out", action="append", default=[],
                    help="output slot[:count], default Out:1")
    ap.add_argument("--repeat", type=int, default=100)
    ap.add_argument("--config", help="JSON list of bench specs")
    args = ap.parse_args(argv)

    specs = []
    if args.config:
        with open(args.config) as f:
            specs = json.load(f)
    if args.op:
        inputs = {}
        for s in args.input:
            slot, sp = _parse_input_flag(s)
            inputs.setdefault(slot, []).append(sp)
        outputs = {}
        for o in args.out:
            slot, _, n = o.partition(":")
            outputs[slot] = int(n or 1)
        specs.append({"op": args.op, "inputs": inputs,
                      "attrs": json.loads(args.attrs),
                      "outputs": outputs or None, "repeat": args.repeat})
    if args.dygraph:
        print(json.dumps(bench_dygraph_mlp()))
        if not specs:
            return
    if not specs:
        ap.error("need --op or --config")

    for sp in specs:
        res = bench_op(sp["op"], sp["inputs"], sp.get("attrs"),
                       sp.get("outputs"), sp.get("repeat", 100))
        print(json.dumps(res))


if __name__ == "__main__":
    main()
