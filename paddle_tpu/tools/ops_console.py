"""Live ops console: one terminal view of the whole fleet + its alerts.

A stdlib-only (urllib + ANSI) dashboard over the introspection
documents the coordinator already serves — ``/fleet`` (per-process
reachability, scrape latency, queue depth, pull p99), ``/alerts``
(the SLO engine's live pending/firing/resolved set), and ``/history``
(the ring TSDB: each autoscaler signal row gains a unicode sparkline of
its last five minutes, so a spike reads as a shape, not a number) —
refreshed in place every ``--interval`` seconds. Firing alerts render
on top in red, because when an operator opens this screen something is
usually already paging.

CLI::

    python -m paddle_tpu.tools.ops_console http://coordinator:8080
    python -m paddle_tpu.tools.ops_console http://c:8080 --interval 0.5
    python -m paddle_tpu.tools.ops_console http://c:8080 --once --no-color

``--once`` renders a single frame and exits (scripts, tests); exit code
is 0 when nothing is firing, 1 when any alert is firing, 2 when the
coordinator is unreachable. Ctrl-C exits 0. Endpoints that 404 (no
scraper / no alert manager installed) degrade to an explanatory row
rather than an error: the console is useful from the moment the
introspection server is up, before the SLO plumbing is wired.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request

__all__ = ["gather", "render", "main"]

_CLEAR = "\x1b[2J\x1b[H"
_RED = "\x1b[31;1m"
_YELLOW = "\x1b[33;1m"
_GREEN = "\x1b[32m"
_DIM = "\x1b[2m"
_RESET = "\x1b[0m"

_SEV_ORDER = {"page": 0, "warn": 1}
_STATE_ORDER = {"firing": 0, "pending": 1, "resolved": 2}


def _fetch(base: str, path: str, timeout: float):
    """(doc-or-None, note): None doc with a human note on 404 (endpoint
    not wired yet) — anything else network-ish raises for gather() to
    turn into an unreachable-coordinator report."""
    url = base.rstrip("/") + path
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.load(resp), ""
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return None, f"{path}: not wired ({e.reason})"
        if e.code == 503:
            # /fleet answers 503 with the full document when any target
            # is down — that IS the interesting frame, keep it
            try:
                return json.load(e), ""
            except Exception:
                return None, f"{path}: HTTP {e.code}"
        return None, f"{path}: HTTP {e.code}"


def gather(base: str, timeout: float = 2.0) -> dict:
    """One console frame's data: ``{"fleet", "alerts", "notes",
    "reachable"}``. Never raises — an unreachable coordinator comes back
    as ``reachable: False`` with the error in notes."""
    notes = []
    out = {"fleet": None, "alerts": None, "history": None,
           "notes": notes, "reachable": True}
    for key, path in (("fleet", "/fleet"), ("alerts", "/alerts"),
                      ("history", "/history?prefix=autoscale/"
                                  "&window=300&max_points=64")):
        try:
            doc, note = _fetch(base, path, timeout)
        except Exception as e:
            out["reachable"] = False
            notes.append(f"{path}: {type(e).__name__}: {e}")
            continue
        out[key] = doc
        if note:
            notes.append(note)
    return out


def _spark_map(history_doc) -> dict:
    """(series_name, sub_label) -> sparkline over the /history window.
    sub_label is the shard for per-shard series, the process for
    per-process series, None for scalar signals."""
    out: dict = {}
    if not isinstance(history_doc, dict):
        return out
    from .postmortem import sparkline
    for s in history_doc.get("series", ()):
        name = s.get("name", "")
        labels = s.get("labels") or {}
        if name == "autoscale/ps_pull_p99_ms":
            sub = labels.get("shard")
        elif name == "autoscale/queue_depth":
            sub = labels.get("process")
        else:
            sub = None
        vals = [p[1] for p in s.get("points", ()) if len(p) > 1]
        if vals:
            out[(name, sub)] = sparkline(vals)
    return out


def _series_get(series, name, field="value"):
    for s in series:
        if s.get("name") != name:
            continue
        if s.get("type") == "summary":
            return (s.get("summary") or {}).get(field)
        return s.get("value")
    return None


def _c(text: str, color: str, on: bool) -> str:
    return f"{color}{text}{_RESET}" if on else text


def render(frame: dict, color: bool = True, now: float = None) -> str:
    """One frame of the dashboard as a string (testable without a tty).
    Sections: firing/pending alerts first, then the per-process fleet
    table, then the autoscaler signal line and any notes."""
    now = time.time() if now is None else now
    lines = [f"paddle_tpu ops console — "
             f"{time.strftime('%H:%M:%S', time.localtime(now))}"]
    if not frame.get("reachable", True):
        lines.append(_c("COORDINATOR UNREACHABLE", _RED, color))
        for n in frame.get("notes", ()):
            lines.append(f"  {n}")
        return "\n".join(lines) + "\n"

    # ---------------------------------------------------------- alerts
    adoc = frame.get("alerts")
    if adoc is None:
        lines.append(_c("alerts: (no AlertManager installed)", _DIM, color))
    else:
        alerts = sorted(
            adoc.get("alerts", ()),
            key=lambda a: (_STATE_ORDER.get(a.get("state"), 9),
                           _SEV_ORDER.get(a.get("severity"), 9),
                           a.get("name", "")))
        firing = [a for a in alerts if a.get("state") == "firing"]
        if not alerts:
            lines.append(_c("alerts: none — all objectives met",
                            _GREEN, color))
        else:
            lines.append(f"alerts: {len(firing)} firing / "
                         f"{adoc.get('pending', 0)} pending / "
                         f"{adoc.get('resolved', 0)} resolved")
            for a in alerts:
                sev = a.get("severity", "?")
                state = a.get("state", "?")
                labels = {k: v for k, v in (a.get("labels") or {}).items()
                          if k != "slo"}
                lstr = ("{" + ",".join(f"{k}={v}" for k, v in
                                       sorted(labels.items())) + "}"
                        if labels else "")
                burn = a.get("value")
                row = (f"  [{sev:>4}] {a.get('name')}{lstr} {state}"
                       + (f"  burn={burn}" if burn is not None else ""))
                if state == "firing":
                    row = _c(row, _RED if sev == "page" else _YELLOW, color)
                elif state == "resolved":
                    row = _c(row, _DIM, color)
                lines.append(row)

    # ----------------------------------------------------------- fleet
    fdoc = frame.get("fleet")
    if fdoc is None:
        lines.append(_c("fleet: (no FederatedScraper installed)",
                        _DIM, color))
    else:
        lines.append("")
        lines.append(f"{'process':<28}{'role':<10}{'shard':>6}{'state':>8}"
                     f"{'scrape_ms':>11}{'queue':>7}{'pull_p99':>10}"
                     f"{'tenant_p99':>12}")
        for r in fdoc.get("targets", ()):
            q = _series_get(r.get("series", ()), "serving/queue_depth")
            p99 = _series_get(r.get("series", ()), "ps/shard_pull_ms",
                              field="p99")
            tp99 = _series_get(r.get("series", ()),
                               "fleet/tenant_latency_ms", field="p99")
            state = "up" if r.get("ok") else "DOWN"
            row = (f"{r.get('process', '?'):<28}{r.get('role', '?'):<10}"
                   f"{'-' if r.get('shard') is None else r['shard']:>6}"
                   f"{state:>8}{r.get('scrape_ms', 0):>11.1f}"
                   f"{'-' if q is None else int(q):>7}"
                   f"{'-' if p99 is None else round(p99, 1):>10}"
                   f"{'-' if tp99 is None else round(tp99, 1):>12}")
            if not r.get("ok"):
                row = _c(row, _RED, color)
            lines.append(row)
            if not r.get("ok") and r.get("error"):
                lines.append(_c(f"    {r['error']}", _DIM, color))
        sig = fdoc.get("signals") or {}
        if sig:
            lines.append("")
            sparks = _spark_map(frame.get("history"))
            for key in sorted(sig):
                val = sig[key]
                nm = f"autoscale/{key}"
                if isinstance(val, dict):
                    # per-label signal (pull p99 by shard, queue by proc)
                    for sub in sorted(val):
                        label = f"{key}[{sub}]"
                        spark = sparks.get((nm, str(sub)), "")
                        lines.append(f"  {label:<26}{val[sub]:>10.1f}  "
                                     + _c(spark, _DIM, color))
                else:
                    spark = sparks.get((nm, None), "")
                    lines.append(f"  {key:<26}{float(val):>10.1f}  "
                                 + _c(spark, _DIM, color))

    for n in frame.get("notes", ()):
        lines.append(_c(f"note: {n}", _DIM, color))
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ops_console",
        description="live terminal dashboard over /fleet + /alerts")
    ap.add_argument("coordinator",
                    help="introspection base URL (http://host:port)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period, seconds (default 2)")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-request timeout, seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (0 = nothing firing, "
                         "1 = alerts firing, 2 = coordinator unreachable)")
    ap.add_argument("--no-color", action="store_true",
                    help="plain text (pipes, logs, dumb terminals)")
    args = ap.parse_args(argv)
    if args.interval <= 0:
        raise SystemExit("ops_console: --interval must be > 0")
    color = not args.no_color and sys.stdout.isatty()

    def frame_rc(frame) -> int:
        if not frame["reachable"]:
            return 2
        adoc = frame.get("alerts") or {}
        return 1 if adoc.get("firing") else 0

    if args.once:
        frame = gather(args.coordinator, timeout=args.timeout)
        sys.stdout.write(render(frame, color=color))
        return frame_rc(frame)
    try:
        while True:
            frame = gather(args.coordinator, timeout=args.timeout)
            sys.stdout.write(_CLEAR + render(frame, color=color))
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
