"""Bench regression gate: compare a fresh bench JSON against a baseline.

The bench rounds (``BENCH_r0x.json``) are the repo's perf ledger; this
tool turns them into a gate. Given a fresh bench doc and a baseline, it
walks a fixed metric table — headline rate, MFU, roofline fractions,
per-model rates, PS prefetch speedup, dispatch overhead — applies a
per-metric noise margin, and exits nonzero when any metric regresses
beyond its margin. Bitwise-equality invariants from the PS sections are
must-not-flip booleans.

Both sides accept three formats (the driver wraps bench output):

* a bare bench doc — ``{"metric", "value", "unit", "extra": {...}}``;
* a driver wrapper — ``{"n", "cmd", "rc", "tail", "parsed"}`` where
  ``parsed`` is the doc;
* a wrapper whose ``parsed`` is null: the last JSON object line in
  ``tail`` is used, and when the tail was truncated mid-line (e.g.
  BENCH_r05.json) known flat metrics are recovered by regex — a
  best-effort baseline beats no gate at all.

CPU-smoke tolerance: a metric absent or null on BOTH sides is skipped
(sections that only run on TPU, or that OOM'd in the baseline round,
don't fail a CPU run). A metric the baseline has but the fresh doc lost
is itself a regression.

Exit codes: 0 pass, 1 regression, 2 usage / unrecoverable input.

Usage::

    python -m paddle_tpu.tools.perf_gate FRESH BASELINE [--margin-scale S]
    python bench.py --gate-against BENCH_r05.json
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Any, Optional

__all__ = ["load_doc", "compare", "gate", "main", "METRICS", "INVARIANTS"]

# (path, relative margin, direction). Margins are per-metric noise
# allowances from the spread observed across BENCH_r01..r05 re-runs;
# "higher" metrics may drop by at most margin x baseline, "lower"
# metrics (overheads) may grow by at most margin x baseline (plus a
# small absolute slack for near-zero baselines).
METRICS = [
    ("value", 0.10, "higher"),
    ("extra.mfu", 0.10, "higher"),
    ("extra.resnet50_imgs_per_sec_per_chip", 0.15, "higher"),
    ("extra.resnet50_mfu", 0.15, "higher"),
    ("extra.resnet50_roofline_frac", 0.15, "higher"),
    ("extra.deepfm_rate", 0.15, "higher"),
    ("extra.nmt_big_rate", 0.15, "higher"),
    ("extra.nmt_big_mfu", 0.10, "higher"),
    ("extra.ps_embedding.prefetch_speedup", 0.20, "higher"),
    ("extra.dispatch_overhead.scan_overhead_pct_of_run", 0.25, "lower"),
]
# Absolute slack for "lower" metrics whose baseline is ~0 (a pct that
# moves 0.1 -> 0.3 is noise, not a 3x regression).
_ABS_SLACK_LOWER = 2.0

# Booleans that must never flip true -> false.
INVARIANTS = [
    "extra.ps_embedding.staleness0_bitwise_equal",
    "extra.ps_embedding.push_depth1_bitwise_equal",
    "extra.ps_embedding.hot_cache_bitwise_equal",
]

# Flat metrics recoverable by regex from a truncated wrapper tail.
_RECOVERABLE = [p.split(".", 1)[1] for p in (
    [m[0] for m in METRICS if m[0].startswith("extra.")])
    if "." not in p.split(".", 1)[1]] + ["nmt_big_vs_baseline",
                                         "resnet50_vs_baseline",
                                         "deepfm_vs_baseline"]


def _lookup(doc: dict, path: str) -> Any:
    cur: Any = doc
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _recover_from_tail(tail: str) -> Optional[dict]:
    """Best-effort doc from a wrapper tail. Try the last parseable JSON
    object line first; fall back to regex-scraping known flat metrics
    out of a line the driver truncated mid-JSON."""
    for ln in reversed(tail.splitlines()):
        ln = ln.strip()
        if ln.startswith("{") and ln.endswith("}"):
            try:
                doc = json.loads(ln)
            except ValueError:
                continue
            if isinstance(doc, dict) and ("metric" in doc or "extra" in doc):
                return doc
    extra = {}
    for name in _RECOVERABLE:
        m = re.search(r'"%s"\s*:\s*(-?[0-9.eE+]+|null|true|false)'
                      % re.escape(name), tail)
        if m:
            extra[name] = json.loads(m.group(1))
    for name in [p.rsplit(".", 1)[1] for p in INVARIANTS]:
        m = re.search(r'"%s"\s*:\s*(true|false)' % re.escape(name), tail)
        if m:
            extra.setdefault("ps_embedding", {})[name] = m.group(1) == "true"
    if not extra:
        return None
    return {"metric": None, "value": None, "extra": extra,
            "_recovered_from_tail": sorted(extra)}


def load_doc(path: str) -> dict:
    """Load a bench doc from any of the accepted formats; raises
    ValueError when nothing recoverable."""
    with open(path) as f:
        raw = json.load(f)
    if not isinstance(raw, dict):
        raise ValueError(f"{path}: not a JSON object")
    if "metric" in raw or ("extra" in raw and "tail" not in raw):
        return raw
    if "parsed" in raw or "tail" in raw:  # driver wrapper
        if isinstance(raw.get("parsed"), dict):
            return raw["parsed"]
        doc = _recover_from_tail(raw.get("tail") or "")
        if doc is not None:
            return doc
        raise ValueError(f"{path}: wrapper has parsed=null and no "
                         f"recoverable metrics in tail")
    raise ValueError(f"{path}: unrecognized bench JSON shape")


def compare(fresh: dict, base: dict, margin_scale: float = 1.0) -> dict:
    """Walk the metric table; return {checked, skipped, regressions,
    improvements}. A regression entry carries path/base/fresh/limit."""
    checked, skipped, regressions, improvements = [], [], [], []
    for path, margin, direction in METRICS:
        margin *= margin_scale
        bv, fv = _lookup(base, path), _lookup(fresh, path)
        if bv is None and fv is None:
            skipped.append({"path": path, "reason": "absent both sides"})
            continue
        if bv is None:
            skipped.append({"path": path, "reason": "no baseline value"})
            continue
        if fv is None:
            regressions.append({"path": path, "base": bv, "fresh": None,
                                "limit": None,
                                "reason": "metric lost (baseline has a "
                                          "value, fresh run does not)"})
            continue
        bv, fv = float(bv), float(fv)
        if direction == "higher":
            limit = bv * (1.0 - margin)
            ok = fv >= limit
        else:
            limit = bv * (1.0 + margin) + _ABS_SLACK_LOWER * margin_scale
            ok = fv <= limit
        entry = {"path": path, "base": bv, "fresh": fv,
                 "limit": round(limit, 6), "direction": direction}
        checked.append(entry)
        if not ok:
            regressions.append(entry)
        elif (fv > bv) == (direction == "higher") and fv != bv:
            improvements.append(entry)
    for path in INVARIANTS:
        bv, fv = _lookup(base, path), _lookup(fresh, path)
        if bv is True and fv is False:
            regressions.append({"path": path, "base": True, "fresh": False,
                                "limit": True,
                                "reason": "bitwise invariant flipped"})
        elif bv is not None and fv is not None:
            checked.append({"path": path, "base": bv, "fresh": fv,
                            "limit": True, "direction": "invariant"})
    return {"checked": checked, "skipped": skipped,
            "regressions": regressions, "improvements": improvements}


def gate(fresh: dict, base: dict, margin_scale: float = 1.0,
         quiet: bool = False, out=None) -> int:
    """Compare and report; returns the intended exit code (0/1)."""
    out = out or sys.stdout
    rep = compare(fresh, base, margin_scale)
    if not quiet:
        if fresh.get("_recovered_from_tail"):
            print("note: fresh doc regex-recovered from wrapper tail",
                  file=out)
        if base.get("_recovered_from_tail"):
            print(f"note: baseline regex-recovered from wrapper tail "
                  f"({len(base['_recovered_from_tail'])} fields)", file=out)
        for e in rep["checked"]:
            if e["direction"] == "invariant":
                continue
            arrow = "within" if e not in rep["regressions"] else "REGRESSED"
            print(f"  {e['path']:<50} base={e['base']:<12g} "
                  f"fresh={e['fresh']:<12g} limit={e['limit']:<12g} "
                  f"{arrow}", file=out)
        for e in rep["skipped"]:
            print(f"  {e['path']:<50} skipped ({e['reason']})", file=out)
        for e in rep["regressions"]:
            if e.get("reason"):
                print(f"  {e['path']:<50} REGRESSED ({e['reason']})",
                      file=out)
    n = len(rep["regressions"])
    if n:
        print(f"perf_gate: FAIL — {n} regression(s) vs baseline", file=out)
        return 1
    print(f"perf_gate: PASS — {len(rep['checked'])} checked, "
          f"{len(rep['skipped'])} skipped, "
          f"{len(rep['improvements'])} improved", file=out)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.tools.perf_gate", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("fresh", help="fresh bench JSON (doc or driver wrapper)")
    p.add_argument("baseline", help="baseline bench JSON, e.g. "
                                    "BENCH_r05.json")
    p.add_argument("--margin-scale", type=float, default=1.0,
                   help="multiply every noise margin (e.g. 2.0 on noisy "
                        "shared machines)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the comparison report as JSON")
    args = p.parse_args(argv)
    try:
        fresh = load_doc(args.fresh)
        base = load_doc(args.baseline)
    except (OSError, ValueError) as e:
        print(f"perf_gate: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        rep = compare(fresh, base, args.margin_scale)
        print(json.dumps(rep))
        return 1 if rep["regressions"] else 0
    return gate(fresh, base, args.margin_scale)


if __name__ == "__main__":
    sys.exit(main())
