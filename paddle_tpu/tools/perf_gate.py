"""Bench regression gate: compare a fresh bench JSON against a baseline.

The bench rounds (``BENCH_r0x.json``) are the repo's perf ledger; this
tool turns them into a gate. Given a fresh bench doc and a baseline, it
walks a fixed metric table — headline rate, MFU, roofline fractions,
per-model rates, PS prefetch speedup, dispatch overhead — applies a
per-metric noise margin, and exits nonzero when any metric regresses
beyond its margin. Bitwise-equality invariants from the PS sections are
must-not-flip booleans.

Both sides accept three formats (the driver wraps bench output):

* a bare bench doc — ``{"metric", "value", "unit", "extra": {...}}``;
* a driver wrapper — ``{"n", "cmd", "rc", "tail", "parsed"}`` where
  ``parsed`` is the doc;
* a wrapper whose ``parsed`` is null: the last JSON object line in
  ``tail`` is used, and when the tail was truncated mid-line (e.g.
  BENCH_r05.json) known flat metrics are recovered by regex — a
  best-effort baseline beats no gate at all.

CPU-smoke tolerance: a metric absent or null on BOTH sides is skipped
(sections that only run on TPU, or that OOM'd in the baseline round,
don't fail a CPU run). A metric the baseline has but the fresh doc lost
is itself a regression.

Context-aware skip: raw hardware-throughput metrics carry *context
paths* (device string, workload scale) whose values must match for the
comparison to mean anything — a TPU-recorded 268k ex/s baseline says
nothing about a CPU smoke run of the 10k-vocab toy config. When both
docs carry a context value and they differ, the metric is skipped with
the mismatch named; when either side lacks the context (old docs,
truncated tails) the comparison proceeds as before, so a baseline can
never dodge the gate by *losing* its context fields.

Exit codes: 0 pass, 1 regression, 2 usage / unrecoverable input.

Usage::

    python -m paddle_tpu.tools.perf_gate FRESH BASELINE [--margin-scale S]
    python bench.py --gate-against BENCH_r05.json
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Any, Optional

__all__ = ["load_doc", "compare", "gate", "main", "METRICS", "INVARIANTS",
           "PRESENCE_INVARIANTS"]

# (path, relative margin, direction[, context paths]). Margins are
# per-metric noise allowances from the spread observed across
# BENCH_r01..r05 re-runs; "higher" metrics may drop by at most margin x
# baseline, "lower" metrics (overheads) may grow by at most margin x
# baseline (plus a small absolute slack for near-zero baselines). The
# optional 4th element lists context paths that must agree on both
# sides for the metric to be compared at all (see module docstring);
# raw hardware rates are device/workload-bound, while MFU, roofline
# fractions and A/B speedups are self-normalized and carry none.
METRICS = [
    ("value", 0.10, "higher", ("extra.device",)),
    ("extra.mfu", 0.10, "higher"),
    ("extra.resnet50_imgs_per_sec_per_chip", 0.15, "higher",
     ("extra.device",)),
    ("extra.resnet50_mfu", 0.15, "higher"),
    ("extra.resnet50_roofline_frac", 0.15, "higher"),
    ("extra.deepfm_rate", 0.15, "higher",
     ("extra.device", "extra.deepfm_roofline.vocab")),
    ("extra.nmt_big_rate", 0.15, "higher", ("extra.device",)),
    ("extra.nmt_big_mfu", 0.10, "higher"),
    ("extra.nmt_big_roofline_frac", 0.15, "higher"),
    ("extra.ps_embedding.prefetch_speedup", 0.20, "higher"),
    ("extra.dispatch_overhead.scan_overhead_pct_of_run", 0.25, "lower"),
    # kernel-campaign outputs (BENCH_r06): the A/B speedups the fused
    # conv+BN and block-sparse attention kernels were adopted on, plus
    # the ring/dygraph sections that now run under the HBM planner ladder
    # (a section losing its number again IS the regression being gated).
    ("extra.resnet50_conv_fusion_speedup", 0.20, "higher"),
    ("extra.nmt_big_sparse_speedup", 0.20, "higher"),
    ("extra.ring_attn_pallas_speedup_t4k", 0.20, "higher"),
    ("extra.ring_attn_bwd_pallas_speedup_t4k", 0.20, "higher"),
    ("extra.dygraph_jit_cache_speedup", 0.25, "higher"),
    # observability-loop latencies (PR 17/20 chaos cells): how long after
    # the injected fault the page fired. Quantized by the 0.25 s sweep
    # interval, hence the generous margins — what the gate protects is
    # the order of magnitude, not the sweep jitter.
    ("extra.slo_alerting.avail_fire_after_kill_ms", 0.75, "lower"),
    ("extra.slo_alerting.stale_fire_after_kill_ms", 0.75, "lower"),
    ("extra.root_cause.page_fire_after_fault_ms", 0.75, "lower"),
]
# Absolute slack for "lower" metrics whose baseline is ~0 (a pct that
# moves 0.1 -> 0.3 is noise, not a 3x regression).
_ABS_SLACK_LOWER = 2.0

# Booleans that must never flip true -> false.
INVARIANTS = [
    "extra.ps_embedding.staleness0_bitwise_equal",
    "extra.ps_embedding.push_depth1_bitwise_equal",
    "extra.ps_embedding.hot_cache_bitwise_equal",
    # planner verdicts for the OOM-prone sections: once a round records a
    # fitting plan, a later round where the chosen plan no longer fits
    # must fail the gate even if the section limps through
    "extra.nmt_big_hbm_plan.fits",
    "extra.ring_attn_hbm_plan.fits",
    "extra.dygraph_hbm_plan.fits",
    # root-cause chaos cell (PR 20): the page must arrive already naming
    # a culprit kernel, and the history ring must stay under its cap
    "extra.root_cause.culprit_named",
    "extra.root_cause.history_under_cap",
]

# Presence invariants: paths that are null/absent when a section ran
# clean and carry a post-mortem payload when it OOM'd. A baseline that
# ran clean followed by a fresh run that emits the payload IS the
# regression (the *_oom_plan fields were UNGATED diagnostics before
# this: a section could silently start OOMing without failing the
# gate, as long as the planner limped it through).
PRESENCE_INVARIANTS = [
    "extra.nmt_big_oom_plan",
    "extra.ring_attn_oom_plan",
    "extra.dygraph_oom_plan",
]

# Metrics bench.py emits that are DELIBERATELY not gated: diagnostics,
# environment records, free-text/error fields, and raw section payloads
# whose gateable scalars are surfaced above. tests/test_perf_gate_metrics
# asserts every key bench.py emits is in METRICS/INVARIANTS or here —
# growing the bench without deciding gate-or-not is the failure mode this
# list exists to block.
UNGATED = [
    # environment / identity
    "batch", "seq_len", "params", "device", "calibration",
    # latency diagnostics (throughput and MFU are gated; ms values vary
    # with shape choices between rounds)
    "step_ms", "resnet50_step_ms", "deepfm_step_ms", "nmt_big_step_ms",
    "dygraph_step_ms", "dygraph_cached_ms", "dygraph_uncached_ms",
    "ring_attn_pallas_ms", "ring_attn_oracle_ms",
    "ring_attn_bwd_pallas_ms", "ring_attn_bwd_oracle_ms",
    # error / post-mortem records
    "resnet50_error", "deepfm_error", "nmt_big_error", "ring_attn_error",
    "dygraph_bench_error", "nmt_big_flight_dump", "ring_attn_flight_dump",
    "dygraph_flight_dump",
    # raw section payloads (gated scalars are lifted out of them; payloads
    # that carry a nested gated metric or invariant — dispatch_overhead,
    # ps_embedding, the *_hbm_plan dicts — are covered by THAT entry and
    # deliberately not re-listed here)
    "resnet50_roofline", "deepfm_roofline", "nmt_big_shapes",
    "nmt_big_buckets", "nmt_big_attn", "section_memory",
    "section_peak_bytes", "section_rss_mb",
    "input_pipeline", "ckpt_integrity", "ps_fault",
    "serving_fleet", "inference_compiler", "online_learning",
    "roofline_diff",
    # *_vs_baseline ratios are derived from gated metrics
    "resnet50_vs_baseline", "nmt_big_vs_baseline", "deepfm_vs_baseline",
]

# Flat metrics recoverable by regex from a truncated wrapper tail.
_RECOVERABLE = [p.split(".", 1)[1] for p in (
    [m[0] for m in METRICS if m[0].startswith("extra.")])
    if "." not in p.split(".", 1)[1]] + ["nmt_big_vs_baseline",
                                         "resnet50_vs_baseline",
                                         "deepfm_vs_baseline"]


def _lookup(doc: dict, path: str) -> Any:
    cur: Any = doc
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _recover_from_tail(tail: str) -> Optional[dict]:
    """Best-effort doc from a wrapper tail. Try the last parseable JSON
    object line first; fall back to regex-scraping known flat metrics
    out of a line the driver truncated mid-JSON."""
    for ln in reversed(tail.splitlines()):
        ln = ln.strip()
        if ln.startswith("{") and ln.endswith("}"):
            try:
                doc = json.loads(ln)
            except ValueError:
                continue
            if isinstance(doc, dict) and ("metric" in doc or "extra" in doc):
                return doc
    extra = {}
    for name in _RECOVERABLE:
        m = re.search(r'"%s"\s*:\s*(-?[0-9.eE+]+|null|true|false)'
                      % re.escape(name), tail)
        if m:
            extra[name] = json.loads(m.group(1))
    for name in [p.rsplit(".", 1)[1] for p in INVARIANTS]:
        m = re.search(r'"%s"\s*:\s*(true|false)' % re.escape(name), tail)
        if m:
            extra.setdefault("ps_embedding", {})[name] = m.group(1) == "true"
    # context fields the recovered metrics are gated under: the device
    # string and the deepfm workload scale (a truncated TPU-round tail
    # still names its 33.5M-row vocab inside deepfm_roofline)
    m = re.search(r'"device\\?"\s*:\s*\\?"([^"\\]+)', tail)
    if m and extra:
        extra["device"] = m.group(1)
    m = re.search(r'"deepfm_roofline\\?"\s*:\s*\{[^{}]*?'
                  r'"vocab\\?"\s*:\s*(\d+)', tail)
    if m and extra:
        extra.setdefault("deepfm_roofline", {})["vocab"] = int(m.group(1))
    if not extra:
        return None
    return {"metric": None, "value": None, "extra": extra,
            "_recovered_from_tail": sorted(extra)}


def load_doc(path: str) -> dict:
    """Load a bench doc from any of the accepted formats; raises
    ValueError when nothing recoverable."""
    with open(path) as f:
        raw = json.load(f)
    if not isinstance(raw, dict):
        raise ValueError(f"{path}: not a JSON object")
    if "metric" in raw or ("extra" in raw and "tail" not in raw):
        return raw
    if "parsed" in raw or "tail" in raw:  # driver wrapper
        if isinstance(raw.get("parsed"), dict):
            return raw["parsed"]
        doc = _recover_from_tail(raw.get("tail") or "")
        if doc is not None:
            return doc
        raise ValueError(f"{path}: wrapper has parsed=null and no "
                         f"recoverable metrics in tail")
    raise ValueError(f"{path}: unrecognized bench JSON shape")


def compare(fresh: dict, base: dict, margin_scale: float = 1.0) -> dict:
    """Walk the metric table; return {checked, skipped, regressions,
    improvements}. A regression entry carries path/base/fresh/limit."""
    checked, skipped, regressions, improvements = [], [], [], []
    for entry in METRICS:
        path, margin, direction = entry[0], entry[1], entry[2]
        contexts = entry[3] if len(entry) > 3 else ()
        margin *= margin_scale
        bv, fv = _lookup(base, path), _lookup(fresh, path)
        if bv is None and fv is None:
            skipped.append({"path": path, "reason": "absent both sides"})
            continue
        mismatch = None
        for ctx in contexts:
            cb, cf = _lookup(base, ctx), _lookup(fresh, ctx)
            if cb is not None and cf is not None and cb != cf:
                mismatch = f"context mismatch: {ctx} base={cb} fresh={cf}"
                break
        if mismatch is not None:
            skipped.append({"path": path, "reason": mismatch})
            continue
        if bv is None:
            skipped.append({"path": path, "reason": "no baseline value"})
            continue
        if fv is None:
            regressions.append({"path": path, "base": bv, "fresh": None,
                                "limit": None,
                                "reason": "metric lost (baseline has a "
                                          "value, fresh run does not)"})
            continue
        bv, fv = float(bv), float(fv)
        if direction == "higher":
            limit = bv * (1.0 - margin)
            ok = fv >= limit
        else:
            limit = bv * (1.0 + margin) + _ABS_SLACK_LOWER * margin_scale
            ok = fv <= limit
        entry = {"path": path, "base": bv, "fresh": fv,
                 "limit": round(limit, 6), "direction": direction}
        checked.append(entry)
        if not ok:
            regressions.append(entry)
        elif (fv > bv) == (direction == "higher") and fv != bv:
            improvements.append(entry)
    for path in INVARIANTS:
        bv, fv = _lookup(base, path), _lookup(fresh, path)
        if bv is True and fv is False:
            regressions.append({"path": path, "base": True, "fresh": False,
                                "limit": True,
                                "reason": "bitwise invariant flipped"})
        elif bv is not None and fv is not None:
            checked.append({"path": path, "base": bv, "fresh": fv,
                            "limit": True, "direction": "invariant"})
    for path in PRESENCE_INVARIANTS:
        bv, fv = _lookup(base, path), _lookup(fresh, path)
        if bv is None and fv is not None:
            regressions.append({"path": path, "base": None, "fresh": fv,
                                "limit": None,
                                "reason": "section OOM'd (baseline ran "
                                          "clean, fresh run emitted a "
                                          "post-mortem payload)"})
        elif fv is None:
            checked.append({"path": path, "base": bv, "fresh": None,
                            "limit": None, "direction": "invariant"})
    return {"checked": checked, "skipped": skipped,
            "regressions": regressions, "improvements": improvements}


def gate(fresh: dict, base: dict, margin_scale: float = 1.0,
         quiet: bool = False, out=None) -> int:
    """Compare and report; returns the intended exit code (0/1)."""
    out = out or sys.stdout
    rep = compare(fresh, base, margin_scale)
    if not quiet:
        if fresh.get("_recovered_from_tail"):
            print("note: fresh doc regex-recovered from wrapper tail",
                  file=out)
        if base.get("_recovered_from_tail"):
            print(f"note: baseline regex-recovered from wrapper tail "
                  f"({len(base['_recovered_from_tail'])} fields)", file=out)
        for e in rep["checked"]:
            if e["direction"] == "invariant":
                continue
            arrow = "within" if e not in rep["regressions"] else "REGRESSED"
            print(f"  {e['path']:<50} base={e['base']:<12g} "
                  f"fresh={e['fresh']:<12g} limit={e['limit']:<12g} "
                  f"{arrow}", file=out)
        for e in rep["skipped"]:
            print(f"  {e['path']:<50} skipped ({e['reason']})", file=out)
        for e in rep["regressions"]:
            if e.get("reason"):
                print(f"  {e['path']:<50} REGRESSED ({e['reason']})",
                      file=out)
    n = len(rep["regressions"])
    if n:
        print(f"perf_gate: FAIL — {n} regression(s) vs baseline", file=out)
        return 1
    print(f"perf_gate: PASS — {len(rep['checked'])} checked, "
          f"{len(rep['skipped'])} skipped, "
          f"{len(rep['improvements'])} improved", file=out)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.tools.perf_gate", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("fresh", help="fresh bench JSON (doc or driver wrapper)")
    p.add_argument("baseline", help="baseline bench JSON, e.g. "
                                    "BENCH_r05.json")
    p.add_argument("--margin-scale", type=float, default=1.0,
                   help="multiply every noise margin (e.g. 2.0 on noisy "
                        "shared machines)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the comparison report as JSON")
    args = p.parse_args(argv)
    try:
        fresh = load_doc(args.fresh)
        base = load_doc(args.baseline)
    except (OSError, ValueError) as e:
        print(f"perf_gate: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        rep = compare(fresh, base, args.margin_scale)
        print(json.dumps(rep))
        return 1 if rep["regressions"] else 0
    return gate(fresh, base, args.margin_scale)


if __name__ == "__main__":
    sys.exit(main())
