#!/usr/bin/env python
"""Input-pipeline micro-bench: sync feed/fetch vs prefetch + fetch handles.

Drives a deliberately slow reader (sleep-augmented, host cost ≈ 50% of
the synchronous step) through the two execution paths:

- **sync**: per step, numpy feed → ``Executor.run(return_numpy=True)`` —
  feed conversion, H2D, dispatch, and the device→host fetch copy all
  serialize on the training loop, exactly the pre-dataio behavior;
- **pipelined**: a ``dataio.DeviceLoader`` worker converts/device_puts
  the next batch while the device runs, and the loop keeps
  ``max_inflight`` un-synced ``FetchHandle`` dispatches outstanding.

Both arms consume IDENTICAL batch data from identically-initialized
scopes, so the per-step losses double as the bitwise-equivalence check
of the handle path against ``return_numpy=True``.

Run: ``python -m paddle_tpu.tools.pipeline_bench [--steps N]`` — prints
one JSON object; ``bench.py`` embeds the same dict in the BENCH json.
"""
from __future__ import annotations

import argparse
import collections
import json
import time

import numpy as np

__all__ = ["run_pipeline_bench"]


def _build(batch: int, dim: int, depth: int, seed: int = 7):
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [dim])
        label = fluid.layers.data("label", [1], dtype="int32")
        h = x
        for _ in range(depth):
            h = fluid.layers.fc(h, dim, act="relu")
        logits = fluid.layers.fc(h, 10)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def run_pipeline_bench(steps: int = 30, batch: int = 256, dim: int = 512,
                       depth: int = 4, reader_cost_frac: float = 1.0,
                       max_inflight: int = 2) -> dict:
    """Returns {sync_steps_per_s, pipelined_steps_per_s, speedup,
    reader_sleep_ms, bare_step_ms, outputs_identical, ...}.

    reader_cost_frac scales the reader's per-batch sleep relative to the
    measured bare step time; 1.0 means host cost equals device step time
    — i.e. ~50% of the SYNCHRONOUS step, the ISSUE's target regime.

    Model sizing note: the step must be COMPUTE-dominated for the overlap
    to be observable on CPU — XLA execution releases the GIL, so the
    reader thread's work runs concurrently; a host-dispatch-dominated toy
    step would serialize on the GIL and understate the win (on a real
    accelerator the device computes while the host dispatches, so the
    overlap is strictly better there)."""
    import paddle_tpu as fluid
    from paddle_tpu.dataio import DeviceLoader

    main, startup, loss = _build(batch, dim, depth)
    exe = fluid.Executor(fluid.TPUPlace())

    rng = np.random.RandomState(0)
    data = [{"x": rng.randn(batch, dim).astype("float32"),
             "label": rng.randint(0, 10, (batch, 1)).astype("int32")}
            for _ in range(steps)]

    # bare device step time (feed resident, async dispatch, one sync)
    import jax.numpy as jnp
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        dev_feed = {k: jnp.asarray(v) for k, v in data[0].items()}
        exe.run(main, feed=dev_feed, fetch_list=[loss])  # compile
        t0 = time.perf_counter()
        for _ in range(10):
            out = exe.run(main, feed=dev_feed, fetch_list=[loss],
                          return_numpy=False)
        np.asarray(out[0])
        bare_step_s = (time.perf_counter() - t0) / 10

    sleep_s = bare_step_s * reader_cost_frac

    def slow_reader():
        for b in data:
            time.sleep(sleep_s)
            yield b

    # -- sync arm ----------------------------------------------------------
    sync_losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        exe.run(main, feed=data[0], fetch_list=[loss])  # warm (discarded)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        t0 = time.perf_counter()
        for feed in slow_reader():
            sync_losses.append(exe.run(main, feed=feed,
                                       fetch_list=[loss])[0])
        sync_s = time.perf_counter() - t0

    # -- pipelined arm -----------------------------------------------------
    pipe_losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        loader = DeviceLoader(slow_reader, capacity=max(2, max_inflight),
                              program=main, name="pipeline_bench")
        inflight: "collections.deque" = collections.deque()
        t0 = time.perf_counter()
        try:
            for feed in loader:
                inflight.append(exe.run(main, feed=feed, fetch_list=[loss],
                                        return_handle=True))
                while len(inflight) > max_inflight:
                    pipe_losses.append(inflight.popleft().numpy()[0])
            while inflight:
                pipe_losses.append(inflight.popleft().numpy()[0])
            pipe_s = time.perf_counter() - t0
        finally:
            loader.close()

    identical = (len(sync_losses) == len(pipe_losses) == steps and all(
        np.array_equal(a, b) for a, b in zip(sync_losses, pipe_losses)))
    return {
        "steps": steps,
        "bare_step_ms": round(bare_step_s * 1e3, 3),
        "reader_sleep_ms": round(sleep_s * 1e3, 3),
        "sync_steps_per_s": round(steps / sync_s, 2),
        "pipelined_steps_per_s": round(steps / pipe_s, 2),
        "speedup": round(sync_s / pipe_s, 3),
        "max_inflight": max_inflight,
        "outputs_identical": bool(identical),
    }


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--dim", type=int, default=512)
    p.add_argument("--depth", type=int, default=4)
    p.add_argument("--reader-cost-frac", type=float, default=1.0)
    p.add_argument("--max-inflight", type=int, default=2)
    args = p.parse_args()
    print(json.dumps(run_pipeline_bench(
        steps=args.steps, batch=args.batch, dim=args.dim, depth=args.depth,
        reader_cost_frac=args.reader_cost_frac,
        max_inflight=args.max_inflight)))


if __name__ == "__main__":
    main()
