"""Post-mortem bundler: one self-contained report per incident.

The root-cause loop leaves its evidence in four places — the flight
recorder's dump (what the process looked like when it paged), the
metrics history (the trajectory that led there), the ProfileTrigger's
attribution (which kernels moved vs golden), and the alert manager's
event timeline (what fired, when, in what order). Each is individually
queryable; an incident review wants them stapled together. This tool
does the stapling:

    # in-process (bench chaos cell, a trainer's atexit hook):
    from paddle_tpu.tools import postmortem
    report = postmortem.build_report()
    open("incident.md", "w").write(postmortem.render_markdown(report))

    # against a live process's introspection server:
    python -m paddle_tpu.tools.postmortem --url http://127.0.0.1:8788 \
        --out incident.json --md incident.md

    # offline, from what survived process death:
    python -m paddle_tpu.tools.postmortem --flight-dump flight_*.json \
        --history-dir /var/log/pdtpu_history --md incident.md

The JSON report is self-contained (no references back into the process
that died); the markdown rendering is the human summary — alert
timeline table, culprit-kernel table, history sparkline per signal.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
import urllib.request
from typing import List, Optional

__all__ = ["build_report", "render_markdown", "load_history_segments",
           "main"]

SPARK_CHARS = "▁▂▃▄▅▆▇█"


def sparkline(values: List[float], width: int = 24) -> str:
    """Unicode sparkline of `values`, downsampled to `width` chars."""
    vals = [float(v) for v in values if isinstance(v, (int, float))]
    if not vals:
        return ""
    if len(vals) > width:
        # stride-sample to width, always keeping the newest point
        step = len(vals) / width
        vals = [vals[min(len(vals) - 1, int(i * step))]
                for i in range(width - 1)] + [vals[-1]]
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return SPARK_CHARS[0] * len(vals)
    return "".join(
        SPARK_CHARS[min(len(SPARK_CHARS) - 1,
                        int((v - lo) / span * len(SPARK_CHARS)))]
        for v in vals)


# ----------------------------------------------------------- gathering
def build_report(center_t: Optional[float] = None,
                 half_width_s: float = 120.0,
                 history_prefix: str = "") -> dict:
    """Bundle the in-process evidence. `center_t` defaults to the last
    attribution's anomaly time, else now."""
    from ..observability.alerts import get_alert_manager
    from ..observability.flight import get_flight_recorder
    from ..observability.history import get_history
    from ..observability.profile_trigger import get_trigger

    report: dict = {"generated_t": time.time(), "source": "in-process"}
    trigger = get_trigger()
    att = trigger.last_attribution() if trigger is not None else None
    report["attribution"] = att
    if center_t is None:
        center_t = (att or {}).get("t") or time.time()
    report["center_t"] = center_t
    mgr = get_alert_manager()
    report["alert_timeline"] = (mgr.recent_events(64)
                                if mgr is not None else [])
    report["alerts"] = mgr.doc() if mgr is not None else None
    rec = get_flight_recorder()
    report["flight"] = {"last_dump_path": rec.last_dump_path,
                        "last_dump": rec.last_dump}
    hist = get_history()
    if hist is not None:
        report["history_stats"] = hist.stats()
        report["history_window"] = hist.window(
            center_t, half_width_s=half_width_s, prefix=history_prefix)
    else:
        report["history_stats"] = None
        report["history_window"] = None
    return report


def load_history_segments(history_dir: str,
                          max_lines: int = 10000) -> List[dict]:
    """Parse the newest JSONL spill segments (newest last); malformed
    lines are skipped — a torn final line must not sink the review."""
    segs = sorted(glob.glob(os.path.join(history_dir, "history_*.jsonl")))
    sweeps: List[dict] = []
    for seg in segs:
        with open(seg) as f:
            for line in f:
                try:
                    sweeps.append(json.loads(line))
                except ValueError:
                    continue
    return sweeps[-max_lines:]


def _report_from_url(base: str) -> dict:
    """Bundle over a live process's introspection endpoints."""
    def fetch(path):
        try:
            with urllib.request.urlopen(base.rstrip("/") + path,
                                        timeout=5.0) as resp:
                return json.load(resp)
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}

    alerts = fetch("/alerts")
    flight = fetch("/debug/flight")
    history = fetch("/history?window=300")
    return {"generated_t": time.time(), "source": base,
            "attribution": (flight.get("last_dump") or {}).get(
                "sections", {}).get("profile_trigger", {}).get("last")
            if isinstance(flight, dict) else None,
            "center_t": time.time(),
            "alert_timeline": (alerts.get("recent_events", [])
                               if isinstance(alerts, dict) else []),
            "alerts": alerts,
            "flight": {"last_dump_path": flight.get("last_dump_path")
                       if isinstance(flight, dict) else None,
                       "last_dump": flight.get("last_dump")
                       if isinstance(flight, dict) else None},
            "history_stats": (history.get("stats")
                              if isinstance(history, dict) else None),
            "history_window": {"series": history.get("series", [])}
            if isinstance(history, dict) else None}


def _report_offline(flight_dump: Optional[str],
                    history_dir: Optional[str]) -> dict:
    report: dict = {"generated_t": time.time(), "source": "offline",
                    "alert_timeline": [], "alerts": None,
                    "attribution": None, "center_t": None,
                    "flight": {"last_dump_path": flight_dump,
                               "last_dump": None},
                    "history_stats": None, "history_window": None}
    if flight_dump:
        with open(flight_dump) as f:
            dump = json.load(f)
        report["flight"]["last_dump"] = dump
        report["center_t"] = dump.get("time")
        sect = (dump.get("sections") or {}).get("profile_trigger") or {}
        report["attribution"] = sect.get("last")
    if history_dir:
        sweeps = load_history_segments(history_dir)
        # rebuild a query-shaped window from the raw sweep lines
        series: dict = {}
        for sw in sweeps:
            t = sw.get("t")
            for s in sw.get("series", ()):
                key = (s.get("name"), json.dumps(s.get("labels"),
                                                 sort_keys=True),
                       s.get("field"))
                series.setdefault(key, []).append([t, s.get("v")])
        report["history_window"] = {"series": [
            {"name": k[0], "labels": json.loads(k[1]), "field": k[2],
             "tier": "raw", "points": pts}
            for k, pts in sorted(series.items())]}
        report["history_stats"] = {"sweeps": len(sweeps),
                                   "source_dir": history_dir}
    return report


# ------------------------------------------------------------ rendering
def _fmt_t(t) -> str:
    if not isinstance(t, (int, float)):
        return "?"
    return time.strftime("%H:%M:%S", time.localtime(t))


def render_markdown(report: dict) -> str:
    """The human summary of one incident bundle."""
    out: List[str] = []
    out.append(f"# Post-mortem — {_fmt_t(report.get('center_t'))} "
               f"(generated {_fmt_t(report.get('generated_t'))}, "
               f"source: {report.get('source')})")
    att = report.get("attribution") or {}
    out.append("\n## Kernel attribution")
    culprits = att.get("culprit_kernels") or []
    if att.get("error"):
        out.append(f"attribution failed: `{att['error']}`")
    elif culprits:
        out.append(f"trigger: `{att.get('trigger', '?')}` at "
                   f"{_fmt_t(att.get('t'))}, capture "
                   f"{att.get('capture_ms', '?')} ms")
        out.append("")
        out.append("| kernel | ms | Δms vs golden | why |")
        out.append("|---|---|---|---|")
        for c in culprits:
            out.append(f"| `{c.get('kernel')}` | {c.get('ms', '')} "
                       f"| {c.get('delta_ms', '')} | {c.get('why', '')} |")
        diff = att.get("trace_diff") or {}
        if diff.get("delta_ms_per_step") is not None:
            out.append(f"\ndevice ms/step moved "
                       f"{diff['delta_ms_per_step']:+.2f} vs golden")
    else:
        out.append("no capture recorded (ProfileTrigger not installed, "
                   "gated, or nothing fired)")
    out.append("\n## Alert timeline")
    timeline = report.get("alert_timeline") or []
    if timeline:
        out.append("| t | event | alert | severity | value |")
        out.append("|---|---|---|---|---|")
        for ev in timeline:
            out.append(f"| {_fmt_t(ev.get('wall_t', ev.get('t')))} "
                       f"| {ev.get('event')} | {ev.get('name')} "
                       f"| {ev.get('severity')} "
                       f"| {ev.get('value', '')} |")
    else:
        out.append("no alert events recorded")
    out.append("\n## Metric trajectories")
    window = report.get("history_window") or {}
    series = window.get("series") or []
    if series:
        out.append("| series | field | points | last | trend |")
        out.append("|---|---|---|---|---|")
        for s in series[:40]:
            pts = s.get("points") or []
            vals = [p[1] for p in pts if len(p) > 1]
            label = s["name"]
            if s.get("labels"):
                inner = ",".join(f"{k}={v}"
                                 for k, v in sorted(s["labels"].items()))
                label += "{" + inner + "}"
            last = f"{vals[-1]:.4g}" if vals else ""
            out.append(f"| `{label}` | {s.get('field')} | {len(pts)} "
                       f"| {last} | {sparkline(vals)} |")
        if len(series) > 40:
            out.append(f"\n({len(series) - 40} more series in the JSON "
                       f"report)")
    else:
        out.append("no history window available")
    stats = report.get("history_stats") or {}
    if stats:
        out.append(f"\nhistory: {stats.get('series', '?')} series, "
                   f"{stats.get('raw_points', '?')} raw points, "
                   f"~{stats.get('est_bytes', 0)} bytes "
                   f"(cap {stats.get('max_bytes', '?')})")
    out.append("\n## Flight dump")
    fl = report.get("flight") or {}
    dump = fl.get("last_dump")
    if dump:
        exc = dump.get("exception") or {}
        out.append(f"`{exc.get('type')}`: {exc.get('message', '')[:200]}")
        out.append(f"context: `{json.dumps(dump.get('context', {}), default=str)[:300]}`")
        out.append(f"{len(dump.get('steps', []))} step records, "
                   f"{len(dump.get('events', []))} events"
                   + (f" — {fl['last_dump_path']}"
                      if fl.get("last_dump_path") else ""))
    else:
        out.append("no flight dump recorded")
    return "\n".join(out) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.tools.postmortem", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--url", help="live process introspection base URL")
    p.add_argument("--flight-dump", help="offline: a flight dump JSON")
    p.add_argument("--history-dir",
                   help="offline: PDTPU_HISTORY_DIR JSONL segments")
    p.add_argument("--out", help="write the JSON bundle here")
    p.add_argument("--md", help="write the markdown rendering here")
    args = p.parse_args(argv)

    if args.url:
        report = _report_from_url(args.url)
    elif args.flight_dump or args.history_dir:
        report = _report_offline(args.flight_dump, args.history_dir)
    else:
        report = build_report()
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, default=str)
    md = render_markdown(report)
    if args.md:
        with open(args.md, "w") as f:
            f.write(md)
    if not args.out and not args.md:
        print(md)
    else:
        print(f"postmortem: {'JSON ' + args.out if args.out else ''}"
              f"{' ' if args.out and args.md else ''}"
              f"{'markdown ' + args.md if args.md else ''}".strip())
    return 0


if __name__ == "__main__":
    sys.exit(main())
