"""Operator CLI for a running PS shard fleet.

The command-line view of what ``ps.ShardMonitor`` watches: point it at
the pserver endpoint list and ask each shard how it's doing. Never
imports JAX (it must run on a bastion or a pserver host), never retries
(an operator wants the truthful instantaneous answer, not the
self-healed one), and always exits 0/1 so it can sit in a cron or a
k8s liveness probe.

CLI::

    python -m paddle_tpu.tools.ps_admin ping
    python -m paddle_tpu.tools.ps_admin stats --endpoints h1:6000,h2:6000
    python -m paddle_tpu.tools.ps_admin meta
    python -m paddle_tpu.tools.ps_admin dump-health --json

Endpoints come from ``--endpoints`` (comma-separated), else
``PADDLE_PSERVER_ENDPOINTS``, else ``PADDLE_PSERVERS_IP_PORT_LIST``
(both reference-style env spellings are honored, same as the fleet role
makers).

Commands:

* ``ping``        — one-shot liveness per shard (fresh connection each);
* ``meta``        — which tables each shard hosts and their row ranges;
* ``stats``       — per-shard pull/push byte counters, plus the worker's
  hot-row-cache block (hit rate, resident/dirty rows, write-back bytes)
  when one is in play, plus a ``vocab`` block when any shard is a
  dynamic-vocab one (live vs provisioned rows, materialized/evicted
  totals and the eviction rate, oldest-row age — the online-learning
  occupancy picture);
* ``dump-health`` — the ShardMonitor view as one JSON document: runs a
  single synchronous sweep and prints ``status`` (ok/degraded/failing),
  per-shard up flags, and the endpoint list — what the in-process
  ``/healthz`` check ``ps/shards`` reports, minus the wedge timer
  (a one-shot CLI has no down-since history). Includes the same
  ``hot_cache`` block as ``stats``, and a dynamic-vocab shard sitting
  within 5% of its row cap escalates ``status`` to ``degraded`` (the
  next sweep will be evicting WARM ids — grow the capacity);
* ``fleet``       — ONE federated scrape of the whole system: every
  pserver endpoint (transport ``metrics`` op) plus every worker/replica
  introspection server given via ``--workers http://h:p,...``
  (``/metrics/series``). Prints a per-process table (role, reachability,
  scrape latency, series count, queue depth / pull p99 where present)
  and the derived ``autoscale/*`` signals; ``--json`` prints the full
  ``/fleet`` document. Exit 1 when ANY scrape failed. ``--watch N``
  re-scrapes and re-renders every N seconds (screen cleared each pass,
  Ctrl-C exits 0) — quick shard-level watching without the full
  ``tools/ops_console`` dashboard;
* ``history``     — the coordinator's ring TSDB (``/history`` on the
  ``--worker`` URL): one row per stored series with point count, last
  value, and a sparkline of the requested window. ``--prefix`` filters
  by series-name prefix (e.g. ``autoscale/``), ``--window`` is the
  lookback in seconds (default 300), ``--tier raw|mid|long`` picks the
  downsampling tier; ``--json`` prints the raw document. Exit 1 when
  no MetricsHistory is installed.

The hot-row cache lives in the WORKER process, not on the shards, so
its ``ps/cache_*`` series come from the worker's introspection plane:
pass ``--worker http://host:port`` (the ``PDTPU_INTROSPECT_PORT``
server; ``/metrics.json`` is fetched) — or, with no ``--worker``, from
this process's own registry, which is only meaningful for in-process
callers (tests, notebooks driving the tier directly).

Exit code 0 when every shard answered, 1 otherwise (plus 2 for usage
errors, argparse's convention).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

__all__ = ["main"]


def _endpoints(arg: str) -> list:
    eps = (arg or os.environ.get("PADDLE_PSERVER_ENDPOINTS")
           or os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST") or "")
    out = [e.strip() for e in eps.replace(";", ",").split(",") if e.strip()]
    if not out:
        raise SystemExit(
            "ps_admin: no endpoints — pass --endpoints host:port,... or "
            "set PADDLE_PSERVER_ENDPOINTS")
    for e in out:
        if ":" not in e:
            raise SystemExit(f"ps_admin: bad endpoint {e!r} "
                             "(expected host:port)")
    return out


# counter / gauge suffixes of the ps/cache_* series (hot_cache.py)
_CACHE_KEYS = ("hits", "misses", "lookup_hits", "lookup_misses",
               "admitted", "evictions", "bypass", "writeback_bytes",
               "resident_rows", "dirty_rows", "capacity")


def cache_fields(worker: str = "", timeout: float = 2.0):
    """The hot-row-cache block for ``stats``/``dump-health``: the
    ``ps/cache_*`` registry series plus derived ratios, read from a
    worker's ``/metrics.json`` (``worker`` is the introspection base
    URL) or from this process's registry when ``worker`` is empty.
    Returns None when no hot cache has ever registered (capacity 0)."""
    if worker:
        import urllib.request
        url = worker.rstrip("/") + "/metrics.json"
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            snap = json.load(resp)
    else:
        from ..observability.registry import get_registry
        snap = get_registry().snapshot(deep=True)
    if not float(snap.get("ps/cache_capacity", 0) or 0):
        return None
    out = {k: snap.get(f"ps/cache_{k}", 0) for k in _CACHE_KEYS}
    total = out["hits"] + out["misses"]
    out["hit_rate"] = (out["hits"] / total) if total else None
    ltotal = out["lookup_hits"] + out["lookup_misses"]
    out["lookup_hit_rate"] = (out["lookup_hits"] / ltotal) if ltotal else None
    out["dirty_fraction"] = (out["dirty_rows"] / out["capacity"]
                             if out["capacity"] else None)
    return out


# a dynamic shard at >= 95% of its slab is one hot batch away from
# evicting warm rows; surface it before quality degrades silently
_VOCAB_CAP_WARN = 0.95


def vocab_fields(payloads):
    """The dynamic-vocab block for ``stats``/``dump-health``, aggregated
    from per-endpoint ``stats`` payloads (``[(endpoint, {table:
    shard.stats()})]``). Returns None when no shard is dynamic;
    otherwise per-table occupancy totals plus ``near_cap`` — the shards
    within ``1 - _VOCAB_CAP_WARN`` of their row cap."""
    tables: dict = {}
    near_cap = []
    for i, (ep, payload) in enumerate(payloads):
        if not isinstance(payload, dict):
            continue
        for tname, st in payload.items():
            if not isinstance(st, dict) or not st.get("dynamic"):
                continue
            t = tables.setdefault(tname, {
                "live_rows": 0, "provisioned_rows": 0, "materialized": 0,
                "evicted": 0, "pinned": 0, "oldest_row_age_s": 0.0})
            live = int(st.get("live_rows", 0))
            cap = int(st.get("capacity", 0))
            t["live_rows"] += live
            t["provisioned_rows"] += cap
            t["materialized"] += int(st.get("materialized", 0))
            t["evicted"] += int(st.get("evicted", 0))
            t["pinned"] += int(st.get("pinned", 0))
            t["oldest_row_age_s"] = max(t["oldest_row_age_s"],
                                        float(st.get("oldest_age_s") or 0))
            if cap and live >= _VOCAB_CAP_WARN * cap:
                near_cap.append({"shard": i, "endpoint": ep,
                                 "table": tname, "live_rows": live,
                                 "capacity": cap})
    if not tables:
        return None
    for t in tables.values():
        t["utilization"] = (t["live_rows"] / t["provisioned_rows"]
                            if t["provisioned_rows"] else None)
        t["eviction_rate"] = (t["evicted"] / t["materialized"]
                              if t["materialized"] else None)
    return {"tables": tables, "near_cap": near_cap}


def _series_get(series, name, field="value"):
    """First series named `name`: its value (counter/gauge) or the
    given summary field; None when the process has no such series."""
    for s in series:
        if s.get("name") != name:
            continue
        if s.get("type") == "summary":
            return (s.get("summary") or {}).get(field)
        return s.get("value")
    return None


def fleet_scrape(endpoints, workers, timeout: float = 2.0) -> dict:
    """One federated sweep over pserver endpoints + worker introspection
    URLs; returns the ``/fleet`` document (see observability.federate)."""
    from ..observability.federate import FederatedScraper, ScrapeTarget

    targets = [ScrapeTarget.ps(ep, shard=i) for i, ep in
               enumerate(endpoints)]
    targets += [ScrapeTarget.http(url) for url in workers]
    return FederatedScraper(targets, timeout=timeout).scrape_once()


def format_fleet(doc: dict) -> str:
    """The per-process table + signal block for ``fleet``."""
    lines = [f"{'process':<28}{'role':<10}{'shard':>6}{'state':>8}"
             f"{'scrape_ms':>11}{'series':>8}{'queue':>7}"
             f"{'pull_p99_ms':>12}"]
    for r in doc["targets"]:
        q = _series_get(r["series"], "serving/queue_depth")
        p99 = _series_get(r["series"], "ps/shard_pull_ms", field="p99")
        lines.append(
            f"{r['process']:<28}{r['role']:<10}"
            f"{'-' if r['shard'] is None else r['shard']:>6}"
            f"{'up' if r['ok'] else 'DOWN':>8}"
            f"{r['scrape_ms']:>11.1f}{len(r['series']):>8}"
            f"{'-' if q is None else int(q):>7}"
            f"{'-' if p99 is None else round(p99, 2):>12}")
        if not r["ok"]:
            lines.append(f"    error: {r['error']}")
    sig = doc.get("signals") or {}
    lines.append("")
    lines.append("autoscaler signals: " + json.dumps(sig, sort_keys=True))
    return "\n".join(lines)


def _ask(endpoint: str, op: str, timeout: float):
    """(ok, payload-or-error) for one shard, single attempt."""
    from ..ps.transport import SocketClient

    c = SocketClient(endpoint, timeout=timeout, retries=0)
    try:
        if op == "ping":
            return True, c.ping()
        return True, getattr(c, op)()
    except Exception as e:
        return False, f"{type(e).__name__}: {e}"
    finally:
        c.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ps_admin",
        description="inspect a running PS shard fleet")
    ap.add_argument("cmd", choices=["ping", "stats", "meta", "dump-health",
                                    "fleet", "history"])
    ap.add_argument("--endpoints", default="",
                    help="comma-separated host:port list (default: "
                         "PADDLE_PSERVER_ENDPOINTS)")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-shard socket timeout, seconds (default 2)")
    ap.add_argument("--worker", default="",
                    help="worker introspection base URL (http://host:port)"
                         " for the hot-row-cache fields; default: this "
                         "process's registry")
    ap.add_argument("--workers", default="",
                    help="fleet: comma-separated worker/replica "
                         "introspection base URLs to scrape alongside "
                         "the pserver endpoints")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (dump-health always is)")
    ap.add_argument("--watch", type=float, default=None, metavar="N",
                    help="fleet: re-scrape and re-render every N seconds "
                         "(clear screen each pass; Ctrl-C exits cleanly)")
    ap.add_argument("--prefix", default="",
                    help="history: series-name prefix filter")
    ap.add_argument("--window", type=float, default=300.0,
                    help="history: lookback window, seconds (default 300)")
    ap.add_argument("--tier", default="raw", choices=["raw", "mid", "long"],
                    help="history: downsampling tier (default raw)")
    args = ap.parse_args(argv)

    if args.cmd == "history":
        if not args.worker:
            raise SystemExit("ps_admin: history needs --worker "
                             "http://host:port (the introspection URL)")
        import urllib.error
        import urllib.parse
        import urllib.request
        qs = urllib.parse.urlencode({
            "prefix": args.prefix, "window": args.window,
            "tier": args.tier})
        url = args.worker.rstrip("/") + "/history?" + qs
        try:
            with urllib.request.urlopen(url, timeout=args.timeout) as resp:
                doc = json.load(resp)
        except urllib.error.HTTPError as e:
            print(f"ps_admin: {url}: HTTP {e.code} "
                  f"({e.read().decode(errors='replace').strip()})",
                  file=sys.stderr)
            return 1
        except Exception as e:
            print(f"ps_admin: {url}: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(doc, sort_keys=True, default=str))
            return 0
        from .postmortem import sparkline
        stats = doc.get("stats") or {}
        print(f"history: {stats.get('series', '?')} series, "
              f"{stats.get('raw_points', '?')} raw points, "
              f"~{stats.get('est_bytes', 0)} / "
              f"{stats.get('max_bytes', '?')} bytes")
        print(f"{'series':<52}{'pts':>5}{'last':>12}  trend")
        for s in doc.get("series", ()):
            label = s["name"]
            if s.get("labels"):
                label += "{" + ",".join(
                    f"{k}={v}"
                    for k, v in sorted(s["labels"].items())) + "}"
            if s.get("field") != "value":
                label += f" [{s['field']}]"
            pts = s.get("points") or []
            vals = [p[1] for p in pts if len(p) > 1]
            last = f"{vals[-1]:.4g}" if vals else "-"
            print(f"{label[:51]:<52}{len(pts):>5}{last:>12}  "
                  f"{sparkline(vals)}")
        return 0

    if args.cmd == "fleet":
        workers = [w.strip() for w in args.workers.split(",") if w.strip()]
        try:
            eps = _endpoints(args.endpoints)
        except SystemExit:
            if not workers:  # a fleet needs SOMETHING to scrape
                raise
            eps = []

        def render_once() -> dict:
            doc = fleet_scrape(eps, workers, timeout=args.timeout)
            if args.json:
                print(json.dumps(doc, sort_keys=True, default=str))
            else:
                print(format_fleet(doc))
            return doc

        if args.watch is not None:
            if args.watch <= 0:
                raise SystemExit("ps_admin: --watch must be > 0")
            try:
                while True:
                    # ANSI clear + home — a poor man's watch(1)
                    sys.stdout.write("\x1b[2J\x1b[H")
                    render_once()
                    sys.stdout.flush()
                    time.sleep(args.watch)
            except KeyboardInterrupt:
                return 0
        doc = render_once()
        return 0 if doc["ok"] else 1

    eps = _endpoints(args.endpoints)

    def _cache():
        try:
            return cache_fields(args.worker, args.timeout)
        except Exception as e:  # unreachable worker != unhealthy shards
            return {"error": f"{type(e).__name__}: {e}"}

    if args.cmd == "dump-health":
        from ..ps.health import ShardMonitor
        mon = ShardMonitor.for_endpoints(eps)
        mon.poll_now()
        doc = mon.status()
        doc["hot_cache"] = _cache()
        payloads = []
        for ep in eps:
            ok, payload = _ask(ep, "stats", args.timeout)
            payloads.append((ep, payload if ok else None))
        doc["vocab"] = vocab_fields(payloads)
        near = (doc["vocab"] or {}).get("near_cap") or []
        if near:
            flagged = {n["shard"] for n in near}
            for s in doc["shards"]:
                s["near_cap"] = s["shard"] in flagged
            if doc["status"] == "ok":
                # up but one hot batch from evicting warm ids: degraded,
                # not failing — the fleet serves, capacity needs growing
                doc["status"] = "degraded"
                doc["detail"] = (
                    f"{len(near)} dynamic shard(s) within "
                    f"{round((1 - _VOCAB_CAP_WARN) * 100)}% of row cap: "
                    + ", ".join(f"{n['endpoint']}/{n['table']} "
                                f"{n['live_rows']}/{n['capacity']}"
                                for n in near))
        print(json.dumps(doc, indent=None if args.json else 2,
                         sort_keys=True))
        return 0 if all(s["up"] for s in doc["shards"]) else 1

    op = {"ping": "ping", "stats": "stats", "meta": "meta"}[args.cmd]
    rows = []
    all_up = True
    for i, ep in enumerate(eps):
        ok, payload = _ask(ep, op, args.timeout)
        all_up &= ok
        rows.append({"shard": i, "endpoint": ep, "up": ok,
                     ("error" if not ok else op): payload})
    cache = _cache() if op == "stats" else None
    vocab = None
    if op == "stats":
        vocab = vocab_fields([(r["endpoint"], r.get("stats"))
                              for r in rows if r["up"]])
    if args.json:
        if op == "stats":
            print(json.dumps({"shards": rows, "hot_cache": cache,
                              "vocab": vocab}, sort_keys=True))
        else:
            print(json.dumps(rows, sort_keys=True))
    else:
        for r in rows:
            state = "up" if r["up"] else f"DOWN ({r['error']})"
            line = f"shard {r['shard']} {r['endpoint']}: {state}"
            if r["up"] and op != "ping":
                line += " " + json.dumps(r[op], sort_keys=True)
            print(line)
        if cache is not None:
            print("hot cache: " + json.dumps(cache, sort_keys=True))
        if vocab is not None:
            print("vocab: " + json.dumps(vocab, sort_keys=True))
    return 0 if all_up else 1


if __name__ == "__main__":
    sys.exit(main())
