"""Per-kernel roofline report from a chrome/jax-profiler trace.

Generalizes bench.py's resnet ``per_kernel`` accounting into a
standalone surface: given a trace (a ``.trace.json[.gz]`` file or a
``jax.profiler`` log dir), report the top-k kernels by device time with
their achieved GB/s and TFLOP/s and ``util_vs_bound`` — the kernel's
achieved fraction of whichever calibrated chip bound (stream or matmul)
it sits closer to — plus the sub-cutoff tail in aggregate. Floors come
from the shared calibration cache (observability/calibrate.py) unless
overridden with ``--matmul-tflops/--stream-gbs``.

``--diff OTHER`` compares two traces: per-kernel ms deltas sorted by
absolute movement, plus kernels that appear in only one trace — the
"what did my change do" view the kernel campaign (ROADMAP item 4) runs
on.

Reading the numbers: GB/s uses the HLO cost model's ``bytes_accessed``
arg, which counts VMEM-staged re-reads — utilizations above 1.0 are
real and mean XLA is feeding the kernel from VMEM faster than HBM could.
``model_flops`` is algorithmic flops, so padded MXU work shows up as a
LOWER rate, as it should.

Usage::

    python -m paddle_tpu.tools.roofline TRACE [--topk 20]
        [--cutoff-ms 0.5] [--steps 1] [--json]
        [--matmul-tflops X --stream-gbs Y] [--diff OTHER]
"""
from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys
from typing import Optional, Tuple

__all__ = ["load_trace", "kernel_table", "capture_kernel_table",
           "diff_tables", "main"]


def load_trace(path: str) -> dict:
    """Load a chrome trace: plain ``.json``, gzipped ``.json.gz``, or a
    jax.profiler log dir (picks the newest
    ``plugins/profile/*/*.trace.json.gz``)."""
    if os.path.isdir(path):
        cands = sorted(
            glob.glob(os.path.join(path, "plugins/profile/*/*.trace.json.gz"))
            + glob.glob(os.path.join(path, "*.trace.json.gz"))
            + glob.glob(os.path.join(path, "*.trace.json")),
            key=os.path.getmtime)
        if not cands:
            raise FileNotFoundError(f"no trace files under {path!r}")
        path = cands[-1]
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f)


def _aggregate(tr: dict) -> "collections.defaultdict":
    """name -> [us, calls, bytes, flops] over the trace's device kernel
    events. Prefers the ``XLA Ops`` thread inside device (``TPU``) pids
    — the per-kernel lane of a jax profiler export; when the trace has
    no such metadata (synthetic or foreign traces) every X event counts,
    minus the loop/step overhead spans."""
    pidname = {e["pid"]: e["args"].get("name", "") for e in tr["traceEvents"]
               if e.get("ph") == "M" and e.get("name") == "process_name"}
    tidname = {(e["pid"], e.get("tid")): e["args"].get("name", "")
               for e in tr["traceEvents"]
               if e.get("ph") == "M" and e.get("name") == "thread_name"}
    dev_pids = {p for p, nm in pidname.items() if "TPU" in nm}
    op_keys = {k for k, nm in tidname.items() if nm == "XLA Ops"
               and (not dev_pids or k[0] in dev_pids)}

    agg = collections.defaultdict(lambda: [0.0, 0, 0.0, 0.0])
    for e in tr["traceEvents"]:
        if e.get("ph") != "X":
            continue
        nm = e.get("name", "")
        if op_keys:
            if (e.get("pid"), e.get("tid")) not in op_keys:
                continue
        else:
            if dev_pids and e.get("pid") not in dev_pids:
                continue
            if nm == "while" or nm.startswith("jit_") or nm.isdigit():
                continue
        a = agg[nm]
        a[0] += e.get("dur", 0.0)
        a[1] += 1
        a[2] += float(e.get("args", {}).get("bytes_accessed", 0) or 0)
        a[3] += float(e.get("args", {}).get("model_flops", 0) or 0)
    return agg


def kernel_table(tr: dict, floors: Tuple[float, float], steps: int = 1,
                 cutoff_ms: float = 0.5, topk: Optional[int] = None) -> dict:
    """The bench ``per_kernel`` dict from an in-memory trace: every
    kernel >= cutoff_ms per step with achieved GB/s / TFLOP/s /
    util_vs_bound, the sub-cutoff tail in aggregate, and whole-trace
    aggregate rates."""
    mm_tflops, stream_gbs = floors
    agg = _aggregate(tr)
    if not agg:
        return {"error": "no kernel events in trace"}
    total_us = sum(a[0] for a in agg.values())
    rows = []
    tail_us = tail_by = tail_fl = tail_n = 0
    for nm, (us, c, by, fl) in sorted(agg.items(), key=lambda kv: -kv[1][0]):
        ms = us / steps / 1e3
        gbs = by / (us * 1e-6) / 1e9 if us else 0.0
        tfs = fl / (us * 1e-6) / 1e12 if us else 0.0
        if ms >= cutoff_ms and (topk is None or len(rows) < topk):
            rows.append({"kernel": nm, "ms": round(ms, 3),
                         "calls": c, "gbs": round(gbs, 1),
                         "tfs": round(tfs, 1),
                         "util_vs_bound": round(
                             max(gbs / stream_gbs, tfs / mm_tflops), 3)})
        else:
            tail_us += us
            tail_by += by
            tail_fl += fl
            tail_n += 1
    return {
        "device_ms_per_step": round(total_us / steps / 1e3, 2),
        "kernels": rows,
        "tail": {"n_kernel_names": tail_n,
                 "ms": round(tail_us / steps / 1e3, 2),
                 "gbs": round(tail_by / (tail_us * 1e-6) / 1e9, 1)
                 if tail_us else 0.0,
                 "tfs": round(tail_fl / (tail_us * 1e-6) / 1e12, 1)
                 if tail_us else 0.0},
        "aggregate_gbs": round(
            sum(a[2] for a in agg.values()) / (total_us * 1e-6) / 1e9, 1),
        "aggregate_tfs": round(
            sum(a[3] for a in agg.values()) / (total_us * 1e-6) / 1e12, 1),
    }


def capture_kernel_table(run_step, floors: Tuple[float, float],
                         steps: int = 2, cutoff_ms: float = 0.5) -> dict:
    """Trace `steps` live invocations of `run_step` and build the kernel
    table (the in-vivo path bench_resnet uses)."""
    import shutil
    import tempfile

    import jax

    run_step()  # warm
    tdir = tempfile.mkdtemp(prefix="pdtpu_kernels_")
    try:
        with jax.profiler.trace(tdir):
            for _ in range(steps):
                run_step()
        try:
            tr = load_trace(tdir)
        except FileNotFoundError:
            return {"error": "no trace captured"}
    finally:
        shutil.rmtree(tdir, ignore_errors=True)
    return kernel_table(tr, floors, steps=steps, cutoff_ms=cutoff_ms)


def diff_tables(a: dict, b: dict, topk: int = 20) -> dict:
    """Per-kernel ms movement between two kernel tables (b − a): the
    biggest movers by |delta|, plus kernels present in only one trace."""
    rows_a = {r["kernel"]: r for r in a.get("kernels", [])}
    rows_b = {r["kernel"]: r for r in b.get("kernels", [])}
    moved = []
    for nm in set(rows_a) | set(rows_b):
        ra, rb = rows_a.get(nm), rows_b.get(nm)
        ms_a = ra["ms"] if ra else 0.0
        ms_b = rb["ms"] if rb else 0.0
        moved.append({"kernel": nm, "ms_a": ms_a, "ms_b": ms_b,
                      "delta_ms": round(ms_b - ms_a, 3),
                      "status": ("only_b" if ra is None
                                 else "only_a" if rb is None else "both")})
    moved.sort(key=lambda r: -abs(r["delta_ms"]))
    return {
        "device_ms_per_step_a": a.get("device_ms_per_step"),
        "device_ms_per_step_b": b.get("device_ms_per_step"),
        "delta_ms_per_step": (
            round(b["device_ms_per_step"] - a["device_ms_per_step"], 2)
            if (a.get("device_ms_per_step") is not None
                and b.get("device_ms_per_step") is not None) else None),
        "movers": moved[:topk],
        "only_in_a": sorted(set(rows_a) - set(rows_b)),
        "only_in_b": sorted(set(rows_b) - set(rows_a)),
    }


def _resolve_floors(args) -> Tuple[float, float, str]:
    if args.matmul_tflops and args.stream_gbs:
        return args.matmul_tflops, args.stream_gbs, "flags"
    from ..observability.calibrate import get_calibration
    c = get_calibration(recalibrate=args.recalibrate)
    return c.matmul_tflops, c.stream_gbs, c.source


def _print_table(tab: dict, floors, source: str) -> None:
    mm, st = floors
    print(f"floors: matmul {mm:.1f} TFLOP/s, stream {st:.1f} GB/s "
          f"({source})")
    if "error" in tab:
        print(f"error: {tab['error']}")
        return
    print(f"device time/step: {tab['device_ms_per_step']:.2f} ms   "
          f"aggregate: {tab['aggregate_gbs']:.1f} GB/s, "
          f"{tab['aggregate_tfs']:.1f} TFLOP/s")
    hdr = f"{'kernel':<48}{'ms':>9}{'calls':>7}{'GB/s':>8}" \
          f"{'TF/s':>8}{'util':>7}"
    print(hdr)
    for r in tab["kernels"]:
        print(f"{r['kernel'][:47]:<48}{r['ms']:>9.3f}{r['calls']:>7}"
              f"{r['gbs']:>8.1f}{r['tfs']:>8.1f}{r['util_vs_bound']:>7.3f}")
    t = tab["tail"]
    print(f"{'(tail: ' + str(t['n_kernel_names']) + ' kernels)':<48}"
          f"{t['ms']:>9.3f}{'':>7}{t['gbs']:>8.1f}{t['tfs']:>8.1f}")


def _print_diff(d: dict) -> None:
    print(f"device ms/step: {d['device_ms_per_step_a']} -> "
          f"{d['device_ms_per_step_b']} "
          f"(delta {d['delta_ms_per_step']})")
    print(f"{'kernel':<48}{'ms_a':>9}{'ms_b':>9}{'delta':>9}  status")
    for r in d["movers"]:
        print(f"{r['kernel'][:47]:<48}{r['ms_a']:>9.3f}{r['ms_b']:>9.3f}"
              f"{r['delta_ms']:>9.3f}  {r['status']}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.tools.roofline", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("trace", help="trace file (.json/.json.gz) or "
                                 "jax.profiler log dir")
    p.add_argument("--diff", metavar="OTHER",
                   help="second trace: report per-kernel deltas "
                        "(OTHER - trace)")
    p.add_argument("--save-golden", action="store_true",
                   help="persist this trace's kernel table as the "
                        "golden for this (device kind, host) — the "
                        "baseline ProfileTrigger diffs captures against")
    p.add_argument("--diff-golden", action="store_true",
                   help="diff this trace against the recorded golden "
                        "(trace - golden)")
    p.add_argument("--golden-path", default=None,
                   help="override the golden cache file "
                        "(default: PDTPU_GOLDEN_DIR keyed like "
                        "calibrate.py)")
    p.add_argument("--topk", type=int, default=20)
    p.add_argument("--cutoff-ms", type=float, default=0.5)
    p.add_argument("--steps", type=int, default=1,
                   help="steps captured in the trace (divides times)")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--matmul-tflops", type=float, default=None)
    p.add_argument("--stream-gbs", type=float, default=None)
    p.add_argument("--recalibrate", action="store_true",
                   help="re-measure the chip floors instead of using the "
                        "calibration cache")
    args = p.parse_args(argv)

    try:
        tr = load_trace(args.trace)
    except Exception as e:
        print(f"roofline: cannot load {args.trace!r}: {e}", file=sys.stderr)
        return 2
    mm, st, source = _resolve_floors(args)
    tab = kernel_table(tr, (mm, st), steps=args.steps,
                       cutoff_ms=args.cutoff_ms, topk=args.topk)
    if args.save_golden:
        from ..observability import profile_trigger
        if "error" in tab:
            print(f"roofline: not saving golden: {tab['error']}",
                  file=sys.stderr)
            return 2
        path = profile_trigger.save_golden(tab, path=args.golden_path,
                                           note=args.trace)
        print(f"golden saved: {path}")
        return 0
    if args.diff_golden:
        from ..observability import profile_trigger
        golden = profile_trigger.load_golden(args.golden_path)
        if golden is None:
            print("roofline: no golden recorded (run --save-golden on a "
                  "healthy trace first)", file=sys.stderr)
            return 2
        d = diff_tables(golden["table"], tab, topk=args.topk)
        if args.as_json:
            print(json.dumps({"golden": golden["table"], "trace": tab,
                              "diff": d}))
        else:
            _print_diff(d)
        return 0
    if args.diff:
        try:
            tr2 = load_trace(args.diff)
        except Exception as e:
            print(f"roofline: cannot load {args.diff!r}: {e}",
                  file=sys.stderr)
            return 2
        tab2 = kernel_table(tr2, (mm, st), steps=args.steps,
                            cutoff_ms=args.cutoff_ms, topk=args.topk)
        d = diff_tables(tab, tab2, topk=args.topk)
        if args.as_json:
            print(json.dumps({"a": tab, "b": tab2, "diff": d}))
        else:
            _print_diff(d)
        return 0
    if args.as_json:
        print(json.dumps({"floors": {"matmul_tflops": mm, "stream_gbs": st,
                                     "source": source}, **tab}))
    else:
        _print_table(tab, (mm, st), source)
    return 0


if __name__ == "__main__":
    sys.exit(main())
