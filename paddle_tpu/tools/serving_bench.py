"""Serving load generator: dynamic batching vs sequential Predictor calls.

Reference analog: the reference ecosystem benchmarked serving with an
external RPC load tool; here the generator is in-process (no network
noise) so BENCH rounds can track the batching win itself: N concurrent
single-row requests served through `serving.InferenceServer` (one padded
XLA dispatch per bucket) against the same N requests run one-by-one
through the bare AOT Predictor.

Arrivals are Poisson (exponential inter-arrival gaps at --qps) over
--duration seconds, or a closed-loop burst of --requests when --qps is 0:
the open-loop mode measures latency under a target load, the closed-loop
mode measures peak throughput.

CLI::

    python -m paddle_tpu.tools.serving_bench --requests 256 --concurrency 32
    python -m paddle_tpu.tools.serving_bench --qps 500 --duration 5 \
        --buckets 1,2,4,8,16,32 --batch-delay-ms 2
    python -m paddle_tpu.tools.serving_bench --precision int8
    python -m paddle_tpu.tools.serving_bench --models ads:2,feed:1,search:1 \
        --replicas 4 --slo-p99-ms 500

Output: one throughput + latency-percentile row per mode, plus the
serving metrics report. Exit code 1 if batched throughput does not beat
sequential (the property BENCH rounds assert).

``--precision int8`` serves the post-training-quantized model: the
bench's own request rows double as the calibration stream
(Config.enable_int8), so the accuracy gate runs before any load is
generated — a model that fails calibration fails the bench.

``--models a:2,b:1`` switches to multi-tenant co-hosting: each
name:weight pair becomes a tenant on ONE ServingFleet (its own
registered model version, replica partition sized by weight), the load
mix draws each request's tenant proportional to weight, and the output
grows one latency row PER TENANT plus the router's ``tenant_stats``.
``--slo-p99-ms`` then gates per tenant — exit 2 if ANY tenant's p99
breaches (same exit-code contract as the single-model gate). Combine
with ``--precision int8`` and the fleet serves quantized replicas,
registered through the registry's int8 promotion gate with the
measured accuracy delta.

Telemetry sidecars: ``--metrics-out m.json`` dumps the unified
observability Registry snapshot (serving counters AND executor
cache-hit/compile-time metrics) and ``--trace-out t.json`` writes the
host tracer's chrome-trace of the run, so BENCH rounds carry cache and
compile telemetry alongside the throughput numbers for free
(``python -m paddle_tpu.tools.timeline t.json --summary`` to read it).
In fleet runs (``--replicas > 1``) the sidecars widen to the whole
fleet: ``--metrics-out`` gains a ``bench/fleet_federated`` block (one
federated scrape across coordinator + every replica, with the derived
``autoscale/*`` signals) and ``--trace-out`` becomes the MERGED
cross-process timeline — per-replica traces clock-aligned against the
coordinator with client→server flow arrows (merge_fleet_traces).
"""
from __future__ import annotations

import argparse
import json
import sys
import tempfile
import threading
import time

from typing import List, Optional

import numpy as np

__all__ = ["build_predictor", "bench_sequential", "bench_served",
           "bench_fleet", "bench_tenants", "percentile_row", "main"]


def _make_config(model_dir: str, precision: Optional[str],
                 calib_feeds=None):
    """Config for `model_dir` at `precision`. int8 needs a calibration
    stream (`calib_feeds`); other precisions flow through enable_tpu so
    an unknown string raises here, before any load is generated."""
    from paddle_tpu import inference

    cfg = inference.Config(model_dir)
    if precision is None:
        return cfg
    if inference._resolve_precision(precision) == "int8":
        cfg.enable_int8(calib_feeds)
    else:
        cfg.enable_tpu(precision=precision)
    return cfg


def build_predictor(model_dir: Optional[str] = None, in_dim: int = 512,
                    hidden: int = 2048, classes: int = 16, layers: int = 2,
                    precision: Optional[str] = None, calib_feeds=None):
    """Save an MLP inference model and return its Predictor. The default
    size (2x2048 hidden) is deliberately weight-heavy: per batch-1 call
    the CPU/TPU must re-read every weight, so batching has real economics
    to demonstrate (one weight read serves the whole bucket)."""
    import paddle_tpu as fluid
    from paddle_tpu import inference

    model_dir = model_dir or tempfile.mkdtemp(prefix="serving_bench_")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [in_dim])
        h = x
        for _ in range(max(1, layers)):
            h = fluid.layers.fc(h, hidden, act="relu")
        out = fluid.layers.fc(h, classes, act="softmax")
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    fluid.io.save_inference_model(model_dir, ["x"], [out], exe, main)
    return inference.create_predictor(
        _make_config(model_dir, precision, calib_feeds))


def _gen_rows(n: int, in_dim: int, seed: int = 0) -> List[np.ndarray]:
    rng = np.random.RandomState(seed)
    return [rng.rand(1, in_dim).astype(np.float32) for _ in range(n)]


def _poisson_gaps(n: int, qps: float, seed: int = 0) -> np.ndarray:
    return np.random.RandomState(seed + 1).exponential(1.0 / qps, size=n)


def bench_sequential(predictor, rows: List[np.ndarray]) -> dict:
    """One blocking batch-1 Predictor call per request (the no-serving
    baseline: what a naive RPC handler per request would do)."""
    predictor.run_padded({"x": rows[0]}, 1)  # compile outside the clock
    lats = []
    t0 = time.monotonic()
    for r in rows:
        s = time.monotonic()
        predictor.run_padded({"x": r}, 1)
        lats.append((time.monotonic() - s) * 1e3)
    wall = time.monotonic() - t0
    return _summarize("sequential", len(rows), wall, lats)


def bench_served(predictor, rows: List[np.ndarray], concurrency: int = 32,
                 buckets=(1, 2, 4, 8, 16, 32), batch_delay_ms: float = 2.0,
                 qps: float = 0.0, seed: int = 0) -> dict:
    """Drive the InferenceServer: closed-loop (`qps`=0, `concurrency`
    submitter threads racing through the request list) or open-loop
    Poisson arrivals at `qps`. Latency is measured from scheduled arrival
    to completion, so open-loop numbers include queueing delay."""
    from paddle_tpu import serving

    server = serving.InferenceServer(
        predictor, buckets=buckets, max_batch_delay_ms=batch_delay_ms,
        max_queue_size=max(len(rows), 1024))
    server.warmup(example_feed={"x": rows[0]})
    lats = [0.0] * len(rows)
    errors = [0]

    with server:
        t0 = time.monotonic()
        if qps > 0:
            gaps = _poisson_gaps(len(rows), qps, seed)
            arrivals = t0 + np.cumsum(gaps)
            futs = []
            for i, r in enumerate(rows):
                now = time.monotonic()
                if arrivals[i] > now:
                    time.sleep(arrivals[i] - now)
                futs.append((i, server.submit({"x": r})))
            for i, f in futs:
                try:
                    f.result()
                    lats[i] = (time.monotonic() - arrivals[i]) * 1e3
                except Exception:
                    errors[0] += 1
        else:
            it = iter(list(enumerate(rows)))
            lock = threading.Lock()

            def drive():
                while True:
                    with lock:
                        nxt = next(it, None)
                    if nxt is None:
                        return
                    i, r = nxt
                    s = time.monotonic()
                    try:
                        server.infer({"x": r})
                        lats[i] = (time.monotonic() - s) * 1e3
                    except Exception:
                        errors[0] += 1

            threads = [threading.Thread(target=drive)
                       for _ in range(max(1, concurrency))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        wall = time.monotonic() - t0
    out = _summarize(f"served(c={concurrency})" if qps <= 0
                     else f"served(qps={qps:g})",
                     len(rows) - errors[0], wall,
                     [x for x in lats if x > 0])
    out["errors"] = errors[0]
    out["metrics"] = server.metrics.snapshot()
    return out


def bench_fleet(model_dir: str, rows: List[np.ndarray], replicas: int = 3,
                concurrency: int = 32, buckets=(1, 2, 4, 8, 16, 32),
                batch_delay_ms: float = 2.0, mode: str = "thread",
                env=None, collect_telemetry: bool = False) -> dict:
    """Closed-loop drive of a ServingFleet: `concurrency` client threads
    racing the request list through the router (least-outstanding). The
    multi-replica analog of bench_served — same latency accounting, so
    the 1-vs-N rows compare directly.

    ``collect_telemetry`` additionally performs, before the fleet is
    torn down, (a) one federated metrics scrape across this process and
    every replica and (b) a per-process trace export — what
    ``--metrics-out``/``--trace-out`` write in fleet runs (the trace
    sidecar is then the MERGED timeline, clock-aligned, with flow
    arrows; see tools.timeline.merge_fleet_traces)."""
    from paddle_tpu.serving import fleet as fleet_mod

    reg = fleet_mod.ModelRegistry()
    reg.register("bench-v1", model_dir)
    fl = fleet_mod.ServingFleet(
        reg, "bench-v1", replicas=replicas, mode=mode, buckets=buckets,
        env=env,
        server_kwargs={"max_batch_delay_ms": batch_delay_ms,
                       "max_queue_size": max(len(rows), 1024)})
    lats = [0.0] * len(rows)
    errors = [0]
    with fl:
        t0 = time.monotonic()
        it = iter(list(enumerate(rows)))
        lock = threading.Lock()

        def drive():
            while True:
                with lock:
                    nxt = next(it, None)
                if nxt is None:
                    return
                i, r = nxt
                s = time.monotonic()
                try:
                    fl.infer({"x": r})
                    lats[i] = (time.monotonic() - s) * 1e3
                except Exception:
                    errors[0] += 1

        threads = [threading.Thread(target=drive)
                   for _ in range(max(1, concurrency))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        stats = fl.stats()
        federated, traces = None, None
        if collect_telemetry:
            federated, traces = _collect_fleet_telemetry(fl)
    out = _summarize(f"fleet(n={replicas},c={concurrency})",
                     len(rows) - errors[0], wall,
                     [x for x in lats if x > 0])
    out["errors"] = errors[0]
    out["replicas"] = replicas
    out["fleet"] = {"mode": stats["mode"],
                    "metrics": stats["router"]["metrics"]}
    if federated is not None:
        out["fleet"]["federated"] = federated
    if traces is not None:
        out["fleet"]["traces"] = traces
    return out


def bench_tenants(model_dir: str, specs: "dict[str, float]",
                  rows: List[np.ndarray], replicas: int = 0,
                  concurrency: int = 32, buckets=(1, 2, 4, 8, 16, 32),
                  batch_delay_ms: float = 2.0,
                  precision: Optional[str] = None, calib_feeds=None,
                  slo_p99_ms: Optional[float] = None,
                  seed: int = 0) -> dict:
    """Multi-tenant co-hosting bench: every `specs` name:weight pair is
    registered as its own model version and co-hosted on ONE fleet whose
    replica pool is partitioned by weight. Mixed load — each request's
    tenant is drawn proportional to weight — then one latency summary
    PER TENANT (the isolation claim is per-tenant p99, not the blended
    number) plus the router's own tenant_stats.

    With ``precision='int8'`` the accuracy delta is measured once
    against `calib_feeds` and every tenant's version is registered
    through the registry's int8 promotion gate with that calibration
    metadata; replicas then build quantized predictors."""
    from paddle_tpu.serving import fleet as fleet_mod

    total = max(replicas, len(specs))
    reg = fleet_mod.ModelRegistry()
    factory, reg_precision, meta = None, None, {}
    if precision is not None:
        from paddle_tpu import inference

        if inference._resolve_precision(precision) == "int8":
            probe = inference.create_predictor(
                _make_config(model_dir, precision, calib_feeds))
            qm = probe.quant_meta
            reg_precision = "int8"
            meta = {"calibration": {
                "accuracy_delta": qm["accuracy_delta"],
                "accuracy_budget": qm["accuracy_budget"],
                "samples": qm["samples"]}}

        def factory(model):
            from paddle_tpu.inference import create_predictor
            return create_predictor(
                _make_config(model.model_dir, precision, calib_feeds))

    tenants = {}
    for name, weight in specs.items():
        reg.register(f"{name}-v1", model_dir, precision=reg_precision,
                     **meta)
        tenants[name] = {"version": f"{name}-v1", "weight": weight,
                         "slo_p99_ms": slo_p99_ms}
    fl = fleet_mod.ServingFleet(
        reg, replicas=total, buckets=buckets, predictor_factory=factory,
        server_kwargs={"max_batch_delay_ms": batch_delay_ms,
                       "max_queue_size": max(len(rows), 1024)},
        tenants=tenants)

    names = list(specs)
    wsum = sum(specs.values())
    p = np.asarray([specs[n] / wsum for n in names])
    assign = np.random.RandomState(seed + 2).choice(
        len(names), size=len(rows), p=p)
    lats = [0.0] * len(rows)
    errors = {n: 0 for n in names}
    throttled = {n: 0 for n in names}
    elock = threading.Lock()

    with fl:
        t0 = time.monotonic()
        it = iter(list(enumerate(rows)))
        lock = threading.Lock()

        def drive():
            while True:
                with lock:
                    nxt = next(it, None)
                if nxt is None:
                    return
                i, r = nxt
                tenant = names[assign[i]]
                s = time.monotonic()
                try:
                    fl.infer({"x": r}, tenant=tenant)
                    lats[i] = (time.monotonic() - s) * 1e3
                except fleet_mod.TenantThrottledError:
                    with elock:
                        throttled[tenant] += 1
                except Exception:
                    with elock:
                        errors[tenant] += 1

        threads = [threading.Thread(target=drive)
                   for _ in range(max(1, concurrency))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        tstats = fl.tenant_stats()

    per_tenant = {}
    for j, name in enumerate(names):
        tl = [lats[i] for i in range(len(rows))
              if assign[i] == j and lats[i] > 0]
        row = _summarize(f"tenant:{name}(w={specs[name]:g})",
                         len(tl), wall, tl)
        row["errors"] = errors[name]
        row["throttled"] = throttled[name]
        row["router"] = tstats.get(name)
        per_tenant[name] = row
    ok = len(rows) - sum(errors.values()) - sum(throttled.values())
    out = _summarize(f"tenants(n={len(names)},r={total})", ok, wall,
                     [x for x in lats if x > 0])
    out["errors"] = sum(errors.values())
    out["throttled"] = sum(throttled.values())
    out["per_tenant"] = per_tenant
    out["precision"] = precision or "fp32"
    return out


def _collect_fleet_telemetry(fl):
    """(federated /fleet doc, [(name, chrome-trace), ...]) for a live
    fleet: coordinator + every replica, per-target failures recorded in
    the doc rather than raised."""
    from paddle_tpu.observability import get_tracer
    from paddle_tpu.observability.federate import (FederatedScraper,
                                                   ScrapeTarget)

    targets = [ScrapeTarget.local()]
    for r in fl.replicas:
        targets.append(ScrapeTarget.call(
            r.metrics, name=r.name, role=f"replica-{r.kind}"))
    doc = FederatedScraper(targets).scrape_once()
    traces = [("coordinator", get_tracer().export_chrome_trace())]
    for r in fl.replicas:
        # a thread replica's trace IS the coordinator trace; exporting
        # it again would duplicate every event on a second track
        if r.kind != "process":
            continue
        try:
            traces.append((r.name, r.trace_export()))
        except Exception:
            pass  # a dead replica has no trace to contribute
    return doc, traces


def _summarize(mode: str, n: int, wall: float, lats: List[float]) -> dict:
    arr = np.asarray(sorted(lats)) if lats else np.asarray([0.0])

    def pct(p):
        return float(arr[min(len(arr) - 1, int(round(p / 100.0 * (len(arr) - 1))))])

    return {"mode": mode, "requests": n, "wall_s": wall,
            "throughput_rps": n / wall if wall > 0 else float("inf"),
            "mean_ms": float(arr.mean()), "p50_ms": pct(50),
            "p95_ms": pct(95), "p99_ms": pct(99)}


def percentile_row(r: dict) -> str:
    return (f"{r['mode']:<18}{r['requests']:>6}{r['wall_s']:>9.3f}"
            f"{r['throughput_rps']:>12.1f}{r['mean_ms']:>10.2f}"
            f"{r['p50_ms']:>10.2f}{r['p95_ms']:>10.2f}{r['p99_ms']:>10.2f}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=256,
                    help="closed-loop request count (ignored with --qps)")
    ap.add_argument("--qps", type=float, default=0.0,
                    help="open-loop Poisson arrival rate; 0 = closed loop")
    ap.add_argument("--duration", type=float, default=3.0,
                    help="open-loop duration in seconds (with --qps)")
    ap.add_argument("--concurrency", type=int, default=32)
    ap.add_argument("--buckets", default="1,2,4,8,16,32")
    ap.add_argument("--batch-delay-ms", type=float, default=2.0)
    ap.add_argument("--in-dim", type=int, default=512)
    ap.add_argument("--hidden", type=int, default=2048)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--skip-sequential", action="store_true")
    ap.add_argument("--replicas", type=int, default=1,
                    help="also closed-loop a ServingFleet of N replicas "
                         "behind the router (1 = single-server only)")
    ap.add_argument("--fleet-mode", choices=("thread", "process"),
                    default="thread",
                    help="fleet replica isolation for --replicas")
    ap.add_argument("--precision", default=None,
                    help="serving precision (fp32/bf16/int8); int8 "
                         "calibrates on the bench's own request rows and "
                         "runs the accuracy gate before generating load")
    ap.add_argument("--models", default=None,
                    help="multi-tenant mode: 'a:2,b:1' name:weight pairs "
                         "co-hosted on one fleet (replica pool from "
                         "--replicas, partitioned by weight); reports "
                         "per-tenant p99 and gates --slo-p99-ms per "
                         "tenant")
    ap.add_argument("--slo-p99-ms", type=float, default=None,
                    help="p99 latency SLO gate: exit 2 if the headline "
                         "mode (fleet with --replicas > 1, else served) "
                         "exceeds it or saw any request error")
    ap.add_argument("--metrics-out", default=None,
                    help="dump the unified observability Registry "
                         "snapshot (serving + executor metrics) as JSON")
    ap.add_argument("--trace-out", default=None,
                    help="write the host tracer's chrome-trace JSON of "
                         "the run (load in Perfetto, or summarize with "
                         "tools.timeline --summary)")
    ap.add_argument("--introspect-port", type=int, default=None,
                    help="serve the live introspection plane on this "
                         "port for the duration of the run (0 = "
                         "ephemeral) and scrape /metrics + /healthz once "
                         "mid-run as a smoke check of the endpoints "
                         "under load")
    args = ap.parse_args(argv)

    buckets = tuple(int(b) for b in args.buckets.split(","))
    n = (args.requests if args.qps <= 0
         else max(1, int(args.qps * args.duration)))
    rows = _gen_rows(n, args.in_dim, args.seed)
    # int8 calibration reuses the head of the request stream — the
    # activation ranges the bench serves are the ranges it calibrated on
    calib = [{"x": r} for r in rows[:8]]
    model_dir = tempfile.mkdtemp(prefix="serving_bench_")
    pred = build_predictor(model_dir=model_dir, in_dim=args.in_dim,
                           hidden=args.hidden, layers=args.layers,
                           precision=args.precision, calib_feeds=calib)
    if args.precision:
        qm = pred.quant_meta
        if qm is not None:
            print(f"int8 calibration: accuracy_delta="
                  f"{qm['accuracy_delta']:.6f} (budget "
                  f"{qm['accuracy_budget']:g}, {qm['samples']} samples)")

    header = (f"{'mode':<18}{'reqs':>6}{'wall_s':>9}{'rps':>12}"
              f"{'mean_ms':>10}{'p50_ms':>10}{'p95_ms':>10}{'p99_ms':>10}")
    print(header)
    seq = None
    if not args.skip_sequential:
        seq = bench_sequential(pred, rows)
        print(percentile_row(seq))
    scrape: dict = {}
    scraper = None
    if args.introspect_port is not None:
        from paddle_tpu.observability import serve_introspection

        srv = serve_introspection(args.introspect_port)
        scraper = threading.Thread(
            target=_scrape_introspection, args=(srv.url, scrape),
            daemon=True)
        scraper.start()
    served = bench_served(pred, rows, concurrency=args.concurrency,
                          buckets=buckets, batch_delay_ms=args.batch_delay_ms,
                          qps=args.qps, seed=args.seed)
    if scraper is not None:
        scraper.join(timeout=10)
    print(percentile_row(served))
    flt = None
    ten = None
    if args.models:
        specs = {}
        for part in args.models.split(","):
            name, _, w = part.partition(":")
            specs[name.strip()] = float(w) if w.strip() else 1.0
        ten = bench_tenants(model_dir, specs, rows,
                            replicas=args.replicas,
                            concurrency=args.concurrency, buckets=buckets,
                            batch_delay_ms=args.batch_delay_ms,
                            precision=args.precision, calib_feeds=calib,
                            slo_p99_ms=args.slo_p99_ms, seed=args.seed)
        print(percentile_row(ten))
        for trow in ten["per_tenant"].values():
            print(percentile_row(trow))
    if args.replicas > 1 and not args.models:
        flt = bench_fleet(model_dir, rows, replicas=args.replicas,
                          concurrency=args.concurrency, buckets=buckets,
                          batch_delay_ms=args.batch_delay_ms,
                          mode=args.fleet_mode,
                          collect_telemetry=bool(args.metrics_out
                                                 or args.trace_out))
        print(percentile_row(flt))
    print()
    bs = served["metrics"].get("serving/batch_rows") or {}
    print(f"batches={served['metrics'].get('serving/batches', 0)} "
          f"mean_batch_rows={bs.get('mean') if bs else None} "
          f"padded_rows={served['metrics'].get('serving/padded_rows', 0)} "
          f"errors={served['errors']}")
    if args.metrics_out:
        from paddle_tpu.observability import get_registry

        snap = get_registry().snapshot(deep=True)
        # the bench server is gone by now (its Metrics child is attached
        # to the registry by weakref), so overlay its final snapshot
        for k, v in served["metrics"].items():
            snap.setdefault(k, v)
        snap["bench/served"] = {k: v for k, v in served.items()
                                if k != "metrics"}
        if scrape:
            snap["bench/introspection"] = scrape
        if seq is not None:
            snap["bench/sequential"] = seq
        if flt is not None and flt["fleet"].get("federated"):
            # the whole fleet's series, per process, + autoscale signals
            snap["bench/fleet_federated"] = flt["fleet"]["federated"]
        if ten is not None:
            snap["bench/tenants"] = ten
        with open(args.metrics_out, "w") as f:
            json.dump(snap, f, indent=2, sort_keys=True)
        print(f"wrote registry snapshot to {args.metrics_out}")
    if args.trace_out:
        from paddle_tpu.observability import get_tracer

        fleet_traces = (flt["fleet"].get("traces")
                        if flt is not None else None)
        if fleet_traces and len(fleet_traces) > 1:
            from paddle_tpu.tools.timeline import merge_fleet_traces

            trace = merge_fleet_traces([t for _, t in fleet_traces],
                                       [n for n, _ in fleet_traces])
            with open(args.trace_out, "w") as f:
                json.dump(trace, f)
        else:
            trace = get_tracer().export_chrome_trace(args.trace_out)
        print(f"wrote {args.trace_out} "
              f"({len(trace['traceEvents'])} events) — load in "
              f"chrome://tracing or ui.perfetto.dev")
    if args.introspect_port is not None:
        ok = scrape and all("error" not in r for r in scrape.values())
        print(f"introspection scrape: {json.dumps(scrape)}")
        if not ok:
            print("FAIL: live /metrics + /healthz scrape failed under load")
            return 1
    if seq is not None:
        speedup = served["throughput_rps"] / max(seq["throughput_rps"], 1e-9)
        print(f"batched/sequential throughput: {speedup:.2f}x")
        if served["throughput_rps"] <= seq["throughput_rps"]:
            print("FAIL: dynamic batching did not beat sequential")
            return 1
    if args.slo_p99_ms is not None:
        if ten is not None:
            # tenancy mode gates PER TENANT: co-hosting only counts as
            # isolation if every tenant holds its own p99
            breaches = []
            for name, trow in ten["per_tenant"].items():
                bad = (trow["p99_ms"] > args.slo_p99_ms
                       or trow["errors"] > 0)
                print(f"SLO p99 <= {args.slo_p99_ms:g}ms tenant "
                      f"{name}: p99={trow['p99_ms']:.2f}ms "
                      f"errors={trow['errors']} "
                      f"throttled={trow['throttled']} "
                      f"-> {'FAIL' if bad else 'ok'}")
                if bad:
                    breaches.append(name)
            if breaches:
                return 2
            return 0
        head = flt if flt is not None else served
        breached = (head["p99_ms"] > args.slo_p99_ms
                    or head.get("errors", 0) > 0)
        print(f"SLO p99 <= {args.slo_p99_ms:g}ms on {head['mode']}: "
              f"p99={head['p99_ms']:.2f}ms errors={head.get('errors', 0)} "
              f"-> {'FAIL' if breached else 'ok'}")
        if breached:
            return 2
    return 0


def _scrape_introspection(url: str, out: dict, delay_s: float = 0.2) -> None:
    """One mid-run GET of /metrics and /healthz — proves the endpoints
    answer while the serve loop is under load (results land in `out`)."""
    import urllib.request

    time.sleep(delay_s)  # let the load generator reach steady state
    for ep in ("/metrics", "/healthz"):
        try:
            with urllib.request.urlopen(url + ep, timeout=5) as r:
                body = r.read()
            out[ep] = {"status": r.status, "bytes": len(body)}
        except Exception as e:
            out[ep] = {"error": f"{type(e).__name__}: {e}"[:160]}


if __name__ == "__main__":
    sys.exit(main())
