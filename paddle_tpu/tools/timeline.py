"""Trace toolbox: XPlane conversion + chrome-trace merge + span summary.

Reference analog: ``tools/timeline.py`` (profiler.proto → chrome trace
JSON, with a --profile_path that accepted multiple "name=file" inputs)
plus the profiler's sorted per-op summary. The TPU build produces TWO
kinds of traces:

- device-side XPlane protos (``paddle_tpu.profiler`` / jax.profiler,
  under ``<logdir>/plugins/profile/<run>/*.xplane.pb``) — converted here
  to chrome-trace JSON via the xprof converter when available;
- host-side chrome-trace JSON written by the observability span tracer
  (``observability.get_tracer().export_chrome_trace(path)``).

This CLI converts, merges, and summarizes them into one file loadable in
chrome://tracing or https://ui.perfetto.dev:

    # convert a jax.profiler logdir (reference behavior, unchanged)
    python -m paddle_tpu.tools.timeline --logdir ./_trace --out trace.json

    # merge host + device traces into one timeline
    python -m paddle_tpu.tools.timeline host.json device.json --out all.json

    # per-span totals (count / total / avg / max ms), sorted like the
    # reference profiler summary
    python -m paddle_tpu.tools.timeline host.json --summary

    # one fleet, many processes: align per-process clocks from RPC span
    # pairs and draw client->server flow arrows (see merge_fleet_traces)
    python -m paddle_tpu.tools.timeline --fleet \\
        coordinator.json worker0.json pserver0.json --out fleet.json
"""
from __future__ import annotations

import argparse
import glob
import json
import os
from typing import Dict, List, Optional

__all__ = ["find_xplanes", "xplane_to_chrome_trace", "load_trace",
           "merge_traces", "merge_fleet_traces", "summarize",
           "format_summary", "format_flight", "main"]


def find_xplanes(logdir: str) -> List[str]:
    """Newest profile run's xplane files under a jax.profiler logdir."""
    runs = sorted(glob.glob(os.path.join(logdir, "plugins", "profile", "*")))
    # newest run that actually holds an xplane (an interrupted newer run must
    # not shadow a complete older one)
    for run in reversed(runs):
        files = glob.glob(os.path.join(run, "*.xplane.pb"))
        if files:
            return files
    direct = glob.glob(os.path.join(logdir, "*.xplane.pb"))
    if direct:
        return direct
    raise FileNotFoundError(
        f"no profile runs under {logdir!r} (expected "
        f"plugins/profile/<run>/*.xplane.pb)")


def xplane_to_chrome_trace(xplane_files: List[str]) -> dict:
    """XPlane → chrome trace events dict ({"traceEvents": [...]})."""
    try:
        from xprof.convert import raw_to_tool_data as rtd
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "timeline conversion needs the xprof package (bundled with "
            "tensorboard-plugin-profile)") from e
    data, _ = rtd.xspace_to_tool_data(list(xplane_files), "trace_viewer@", {})
    if isinstance(data, bytes):
        data = data.decode("utf-8", errors="replace")
    out = json.loads(data)
    if "traceEvents" not in out:
        out = {"traceEvents": out if isinstance(out, list) else []}
    return out


# -- chrome-trace plumbing ---------------------------------------------------

def load_trace(path: str) -> dict:
    """Read one chrome-trace JSON file; accepts both the object form
    ({"traceEvents": [...]}) and the bare event-array form."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, list):
        return {"traceEvents": data}
    if not isinstance(data, dict) or "traceEvents" not in data:
        raise ValueError(f"{path!r}: not a chrome-trace file "
                         f"(no traceEvents)")
    return data


def merge_traces(traces: List[dict],
                 names: Optional[List[str]] = None) -> dict:
    """One trace from many: pids are remapped so same-numbered processes
    from different files (e.g. a host trace and a converted device trace
    both recorded under one OS pid) land on separate tracks, each tagged
    with a process_name metadata row naming its source."""
    out: List[dict] = []
    next_pid = [0]
    for i, trace in enumerate(traces):
        src = names[i] if names and i < len(names) else f"trace{i}"
        pid_map: Dict[object, int] = {}

        def mapped(old):
            if old not in pid_map:
                pid_map[old] = next_pid[0]
                next_pid[0] += 1
            return pid_map[old]

        renamed = set()
        for ev in trace.get("traceEvents", []):
            ev = dict(ev)
            pid = mapped(ev.get("pid", 0))
            ev["pid"] = pid
            if (ev.get("ph") == "M" and ev.get("name") == "process_name"
                    and pid not in renamed):
                renamed.add(pid)
                old_name = (ev.get("args") or {}).get("name", "")
                ev["args"] = {"name": f"{src}: {old_name}".rstrip(": ")}
            out.append(ev)
        for old, pid in pid_map.items():
            if pid not in renamed:
                out.append({"name": "process_name", "ph": "M", "pid": pid,
                            "tid": 0, "args": {"name": f"{src} (pid {old})"}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# -- fleet merge -------------------------------------------------------------

def _spans(trace: dict, index: int) -> List[dict]:
    """Pair B/E events per (pid, tid) into spans: {name, ts, dur, args,
    pid, tid, trace: index}. Stray E events are dropped; an unclosed B
    becomes a zero-duration span (a process that died mid-span still
    shows where it was)."""
    stacks: Dict[tuple, list] = {}
    spans: List[dict] = []
    events = [ev for ev in trace.get("traceEvents", [])
              if ev.get("ph") in ("B", "E")]
    events.sort(key=lambda ev: ev.get("ts", 0))
    for ev in events:
        key = (ev.get("pid"), ev.get("tid"))
        stack = stacks.setdefault(key, [])
        if ev.get("ph") == "B":
            stack.append(ev)
        elif stack:
            b = stack.pop()
            spans.append({"name": b.get("name", "?"),
                          "ts": float(b.get("ts", 0)),
                          "dur": float(ev.get("ts", 0)) - float(
                              b.get("ts", 0)),
                          "args": b.get("args") or {},
                          "pid": b.get("pid"), "tid": b.get("tid"),
                          "trace": index})
    for stack in stacks.values():
        for b in stack:
            spans.append({"name": b.get("name", "?"),
                          "ts": float(b.get("ts", 0)), "dur": 0.0,
                          "args": b.get("args") or {},
                          "pid": b.get("pid"), "tid": b.get("tid"),
                          "trace": index})
    return spans


def _rpc_pairs(all_spans: List[dict]) -> List[tuple]:
    """(client_span, server_span) pairs: a server-side RPC span
    (args.rpc == "server") whose parent_id is a client RPC span's
    span_id in the same distributed trace_id."""
    clients: Dict[tuple, dict] = {}
    for s in all_spans:
        a = s["args"]
        if a.get("rpc") == "client" and a.get("span_id"):
            clients[(a.get("trace_id"), a["span_id"])] = s
    pairs = []
    for s in all_spans:
        a = s["args"]
        if a.get("rpc") != "server" or not a.get("parent_id"):
            continue
        c = clients.get((a.get("trace_id"), a["parent_id"]))
        if c is not None and c["trace"] != s["trace"]:
            pairs.append((c, s))
    return pairs


def _clock_offsets(n_traces: int, pairs: List[tuple]) -> List[float]:
    """Per-trace clock offset (µs) from RPC send/recv pairs, NTP-style:
    a server span is causally inside its client span, so for each pair
    theta = ((s0 - c0) + (s1 - c1)) / 2 estimates the server clock's
    lead over the client clock (symmetric-delay assumption). Offsets are
    averaged per trace-pair edge and chained by BFS from the reference
    trace (index 0); unreachable traces keep offset 0."""
    edges: Dict[tuple, list] = {}
    for c, s in pairs:
        c0, c1 = c["ts"], c["ts"] + c["dur"]
        s0, s1 = s["ts"], s["ts"] + s["dur"]
        theta = ((s0 - c0) + (s1 - c1)) / 2.0
        edges.setdefault((c["trace"], s["trace"]), []).append(theta)
    adj: Dict[int, list] = {}
    for (i, j), thetas in edges.items():
        mean = sum(thetas) / len(thetas)
        adj.setdefault(i, []).append((j, mean))
        adj.setdefault(j, []).append((i, -mean))
    offsets = [0.0] * n_traces
    seen = {0}
    frontier = [0]
    while frontier:
        nxt = []
        for i in frontier:
            for j, theta in adj.get(i, []):
                if j in seen:
                    continue
                seen.add(j)
                offsets[j] = offsets[i] + theta
                nxt.append(j)
        frontier = nxt
    return offsets


def merge_fleet_traces(traces: List[dict],
                       names: Optional[List[str]] = None) -> dict:
    """Merge per-process chrome traces from one fleet into a single
    aligned timeline.

    Each process's tracer timestamps are relative to its own
    ``perf_counter`` start, so raw merging scatters one request's spans
    across the whole time axis. This merge (1) estimates each trace's
    clock offset against the first trace from matched client/server RPC
    span pairs (same trace_id, server parent_id == client span_id) and
    shifts its events onto the common clock, (2) remaps pids so every
    process gets its own track (named by its tracer ``process_name``),
    and (3) draws chrome-trace flow arrows (s/f events, cat "rpc") from
    each client RPC span to the server span it caused — in the viewer a
    routed request reads as one connected path through router, replica,
    and pserver tracks."""
    all_spans: List[dict] = []
    for i, t in enumerate(traces):
        all_spans.extend(_spans(t, i))
    pairs = _rpc_pairs(all_spans)
    offsets = _clock_offsets(len(traces), pairs)

    out: List[dict] = []
    next_pid = [0]
    pid_maps: List[Dict[object, int]] = []
    for i, trace in enumerate(traces):
        src = names[i] if names and i < len(names) else f"proc{i}"
        pid_map: Dict[object, int] = {}
        pid_maps.append(pid_map)

        def mapped(old):
            if old not in pid_map:
                pid_map[old] = next_pid[0]
                next_pid[0] += 1
            return pid_map[old]

        renamed = set()
        for ev in trace.get("traceEvents", []):
            ev = dict(ev)
            pid = mapped(ev.get("pid", 0))
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = float(ev["ts"]) - offsets[i]
            if (ev.get("ph") == "M" and ev.get("name") == "process_name"
                    and pid not in renamed):
                renamed.add(pid)
                old_name = (ev.get("args") or {}).get("name", "")
                ev["args"] = {"name": f"{src}: {old_name}".rstrip(": ")}
            out.append(ev)
        for old, pid in pid_map.items():
            if pid not in renamed:
                out.append({"name": "process_name", "ph": "M", "pid": pid,
                            "tid": 0, "args": {"name": f"{src} (pid "
                                                       f"{old})"}})
    # flow arrows client -> server, one per RPC pair; the id is the
    # client RPC span_id (unique per attempt, so retries get their own
    # arrows). ts is nudged inside the span so the viewer binds the
    # arrow to the enclosing slice.
    for c, s in pairs:
        fid = str(c["args"]["span_id"])
        out.append({"name": "rpc", "cat": "rpc", "ph": "s", "id": fid,
                    "pid": pid_maps[c["trace"]].get(c["pid"], 0),
                    "tid": c["tid"],
                    "ts": c["ts"] - offsets[c["trace"]] + 0.01})
        out.append({"name": "rpc", "cat": "rpc", "ph": "f", "bp": "e",
                    "id": fid,
                    "pid": pid_maps[s["trace"]].get(s["pid"], 0),
                    "tid": s["tid"],
                    "ts": s["ts"] - offsets[s["trace"]] + 0.01})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def summarize(trace: dict) -> Dict[str, dict]:
    """Per-span-name totals: {"name": {count, total_ms, avg_ms, max_ms}}.

    Handles both duration forms: B/E pairs (matched per pid/tid with a
    stack, so nesting is honored and stray E events are ignored) and
    complete "X" events carrying an explicit dur."""
    stats: Dict[str, dict] = {}

    def add(name, dur_us):
        s = stats.setdefault(name, {"count": 0, "total_ms": 0.0,
                                    "avg_ms": 0.0, "max_ms": 0.0})
        ms = dur_us / 1e3
        s["count"] += 1
        s["total_ms"] += ms
        s["max_ms"] = max(s["max_ms"], ms)

    stacks: Dict[tuple, list] = {}
    events = [ev for ev in trace.get("traceEvents", [])
              if ev.get("ph") in ("B", "E", "X")]
    events.sort(key=lambda ev: ev.get("ts", 0))
    for ev in events:
        ph = ev.get("ph")
        if ph == "X":
            add(ev.get("name", "?"), float(ev.get("dur", 0)))
            continue
        key = (ev.get("pid"), ev.get("tid"))
        stack = stacks.setdefault(key, [])
        if ph == "B":
            stack.append((ev.get("name", "?"), float(ev.get("ts", 0))))
        elif stack:  # E closes the innermost open B on this thread
            name, ts0 = stack.pop()
            add(name, float(ev.get("ts", 0)) - ts0)
    for s in stats.values():
        s["avg_ms"] = s["total_ms"] / max(s["count"], 1)
    return stats


def format_summary(stats: Dict[str, dict]) -> str:
    """Sorted text table, total-time-descending — the analog of the
    reference profiler's sorted per-op summary."""
    lines = [f"{'span':<40}{'calls':>8}{'total_ms':>12}"
             f"{'avg_ms':>10}{'max_ms':>10}"]
    for name in sorted(stats, key=lambda n: -stats[n]["total_ms"]):
        s = stats[name]
        lines.append(f"{name:<40}{s['count']:>8}{s['total_ms']:>12.3f}"
                     f"{s['avg_ms']:>10.4f}{s['max_ms']:>10.3f}")
    return "\n".join(lines)


def format_flight(dump: dict) -> str:
    """Render a flight-recorder post-mortem (observability.flight) as a
    step-time table with anomaly annotations, headed by the exception
    and device-memory state — the operator's first read after an OOM."""
    exc = dump.get("exception") or {}
    ctx = dump.get("context") or {}
    lines = [
        f"flight dump: {exc.get('type', '?')} during "
        f"{ctx.get('where', '?')} (pid {dump.get('pid', '?')})",
        f"  message: {exc.get('message', '')[:200]}",
    ]
    for dev, stats in (dump.get("device_memory") or {}).items():
        in_use = stats.get("bytes_in_use")
        peak = stats.get("peak_bytes_in_use")
        limit = stats.get("bytes_limit")
        lines.append(
            f"  {dev}: in_use="
            f"{in_use / 1e9:.2f}GB" if in_use is not None else f"  {dev}:")
        if peak is not None or limit is not None:
            lines[-1] += (f" peak={peak / 1e9:.2f}GB" if peak else "") + \
                         (f" limit={limit / 1e9:.2f}GB" if limit else "")
    steps = dump.get("steps") or []
    lines.append("")
    lines.append(f"{'step':>6}{'wall_ms':>10}{'compile':>9}{'sig':>10}"
                 f"{'queue':>7}{'h2d_ms':>8}{'mem_GB':>8}  anomaly")
    for r in steps:
        mem = r.get("mem_bytes_in_use")
        note = r.get("anomaly", "")
        if note and r.get("deviation") is not None:
            note += f" ({r['deviation']}x sigma)"
        lines.append(
            f"{r.get('step', '?'):>6}{r.get('wall_ms', 0):>10.2f}"
            f"{'yes' if r.get('compile') else '-':>9}"
            f"{r.get('sig', '-'):>10}"
            f"{str(r.get('queue_depth', '-')):>7}"
            f"{str(r.get('h2d_ms', '-')):>8}"
            f"{f'{mem / 1e9:.2f}' if mem is not None else '-':>8}"
            f"  {note}")
    events = dump.get("events") or []
    if events:
        lines.append("")
        lines.append("events:")
        for ev in events:
            lines.append(f"  [{ev.get('level', '?')}] "
                         f"{ev.get('message', '')[:160]}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("traces", nargs="*",
                    help="chrome-trace JSON files to merge/summarize "
                         "(host tracer exports, prior conversions)")
    ap.add_argument("--logdir",
                    help="jax.profiler trace dir (the arg of "
                         "profiler.start); converted and merged in")
    ap.add_argument("--out",
                    help="output chrome-trace JSON path "
                         "(default timeline.json unless --summary only)")
    ap.add_argument("--summary", action="store_true",
                    help="print per-span totals sorted by total time")
    ap.add_argument("--fleet", action="store_true",
                    help="treat the inputs as per-process traces of ONE "
                         "fleet: align clocks via RPC span pairs, give "
                         "each process its own named track, draw flow "
                         "arrows from client to server RPC spans")
    ap.add_argument("--flight",
                    help="render a flight-recorder dump JSON "
                         "(observability.flight / PDTPU_FLIGHT_DIR) as a "
                         "step-time table with anomaly annotations")
    args = ap.parse_args(argv)
    if not args.traces and not args.logdir and not args.flight:
        ap.error("give chrome-trace files, --logdir, and/or --flight")

    if args.flight:
        with open(args.flight) as f:
            print(format_flight(json.load(f)))
        if not args.traces and not args.logdir:
            return

    traces, names = [], []
    for path in args.traces:
        traces.append(load_trace(path))
        names.append(os.path.basename(path))
    if args.logdir:
        traces.append(xplane_to_chrome_trace(find_xplanes(args.logdir)))
        names.append(os.path.basename(args.logdir.rstrip("/")) or "xplane")

    if args.fleet:
        merged = merge_fleet_traces(traces, names)
    else:
        merged = (traces[0] if len(traces) == 1
                  else merge_traces(traces, names))
    out_path = args.out
    if out_path is None and not args.summary:
        out_path = "timeline.json"
    if out_path:
        with open(out_path, "w") as f:
            json.dump(merged, f)
        print(f"wrote {out_path} "
              f"({len(merged.get('traceEvents', []))} events) — "
              f"load in chrome://tracing or ui.perfetto.dev")
    if args.summary:
        print(format_summary(summarize(merged)))


if __name__ == "__main__":
    main()
