"""Profile → chrome://tracing converter.

Reference analog: ``tools/timeline.py`` (profiler.proto → chrome trace
JSON). The TPU build profiles through jax.profiler (XPlane protos under
``<logdir>/plugins/profile/<run>/*.xplane.pb``, written by
``paddle_tpu.profiler`` / ``jax.profiler.trace``); this tool converts a
run's XPlane to the same chrome://tracing JSON the reference produced, via
the xprof trace-viewer converter when available.

CLI::

    python -m paddle_tpu.tools.timeline --logdir ./_trace --out trace.json
    # then open chrome://tracing (or https://ui.perfetto.dev) and load it
"""
from __future__ import annotations

import argparse
import glob
import json
import os
from typing import List, Optional


def find_xplanes(logdir: str) -> List[str]:
    """Newest profile run's xplane files under a jax.profiler logdir."""
    runs = sorted(glob.glob(os.path.join(logdir, "plugins", "profile", "*")))
    # newest run that actually holds an xplane (an interrupted newer run must
    # not shadow a complete older one)
    for run in reversed(runs):
        files = glob.glob(os.path.join(run, "*.xplane.pb"))
        if files:
            return files
    direct = glob.glob(os.path.join(logdir, "*.xplane.pb"))
    if direct:
        return direct
    raise FileNotFoundError(
        f"no profile runs under {logdir!r} (expected "
        f"plugins/profile/<run>/*.xplane.pb)")


def xplane_to_chrome_trace(xplane_files: List[str]) -> dict:
    """XPlane → chrome trace events dict ({"traceEvents": [...]})."""
    try:
        from xprof.convert import raw_to_tool_data as rtd
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "timeline conversion needs the xprof package (bundled with "
            "tensorboard-plugin-profile)") from e
    data, _ = rtd.xspace_to_tool_data(list(xplane_files), "trace_viewer@", {})
    if isinstance(data, bytes):
        data = data.decode("utf-8", errors="replace")
    out = json.loads(data)
    if "traceEvents" not in out:
        out = {"traceEvents": out if isinstance(out, list) else []}
    return out


def main(argv: Optional[List[str]] = None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--logdir", required=True,
                    help="jax.profiler trace dir (the arg of profiler.start)")
    ap.add_argument("--out", default="timeline.json",
                    help="output chrome-trace JSON path")
    args = ap.parse_args(argv)
    files = find_xplanes(args.logdir)
    trace = xplane_to_chrome_trace(files)
    with open(args.out, "w") as f:
        json.dump(trace, f)
    print(f"wrote {args.out} ({len(trace.get('traceEvents', []))} events) — "
          f"load in chrome://tracing or ui.perfetto.dev")


if __name__ == "__main__":
    main()
