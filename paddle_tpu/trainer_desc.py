"""fluid.trainer_desc (reference trainer_desc.py over trainer_desc.proto).

Config-object parity for the Dataset/trainer runtime: the reference builds
a protobuf TrainerDesc naming a trainer class + device worker; here
`Executor.train_from_dataset` drives the loop and these classes carry the
same knobs (SURVEY §1 row 8).
"""
from __future__ import annotations

__all__ = ["TrainerDesc", "MultiTrainer", "DistMultiTrainer",
           "PipelineTrainer"]


class TrainerDesc:
    def __init__(self):
        self._fetch_vars = []
        self._fetch_info = []
        self._print_period = 100
        self._thread_num = 1
        self._device_worker = None
        self._program = None

    def set_fetch_var_and_info(self, fetch_vars, fetch_info, print_period):
        self._fetch_vars = list(fetch_vars)
        self._fetch_info = list(fetch_info)
        self._print_period = print_period

    def set_thread(self, thread_num):
        self._thread_num = thread_num

    def set_device_worker(self, device_worker):
        self._device_worker = device_worker

    def set_program(self, program):
        self._program = program

    def _desc(self):
        return self.__class__.__name__


class MultiTrainer(TrainerDesc):
    """trainer.h:63 MultiTrainer — N loader threads, one device loop."""


class DistMultiTrainer(TrainerDesc):
    """trainer.h:82 DistMultiTrainer — multi-trainer with fleet hooks."""


class PipelineTrainer(TrainerDesc):
    """trainer.h:110 PipelineTrainer — superseded by PipelineOptimizer's
    compiled GPipe schedule (optimizer.py PipelineOptimizer)."""
