"""Transpiler compatibility surface.

Reference analog: ``python/paddle/fluid/transpiler/`` —
DistributeTranspiler (distribute_transpiler.py:181, pserver/nccl2 program
rewriting), DistributeTranspilerConfig (:131), memory_optimize /
release_memory (memory_optimization_transpiler.py).

TPU-native stance (SURVEY §2.2): the pserver runtime is a declared
non-goal — sharded embeddings over the tp axis replace it — and collective
("nccl2") data parallelism needs NO program rewriting because GSPMD inserts
the collectives when a `CompiledProgram` runs over a mesh. These classes
keep reference training scripts importable and fail loudly only where real
pserver semantics are requested. Memory passes are absorbed by XLA
(buffer assignment + donation); memory_optimize/release_memory are no-ops
kept for API parity, like the reference's own deprecation path.
"""
from __future__ import annotations

from typing import Optional


class DistributeTranspilerConfig:
    """distribute_transpiler.py:131 parity (field bag)."""

    slice_var_up = True
    split_method = None
    min_block_size = 8192
    enable_dc_asgd = False
    mode = "pserver"
    print_log = False
    wait_port = True
    runtime_split_send_recv = False
    sync_mode = True


class DistributeTranspiler:
    """distribute_transpiler.py:181 parity.

    mode="nccl2"/"collective": transpile() is the identity — run the SAME
    program through `CompiledProgram(...).with_mesh(...)` (GSPMD inserts
    gradient collectives; trainer_id/endpoints map to
    `paddle_tpu.distributed.launch` + jax.distributed env bootstrap).
    mode="pserver": not implemented (non-goal) — raises with the migration
    pointer (sharded embedding via TP, parallel/tensor_parallel.py).
    """

    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._program = None

    def transpile(self, trainer_id: int, program=None, pservers: str = "",
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint: str = ""):
        mode = getattr(self.config, "mode", "pserver")
        if isinstance(trainers, str) or mode in ("nccl2", "collective"):
            # endpoint-list form ⇒ collective mode: nothing to rewrite
            from .core.program import default_main_program
            self._program = program or default_main_program()
            return
        raise NotImplementedError(
            "parameter-server transpilation is a declared non-goal of the "
            "TPU build: dense training needs no pservers under GSPMD data "
            "parallelism, and sparse embeddings shard over the tp mesh axis "
            "(paddle_tpu.parallel.tensor_parallel). Use "
            "DistributeTranspilerConfig.mode='nccl2' + CompiledProgram."
        )

    def get_trainer_program(self, wait_port=True):
        if self._program is None:
            raise RuntimeError("call transpile() first")
        return self._program

    def get_pserver_program(self, endpoint):
        raise NotImplementedError(
            "no parameter-server runtime in the TPU build (non-goal)")

    def get_pserver_programs(self, endpoint):
        raise NotImplementedError(
            "no parameter-server runtime in the TPU build (non-goal)")

    def get_startup_program(self, endpoint, pserver_program=None,
                            startup_program=None):
        raise NotImplementedError(
            "no parameter-server runtime in the TPU build (non-goal)")


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    """memory_optimization_transpiler.py parity: a no-op here — XLA buffer
    assignment + the executor's donation pass (ir/passes.py liveness)
    already reuse dead-variable memory inside the one compiled step."""
    return None


def release_memory(input_program, skip_opt_set=None):
    """Same absorption as memory_optimize — kept for API parity."""
    return None
