"""Test config: force an 8-device virtual CPU mesh (SURVEY §4 TPU note —
the test_dist_base.py localhost-cluster trick, XLA edition)."""
import os

# Force a virtual 8-device CPU mesh: the session env pins JAX to the real TPU
# tunnel (axon plugin overrides JAX_PLATFORMS env), so use jax.config instead.
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
# numeric tests compare against float64 numpy references; use exact f32 dots
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np
import pytest


def pytest_configure(config):
    # tier-1 CI deselects these (`-m "not slow"`); registration keeps
    # pytest from warning on the unknown marker
    config.addinivalue_line(
        "markers", "slow: long chaos/soak cells excluded from tier-1")


@pytest.fixture()
def xla_8dev_subprocess_env():
    """Env for subprocess runners that must see 8 fake CPU devices from a
    clean interpreter (the CI sharding smoke job — mirrors how
    dist_mlp_runner.py forces its own XLA_FLAGS before importing jax)."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    return env


@pytest.fixture(autouse=True)
def _fresh_programs():
    """Give every test fresh default programs + scope + unique names."""
    import paddle_tpu as fluid
    from paddle_tpu.core import program as prog_mod
    from paddle_tpu.core import scope as scope_mod
    from paddle_tpu.core import unique_name

    old_main, old_startup = prog_mod._main_program, prog_mod._startup_program
    old_scope = scope_mod._global_scope
    prog_mod._main_program = prog_mod.Program()
    prog_mod._startup_program = prog_mod.Program()
    scope_mod._global_scope = scope_mod.Scope()
    scope_mod._current_scope = scope_mod._global_scope
    with unique_name.guard():
        yield
    prog_mod._main_program, prog_mod._startup_program = old_main, old_startup
    scope_mod._global_scope = old_scope
    scope_mod._current_scope = old_scope
