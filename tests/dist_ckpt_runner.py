"""Shard-parallel checkpoint runner (VERDICT r2 #7).

Modes:
  --save DIR     train 2 steps with a tp-sharded parameter, save a sharded
                 checkpoint (each process writes only its addressable
                 replica-0 shards + a JSON index), print the full param sum.
  --restore DIR  restore into a fresh scope, print the loaded param sum.

Runs either single-process (8 local CPU devices) or as a 2-process
jax.distributed cluster under paddle_tpu.distributed.launch — save under one
topology, restore under the other (reshardable across process counts).
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def build():
    import paddle_tpu as fluid
    from paddle_tpu.initializer import NumpyArrayInitializer
    from paddle_tpu.param_attr import ParamAttr

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [16])
        y = fluid.layers.data("y", [1], dtype="int64")
        w = (np.random.RandomState(5).rand(16, 8).astype("float32") - 0.5)
        logits = fluid.layers.fc(
            x, 8, bias_attr=False,
            param_attr=ParamAttr(name="w_tp",
                                 initializer=NumpyArrayInitializer(w),
                                 shard_spec=(None, "tp")))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def main_entry():
    import paddle_tpu as fluid
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.checkpoint import Checkpointer

    mode = sys.argv[1]
    ckdir = sys.argv[2]
    multi = "PADDLE_TRAINER_ID" in os.environ
    if multi:
        from paddle_tpu.parallel import env as penv
        penv.init_parallel_env()
    rank = jax.process_index()

    main, startup, loss = build()
    mesh = make_mesh({"tp": 2, "dp": jax.device_count() // 2})
    prog = fluid.CompiledProgram(main).with_mesh(mesh, data_axis="dp")
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(8, 16).astype("float32"),
            "y": rng.randint(0, 8, (8, 1)).astype("int64")}

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        ck = Checkpointer(ckdir, keep=0)
        if mode == "--save":
            for _ in range(2):
                exe.run(prog, feed=feed, fetch_list=[loss])
            w = scope.find_var("w_tp")
            # a sharded array spanning processes can't be fetched directly —
            # reduce on device (replicated scalar result)
            import jax.numpy as jnp
            wsum = float(jax.jit(lambda a: jnp.sum(a.astype(jnp.float64)))(w))
            ck.save(7, program=main, scope=scope, blocking=True)
            print(json.dumps({"rank": rank, "mode": "save", "wsum": wsum}))
        else:
            step = ck.restore(program=main, scope=scope)
            w = np.asarray(scope.find_var("w_tp"), dtype=np.float64)
            # run one step under THIS topology to prove the restored host
            # arrays lift into the new mesh's shardings
            out = exe.run(prog, feed=feed, fetch_list=[loss])
            print(json.dumps({"rank": rank, "mode": "restore", "step": step,
                              "wsum": float(w.sum()),
                              "loss": float(np.asarray(out[0]))}))


if __name__ == "__main__":
    main_entry()
