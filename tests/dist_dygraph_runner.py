"""Two-process dygraph DataParallel trainer (VERDICT r3 #10 — reference
dygraph/parallel.py:84 DataParallel + imperative/nccl_context.cc, the
test_dist_base localhost edition).

Each process hosts 4 virtual CPU devices; jax.distributed joins them into
one 8-device world. The dygraph loop runs scale_loss → backward →
apply_collective_grads → minimize, the reference DataParallel recipe.
Both ranks feed the SAME batch, so cross-process gradient averaging must
reproduce the single-process run exactly. Prints one JSON line:
{"rank": r, "losses": [...]}. Run with --local for the single-process
reference.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def build_and_run(dp: bool, steps=4):
    import paddle_tpu as fluid
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph.tracer import trace_op

    rng = np.random.RandomState(0)
    X = rng.rand(32, 16).astype("float32")
    Y = (X @ rng.rand(16, 1)).astype("float32")

    with dygraph.guard(seed=3):
        model = dygraph.Linear(16, 1, bias_attr=False)
        wrapped = dygraph.DataParallel(model) if dp else model
        opt = fluid.optimizer.SGD(0.1)
        losses = []
        for _ in range(steps):
            x = dygraph.to_variable(X)
            y = dygraph.to_variable(Y)
            out = wrapped(x)
            diff = trace_op("elementwise_sub", {"X": [out], "Y": [y]},
                            {"axis": -1})["Out"][0]
            sq = trace_op("square", {"X": [diff]}, {})["Out"][0]
            loss = trace_op("mean", {"X": [sq]}, {})["Out"][0]
            losses.append(float(np.asarray(loss.value)))
            if dp:
                scaled = wrapped.scale_loss(loss)
                scaled.backward()
                wrapped.apply_collective_grads()
            else:
                loss.backward()
            opt.minimize(loss, parameter_list=model.parameters())
            model.clear_gradients()
        return losses


def main():
    if "--local" in sys.argv:
        print(json.dumps({"rank": -1, "losses": build_and_run(dp=False)}),
              flush=True)
        return
    from paddle_tpu.parallel import env as penv

    active = penv.init_parallel_env()
    assert active, "init_parallel_env did not activate distributed mode"
    assert jax.process_count() == 2, jax.process_count()
    losses = build_and_run(dp=True)
    print(json.dumps({"rank": penv.get_rank(), "losses": losses}),
          flush=True)


if __name__ == "__main__":
    main()
