"""Two-process localhost-cluster trainer (reference test_dist_base.py:61
TestDistRunnerBase.run_trainer analog).

Launched by tests/test_dist_cluster.py via paddle_tpu.distributed.launch with
PADDLE_TRAINER_* env wiring. Each process hosts 4 virtual CPU devices; the
two processes form one 8-device dp mesh through jax.distributed. Prints one
JSON line: {"rank": r, "losses": [...]}.

Run with --local for the single-process reference (no jax.distributed).
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def build_and_run(steps=4):
    import paddle_tpu as fluid
    from paddle_tpu.initializer import NumpyArrayInitializer
    from paddle_tpu.param_attr import ParamAttr

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [16])
        y = fluid.layers.data("y", [1], dtype="int64")
        w = np.random.RandomState(5).rand(16, 4).astype("float32") * 0.1
        logits = fluid.layers.fc(
            x, 4, bias_attr=False,
            param_attr=ParamAttr(name="w", initializer=NumpyArrayInitializer(w)))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(0.1).minimize(loss)

    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    prog = fluid.CompiledProgram(main).with_data_parallel(loss_name=loss.name)

    # every rank feeds the same local batch; with the batch duplicated
    # across the two ranks the global mean loss/grads equal the
    # single-process run on one copy — the test_dist_base loss-equality trick
    rng = np.random.RandomState(0)
    xv = rng.rand(32, 16).astype("float32")
    yv = rng.randint(0, 4, (32, 1)).astype("int64")
    return [float(exe.run(prog, feed={"x": xv, "y": yv},
                          fetch_list=[loss])[0]) for _ in range(steps)]


def main():
    if "--local" in sys.argv:
        print(json.dumps({"rank": -1, "losses": build_and_run()}), flush=True)
        return
    from paddle_tpu.parallel import env as penv

    active = penv.init_parallel_env()
    assert active, "init_parallel_env did not activate distributed mode"
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())
    losses = build_and_run()
    print(json.dumps({"rank": penv.get_rank(), "losses": losses}), flush=True)


if __name__ == "__main__":
    main()
