"""Cross-trainer global shuffle runner (VERDICT r2 #9): each rank loads a
DISJOINT set of records; after global_shuffle every record must live on
exactly one rank, chosen by content hash — records cross the process
boundary, unlike a local partition."""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main():
    from paddle_tpu.dataset import factory
    from paddle_tpu.parallel import env as penv

    penv.init_parallel_env()
    rank = jax.process_index()

    ds = factory.InMemoryDataset()
    # disjoint per-rank records: rank 0 loads 0..39, rank 1 loads 40..79
    ds._memory = [(f"rec-{i}", i) for i in range(rank * 40, rank * 40 + 40)]
    ds.global_shuffle()
    ids = sorted(i for _, i in ds._memory)
    print(json.dumps({"rank": rank, "ids": ids}))


if __name__ == "__main__":
    main()
