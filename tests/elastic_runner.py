"""Preemptible trainer subprocess for tests/test_elastic.py.

Trains a deterministic MLP via run_elastic; prints one line per completed
step: `step <i> <loss>` (flushed, so the parent can SIGTERM mid-run), then
`done <next_step>` on exit. Re-launching with the same --ckpt resumes.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt", required=True)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--save-interval", type=int, default=2)
    ap.add_argument("--step-delay", type=float, default=0.0)
    args = ap.parse_args()

    import paddle_tpu as fluid
    from paddle_tpu.distributed import run_elastic
    from paddle_tpu.initializer import NumpyArrayInitializer
    from paddle_tpu.param_attr import ParamAttr

    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data("x", [16])
        y = fluid.layers.data("y", [1], dtype="int64")
        w = np.random.RandomState(5).rand(16, 4).astype("float32") * 0.1
        logits = fluid.layers.fc(
            x, 4, bias_attr=False,
            param_attr=ParamAttr(name="w",
                                 initializer=NumpyArrayInitializer(w)))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(0.05).minimize(loss)

    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(32, 16).astype("float32"),
            "y": rng.randint(0, 4, (32, 1)).astype("int64")}

    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)

        def step_fn(i):
            (lv,) = exe.run(main_p, feed=feed, fetch_list=[loss])
            print(f"step {i} {float(lv):.8f}", flush=True)
            if args.step_delay:
                time.sleep(args.step_delay)

        nxt = run_elastic(step_fn, args.ckpt, args.steps,
                          save_interval=args.save_interval,
                          program=main_p,
                          heartbeat=os.path.join(args.ckpt, "heartbeat"))
    print(f"done {nxt}", flush=True)


if __name__ == "__main__":
    main()
