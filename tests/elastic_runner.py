"""Preemptible trainer subprocess for tests/test_elastic.py.

Trains a deterministic MLP via run_elastic; prints one line per completed
step: `step <i> <loss>` (full float repr, flushed, so the parent can
SIGTERM mid-run), then `done <next_step>` on exit. Re-launching with the
same --ckpt resumes.

Modes:
- default: one fixed feed dict, plain Executor — the minimal loop;
- ``--reader``: a STATEFUL epoch-aware reader (each epoch's batches are a
  function of the epoch index and batch position) pulled through a
  DeviceLoader that run_elastic checkpoints/restores — resume must
  skip-ahead to the exact next undelivered batch or losses diverge;
- ``--tp N``: the weight carries a tensor-parallel shard_spec over a
  dp×tp mesh, so every checkpoint writes per-rank shard files (the
  ``ckpt.shard_write`` chaos target).

Fault injection: the parent sets ``PDTPU_FAULT_SPEC`` in the environment;
an injected ``crash`` exits with ``faults.CRASH_EXIT_CODE``.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

BATCHES_PER_EPOCH = 4


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt", required=True)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--save-interval", type=int, default=2)
    ap.add_argument("--step-delay", type=float, default=0.0)
    ap.add_argument("--reader", action="store_true",
                    help="stateful epoch-aware reader via DeviceLoader")
    ap.add_argument("--tp", type=int, default=0,
                    help="tensor-parallel degree (shard files on save)")
    args = ap.parse_args()

    import paddle_tpu as fluid
    from paddle_tpu.distributed import run_elastic
    from paddle_tpu.initializer import NumpyArrayInitializer
    from paddle_tpu.param_attr import ParamAttr

    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data("x", [16])
        y = fluid.layers.data("y", [1], dtype="int64")
        w = np.random.RandomState(5).rand(16, 4).astype("float32") * 0.1
        logits = fluid.layers.fc(
            x, 4, bias_attr=False,
            param_attr=ParamAttr(name="w",
                                 initializer=NumpyArrayInitializer(w),
                                 shard_spec=((None, "tp") if args.tp
                                             else None)))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(0.05).minimize(loss)

    rng = np.random.RandomState(0)
    fixed_feed = {"x": rng.rand(32, 16).astype("float32"),
                  "y": rng.randint(0, 4, (32, 1)).astype("int64")}

    def reader(epoch):
        # epoch-aware and position-dependent: batch b of epoch e is always
        # the same data, so a correct mid-epoch resume is bitwise-exact
        # and a wrong cursor is immediately visible in the losses
        r = np.random.RandomState(1000 + epoch)
        for _ in range(BATCHES_PER_EPOCH):
            yield {"x": r.rand(32, 16).astype("float32"),
                   "y": r.randint(0, 4, (32, 1)).astype("int64")}

    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        prog = main_p
        if args.tp:
            from paddle_tpu.parallel import make_mesh
            dp = max(1, len(jax.devices()) // args.tp)
            prog = fluid.CompiledProgram(main_p).with_mesh(
                make_mesh({"dp": dp, "tp": args.tp}))

        loader = None
        if args.reader:
            loader = fluid.DeviceLoader(reader, capacity=2, program=main_p)
            it = None

            def get_feed():
                nonlocal it
                if it is None:
                    it = iter(loader)
                try:
                    return next(it)
                except StopIteration:
                    it = iter(loader)
                    return next(it)
        else:
            def get_feed():
                return fixed_feed

        def step_fn(i):
            (lv,) = exe.run(prog, feed=get_feed(), fetch_list=[loss])
            print(f"step {i} {float(lv)!r}", flush=True)
            if args.step_delay:
                time.sleep(args.step_delay)

        nxt = run_elastic(step_fn, args.ckpt, args.steps,
                          save_interval=args.save_interval,
                          program=main_p, loader=loader,
                          heartbeat=os.path.join(args.ckpt, "heartbeat"))
        if loader is not None:
            loader.close()
    print(f"done {nxt}", flush=True)


if __name__ == "__main__":
    main()
