"""Generate the checked-in reference-format MNIST artifact
(tests/data/ref_mnist_model/) with paddle_tpu.compat's writer, plus the
independently-computed (pure numpy) expected outputs. Run once; the test
then guards the loader against the frozen bytes."""
import os

import numpy as np

from paddle_tpu.compat import reference_format as rf


def build(dirname):
    rng = np.random.RandomState(42)
    w0 = (rng.randn(784, 32) * 0.05).astype("float32")
    b0 = (rng.randn(32) * 0.05).astype("float32")
    w1 = (rng.randn(32, 10) * 0.05).astype("float32")
    b1 = (rng.randn(10) * 0.05).astype("float32")

    def var(name, shape, persistable=False):
        return {"name": name, "type": rf.VT_LOD_TENSOR, "dtype": "float32",
                "shape": list(shape), "persistable": persistable,
                "lod_level": 0}

    prog = {"blocks": [{
        "idx": 0, "parent_idx": -1,
        "vars": {
            "img": var("img", [-1, 784]),
            "fc0.w": var("fc0.w", [784, 32], True),
            "fc0.b": var("fc0.b", [32], True),
            "fc1.w": var("fc1.w", [32, 10], True),
            "fc1.b": var("fc1.b", [10], True),
            "h0": var("h0", [-1, 32]), "h0b": var("h0b", [-1, 32]),
            "h0r": var("h0r", [-1, 32]),
            "h1": var("h1", [-1, 10]), "h1b": var("h1b", [-1, 10]),
            "prob": var("prob", [-1, 10]),
        },
        "ops": [
            {"type": "feed", "inputs": {"X": ["feed"]},
             "outputs": {"Out": ["img"]}, "attrs": {"col": 0}},
            {"type": "mul", "inputs": {"X": ["img"], "Y": ["fc0.w"]},
             "outputs": {"Out": ["h0"]},
             "attrs": {"x_num_col_dims": 1, "y_num_col_dims": 1}},
            {"type": "elementwise_add",
             "inputs": {"X": ["h0"], "Y": ["fc0.b"]},
             "outputs": {"Out": ["h0b"]}, "attrs": {"axis": 1}},
            {"type": "relu", "inputs": {"X": ["h0b"]},
             "outputs": {"Out": ["h0r"]}, "attrs": {}},
            {"type": "mul", "inputs": {"X": ["h0r"], "Y": ["fc1.w"]},
             "outputs": {"Out": ["h1"]},
             "attrs": {"x_num_col_dims": 1, "y_num_col_dims": 1}},
            {"type": "elementwise_add",
             "inputs": {"X": ["h1"], "Y": ["fc1.b"]},
             "outputs": {"Out": ["h1b"]}, "attrs": {"axis": 1}},
            {"type": "softmax", "inputs": {"X": ["h1b"]},
             "outputs": {"Out": ["prob"]}, "attrs": {}},
            {"type": "fetch", "inputs": {"X": ["prob"]},
             "outputs": {"Out": ["fetch"]}, "attrs": {"col": 0}},
        ],
    }]}

    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, "__model__"), "wb") as f:
        f.write(rf.serialize_program_desc(prog))
    for name, arr in [("fc0.w", w0), ("fc0.b", b0),
                      ("fc1.w", w1), ("fc1.b", b1)]:
        with open(os.path.join(dirname, name), "wb") as f:
            rf.write_lod_tensor_stream(f, arr)

    # expected outputs: INDEPENDENT numpy forward (not the loader under
    # test) on a fixed input batch
    x = rng.rand(4, 784).astype("float32")
    h = np.maximum(x @ w0 + b0, 0.0)
    logits = h @ w1 + b1
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    prob = e / e.sum(axis=1, keepdims=True)
    np.savez(os.path.join(dirname, "expected.npz"), x=x, prob=prob)
    print("wrote", dirname)


if __name__ == "__main__":
    build(os.path.join(os.path.dirname(__file__), "data",
                       "ref_mnist_model"))
