"""OpTest harness — the per-op contract from the reference
(python/paddle/fluid/tests/unittests/op_test.py:135): run a single op through
a real program+executor, compare outputs to numpy, and compare analytic
gradients (via the autodiff machinery) against finite differences
(op_test.py:46 get_numeric_gradient).
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as fluid


class OpTest:
    """Subclass sets: op_type, inputs (slot->np array or list), attrs,
    and a numpy reference via expected_outputs()."""

    op_type: str = ""
    atol = 1e-5
    rtol = 1e-5

    def run_op(self, inputs, attrs=None, output_slots=("Out",), multi_output_counts=None):
        """Build a one-op program, execute, return dict slot -> np arrays."""
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            block = main.global_block()
            in_map = {}
            feed = {}
            for slot, arrs in inputs.items():
                arrs = arrs if isinstance(arrs, (list, tuple)) else [arrs]
                names = []
                for i, a in enumerate(arrs):
                    name = f"{slot.lower()}_{i}"
                    block.create_var(name=name, shape=a.shape, dtype=str(a.dtype),
                                     is_data=True, stop_gradient=False)
                    feed[name] = a
                    names.append(name)
                in_map[slot] = names
            out_map = {}
            counts = multi_output_counts or {}
            for slot in output_slots:
                n = counts.get(slot, 1)
                out_map[slot] = [f"out_{slot.lower()}_{i}" for i in range(n)]
                for nm in out_map[slot]:
                    block.create_var(name=nm, dtype="float32")
            block.append_op(self.op_type, in_map, out_map, attrs or {})
            exe = fluid.Executor(fluid.CPUPlace())
            fetch = [nm for slot in output_slots for nm in out_map[slot]]
            res = exe.run(main, feed=feed, fetch_list=fetch)
        out = {}
        i = 0
        for slot in output_slots:
            vals = []
            for _ in out_map[slot]:
                vals.append(res[i])
                i += 1
            out[slot] = vals if len(vals) > 1 else vals[0]
        return out

    def check_output(self, inputs, attrs, expected, output_slots=("Out",), atol=None):
        got = self.run_op(inputs, attrs, output_slots)
        for slot, exp in expected.items():
            np.testing.assert_allclose(
                np.asarray(got[slot]), exp, atol=atol or self.atol, rtol=self.rtol,
                err_msg=f"op {self.op_type} output {slot} mismatch")

    def check_grad(self, inputs, attrs, grad_input_slot="X", output_slot="Out",
                   delta=5e-3, max_relative_error=5e-3):
        """Analytic-vs-numeric gradient of sum(output) wrt one input."""
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            block = main.global_block()
            in_map = {}
            feed = {}
            for slot, arrs in inputs.items():
                arrs = arrs if isinstance(arrs, (list, tuple)) else [arrs]
                names = []
                for i, a in enumerate(arrs):
                    name = f"{slot.lower()}_{i}"
                    block.create_var(name=name, shape=a.shape, dtype=str(a.dtype),
                                     is_data=True, stop_gradient=False)
                    feed[name] = a
                    names.append(name)
                in_map[slot] = names
            out_name = "out_0"
            block.create_var(name=out_name, dtype="float32")
            block.append_op(self.op_type, in_map, {output_slot: [out_name]}, attrs or {})
            out_var = block.var(out_name)
            # loss = sum(out)
            loss = fluid.layers.reduce_sum(out_var)
            target = block.var(in_map[grad_input_slot][0])
            (gvar,) = fluid.gradients([loss], [target])
            exe = fluid.Executor(fluid.CPUPlace())
            (analytic,) = exe.run(main, feed=feed, fetch_list=[gvar])

        # numeric: central differences on the same op via eager dispatch
        x0 = np.array(feed[in_map[grad_input_slot][0]], dtype=np.float64)
        numeric = np.zeros_like(x0)

        def eval_sum(xv):
            f2 = dict(feed)
            f2[in_map[grad_input_slot][0]] = xv.astype(feed[in_map[grad_input_slot][0]].dtype)
            import paddle_tpu.ops as ops
            vals = {s: [np.asarray(f2[n]) for n in ns] for s, ns in in_map.items()}
            import jax.numpy as jnp
            jvals = {s: [jnp.asarray(v) for v in vs] for s, vs in vals.items()}
            out = ops.eager_call(self.op_type, jvals, attrs or {})
            return float(np.sum(np.asarray(out[output_slot][0], dtype=np.float64)))

        flat = x0.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + delta
            fp = eval_sum(x0)
            flat[i] = orig - delta
            fm = eval_sum(x0)
            flat[i] = orig
            numeric.reshape(-1)[i] = (fp - fm) / (2 * delta)

        abs_err = np.abs(analytic - numeric)
        denom = np.maximum(np.abs(numeric), 1e-3)
        assert (abs_err / denom).max() < max_relative_error, (
            f"op {self.op_type} grad mismatch: max rel err "
            f"{(abs_err / denom).max()}\nanalytic={analytic}\nnumeric={numeric}")
