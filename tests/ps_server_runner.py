"""Standalone socket pserver process for the PS chaos tests.

Hosts EmbeddingShard slices behind a ShardServer and serves until
killed (the tests SIGKILL it to model a preempted pserver) or until a
client sends the ``shutdown`` op. Shards start ZERO-initialized: the
parent seeds them over the wire with ``load`` — which is also exactly
what a freshly restarted (and therefore empty) shard looks like to the
recovery machinery.

Run::

    python tests/ps_server_runner.py --table tb:0:25 [--port 0]
        [--delay-ms 5]

Prints the bound endpoint as the first stdout line (port 0 picks an
ephemeral port), then serves. ``PDTPU_FAULT_SPEC`` in the environment
arms server-side ``ps.rpc`` injections (drop/reset/delay_ms/crash).

Deliberately NEVER imports JAX — the module chain is loaded under a
stub ``paddle_tpu`` parent so ``paddle_tpu/__init__`` (which drags in
the whole fluid surface and jax) never runs. The final assert enforces
the pserver contract from the ps package docs: shard hosts are
numpy + stdlib only.
"""
import argparse
import os
import sys
import types

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_ps_modules():
    """Import paddle_tpu.ps.{shard,transport} without paddle_tpu's
    package __init__ (which imports jax)."""
    if "paddle_tpu" not in sys.modules:
        stub = types.ModuleType("paddle_tpu")
        stub.__path__ = [os.path.join(_ROOT, "paddle_tpu")]
        sys.modules["paddle_tpu"] = stub
    import paddle_tpu.ps.shard as shard_mod
    import paddle_tpu.ps.transport as transport_mod
    return shard_mod, transport_mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ps_server_runner")
    ap.add_argument("--table", action="append", default=[],
                    help="name:lo:hi[:lanes] — one shard slice to host; "
                         "repeatable")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--delay-ms", type=float, default=0.0,
                    help="simulated per-request RTT on pull/push")
    args = ap.parse_args(argv)
    if not args.table:
        ap.error("need at least one --table name:lo:hi")
    shard_mod, transport_mod = _load_ps_modules()
    shards = []
    for t in args.table:
        parts = t.split(":")
        if len(parts) not in (3, 4):
            ap.error(f"bad --table {t!r} (expected name:lo:hi[:lanes])")
        name, lo, hi = parts[0], int(parts[1]), int(parts[2])
        lanes = int(parts[3]) if len(parts) == 4 else 128
        shards.append(shard_mod.EmbeddingShard(name, lo, hi, lanes=lanes))
    srv = transport_mod.ShardServer(shards, host=args.host, port=args.port,
                                    delay_ms=args.delay_ms)
    assert "jax" not in sys.modules, \
        "pserver contract violated: the shard host imported jax"
    print(srv.endpoint, flush=True)
    srv.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
