"""Top-level API parity (reference python/paddle/fluid/__init__.py): every
public name a reference user imports from `fluid` must exist, and the
compat surfaces (LoDTensor, data_generator, transpiler) must behave."""
import ast
import io
import os

import numpy as np
import pytest

import paddle_tpu as fluid

REF_INIT = "/root/reference/python/paddle/fluid/__init__.py"


def test_fluid_toplevel_names_exist():
    if not os.path.exists(REF_INIT):
        pytest.skip("reference tree not mounted")
    names = set()
    for node in ast.walk(ast.parse(open(REF_INIT).read())):
        if isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if a.name != "*":
                    names.add(a.asname or a.name)
    names.discard("print_function")
    missing = sorted(n for n in names if not hasattr(fluid, n))
    assert not missing, f"fluid top-level names missing: {missing}"


def test_lod_tensor_roundtrip():
    """lod_tensor.py create_lod_tensor parity + the TPU padded bridge."""
    flat = np.arange(12, dtype="int64").reshape(6, 2)
    t = fluid.create_lod_tensor(flat, [[2, 1, 3]])
    assert t.recursive_sequence_lengths() == [[2, 1, 3]]
    assert t.lod() == [[0, 2, 3, 6]]
    assert t.has_valid_recursive_sequence_lengths()
    padded, lens = t.to_padded()
    assert padded.shape == (3, 3, 2)
    np.testing.assert_array_equal(lens, [2, 1, 3])
    np.testing.assert_array_equal(padded[0, :2], flat[:2])
    np.testing.assert_array_equal(padded[2], flat[3:6])
    assert padded[0, 2].sum() == 0  # padding

    r = fluid.create_random_int_lodtensor([[2, 3]], [1], low=0, high=9)
    assert r.numpy().shape == (5, 1)
    assert r.numpy().max() <= 9

    with pytest.raises(ValueError):
        fluid.create_lod_tensor(flat, [[2, 2]])  # doesn't cover 6 rows

    arr = fluid.LoDTensorArray([t])
    assert len(arr) == 1 and arr[0] is t


def test_data_generator_emits_native_loader_format(tmp_path):
    """incubate/data_generator parity: the emitted MultiSlot text is parsed
    back by the native C++ loader."""
    from paddle_tpu.native import available as native_available

    class G(fluid.data_generator.MultiSlotDataGenerator):
        def generate_sample(self, line):
            def gen():
                yield [("feat", [0.5, 1.5]), ("label", [int(line)])]
            return gen

    g = G()
    buf = io.StringIO()
    g.run_from_memory(lines=["3", "7"], out=buf)
    text = buf.getvalue()
    assert text == "2 0.5 1.5 1 3\n2 0.5 1.5 1 7\n"

    if native_available():
        from paddle_tpu.native import NativeDataLoader
        f = tmp_path / "part-0"
        f.write_text(text)
        samples = sorted(NativeDataLoader([str(f)], "fi").__iter__(),
                         key=lambda s: int(s[1][0]))
        assert len(samples) == 2
        np.testing.assert_allclose(samples[0][0], [0.5, 1.5])
        np.testing.assert_array_equal(samples[1][1], [7])


def test_transpiler_shims():
    cfg = fluid.DistributeTranspilerConfig()
    cfg.mode = "nccl2"
    t = fluid.DistributeTranspiler(config=cfg)
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.layers.data("x", [4])
        fluid.layers.fc(x, 2)
    t.transpile(trainer_id=0, program=main,
                trainers="127.0.0.1:6170,127.0.0.1:6171",
                current_endpoint="127.0.0.1:6170")
    assert t.get_trainer_program() is main

    ps = fluid.DistributeTranspiler()  # default pserver mode
    with pytest.raises(NotImplementedError, match="non-goal"):
        ps.transpile(trainer_id=0, program=main, pservers="h:1", trainers=2)

    assert fluid.memory_optimize(main) is None
    assert fluid.release_memory(main) is None
    assert isinstance(fluid.CUDAPinnedPlace(), type(fluid.CPUPlace()))


def test_static_conv2d_transpose_output_size():
    """Static layers.conv2d_transpose honors output_size (reference
    conv_transpose_op.cc output-size resolution)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [3, 7, 7])
        y = fluid.layers.conv2d_transpose(x, 5, output_size=16,
                                          filter_size=3, stride=2,
                                          bias_attr=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    out = exe.run(main, feed={"x": np.zeros((2, 3, 7, 7), "float32")},
                  fetch_list=[y])
    assert out[0].shape == (2, 5, 16, 16)
