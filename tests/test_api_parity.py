"""Top-level API parity (reference python/paddle/fluid/__init__.py): every
public name a reference user imports from `fluid` must exist, and the
compat surfaces (LoDTensor, data_generator, transpiler) must behave."""
import ast
import io
import os

import numpy as np
import pytest

import paddle_tpu as fluid

REF_INIT = "/root/reference/python/paddle/fluid/__init__.py"


def test_fluid_toplevel_names_exist():
    if not os.path.exists(REF_INIT):
        pytest.skip("reference tree not mounted")
    names = set()
    for node in ast.walk(ast.parse(open(REF_INIT).read())):
        if isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if a.name != "*":
                    names.add(a.asname or a.name)
    names.discard("print_function")
    missing = sorted(n for n in names if not hasattr(fluid, n))
    assert not missing, f"fluid top-level names missing: {missing}"


def test_lod_tensor_roundtrip():
    """lod_tensor.py create_lod_tensor parity + the TPU padded bridge."""
    flat = np.arange(12, dtype="int64").reshape(6, 2)
    t = fluid.create_lod_tensor(flat, [[2, 1, 3]])
    assert t.recursive_sequence_lengths() == [[2, 1, 3]]
    assert t.lod() == [[0, 2, 3, 6]]
    assert t.has_valid_recursive_sequence_lengths()
    padded, lens = t.to_padded()
    assert padded.shape == (3, 3, 2)
    np.testing.assert_array_equal(lens, [2, 1, 3])
    np.testing.assert_array_equal(padded[0, :2], flat[:2])
    np.testing.assert_array_equal(padded[2], flat[3:6])
    assert padded[0, 2].sum() == 0  # padding

    r = fluid.create_random_int_lodtensor([[2, 3]], [1], low=0, high=9)
    assert r.numpy().shape == (5, 1)
    assert r.numpy().max() <= 9

    with pytest.raises(ValueError):
        fluid.create_lod_tensor(flat, [[2, 2]])  # doesn't cover 6 rows

    arr = fluid.LoDTensorArray([t])
    assert len(arr) == 1 and arr[0] is t


def test_data_generator_emits_native_loader_format(tmp_path):
    """incubate/data_generator parity: the emitted MultiSlot text is parsed
    back by the native C++ loader."""
    from paddle_tpu.native import available as native_available

    class G(fluid.data_generator.MultiSlotDataGenerator):
        def generate_sample(self, line):
            def gen():
                yield [("feat", [0.5, 1.5]), ("label", [int(line)])]
            return gen

    g = G()
    buf = io.StringIO()
    g.run_from_memory(lines=["3", "7"], out=buf)
    text = buf.getvalue()
    assert text == "2 0.5 1.5 1 3\n2 0.5 1.5 1 7\n"

    if native_available():
        from paddle_tpu.native import NativeDataLoader
        f = tmp_path / "part-0"
        f.write_text(text)
        samples = sorted(NativeDataLoader([str(f)], "fi").__iter__(),
                         key=lambda s: int(s[1][0]))
        assert len(samples) == 2
        np.testing.assert_allclose(samples[0][0], [0.5, 1.5])
        np.testing.assert_array_equal(samples[1][1], [7])


def test_transpiler_shims():
    cfg = fluid.DistributeTranspilerConfig()
    cfg.mode = "nccl2"
    t = fluid.DistributeTranspiler(config=cfg)
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.layers.data("x", [4])
        fluid.layers.fc(x, 2)
    t.transpile(trainer_id=0, program=main,
                trainers="127.0.0.1:6170,127.0.0.1:6171",
                current_endpoint="127.0.0.1:6170")
    assert t.get_trainer_program() is main

    ps = fluid.DistributeTranspiler()  # default pserver mode
    with pytest.raises(NotImplementedError, match="non-goal"):
        ps.transpile(trainer_id=0, program=main, pservers="h:1", trainers=2)

    assert fluid.memory_optimize(main) is None
    assert fluid.release_memory(main) is None
    assert isinstance(fluid.CUDAPinnedPlace(), type(fluid.CPUPlace()))


def test_static_conv2d_transpose_output_size():
    """Static layers.conv2d_transpose honors output_size (reference
    conv_transpose_op.cc output-size resolution)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [3, 7, 7])
        y = fluid.layers.conv2d_transpose(x, 5, output_size=16,
                                          filter_size=3, stride=2,
                                          bias_attr=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    out = exe.run(main, feed={"x": np.zeros((2, 3, 7, 7), "float32")},
                  fetch_list=[y])
    assert out[0].shape == (2, 5, 16, 16)


def test_fluid_layers_names_exist():
    """Every name any reference layers/*.py exports must resolve on
    fluid.layers (SURVEY §2.3 — the 184-layer DSL plus detection/tensor/io
    surfaces)."""
    import ast
    import glob
    import warnings
    ref = "/root/reference/python/paddle/fluid/layers"
    if not os.path.isdir(ref):
        pytest.skip("reference tree not mounted")
    names = set()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for f in glob.glob(ref + "/*.py"):
            try:
                tree = ast.parse(open(f).read())
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "__all__":
                            try:
                                names.update(ast.literal_eval(node.value))
                            except Exception:
                                pass
    missing = sorted(n for n in names if not hasattr(fluid.layers, n))
    assert not missing, f"layers names missing ({len(missing)}): {missing}"


def test_coverage_layers_execute():
    """Functional smoke over the new coverage wrappers: build one program
    using a cross-section and execute it."""
    L = fluid.layers
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data("x", [6])
        img = L.data("img", [4, 8, 8])
        lab = L.data("lab", [1], dtype="int64")
        outs = {
            "brelu": L.brelu(x, 0.0, 2.0),
            "selu": L.selu(x),
            "soft_relu": L.soft_relu(x),
            "maxout": L.maxout(img, groups=2),
            "huber": L.huber_loss(x, x, delta=1.0),
            "log_loss": L.log_loss(L.sigmoid(x), L.sigmoid(x)),
            "dice": L.dice_loss(L.sigmoid(x), L.cast(lab, "float32")),
            "pad": L.pad(x, [0, 0, 1, 1]),
            "shape": L.shape(x),
            "rank": L.rank(x),
            "size": L.size(x),
            "ones_like": L.ones_like(x),
            "eye": L.eye(3),
            "linspace": L.linspace(0.0, 1.0, 5),
            "rng": L.range(0, 6, 2),
            "hash": L.hash(L.cast(lab, "int64"), hash_size=97, num_hash=2),
            "has_nan": L.has_nan(x),
            "resize": L.resize_bilinear(img, out_shape=[4, 4]),
            "pool3": L.adaptive_pool2d(img, [2, 2], "avg"),
            "pixshuf": L.pixel_shuffle(img, 2),
            "sfs": L.sequence_first_step(
                img, length=L.cast(L.ones_like(lab), "int64")),
        }
        uniq, idx = L.unique(L.cast(lab, "int64"))
        outs["unique"] = uniq
        step = L.autoincreased_step_counter()
        outs["step"] = step
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(3, 6).astype("float32"),
            "img": rng.rand(3, 4, 8, 8).astype("float32"),
            "lab": rng.randint(0, 2, (3, 1)).astype("int64")}
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    keys = list(outs)
    res = exe.run(main, feed=feed, fetch_list=[outs[k] for k in keys])
    got = dict(zip(keys, res))
    np.testing.assert_allclose(got["brelu"], np.clip(feed["x"], 0, 2))
    assert got["resize"].shape == (3, 4, 4, 4)
    assert got["maxout"].shape == (3, 2, 8, 8)
    assert got["eye"].shape == (3, 3)
    assert int(got["step"][0]) == 1
    res2 = exe.run(main, feed=feed, fetch_list=[step])
    assert int(res2[0][0]) == 2  # counter persists and increments
    for k, v in got.items():
        assert np.asarray(v).size > 0, k


def test_coverage_chunk_eval_and_detection_output():
    L = fluid.layers
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        inf = L.data("inf", [6], dtype="int64")
        lab = L.data("lab2", [6], dtype="int64")
        p, r, f1, ni, nl, nc = L.chunk_eval(inf, lab, "IOB",
                                            num_chunk_types=2)
        loc = L.data("loc", [4, 4])
        scores = L.data("scores", [4, 3])
        pb = L.data("pb", [4, 4], append_batch_size=False)
        pbv = L.data("pbv", [4, 4], append_batch_size=False)
        det = L.detection_output(loc, L.softmax(scores), pb, pbv,
                                 score_threshold=0.0, nms_top_k=4,
                                 keep_top_k=4)
    tags = np.array([[0, 1, 4, 2, 3, 4]], "int64")
    rng = np.random.RandomState(0)
    feed = {"inf": tags, "lab2": tags,
            "loc": rng.rand(1, 4, 4).astype("float32"),
            "scores": rng.rand(1, 4, 3).astype("float32"),
            "pb": rng.rand(4, 4).astype("float32"),
            "pbv": np.full((4, 4), 0.1, "float32")}
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    out = exe.run(main, feed=feed, fetch_list=[f1, nc, det])
    np.testing.assert_allclose(out[0], [1.0])
    assert int(out[1][0]) == 2
    assert np.asarray(out[2]).shape[-1] == 6  # [label, score, x1..y2]


def test_other_namespace_parity():
    """initializer/optimizer/metrics/dygraph/profiler/unique_name names."""
    import ast as _ast
    import glob as _glob
    import warnings as _warnings
    R = "/root/reference/python/paddle/fluid"
    if not os.path.isdir(R):
        pytest.skip("reference tree not mounted")

    def allnames(path):
        names = set()
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            for f in _glob.glob(path):
                try:
                    tree = _ast.parse(open(f).read())
                except SyntaxError:
                    continue
                for node in _ast.walk(tree):
                    if isinstance(node, _ast.Assign):
                        for t in node.targets:
                            if isinstance(t, _ast.Name) and t.id == "__all__":
                                try:
                                    names.update(_ast.literal_eval(node.value))
                                except Exception:
                                    pass
        return names

    checks = [("initializer", R + "/initializer.py"),
              ("optimizer", R + "/optimizer.py"),
              ("regularizer", R + "/regularizer.py"),
              ("clip", R + "/clip.py"),
              ("metrics", R + "/metrics.py"),
              ("dygraph", R + "/dygraph/*.py"),
              ("profiler", R + "/profiler.py"),
              ("io", R + "/io.py"),
              ("backward", R + "/backward.py")]
    problems = {}
    for mod, path in checks:
        target = getattr(fluid, mod)
        missing = [n for n in allnames(path)
                   if not hasattr(target, n) and not hasattr(fluid, n)]
        if missing:
            problems[mod] = sorted(missing)
    assert not problems, problems
    assert hasattr(fluid, "unique_name") and callable(fluid.unique_name.generate)


def test_lookahead_optimizer_trains():
    """LookaheadOptimizer (reference optimizer.py:2970): trains, and the
    fast weights snap to the slow blend every k steps."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        y = fluid.layers.data("y", [1])
        pred = fluid.layers.fc(x, 1, bias_attr=False)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        opt = fluid.optimizer.LookaheadOptimizer(
            fluid.optimizer.SGD(0.1), alpha=0.5, k=3)
        opt.minimize(loss)
    rng = np.random.RandomState(0)
    w_true = rng.rand(4, 1).astype("float32")
    xv = rng.rand(16, 4).astype("float32")
    feed = {"x": xv, "y": xv @ w_true}
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])
                  for _ in range(12)]
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_dygraph_decays_and_metrics_classes():
    d = fluid.dygraph
    nd = d.NoamDecay(d_model=512, warmup_steps=10)
    lrs = [nd() for _ in range(20)]
    assert max(lrs) == lrs[9]  # peaks at warmup boundary
    pd = d.PiecewiseDecay([5, 10], [1.0, 0.5, 0.1], begin=0)
    vals = [pd() for _ in range(12)]
    assert vals[0] == 1.0 and vals[6] == 0.5 and vals[-1] == 0.1
    cd = d.CosineDecay(1.0, step_each_epoch=1, epochs=10)
    first = cd()
    assert abs(first - 1.0) < 1e-6

    m = fluid.metrics.ChunkEvaluator()
    m.update(10, 10, 8)
    p, r, f1 = m.eval()
    assert abs(p - 0.8) < 1e-9 and abs(f1 - 0.8) < 1e-9

    dm = fluid.metrics.DetectionMAP()
    dm.update([[0, 0.9, 1], [0, 0.8, 0], [1, 0.7, 1]], [0, 1])
    assert 0.0 < dm.eval() <= 1.0


def test_fluid_submodule_attrs_exist():
    """Bare `from . import X` submodules of the reference __init__ must all
    resolve (average, evaluator, parallel_executor, incubate, ...)."""
    import ast as _ast
    ref = "/root/reference/python/paddle/fluid/__init__.py"
    if not os.path.exists(ref):
        pytest.skip("reference tree not mounted")
    names = set()
    for node in _ast.walk(_ast.parse(open(ref).read())):
        if isinstance(node, _ast.ImportFrom):
            for a in node.names:
                if a.name != "*":
                    names.add(a.asname or a.name)
    names.discard("print_function")
    missing = sorted(n for n in names if not hasattr(fluid, n))
    assert not missing, missing


def test_parallel_executor_compat_and_small_modules():
    # ParallelExecutor facade over CompiledProgram
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        y = fluid.layers.data("y", [1])
        pred = fluid.layers.fc(x, 1, bias_attr=False)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    rng = np.random.RandomState(0)
    xv = rng.rand(16, 4).astype("float32")
    feed = {"x": xv, "y": xv.sum(1, keepdims=True)}
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pe = fluid.ParallelExecutor(loss_name=loss.name, main_program=main)
        l0 = float(pe.run([loss], feed=feed)[0])
        for _ in range(5):
            l1 = float(pe.run([loss], feed=feed)[0])
    assert l1 < l0

    # WeightedAverage
    wa = fluid.WeightedAverage()
    wa.add(2.0, 1.0)
    wa.add(4.0, 3.0)
    assert abs(wa.eval() - 3.5) < 1e-9

    # dygraph grad clip
    from paddle_tpu import dygraph
    with dygraph.guard():
        import jax.numpy as jnp
        from paddle_tpu.dygraph.varbase import VarBase
        g = VarBase(jnp.asarray([3.0, -4.0]))
        p = VarBase(jnp.asarray([0.0, 0.0]))
        clipped = fluid.dygraph_grad_clip.GradClipByGlobalNorm(1.0)(
            [(p, g)])
        norm = float(np.sqrt((np.asarray(clipped[0][1].value) ** 2).sum()))
        assert abs(norm - 1.0) < 1e-5

    # trainer_desc + evaluator shims instantiate
    td = fluid.trainer_desc.MultiTrainer()
    td.set_thread(4)
    ce = fluid.evaluator.ChunkEvaluator()
    ce.update(5, 5, 5)
    assert ce.eval() == (1.0, 1.0, 1.0)

    # reference import forms resolve (review: sys.modules registration)
    from paddle_tpu.framework import default_main_program, Variable  # noqa
    from paddle_tpu.incubate.fleet.collective import fleet as fl  # noqa
    from paddle_tpu.incubate.fleet.base import role_maker  # noqa
    assert hasattr(role_maker, "PaddleCloudRoleMaker")
    dot = fluid.net_drawer.draw_graph(fluid.Program(), td and
                                      fluid.default_main_program())
    assert "digraph" in dot


def test_data_feed_desc(tmp_path):
    proto = tmp_path / "feed.prototxt"
    proto.write_text('''name: "MultiSlotDataFeed"
batch_size: 64
multi_slot_desc {
  slots { name: "words"  type: "uint64" is_dense: false is_used: true }
  slots { name: "label"  type: "uint64" is_dense: false is_used: true }
}''')
    d = fluid.DataFeedDesc(str(proto))
    assert d.batch_size == 64
    assert d.slots == ["words", "label"]
    assert len(d.slots) == len(d.types)
    d.set_batch_size(128)
    assert d.batch_size == 128
    assert "batch_size: 128" in d.desc()  # desc() reflects mutations
    assert "MultiSlotDataFeed" in d.desc()


def test_core_pybind_aliases():
    """fluid.core pybind-name surface (pybind.cc): the names scripts touch
    directly on core."""
    from paddle_tpu import core
    assert core.is_compiled_with_cuda() is False
    assert core.is_compiled_with_dist() is True
    assert core.op_support_gpu("relu") and not core.op_support_gpu("nope")
    assert "relu" in core.get_all_op_names()
    t = core.LoDTensor(np.ones((3, 2)), [[1, 2]])
    assert t.recursive_sequence_lengths() == [[1, 2]]
    # pserver transpiler import path resolves and points at GSPMD
    from paddle_tpu.incubate.fleet.parameter_server import (
        distribute_transpiler as dt)
    with pytest.raises(NotImplementedError, match="non-goal"):
        dt.fleet.init(None)
