"""Aux subsystem tests: lr schedulers, AMP, clip, regularizer, metrics,
flags/nan guard, train_from_dataset, debugger (reference: test_optimizer.py,
test_learning_rate_scheduler.py, test_mixed_precision*, test_regularizer.py)."""
import numpy as np
import pytest

import paddle_tpu as fluid


def _simple_net(lr):
    x = fluid.layers.data("x", [4])
    y = fluid.layers.fc(x, 1, bias_attr=False)
    loss = fluid.layers.mean(y)
    opt = fluid.optimizer.SGD(lr)
    opt.minimize(loss)
    return loss, opt


def test_lr_scheduler_piecewise():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        lr = fluid.layers.piecewise_decay([2, 4], [0.1, 0.01, 0.001])
        loss, opt = _simple_net(lr)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xv = np.ones((2, 4), dtype="float32")
        lrs = []
        for _ in range(6):
            exe.run(main, feed={"x": xv}, fetch_list=[loss])
            lrs.append(float(np.asarray(fluid.global_scope().find_var(lr.name))))
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.01, 0.01, 0.001, 0.001], rtol=1e-6)


def test_lr_scheduler_noam_shape():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        lr = fluid.layers.noam_decay(d_model=64, warmup_steps=4, learning_rate=1.0)
        loss, opt = _simple_net(lr)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xv = np.ones((2, 4), dtype="float32")
        lrs = []
        for _ in range(6):
            exe.run(main, feed={"x": xv}, fetch_list=[loss])
            lrs.append(np.asarray(fluid.global_scope().find_var(lr.name)).item())
    # noam: rising through warmup (4 steps), then decaying
    assert lrs[1] > lrs[0] and lrs[2] > lrs[1]
    assert lrs[5] < lrs[3]


def test_amp_bf16_casts_matmul():
    from paddle_tpu.contrib import mixed_precision as mp
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [16])
        y = fluid.layers.data("y", [1], dtype="int64")
        logits = fluid.layers.fc(x, 4)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
        opt = mp.decorate(fluid.optimizer.SGD(0.1), dtype="bfloat16")
        opt.minimize(loss)
        assert main._amp["dtype"] == "bfloat16"
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(0)
        xv = rng.rand(8, 16).astype("float32")
        yv = rng.randint(0, 4, (8, 1)).astype("int64")
        losses = [float(exe.run(main, feed={"x": xv, "y": yv},
                                fetch_list=[loss])[0]) for _ in range(5)]
    assert losses[-1] < losses[0]


def test_amp_fp16_dynamic_loss_scaling():
    from paddle_tpu.contrib import mixed_precision as mp
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8])
        logits = fluid.layers.fc(x, 2)
        loss = fluid.layers.mean(logits)
        opt = mp.decorate(fluid.optimizer.SGD(0.01), dtype="float16",
                          init_loss_scaling=1024.0)
        opt.minimize(loss)
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        xv = np.random.rand(4, 8).astype("float32")
        exe.run(main, feed={"x": xv}, fetch_list=[loss])
        scale = float(np.asarray(
            fluid.global_scope().find_var(opt.get_loss_scaling().name)))
    assert scale == 1024.0  # finite grads: unchanged (good_steps < incr_every)


def test_grad_clip_global_norm():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        y = fluid.layers.fc(x, 1, bias_attr=False)
        loss = fluid.layers.mean(y)
        from paddle_tpu.clip import GradientClipByGlobalNorm
        opt = fluid.optimizer.SGD(1.0, grad_clip=GradientClipByGlobalNorm(0.1))
        opt.minimize(loss)
        p = main.all_parameters()[0]
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        w0 = np.asarray(fluid.global_scope().find_var(p.name)).copy()
        xv = np.full((2, 4), 100.0, dtype="float32")  # huge grads
        exe.run(main, feed={"x": xv}, fetch_list=[loss])
        w1 = np.asarray(fluid.global_scope().find_var(p.name))
    # update norm bounded by lr * clip_norm
    assert np.linalg.norm(w1 - w0) <= 0.1 + 1e-5


def test_l2_regularizer_changes_update():
    def run(reg):
        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.scope_guard(scope), fluid.program_guard(main, startup):
            from paddle_tpu.initializer import NumpyArrayInitializer
            from paddle_tpu.param_attr import ParamAttr
            w = np.ones((4, 1), dtype="float32")
            x = fluid.layers.data("x", [4])
            y = fluid.layers.fc(x, 1, bias_attr=False,
                                param_attr=ParamAttr(name="w",
                                                     initializer=NumpyArrayInitializer(w)))
            loss = fluid.layers.mean(y)
            from paddle_tpu.regularizer import L2Decay
            opt = fluid.optimizer.SGD(0.1, regularization=L2Decay(0.5) if reg else None)
            opt.minimize(loss)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            exe.run(main, feed={"x": np.zeros((2, 4), "float32")}, fetch_list=[loss])
            return np.asarray(scope.find_var("w"))

    w_plain = run(False)
    w_reg = run(True)
    # zero input → zero data grad; reg pulls weights toward 0 by lr*coeff*w
    np.testing.assert_allclose(w_plain, np.ones((4, 1)), atol=1e-6)
    np.testing.assert_allclose(w_reg, np.full((4, 1), 0.95), rtol=1e-5)


def test_metrics_accuracy_precision_recall_auc():
    from paddle_tpu import metrics
    acc = metrics.Accuracy()
    acc.update(0.5, 10)
    acc.update(1.0, 10)
    assert abs(acc.eval() - 0.75) < 1e-9

    p = metrics.Precision()
    p.update([1, 1, 0, 1], [1, 0, 0, 1])
    assert abs(p.eval() - 2 / 3) < 1e-9

    r = metrics.Recall()
    r.update([1, 0, 0, 1], [1, 1, 0, 1])
    assert abs(r.eval() - 2 / 3) < 1e-9

    auc = metrics.Auc(num_thresholds=1023)
    rng = np.random.RandomState(0)
    labels = rng.randint(0, 2, 1000)
    preds = np.clip(labels * 0.6 + rng.rand(1000) * 0.4, 0, 1)
    auc.update(preds, labels)
    assert auc.eval() > 0.8


def test_check_nan_inf_flag():
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", [2])
            y = fluid.layers.log(x)  # log(-1) = nan
            exe = fluid.Executor(fluid.CPUPlace())
            with pytest.raises(FloatingPointError):
                exe.run(main, feed={"x": -np.ones((1, 2), "float32")},
                        fetch_list=[y])
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})


def test_train_from_dataset(tmp_path):
    from paddle_tpu.dataset import DatasetFactory
    f = tmp_path / "train.txt"
    rng = np.random.RandomState(0)
    lines = []
    for i in range(32):
        feats = rng.rand(4)
        label = int(feats.sum() > 2)
        lines.append("4 " + " ".join(f"{v:.4f}" for v in feats) + f" 1 {label}")
    f.write_text("\n".join(lines))

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        feats = fluid.layers.data("feats", [4])
        label = fluid.layers.data("label", [1], dtype="int64")
        logits = fluid.layers.fc(feats, 2)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Adam(0.01).minimize(loss)
        ds = DatasetFactory().create_dataset("QueueDataset")
        ds.set_filelist([str(f)])
        ds.set_batch_size(8)
        ds.set_use_var([feats, label])
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        out = exe.train_from_dataset(main, ds, fetch_list=[loss])
    assert out is not None and np.isfinite(out[0]).all()


def test_debugger_dot_and_summary():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        fluid.layers.fc(x, 2)
        dot = fluid.debugger.program_to_dot(main)
        assert "digraph" in dot and "mul" in dot
        summary = fluid.debugger.program_summary(main)
        assert "block 0" in summary


def test_profiler_record_event():
    import jax.numpy as jnp
    with fluid.profiler.record_event("test_region"):
        _ = jnp.ones(4) + 1
