"""Book-model end-to-end convergence smokes — the reference's
tests/book suite (test_fit_a_line.py, test_word2vec.py,
test_recommender_system.py, test_rnn_encoder_decoder.py,
test_label_semantic_roles.py, test_machine_translation.py). Each builds the
classic model through the layers DSL, trains a few steps on synthetic data,
and asserts the loss drops; fit_a_line also round-trips
save/load_inference_model like the originals. (recognize_digits lives in
test_mnist.py, image_classification in test_parallel/bench.)
"""
import numpy as np

import paddle_tpu as fluid


def _train(main, startup, feed_fn, loss, steps=12, exe=None):
    exe = exe or fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    for i in range(steps):
        out = exe.run(main, feed=feed_fn(i), fetch_list=[loss])
        losses.append(float(np.asarray(out[0]).ravel()[0]))
    return losses, exe


def test_fit_a_line(tmp_path):
    """test_fit_a_line.py: linear regression, SGD, save/load inference."""
    rng = np.random.RandomState(0)
    true_w = rng.randn(13, 1).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)

    def feed(i):
        xv = rng.randn(16, 13).astype("float32")
        return {"x": xv, "y": xv @ true_w}

    losses, exe = _train(main, startup, feed, loss, steps=30)
    assert losses[-1] < losses[0] * 0.2, losses
    # save / reload / infer (book pattern)
    d = str(tmp_path / "fit_a_line")
    fluid.io.save_inference_model(d, ["x"], [pred], exe, main_program=main)
    prog, feeds, fetches = fluid.io.load_inference_model(d, exe)
    xv = rng.randn(4, 13).astype("float32")
    out = exe.run(prog, feed={feeds[0]: xv}, fetch_list=fetches)
    assert np.asarray(out[0]).shape == (4, 1)


def test_word2vec_nce_and_hsigmoid():
    """test_word2vec.py: N-gram LM — embeddings concat -> hidden -> nce
    (and an hsigmoid variant), loss decreases."""
    vocab, emb_dim = 40, 8
    rng = np.random.RandomState(0)
    for head in ("nce", "hsigmoid"):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            words = [fluid.layers.data(name=f"w{i}", shape=[1], dtype="int64")
                     for i in range(4)]
            target = fluid.layers.data(name="tgt", shape=[1], dtype="int64")
            embs = [fluid.layers.embedding(w, size=[vocab, emb_dim])
                    for w in words]
            concat = fluid.layers.concat(embs, axis=1)
            hidden = fluid.layers.fc(concat, size=16, act="sigmoid")
            if head == "nce":
                cost = fluid.layers.nce(hidden, target,
                                        num_total_classes=vocab,
                                        num_neg_samples=5)
            else:
                cost = fluid.layers.hsigmoid(hidden, target,
                                             num_classes=vocab)
            loss = fluid.layers.mean(cost)
            fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)

        def feed(i):
            f = {f"w{j}": rng.randint(0, vocab, (8, 1)).astype("int64")
                 for j in range(4)}
            f["tgt"] = rng.randint(0, vocab, (8, 1)).astype("int64")
            return f

        # fixed batch each step so memorization is measurable
        batch = feed(0)
        losses, _ = _train(main, startup, lambda i: batch, loss, steps=20)
        assert losses[-1] < losses[0] * 0.8, (head, losses)


def test_recommender_system():
    """test_recommender_system.py: user/item embeddings -> fc towers ->
    cos_sim -> regression on rating."""
    n_users, n_items = 30, 50
    rng = np.random.RandomState(0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        uid = fluid.layers.data(name="uid", shape=[1], dtype="int64")
        mid = fluid.layers.data(name="mid", shape=[1], dtype="int64")
        rating = fluid.layers.data(name="score", shape=[1], dtype="float32")
        uemb = fluid.layers.embedding(uid, size=[n_users, 16])
        memb = fluid.layers.embedding(mid, size=[n_items, 16])
        uvec = fluid.layers.fc(uemb, size=16, act="relu")
        mvec = fluid.layers.fc(memb, size=16, act="relu")
        sim = fluid.layers.cos_sim(uvec, mvec)
        pred = fluid.layers.scale(sim, scale=5.0)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, rating))
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)

    users = rng.randint(0, n_users, (32, 1)).astype("int64")
    items = rng.randint(0, n_items, (32, 1)).astype("int64")
    scores = rng.randint(1, 6, (32, 1)).astype("float32")
    batch = {"uid": users, "mid": items, "score": scores}
    losses, _ = _train(main, startup, lambda i: batch, loss, steps=25)
    assert losses[-1] < losses[0] * 0.5, losses


def test_rnn_encoder_decoder():
    """test_rnn_encoder_decoder.py / test_machine_translation.py train halves:
    GRU encoder -> decoder with teacher forcing -> per-step softmax CE."""
    src_vocab, tgt_vocab, hid = 25, 20, 16
    rng = np.random.RandomState(0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data(name="src", shape=[6], dtype="int64")
        tgt_in = fluid.layers.data(name="tgt_in", shape=[5], dtype="int64")
        tgt_out = fluid.layers.data(name="tgt_out", shape=[5], dtype="int64")
        semb = fluid.layers.embedding(src, size=[src_vocab, hid])
        enc = fluid.layers.dynamic_gru(
            fluid.layers.fc(semb, size=3 * hid, num_flatten_dims=2), size=hid)
        enc_last = fluid.layers.reduce_max(enc, dim=1)
        temb = fluid.layers.embedding(tgt_in, size=[tgt_vocab, hid])
        dec = fluid.layers.dynamic_gru(
            fluid.layers.fc(temb, size=3 * hid, num_flatten_dims=2),
            size=hid, h_0=enc_last)
        logits = fluid.layers.fc(dec, size=tgt_vocab, num_flatten_dims=2)
        lbl = fluid.layers.reshape(tgt_out, shape=[-1, 5, 1])
        ce = fluid.layers.softmax_with_cross_entropy(logits, lbl)
        loss = fluid.layers.mean(ce)
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)

    batch = {"src": rng.randint(0, src_vocab, (8, 6)).astype("int64"),
             "tgt_in": rng.randint(0, tgt_vocab, (8, 5)).astype("int64"),
             "tgt_out": rng.randint(0, tgt_vocab, (8, 5)).astype("int64")}
    losses, _ = _train(main, startup, lambda i: batch, loss, steps=20)
    assert losses[-1] < losses[0] * 0.5, losses


def test_label_semantic_roles():
    """test_label_semantic_roles.py: embedding -> lstm -> linear_chain_crf
    training + crf_decoding inference."""
    vocab, n_labels, hid = 30, 7, 12
    rng = np.random.RandomState(0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        word = fluid.layers.data(name="word", shape=[6], dtype="int64")
        mark = fluid.layers.data(name="mark", shape=[6], dtype="int64")
        label = fluid.layers.data(name="label", shape=[6], dtype="int64")
        wemb = fluid.layers.embedding(word, size=[vocab, hid],
                                      param_attr=fluid.ParamAttr(name="wemb"))
        memb = fluid.layers.embedding(mark, size=[2, hid],
                                      param_attr=fluid.ParamAttr(name="memb"))
        feat = fluid.layers.concat([wemb, memb], axis=2)
        rnn, _ = fluid.layers.dynamic_lstm(
            fluid.layers.fc(feat, size=4 * hid, num_flatten_dims=2,
                            param_attr=fluid.ParamAttr(name="proj_w"),
                            bias_attr=fluid.ParamAttr(name="proj_b")),
            size=4 * hid, param_attr=fluid.ParamAttr(name="lstm_w"),
            bias_attr=fluid.ParamAttr(name="lstm_b"))
        emission = fluid.layers.fc(rnn, size=n_labels, num_flatten_dims=2,
                                   param_attr=fluid.ParamAttr(name="emis_w"),
                                   bias_attr=fluid.ParamAttr(name="emis_b"))
        crf_cost = fluid.layers.linear_chain_crf(
            emission, label, param_attr=fluid.ParamAttr(name="crfw"))
        loss = fluid.layers.mean(crf_cost)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    batch = {"word": rng.randint(0, vocab, (4, 6)).astype("int64"),
             "mark": rng.randint(0, 2, (4, 6)).astype("int64"),
             "label": rng.randint(0, n_labels, (4, 6)).astype("int64")}
    losses, exe = _train(main, startup, lambda i: batch, loss, steps=15)
    assert losses[-1] < losses[0] * 0.9, losses

    # decoding path (inference half of the book test)
    infer = fluid.Program()
    with fluid.program_guard(infer, fluid.Program()):
        word = fluid.layers.data(name="word", shape=[6], dtype="int64")
        mark = fluid.layers.data(name="mark", shape=[6], dtype="int64")
        wemb = fluid.layers.embedding(word, size=[vocab, hid],
                                      param_attr=fluid.ParamAttr(name="wemb"))
        memb = fluid.layers.embedding(mark, size=[2, hid],
                                      param_attr=fluid.ParamAttr(name="memb"))
        feat = fluid.layers.concat([wemb, memb], axis=2)
        rnn, _ = fluid.layers.dynamic_lstm(
            fluid.layers.fc(feat, size=4 * hid, num_flatten_dims=2,
                            param_attr=fluid.ParamAttr(name="proj_w"),
                            bias_attr=fluid.ParamAttr(name="proj_b")),
            size=4 * hid, param_attr=fluid.ParamAttr(name="lstm_w"),
            bias_attr=fluid.ParamAttr(name="lstm_b"))
        emission = fluid.layers.fc(rnn, size=n_labels, num_flatten_dims=2,
                                   param_attr=fluid.ParamAttr(name="emis_w"),
                                   bias_attr=fluid.ParamAttr(name="emis_b"))
        decode = fluid.layers.crf_decoding(
            emission, param_attr=fluid.ParamAttr(name="crfw"))
    out = exe.run(infer, feed={"word": batch["word"], "mark": batch["mark"]},
                  fetch_list=[decode])
    path = np.asarray(out[0])
    assert path.shape[0] == 4 and path.min() >= 0 and path.max() < n_labels


def test_image_classification_vgg():
    """test_image_classification.py vgg16_bn_drop (shrunk): img_conv_group
    blocks with batch norm + dropout on cifar-shaped input, loss decreases."""
    from paddle_tpu import nets

    rng = np.random.RandomState(0)
    classes = 4
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        main.random_seed = startup.random_seed = 5
        img = fluid.layers.data("img", [3, 16, 16])
        label = fluid.layers.data("label", [1], dtype="int64")
        g1 = nets.img_conv_group(img, conv_num_filter=[8, 8], pool_size=2,
                                 conv_act="relu", conv_with_batchnorm=True,
                                 conv_batchnorm_drop_rate=0.3, pool_stride=2)
        g2 = nets.img_conv_group(g1, conv_num_filter=[16, 16], pool_size=2,
                                 conv_act="relu", conv_with_batchnorm=True,
                                 pool_stride=2)
        fc1 = fluid.layers.fc(g2, 32, act="relu")
        logits = fluid.layers.fc(fc1, classes)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(label=label, logits=logits))
        fluid.optimizer.Adam(2e-3).minimize(loss)

    xs = rng.rand(32, 3, 16, 16).astype("float32")
    ys = rng.randint(0, classes, (32, 1)).astype("int64")
    losses, _ = _train(main, startup, lambda i: {"img": xs, "label": ys},
                       loss, steps=25)
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_understand_sentiment_conv():
    """notest_understand_sentiment.py convolution_net: embedding →
    sequence_conv_pool ×2 → fc softmax over imdb-shaped id sequences."""
    from paddle_tpu import nets

    rng = np.random.RandomState(0)
    vocab, T, B = 60, 12, 16
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        main.random_seed = startup.random_seed = 9
        data = fluid.layers.data("words", [T], dtype="int64")
        length = fluid.layers.data("length", [1], dtype="int64")
        label = fluid.layers.data("label", [1], dtype="int64")
        emb = fluid.layers.embedding(data, size=[vocab, 16])
        conv3 = nets.sequence_conv_pool(emb, num_filters=16, filter_size=3,
                                        length=length, act="tanh",
                                        pool_type="sqrt")
        conv4 = nets.sequence_conv_pool(emb, num_filters=16, filter_size=4,
                                        length=length, act="tanh",
                                        pool_type="sqrt")
        both = fluid.layers.concat([conv3, conv4], axis=1)
        logits = fluid.layers.fc(both, 2)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(label=label, logits=logits))
        fluid.optimizer.Adam(5e-3).minimize(loss)

    # synthetic sentiment: words < vocab//2 → positive
    lens = rng.randint(4, T + 1, (B,))
    words = np.zeros((B, T), "int64")
    labels = np.zeros((B, 1), "int64")
    for i, L in enumerate(lens):
        pos = i % 2 == 0
        lo, hi = (0, vocab // 2) if pos else (vocab // 2, vocab)
        words[i, :L] = rng.randint(lo, hi, (L,))
        labels[i, 0] = int(pos)
    feed = {"words": words, "length": lens.reshape(-1, 1).astype("int64"),
            "label": labels}
    losses, _ = _train(main, startup, lambda i: feed, loss, steps=30)
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_nets_glu_and_attention():
    """nets.py glu (:307) + scaled_dot_product_attention (:345) parity."""
    from paddle_tpu import nets

    rng = np.random.RandomState(1)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [6, 8])
        g = nets.glu(x, dim=-1)
        q = fluid.layers.data("q", [5, 8])
        kv = fluid.layers.data("kv", [7, 8])
        att = nets.scaled_dot_product_attention(q, kv, kv, num_heads=2)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = rng.rand(2, 6, 8).astype("float32")
    qv = rng.rand(2, 5, 8).astype("float32")
    kvv = rng.rand(2, 7, 8).astype("float32")
    g_out, a_out = exe.run(main, feed={"x": xv, "q": qv, "kv": kvv},
                           fetch_list=[g, att])
    a, b = xv[..., :4], xv[..., 4:]
    np.testing.assert_allclose(g_out, a * (1 / (1 + np.exp(-b))), rtol=1e-5)
    assert a_out.shape == (2, 5, 8)
    # attention rows are convex combinations of v rows: bounded by min/max
    assert a_out.max() <= kvv.max() + 1e-5 and a_out.min() >= kvv.min() - 1e-5
