"""Reshardable + async checkpointing (beats the reference: io.py:487 has no
resharding — SURVEY §5 bar). Save under mesh A (dp=8), restore under mesh B
(dp=4 × tp=2), loss continuity vs an uninterrupted run."""
import os

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.parallel import Checkpointer, make_mesh


def _build(tp_axis=None):
    from paddle_tpu.initializer import NumpyArrayInitializer
    from paddle_tpu.param_attr import ParamAttr

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [16])
        y = fluid.layers.data("y", [1], dtype="int64")
        w0 = np.random.RandomState(5).rand(16, 8).astype("float32") * 0.1
        # hidden layer parameter carries a TP shard_spec when tp is active
        h = fluid.layers.fc(
            x, 8, act="relu", bias_attr=False,
            param_attr=ParamAttr(name="w0",
                                 initializer=NumpyArrayInitializer(w0),
                                 shard_spec=(None, tp_axis) if tp_axis else None))
        w1 = np.random.RandomState(6).rand(8, 4).astype("float32") * 0.1
        logits = fluid.layers.fc(
            h, 4, bias_attr=False,
            param_attr=ParamAttr(name="w1",
                                 initializer=NumpyArrayInitializer(w1)))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(0.05).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(32, 16).astype("float32"),
            "y": rng.randint(0, 4, (32, 1)).astype("int64")}
    return main, startup, feed, loss


def _compiled(main, mesh, data_axis="dp"):
    return fluid.CompiledProgram(main).with_mesh(mesh, data_axis=data_axis)


def test_save_dp8_restore_dp4tp2_loss_continuity(tmp_path):
    steps_a, steps_b = 3, 4

    # uninterrupted reference: 7 steps under dp=8
    main, startup, feed, loss = _build()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        prog = _compiled(main, make_mesh({"dp": 8}))
        ref = [float(exe.run(prog, feed=feed, fetch_list=[loss])[0])
               for _ in range(steps_a + steps_b)]

    # phase A: dp=8, save at step 3 (async), then stop
    main, startup, feed, loss = _build()
    ck = Checkpointer(str(tmp_path / "ck"))
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        prog = _compiled(main, make_mesh({"dp": 8}))
        got_a = [float(exe.run(prog, feed=feed, fetch_list=[loss])[0])
                 for _ in range(steps_a)]
        ck.save(steps_a, program=main)
        ck.wait()

    # phase B: fresh process-state under a DIFFERENT topology dp=4 × tp=2
    main2, startup2, feed, loss2 = _build(tp_axis="tp")
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup2)  # garbage init, to be overwritten by restore
        restored = ck.restore(program=main2)
        assert restored == steps_a
        prog2 = _compiled(main2, make_mesh({"dp": 4, "tp": 2}))
        got_b = [float(exe.run(prog2, feed=feed, fetch_list=[loss2])[0])
                 for _ in range(steps_b)]

    np.testing.assert_allclose(got_a + got_b, ref, rtol=5e-4, atol=1e-6)


def test_async_save_preemption_safe(tmp_path):
    """The latest marker only moves once the bundle is durable; repeated
    saves keep at most `keep` bundles."""
    main, startup, feed, loss = _build()
    ck = Checkpointer(str(tmp_path / "ck"), keep=2)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        for s in range(1, 5):
            exe.run(main, feed=feed, fetch_list=[loss])
            ck.save(s, program=main)   # async — overlaps next step
        ck.wait()
    assert ck.latest_step() == 4
    assert sorted(ck.all_steps()) == [3, 4]
    # a stray .tmp never shadows a durable checkpoint
    assert not any(f.endswith(".tmp") for f in (tmp_path / "ck").iterdir()
                   if f.is_file() for f in [f.name] )


def test_functional_roundtrip(tmp_path):
    from paddle_tpu.parallel import load_checkpoint, save_checkpoint

    main, startup, feed, loss = _build()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        l0 = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
        save_checkpoint(str(tmp_path / "f"), 1, program=main)
        w_saved = np.asarray(fluid.global_scope().find_var("w0"))
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        step = load_checkpoint(str(tmp_path / "f"), program=main)
        assert step == 1
        np.testing.assert_array_equal(
            np.asarray(fluid.global_scope().find_var("w0")), w_saved)


def test_checkpoint_resumes_rng_stream(tmp_path):
    """Dropout sequences after restore continue the saved random stream
    (same as the uninterrupted run) rather than restarting from the seed."""
    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            main.random_seed = startup.random_seed = 5
            x = fluid.layers.data("x", [8])
            h = fluid.layers.dropout(
                x, 0.5, dropout_implementation="upscale_in_train")
            loss = fluid.layers.reduce_mean(h)
        return main, startup, loss

    feed = {"x": np.ones((4, 8), "float32")}

    main, startup, loss = build()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        ref = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])
               for _ in range(6)]

    ck = Checkpointer(str(tmp_path / "rng"))
    main, startup, loss = build()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        got_a = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])
                 for _ in range(3)]
        ck.save(3, program=main, blocking=True)
    main2, startup2, loss2 = build()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup2)
        ck.restore(program=main2)
        got_b = [float(exe.run(main2, feed=feed, fetch_list=[loss2])[0])
                 for _ in range(3)]
    np.testing.assert_allclose(got_a + got_b, ref, rtol=1e-6)


def test_native_bundle_backend(tmp_path):
    """Checkpoints ride the native C++ bundle writer when the toolchain is
    available (save_combine_op.cc analog): .ptck files on disk, identical
    restore semantics, pickle interop preserved."""
    from paddle_tpu import native

    if not native.available():
        import pytest
        pytest.skip("no native toolchain")

    main, startup, feed, loss = _build()
    ck = Checkpointer(str(tmp_path / "nk"))
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        l0 = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
        ck.save(3, program=main, blocking=True)
        l1 = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
    import os
    files = os.listdir(tmp_path / "nk")
    assert "ckpt-3.ptck" in files, files

    # restore into a fresh scope → training continues identically
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        got = ck.restore(program=main)
        assert got == 3
        l1b = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
    np.testing.assert_allclose(l1b, l1, rtol=1e-6)

    # a legacy pickle checkpoint in the same dir still restores
    import pickle
    w = np.random.RandomState(0).rand(16, 8).astype("float32")
    with open(tmp_path / "nk" / "ckpt-9.pkl", "wb") as f:
        pickle.dump({"step": 9, "vars": {"w0": w}}, f)
    (tmp_path / "nk" / "latest").write_text("9")
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        got = ck.restore(program=main)
        assert got == 9
        np.testing.assert_allclose(
            np.asarray(fluid.global_scope().find_var("w0")), w)


def test_shard_parallel_checkpoint_across_process_counts(tmp_path):
    """2-proc sharded save -> 1-proc restore and 1-proc save -> 2-proc
    restore (VERDICT r2 #7): per-rank shard+index files, no full-array
    gather on save, restore assembles under any topology."""
    import json as _json
    import socket
    import subprocess
    import sys as _sys

    runner = os.path.join(os.path.dirname(__file__), "dist_ckpt_runner.py")

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def run_single(mode, ckdir):
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("PADDLE_", "XLA_", "JAX_"))}
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        out = subprocess.run([_sys.executable, "-u", runner, mode, ckdir],
                             capture_output=True, text=True, timeout=300,
                             env=env)
        assert out.returncode == 0, out.stderr[-2000:]
        return _json.loads(out.stdout.strip().splitlines()[-1])

    def run_cluster(mode, ckdir, logdir):
        from paddle_tpu.distributed import launch
        env_backup = dict(os.environ)
        for k in list(os.environ):
            if k.startswith(("PADDLE_", "XLA_", "JAX_")):
                del os.environ[k]
        try:
            procs, fds = launch.start_procs(
                2, runner, [mode, ckdir], started_port=free_port(),
                log_dir=str(logdir))
            rc = launch.wait_procs(procs, fds)
        finally:
            os.environ.clear()
            os.environ.update(env_backup)
        logs = {}
        for rank in range(2):
            text = (logdir / f"workerlog.{rank}").read_text()
            assert rc == 0, f"rank{rank} log:\n{text[-2000:]}"
            line = [l for l in text.splitlines() if l.startswith("{")][-1]
            logs[rank] = _json.loads(line)
        return logs

    # --- 2-proc save -> 1-proc restore -----------------------------------
    ck1 = tmp_path / "ck_2to1"
    logs = run_cluster("--save", str(ck1), tmp_path / "log_save")
    # both ranks wrote a shard file + index (tp axis spans the processes)
    for r in range(2):
        assert (ck1 / f"ckpt-7.shards-{r}.pkl").exists()
        idx = _json.loads((ck1 / f"ckpt-7.index-{r}.json").read_text())
        assert "w_tp" in idx and len(idx["w_tp"]["shards"]) >= 1
    got = run_single("--restore", str(ck1))
    assert got["step"] == 7
    np.testing.assert_allclose(got["wsum"], logs[0]["wsum"], rtol=1e-6)
    assert np.isfinite(got["loss"])

    # --- 1-proc save -> 2-proc restore -----------------------------------
    ck2 = tmp_path / "ck_1to2"
    saved = run_single("--save", str(ck2))
    logs2 = run_cluster("--restore", str(ck2), tmp_path / "log_restore")
    for r in range(2):
        assert logs2[r]["step"] == 7
        np.testing.assert_allclose(logs2[r]["wsum"], saved["wsum"],
                                   rtol=1e-6)

def test_restore_raises_on_missing_rank_shard_files(tmp_path):
    """ADVICE r3: a var whose shard/index files are entirely missing must
    fail restore loudly (manifest check), not silently keep init values."""
    import pytest

    main, startup, feed, loss = _build(tp_axis="tp")
    ck = Checkpointer(str(tmp_path / "mk"))
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        prog = _compiled(main, make_mesh({"dp": 4, "tp": 2}))
        exe.run(prog, feed=feed, fetch_list=[loss])
        ck.save(5, program=main, blocking=True)

    # the sharded w0 landed in per-rank shard files; wipe them all to
    # simulate the crash window where rank-0's marker is durable but a
    # rank's background shard write never finished
    removed = 0
    for f in os.listdir(tmp_path / "mk"):
        if ".shards-" in f or ".index-" in f:
            os.remove(tmp_path / "mk" / f)
            removed += 1
    assert removed >= 2  # shard pkl + index json existed

    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        with pytest.raises(RuntimeError, match="manifest"):
            ck.restore(program=main)


def test_latest_step_tolerates_torn_marker(tmp_path):
    """A crash between the marker tmp-write and its rename (or a pre-fsync
    power loss) can leave `latest` empty or garbled; latest_step must fall
    back to the directory scan instead of raising."""
    main, startup, feed, loss = _build()
    ck = Checkpointer(str(tmp_path / "tm"))
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss])
        ck.save(3, program=main, blocking=True)

    marker = tmp_path / "tm" / "latest"
    marker.write_text("")  # torn: zero bytes made it durable
    assert ck.latest_step() == 3
    marker.write_text("4x7\x00")  # garbled
    assert ck.latest_step() == 3

    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        assert ck.restore(program=main) == 3


def test_corrupt_bundle_falls_back_with_warning_and_counter(tmp_path):
    """Bitrot in the newest committed bundle: the manifest's sha256 catches
    it, restore warns naming the file, increments
    checkpoint/fallback_steps, and loads the older verified step."""
    import pytest
    from paddle_tpu.observability import get_registry

    main, startup, feed, loss = _build()
    ck = Checkpointer(str(tmp_path / "cb"))
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss])
        ck.save(2, program=main, blocking=True)
        w_at_2 = np.asarray(fluid.global_scope().find_var("w0"))
        exe.run(main, feed=feed, fetch_list=[loss])
        ck.save(4, program=main, blocking=True)

    # flip bytes mid-file in the committed step-4 bundle
    bundle = ck._existing_path(4)
    with open(bundle, "r+b") as f:
        f.seek(os.path.getsize(bundle) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))

    fallback = get_registry().counter("checkpoint/fallback_steps")
    before = fallback.value
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        with pytest.warns(RuntimeWarning, match="ckpt-4"):
            assert ck.restore(program=main) == 2
        np.testing.assert_array_equal(
            np.asarray(fluid.global_scope().find_var("w0")), w_at_2)
    assert fallback.value == before + 1

    # an explicitly requested corrupt step is NEVER silently substituted
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        with pytest.raises(RuntimeError, match="sha256 mismatch"), \
                pytest.warns(RuntimeWarning):
            ck.restore(step=4, program=main)


def test_writer_retries_transient_io_failure(tmp_path, monkeypatch):
    """One injected bundle-write failure (InjectedFault is an OSError, like
    an NFS blip): the background writer retries and the save lands;
    checkpoint/write_retries counts the retry."""
    from paddle_tpu import faults
    from paddle_tpu.observability import get_registry

    monkeypatch.setenv("PDTPU_CKPT_RETRY_BACKOFF_MS", "1")
    retries = get_registry().counter("checkpoint/write_retries")
    before = retries.value

    main, startup, feed, loss = _build()
    ck = Checkpointer(str(tmp_path / "rt"))
    faults.clear()
    faults.install("ckpt.bundle_write", "raise", count=1)
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.TPUPlace())
            exe.run(startup)
            exe.run(main, feed=feed, fetch_list=[loss])
            ck.save(3, program=main, blocking=True)  # wait() must NOT raise
    finally:
        faults.clear()

    assert retries.value == before + 1
    assert ck.latest_step() == 3
    assert ck.verify(3) == []
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        assert ck.restore(program=main) == 3


def test_wait_error_names_step_and_path(tmp_path, monkeypatch):
    """When retries are exhausted, wait() must say WHICH step and WHICH
    file failed, and how many attempts were made — 'checkpoint write
    failed' alone is undebuggable at 3am."""
    import pytest
    from paddle_tpu import faults

    monkeypatch.setenv("PDTPU_CKPT_RETRIES", "1")
    monkeypatch.setenv("PDTPU_CKPT_RETRY_BACKOFF_MS", "1")

    main, startup, feed, loss = _build()
    ck = Checkpointer(str(tmp_path / "we"))
    faults.clear()
    faults.install("ckpt.bundle_write", "raise")  # persistent: every attempt
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.TPUPlace())
            exe.run(startup)
            exe.run(main, feed=feed, fetch_list=[loss])
            with pytest.raises(
                    RuntimeError,
                    match=r"step 7 .*ckpt-7.* after 2 attempts") as ei:
                ck.save(7, program=main, blocking=True)
            assert isinstance(ei.value.__cause__, faults.InjectedFault)
    finally:
        faults.clear()
