"""contrib surface (reference python/paddle/fluid/contrib/): name parity +
functional checks for the rnn stacks, decoder, trainer, slim framework,
and quantization deployment passes."""
import ast
import glob
import os
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import contrib, dygraph


def test_contrib_names_exist():
    ref = "/root/reference/python/paddle/fluid/contrib"
    if not os.path.isdir(ref):
        pytest.skip("reference tree not mounted")
    names = set()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for f in glob.glob(ref + "/**/*.py", recursive=True):
            try:
                tree = ast.parse(open(f).read())
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "__all__":
                            try:
                                names.update(ast.literal_eval(node.value))
                            except Exception:
                                pass

    def have(n):
        return any(hasattr(t, n) for t in
                   (contrib, contrib.mixed_precision, contrib.slim,
                    contrib.slim.quantization, fluid))

    missing = sorted(n for n in names if not have(n))
    assert not missing, missing


def test_basic_lstm_gru_stacks_train():
    rng = np.random.RandomState(0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [6, 8])
        y = fluid.layers.data("y", [1])
        out, last_h, last_c = contrib.basic_lstm(x, None, None, 16,
                                                 num_layers=2)
        g_out, g_last = contrib.basic_gru(x, None, 16, bidirectional=True)
        feat = fluid.layers.concat(
            [fluid.layers.reduce_mean(out, dim=1),
             fluid.layers.reduce_mean(g_out, dim=1)], axis=1)
        pred = fluid.layers.fc(feat, 1, bias_attr=False)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(0.01).minimize(loss)
    xv = rng.rand(4, 6, 8).astype("float32")
    yv = rng.rand(4, 1).astype("float32")
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        ls = [float(exe.run(main, feed={"x": xv, "y": yv},
                            fetch_list=[loss])[0]) for _ in range(12)]
    assert ls[-1] < ls[0], ls
    # shapes
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        o, go = exe.run(main, feed={"x": xv, "y": yv},
                        fetch_list=[out, g_out])
    assert o.shape == (4, 6, 16)
    assert go.shape == (4, 6, 32)  # bidirectional concat


def test_basic_lstm_init_and_last_state_contract():
    """init_hidden/init_cell are honored and last states come from the
    length-aware op outputs with the [layers·dirs, B, H] layout."""
    rng = np.random.RandomState(7)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [5, 8])
        h0 = fluid.layers.data("h0", [1, 12], append_batch_size=False)
        c0 = fluid.layers.data("c0", [1, 12], append_batch_size=False)
        # feed layout [L*dirs, B, H] with B=2
        h0r = fluid.layers.reshape(h0, [1, 2, 6])
        c0r = fluid.layers.reshape(c0, [1, 2, 6])
        out, lh, lc = contrib.basic_lstm(x, h0r, c0r, 6)
        out0, lh0, lc0 = contrib.basic_lstm(x, None, None, 6)
    xv = rng.rand(2, 5, 8).astype("float32")
    hv = rng.rand(1, 12).astype("float32")
    cv = rng.rand(1, 12).astype("float32")
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        o, l_h, l_c, o0 = exe.run(
            main, feed={"x": xv, "h0": hv, "c0": cv},
            fetch_list=[out, lh, lc, out0])
    assert l_h.shape == (1, 2, 6) and l_c.shape == (1, 2, 6)
    # nonzero init must change the outputs vs the zero-init stack
    assert not np.allclose(o, o0)
    # last hidden equals the final output step (full-length sequences)
    np.testing.assert_allclose(l_h[0], o[:, -1], rtol=1e-5, atol=1e-6)


def test_basic_units_dygraph():
    rng = np.random.RandomState(1)
    with dygraph.guard():
        lstm = contrib.BasicLSTMUnit(hidden_size=8)
        h = dygraph.to_variable(np.zeros((2, 8), "float32"))
        c = dygraph.to_variable(np.zeros((2, 8), "float32"))
        x = dygraph.to_variable(rng.rand(2, 8).astype("float32"))
        nh, nc = lstm(x, h, c)
        assert nh.shape == (2, 8) and nc.shape == (2, 8)
        gru = contrib.BasicGRUUnit(hidden_size=8)
        nh2 = gru(x, h)
        assert nh2.shape == (2, 8)


def test_training_decoder():
    """TrainingDecoder over a StateCell == manual GRU-ish recurrence."""
    rng = np.random.RandomState(2)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        emb = fluid.layers.data("emb", [5, 4])
        boot = fluid.layers.data("boot", [8])
        cell = contrib.StateCell(inputs={"x": None},
                                 states={"h": contrib.InitState(init=boot)},
                                 out_state="h")

        @cell.register_updater
        def _update(sc):
            x = sc.get_input("x")
            h = sc.get_state("h")
            nh = fluid.layers.fc(fluid.layers.concat([x, h], axis=1), 8,
                                 act="tanh",
                                 param_attr=fluid.ParamAttr(name="dec_w"),
                                 bias_attr=fluid.ParamAttr(name="dec_b"))
            sc.set_state("h", nh)

        dec = contrib.TrainingDecoder(cell)
        with dec.block():
            w = dec.step_input(emb)
            cell.compute_state(inputs={"x": w})
            dec.output(cell.get_state("h"))
        out = dec()
    ev = rng.rand(3, 5, 4).astype("float32")
    bv = rng.rand(3, 8).astype("float32")
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        o, w_, b_ = exe.run(main, feed={"emb": ev, "boot": bv},
                            fetch_list=[out, "dec_w", "dec_b"])
    assert o.shape == (3, 5, 8)
    h = bv
    for t in range(5):
        h = np.tanh(np.concatenate([ev[:, t], h], 1) @ w_ + b_)
        np.testing.assert_allclose(o[:, t], h, rtol=1e-4, atol=1e-5)


def test_trainer_inferencer_roundtrip(tmp_path):
    rng = np.random.RandomState(3)
    w_true = rng.rand(4, 1).astype("float32")

    def train_func():
        x = fluid.layers.data("x", [4])
        y = fluid.layers.data("y", [1])
        pred = fluid.layers.fc(x, 1, bias_attr=False,
                               param_attr=fluid.ParamAttr(name="tw"))
        return fluid.layers.mean(fluid.layers.square_error_cost(pred, y))

    def reader():
        for _ in range(8):
            xv = rng.rand(16, 4).astype("float32")
            yield {"x": xv, "y": xv @ w_true}

    seen = []
    trainer = contrib.Trainer(train_func,
                              lambda: fluid.optimizer.SGD(0.2))
    trainer.train(num_epochs=4,
                  event_handler=lambda e: seen.append(type(e).__name__),
                  reader=reader)
    assert "BeginEpochEvent" in seen and "EndStepEvent" in seen
    d = str(tmp_path / "params")
    trainer.save_params(d)

    def infer_func():
        x = fluid.layers.data("x", [4])
        return fluid.layers.fc(x, 1, bias_attr=False,
                               param_attr=fluid.ParamAttr(name="tw"))

    inf = contrib.Inferencer(infer_func, d)
    xv = rng.rand(8, 4).astype("float32")
    (pred,) = inf.infer({"x": xv})
    np.testing.assert_allclose(pred, xv @ w_true, atol=0.3)


def _quantizable_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8])
        h = fluid.layers.fc(x, 8, act="relu",
                            param_attr=fluid.ParamAttr(name="qw"))
        out = fluid.layers.fc(h, 4)
    return main, startup, x, out


def test_quantization_freeze_and_int8(tmp_path):
    from paddle_tpu.contrib.slim.quantization import (
        ConvertToInt8Pass, QuantizationFreezePass, QuantizeTranspiler)

    rng = np.random.RandomState(4)
    main, startup, x, out = _quantizable_program()
    t = QuantizeTranspiler(activation_quantize_type="abs_max")
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        t.training_transpile(main)
        assert any(op.type.startswith("fake_quantize")
                   for op in main.global_block().ops)
        feed = {"x": rng.rand(4, 8).astype("float32")}
        exe.run(main, feed=feed, fetch_list=[out])   # QAT program runs

        scope = fluid.global_scope()
        w_before = np.asarray(scope.find_var("qw")).copy()
        t.freeze_program(main, scope=scope)
        # weight fake-quant removed; weights snapped to the 8-bit grid
        wts = [op for op in main.global_block().ops
               if op.type.startswith("fake_quantize")
               and op.inputs["X"][0] == "qw"]
        assert not wts
        w_after = np.asarray(scope.find_var("qw"))
        scale = np.abs(w_before).max() / 127.0
        np.testing.assert_allclose(w_after / scale,
                                   np.round(w_after / scale), atol=1e-4)
        (o1,) = exe.run(main, feed=feed, fetch_list=[out])
        assert np.isfinite(o1).all()

        t.convert_to_int8(main, scope=scope)
        w8 = np.asarray(scope.find_var("qw.int8"))
        assert w8.dtype == np.int8


def test_slim_framework_prune_and_compressor():
    from paddle_tpu.contrib.slim import (Compressor, GraphWrapper,
                                         PruneStrategy, Pruner,
                                         StructurePruner)

    rng = np.random.RandomState(5)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8])
        y = fluid.layers.data("y", [1])
        pred = fluid.layers.fc(x, 1, bias_attr=False,
                               param_attr=fluid.ParamAttr(name="pw"))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.05).minimize(loss)

    g = GraphWrapper(main)
    assert any(p.name() == "pw" for p in g.all_parameters())
    assert g.numel_params() >= 8

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)

        def reader():
            for _ in range(4):
                xv = rng.rand(8, 8).astype("float32")
                yield {"x": xv, "y": xv.sum(1, keepdims=True)}

        comp = Compressor(fluid.CPUPlace(), scope, main,
                          train_reader=reader, train_fetch_list=[loss],
                          epoch=2)
        comp.add_strategy(PruneStrategy(Pruner(0.5), start_epoch=0,
                                        target_ratio=0.5,
                                        pruned_params="pw"))
        comp.run()
        w = np.asarray(scope.find_var("pw"))
        assert (w == 0).mean() >= 0.45  # half the weights stay pruned

    # structure pruner zeroes whole rows
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        StructurePruner(0.5).prune(scope2, ["pw"])
        w = np.asarray(scope2.find_var("pw"))
        zero_rows = (np.abs(w).sum(1) == 0).sum()
        assert zero_rows == w.shape[0] // 2


def test_contrib_extras():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        h = fluid.layers.fc(x, 8)
        fluid.layers.fc(h, 2)
    mb = contrib.memory_usage(main, batch_size=32)
    assert mb > 0
    uni, pair = contrib.op_freq_statistic(main)
    assert uni.get("mul", 0) == 2 and sum(pair.values()) >= 1

    # decoupled weight decay factory
    AdamWLike = contrib.extend_with_decoupled_weight_decay(
        fluid.optimizer.SGD)
    assert AdamWLike.__name__ == "DecoupledSGDOptimizer"

    # distributed_batch_reader strides batches
    r = contrib.distributed_batch_reader(lambda: iter(range(6)))
    assert list(r()) == [0, 1, 2, 3, 4, 5]  # single process: all batches
