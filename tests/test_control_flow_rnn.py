"""Control-flow layers (While/Switch/cond/IfElse/StaticRNN/DynamicRNN),
RNN ops (lstm/gru), CRF, and beam search — numeric checks vs numpy refs.

Mirrors the reference's test_while_op.py, test_lstm_op.py, test_gru_op.py,
test_linear_chain_crf_op.py, test_beam_search_op.py shapes (fixture style of
unittests/op_test.py, padded+mask instead of LoD)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def run_prog(main, startup, feed, fetch):
    exe = fluid.Executor(fluid.CPUPlace())
    if startup is not None:
        exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=fetch)


# ---------------------------------------------------------------------------
# While
# ---------------------------------------------------------------------------

def test_while_loop_sum():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = layers.fill_constant([1], "int64", 0)
        limit = layers.fill_constant([1], "int64", 10)
        acc = layers.fill_constant([1], "float32", 0.0)
        cond = layers.less_than(i, limit)
        with layers.While(cond):
            acc2 = layers.elementwise_add(acc, layers.fill_constant([1], "float32", 2.0))
            layers.assign(acc2, acc)
            layers.increment(i, value=1)
            layers.less_than(i, limit, cond=cond)
        (out,) = run_prog(main, None, {}, [acc])
    assert np.allclose(out, [20.0])


def test_while_with_array_write():
    """Decode-loop idiom: write per-step values into a preallocated array."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = layers.fill_constant([1], "int64", 0)
        limit = layers.fill_constant([1], "int64", 5)
        x = layers.fill_constant([3], "float32", 1.0)
        arr = layers.create_array("float32", element_shape=[3], max_len=5)
        cond = layers.less_than(i, limit)
        with layers.While(cond):
            val = layers.scale(x, scale=2.0)
            layers.array_write(val, i, arr)
            layers.increment(i, value=1)
            layers.less_than(i, limit, cond=cond)
        (buf,) = run_prog(main, None, {}, [arr])
    assert buf.shape == (5, 3)
    assert np.allclose(buf, 2.0)


# ---------------------------------------------------------------------------
# Switch / cond / IfElse
# ---------------------------------------------------------------------------

def test_switch_first_match():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        step = fluid.layers.data(name="step", shape=[1], dtype="float32", append_batch_size=False)
        lr = layers.fill_constant([1], "float32", 0.0)
        b1 = layers.fill_constant([1], "float32", 5.0)
        b2 = layers.fill_constant([1], "float32", 10.0)
        with layers.Switch() as sw:
            with sw.case(layers.less_than(step, b1)):
                layers.assign(layers.fill_constant([1], "float32", 0.1), lr)
            with sw.case(layers.less_than(step, b2)):
                layers.assign(layers.fill_constant([1], "float32", 0.01), lr)
            with sw.default():
                layers.assign(layers.fill_constant([1], "float32", 0.001), lr)
        for sval, expect in [(3.0, 0.1), (7.0, 0.01), (50.0, 0.001)]:
            (out,) = run_prog(main, None,
                              {"step": np.array([sval], "float32")}, [lr])
            assert np.allclose(out, [expect]), (sval, out)


def test_functional_cond():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32", append_batch_size=False)
        flag = fluid.layers.data(name="flag", shape=[1], dtype="bool", append_batch_size=False)
        out = layers.cond(flag,
                          lambda: layers.scale(x, scale=2.0),
                          lambda: layers.scale(x, scale=-1.0))
        xv = np.arange(4, dtype="float32")
        (r_t,) = run_prog(main, None, {"x": xv, "flag": np.array([True])}, [out])
        (r_f,) = run_prog(main, None, {"x": xv, "flag": np.array([False])}, [out])
    assert np.allclose(r_t, xv * 2)
    assert np.allclose(r_f, -xv)


def test_ifelse_rowwise():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4, 2], dtype="float32", append_batch_size=False)
        c = fluid.layers.data(name="c", shape=[4, 1], dtype="bool", append_batch_size=False)
        ie = layers.IfElse(c)
        with ie.true_block():
            t = ie.input(x)
            ie.output(layers.scale(t, scale=3.0))
        with ie.false_block():
            f = ie.input(x)
            ie.output(layers.scale(f, scale=0.5))
        merged = ie()[0]
        xv = np.arange(8, dtype="float32").reshape(4, 2)
        cv = np.array([[True], [False], [True], [False]])
        (out,) = run_prog(main, None, {"x": xv, "c": cv}, [merged])
    expect = np.where(cv, xv * 3.0, xv * 0.5)
    assert np.allclose(out, expect)


# ---------------------------------------------------------------------------
# StaticRNN / DynamicRNN
# ---------------------------------------------------------------------------

def test_static_rnn_cumsum():
    """h_t = h_{t-1} + x_t — outputs the running sum along T."""
    B, T, D = 2, 5, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[B, T, D], dtype="float32", append_batch_size=False)
        h0 = layers.fill_constant([B, D], "float32", 0.0)
        rnn = layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            h = rnn.memory(init=h0)
            nh = layers.elementwise_add(h, xt)
            rnn.update_memory(h, nh)
            rnn.output(nh)
        out = rnn()
        xv = np.random.RandomState(0).randn(B, T, D).astype("float32")
        (res,) = run_prog(main, None, {"x": xv}, [out])
    assert np.allclose(res, np.cumsum(xv, axis=1), atol=1e-5)


def test_static_rnn_with_fc_params_trains():
    """Params used inside the scan get gradients (vjp through lax.scan)."""
    B, T, D, H = 4, 6, 5, 8
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[B, T, D], dtype="float32", append_batch_size=False)
        y = fluid.layers.data(name="y", shape=[B, H], dtype="float32", append_batch_size=False)
        h0 = layers.fill_constant([B, H], "float32", 0.0)
        rnn = layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            h = rnn.memory(init=h0)
            inp = layers.concat([xt, h], axis=1)
            nh = layers.fc(inp, size=H, act="tanh")
            rnn.update_memory(h, nh)
            rnn.output(nh)
        out = rnn()
        last = layers.slice(out, axes=[1], starts=[T - 1], ends=[T])
        last = layers.reshape(last, [B, H])
        loss = layers.mean(layers.square_error_cost(last, y))
        opt = fluid.optimizer.SGD(learning_rate=0.5)
        opt.minimize(loss)

        rng = np.random.RandomState(1)
        feed = {"x": rng.randn(B, T, D).astype("float32"),
                "y": rng.randn(B, H).astype("float32")}
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = [exe.run(main, feed=feed, fetch_list=[loss])[0] for _ in range(12)]
    assert losses[-1] < losses[0] * 0.7, losses


def test_dynamic_rnn_masks_by_length():
    """Rows freeze at their last valid step: final output for a row with
    length L equals the static value at step L-1."""
    B, T, D = 3, 6, 2
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[B, T, D], dtype="float32", append_batch_size=False)
        length = fluid.layers.data(name="len", shape=[B], dtype="int32", append_batch_size=False)
        h0 = layers.fill_constant([B, D], "float32", 0.0)
        drnn = layers.DynamicRNN()
        with drnn.block():
            xt = drnn.step_input(x, length=length)
            h = drnn.memory(init=h0)
            nh = layers.elementwise_add(h, xt)
            drnn.update_memory(h, nh)
            drnn.output(nh)
        out = drnn()
        xv = np.ones((B, T, D), "float32")
        lv = np.array([2, 4, 6], "int32")
        (res,) = run_prog(main, None, {"x": xv, "len": lv}, [out])
    # cumsum that freezes at each row's length; padded positions emit zeros
    for b, L in enumerate(lv):
        assert np.allclose(res[b, L - 1], float(L)), res[b]
        if L < res.shape[1]:
            assert np.allclose(res[b, L:], 0.0), res[b]


# ---------------------------------------------------------------------------
# LSTM / GRU numeric vs numpy
# ---------------------------------------------------------------------------

def _np_sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def test_dynamic_lstm_matches_numpy():
    B, T, H = 2, 4, 3
    rng = np.random.RandomState(0)
    xv = rng.randn(B, T, 4 * H).astype("float32") * 0.5
    lv = np.array([3, 4], "int32")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[B, T, 4 * H], dtype="float32", append_batch_size=False)
        length = fluid.layers.data(name="len", shape=[B], dtype="int32", append_batch_size=False)
        hidden, cell = layers.dynamic_lstm(x, size=4 * H, length=length,
                                           use_peepholes=False)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        # pull the created weight/bias for the numpy reference
        scope = fluid.global_scope()
        wname = [v.name for v in main.all_parameters() if "w" in v.name][0]
        bname = [v.name for v in main.all_parameters() if ".b" in v.name][0]
        W = np.asarray(scope.find_var(wname))
        bias = np.asarray(scope.find_var(bname))
        hv, cv_ = exe.run(main, feed={"x": xv, "len": lv},
                          fetch_list=[hidden, cell])

    h = np.zeros((B, H), "float32")
    c = np.zeros((B, H), "float32")
    expect_h = np.zeros((B, T, H), "float32")
    for t in range(T):
        gates = xv[:, t, :] + h @ W + bias
        gi, gf, gc, go = np.split(gates, 4, axis=-1)
        i, f, o = _np_sigmoid(gi), _np_sigmoid(gf), _np_sigmoid(go)
        c_new = f * c + i * np.tanh(gc)
        h_new = o * np.tanh(c_new)
        m = (t < lv).astype("float32")[:, None]
        h = h_new * m + h * (1 - m)
        c = c_new * m + c * (1 - m)
        expect_h[:, t] = h
    assert np.allclose(hv, expect_h, atol=1e-4), np.abs(hv - expect_h).max()


def test_dynamic_gru_matches_numpy():
    B, T, H = 2, 3, 4
    rng = np.random.RandomState(1)
    xv = rng.randn(B, T, 3 * H).astype("float32") * 0.5

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[B, T, 3 * H], dtype="float32", append_batch_size=False)
        hidden = layers.dynamic_gru(x, size=H)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.global_scope()
        wname = [v.name for v in main.all_parameters() if ".w" in v.name][0]
        bname = [v.name for v in main.all_parameters() if ".b" in v.name][0]
        W = np.asarray(scope.find_var(wname))
        bias = np.asarray(scope.find_var(bname))
        (hv,) = exe.run(main, feed={"x": xv}, fetch_list=[hidden])

    h = np.zeros((B, H), "float32")
    expect = np.zeros((B, T, H), "float32")
    for t in range(T):
        xg = xv[:, t, :2 * H] + bias[:2 * H]
        xc = xv[:, t, 2 * H:] + bias[2 * H:]
        uz = _np_sigmoid(xg + h @ W[:, :2 * H])
        u, r = np.split(uz, 2, axis=-1)
        cand = np.tanh(xc + (r * h) @ W[:, 2 * H:])
        h = (1 - u) * h + u * cand
        expect[:, t] = h
    assert np.allclose(hv, expect, atol=1e-4)


def test_multilayer_bidirec_lstm_shapes():
    B, T, D, H = 2, 5, 6, 4
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[B, T, D], dtype="float32", append_batch_size=False)
        out, lh, lc = layers.lstm(x, hidden_size=H, num_layers=2,
                                  is_bidirec=True)
        xv = np.random.RandomState(0).randn(B, T, D).astype("float32")
        res, lhv, lcv = run_prog(main, startup, {"x": xv}, [out, lh, lc])
    assert res.shape == (B, T, 2 * H)
    assert lhv.shape == (4, B, H)   # layers*dirs


# ---------------------------------------------------------------------------
# CRF
# ---------------------------------------------------------------------------

def _np_crf_loglik(em, trans, label, length):
    start_w, end_w, pw = trans[0], trans[1], trans[2:]
    B, T, D = em.shape
    lls = []
    for b in range(B):
        L = length[b]
        e, y = em[b, :L], label[b, :L]
        # brute-force partition over all paths
        from itertools import product
        logz_terms = []
        for path in product(range(D), repeat=L):
            s = start_w[path[0]] + end_w[path[-1]] + sum(e[t, path[t]] for t in range(L))
            s += sum(pw[path[t], path[t + 1]] for t in range(L - 1))
            logz_terms.append(s)
        logz = np.log(np.sum(np.exp(np.array(logz_terms))))
        gold = start_w[y[0]] + end_w[y[L - 1]] + sum(e[t, y[t]] for t in range(L))
        gold += sum(pw[y[t], y[t + 1]] for t in range(L - 1))
        lls.append(gold - logz)
    return np.array(lls, "float32")


def test_linear_chain_crf_matches_bruteforce():
    B, T, D = 2, 4, 3
    rng = np.random.RandomState(0)
    emv = rng.randn(B, T, D).astype("float32")
    labv = rng.randint(0, D, (B, T)).astype("int64")
    lenv = np.array([3, 4], "int32")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        em = fluid.layers.data(name="em", shape=[B, T, D], dtype="float32", append_batch_size=False)
        lab = fluid.layers.data(name="lab", shape=[B, T], dtype="int64", append_batch_size=False)
        length = fluid.layers.data(name="len", shape=[B], dtype="int32", append_batch_size=False)
        nll = layers.linear_chain_crf(em, lab, length=length)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.global_scope()
        tname = main.all_parameters()[0].name
        trans = np.asarray(scope.find_var(tname))
        (out,) = exe.run(main, feed={"em": emv, "lab": labv, "len": lenv},
                         fetch_list=[nll])
    expect = -_np_crf_loglik(emv, trans, labv, lenv)
    assert np.allclose(out.reshape(-1), expect, atol=1e-4), (out, expect)


def test_crf_decoding_matches_bruteforce():
    B, T, D = 2, 4, 3
    rng = np.random.RandomState(3)
    emv = rng.randn(B, T, D).astype("float32")
    lenv = np.array([4, 3], "int32")
    transv = rng.randn(D + 2, D).astype("float32")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        em = fluid.layers.data(name="em", shape=[B, T, D], dtype="float32", append_batch_size=False)
        length = fluid.layers.data(name="len", shape=[B], dtype="int32", append_batch_size=False)
        # create the transition param with a known name + value
        from paddle_tpu.param_attr import ParamAttr
        from paddle_tpu.initializer import NumpyArrayInitializer
        path = layers.crf_decoding(
            em, param_attr=ParamAttr(name="crf_trans",
                                     initializer=NumpyArrayInitializer(transv)),
            length=length)
        # crf_decoding's helper doesn't create the param itself; make it
        blk = main.global_block()
        if not blk.has_var("crf_trans"):
            pytest.skip("transition param not created by crf_decoding")
        (pv,) = run_prog(main, startup, {"em": emv, "len": lenv}, [path])

    from itertools import product
    start_w, end_w, pw = transv[0], transv[1], transv[2:]
    for b in range(B):
        L = lenv[b]
        best, best_s = None, -np.inf
        for cand in product(range(D), repeat=int(L)):
            s = start_w[cand[0]] + end_w[cand[-1]]
            s += sum(emv[b, t, cand[t]] for t in range(L))
            s += sum(pw[cand[t], cand[t + 1]] for t in range(L - 1))
            if s > best_s:
                best, best_s = cand, s
        assert tuple(pv[b, :L]) == best, (b, pv[b], best)


# ---------------------------------------------------------------------------
# Beam search
# ---------------------------------------------------------------------------

def test_beam_search_step_and_decode():
    batch, beam, vocab, T = 1, 2, 5, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        scores = fluid.layers.data(name="s", shape=[batch, beam, vocab],
                                   dtype="float32", append_batch_size=False)
        pre = fluid.layers.data(name="p", shape=[batch, beam], dtype="float32", append_batch_size=False)
        ids, sel, parent, fin = layers.beam_search(
            None, pre, scores, beam_size=beam, end_id=0)
        sv = np.log(np.array([[[.1, .5, .2, .1, .1],
                               [.3, .1, .4, .1, .1]]], "float32"))
        pv = np.zeros((batch, beam), "float32")
        idv, selv, parv = run_prog(main, None, {"s": sv, "p": pv},
                                   [ids, sel, parent])[:3]
    # top-2 over {beam0: token1 p=.5, beam1: token2 p=.4, ...}
    assert set(map(tuple, np.stack([parv[0], idv[0]], -1))) == {(0, 1), (1, 2)}


def test_beam_search_decode_backtracks():
    """Hand-built 2-step beam history: decode must follow parent pointers."""
    batch, beam, T = 1, 2, 2
    # step0: beams picked tokens [3, 4]; step1: beam0 extends old beam1 with
    # token 7, beam1 extends old beam0 with token 8.
    ids_np = np.array([[[3, 4]], [[7, 8]]], "int64")        # [T, b, beam]
    par_np = np.array([[[0, 1]], [[1, 0]]], "int64")
    scores_np = np.array([[0.5, 0.4]], "float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[T, batch, beam],
                                dtype="int64", append_batch_size=False)
        par = fluid.layers.data(name="par", shape=[T, batch, beam],
                                dtype="int64", append_batch_size=False)
        sc = fluid.layers.data(name="sc", shape=[batch, beam],
                               dtype="float32", append_batch_size=False)
        sent, sent_sc = layers.beam_search_decode(ids, par, sc)
        sv, ssv = run_prog(main, None,
                           {"ids": ids_np, "par": par_np, "sc": scores_np},
                           [sent, sent_sc])
    assert sv.shape == (batch, beam, T)
    assert list(sv[0, 0]) == [4, 7]     # beam0 @ step1 came from old beam1
    assert list(sv[0, 1]) == [3, 8]     # beam1 @ step1 came from old beam0
    assert np.allclose(ssv, scores_np)


def test_cond_branch_returning_parent_var():
    """cond() where one branch passes an existing var through untouched."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[3], dtype="float32",
                              append_batch_size=False)
        flag = fluid.layers.data(name="flag", shape=[1], dtype="bool",
                                 append_batch_size=False)
        out = layers.cond(flag, lambda: x, lambda: layers.scale(x, scale=-1.0))
        xv = np.arange(3, dtype="float32")
        (r_t,) = run_prog(main, None, {"x": xv, "flag": np.array([True])}, [out])
        (r_f,) = run_prog(main, None, {"x": xv, "flag": np.array([False])}, [out])
    assert np.allclose(r_t, xv)
    assert np.allclose(r_f, -xv)


def test_static_rnn_memory_by_shape():
    """memory(shape=..., value=...) builds its init in the parent block."""
    B, T, D = 2, 3, 4
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[B, T, D], dtype="float32",
                              append_batch_size=False)
        rnn = layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            h = rnn.memory(shape=[B, D], value=0.0)
            nh = layers.elementwise_add(h, xt)
            rnn.update_memory(h, nh)
            rnn.output(nh)
        out = rnn()
        xv = np.random.RandomState(0).randn(B, T, D).astype("float32")
        (res,) = run_prog(main, None, {"x": xv}, [out])
    assert np.allclose(res, np.cumsum(xv, axis=1), atol=1e-5)


def test_bounded_while_differentiable():
    """`While(max_iters=N)` lowers to a fixed-length scan of masked updates
    and is reverse-mode differentiable (reference WhileGradOp capability,
    while_op.cc). d(sum x*2^k)/dx must flow through the loop."""
    B, D, N = 2, 3, 4
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[B, D], dtype="float32",
                              append_batch_size=False)
        acc = layers.fill_constant([B, D], "float32", 0.0)
        acc = layers.elementwise_add(acc, x)  # make acc depend on x
        i = layers.fill_constant([1], "int64", 0)
        n = layers.fill_constant([1], "int64", N)
        cond = layers.less_than(i, n)
        with layers.While(cond, max_iters=N):
            doubled = layers.scale(acc, scale=2.0)
            layers.assign(doubled, acc)
            layers.assign(layers.increment(i, value=1), i)
            layers.assign(layers.less_than(i, n), cond)
        loss = layers.reduce_sum(acc)
        (gx,) = fluid.gradients([loss], [x])
        xv = np.ones((B, D), np.float32)
        (lv, gv) = run_prog(main, startup, {"x": xv}, [loss, gx])
    # acc = x * 2^N  → loss = sum(x)·16, dloss/dx = 16
    assert abs(float(lv) - 2 ** N * B * D) < 1e-4
    np.testing.assert_allclose(gv, np.full((B, D), 2.0 ** N), rtol=1e-6)


def test_dynamic_rnn_trains_matching_static_rnn():
    """A trained DynamicRNN (full-length rows) follows the same loss curve
    as StaticRNN — the VERDICT r1 'trained dynamic-RNN' gate."""
    B, T, D, H = 4, 5, 3, 6

    def build(use_dynamic):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            main.random_seed = 7
            startup.random_seed = 7
            x = fluid.layers.data(name="x", shape=[B, T, D], dtype="float32",
                                  append_batch_size=False)
            y = fluid.layers.data(name="y", shape=[B, H], dtype="float32",
                                  append_batch_size=False)
            h0 = layers.fill_constant([B, H], "float32", 0.0)
            if use_dynamic:
                length = layers.fill_constant([B], "int64", T)
                rnn = layers.DynamicRNN()
                with rnn.block():
                    xt = rnn.step_input(x, length=length)
                    h = rnn.memory(init=h0)
                    inp = layers.concat([xt, h], axis=1)
                    nh = layers.fc(inp, size=H, act="tanh",
                                   param_attr=fluid.ParamAttr(name="w"),
                                   bias_attr=fluid.ParamAttr(name="b"))
                    rnn.update_memory(h, nh)
                    rnn.output(nh)
            else:
                rnn = layers.StaticRNN()
                with rnn.step():
                    xt = rnn.step_input(x)
                    h = rnn.memory(init=h0)
                    inp = layers.concat([xt, h], axis=1)
                    nh = layers.fc(inp, size=H, act="tanh",
                                   param_attr=fluid.ParamAttr(name="w"),
                                   bias_attr=fluid.ParamAttr(name="b"))
                    rnn.update_memory(h, nh)
                    rnn.output(nh)
            out = rnn()
            last = layers.slice(out, axes=[1], starts=[T - 1], ends=[T])
            last = layers.reshape(last, [B, H])
            loss = layers.mean(layers.square_error_cost(last, y))
            fluid.optimizer.SGD(learning_rate=0.3).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(3)
    feed = {"x": rng.randn(B, T, D).astype("float32"),
            "y": rng.randn(B, H).astype("float32")}
    curves = {}
    for use_dynamic in (False, True):
        main, startup, loss = build(use_dynamic)
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            curves[use_dynamic] = [
                float(exe.run(main, feed=feed, fetch_list=[loss])[0])
                for _ in range(8)]
    np.testing.assert_allclose(curves[False], curves[True], rtol=1e-4)
    assert curves[True][-1] < curves[True][0] * 0.8
