"""Core IR + executor tests (reference analogs: test_program.py,
test_executor_and_mul.py, test_backward.py)."""
import numpy as np
import pytest

import paddle_tpu as fluid


def test_program_build():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.layers.data("x", [4])
        y = fluid.layers.fc(x, 3)
        assert y.name in main.global_block().vars
        assert len(main.all_parameters()) == 2  # w, b
        ops = [op.type for op in main.global_block().ops]
        assert "mul" in ops and "elementwise_add" in ops


def test_executor_feed_fetch():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.layers.data("x", [4])
        y = fluid.layers.scale(x, scale=2.0, bias=1.0)
        exe = fluid.Executor(fluid.CPUPlace())
        xv = np.random.rand(3, 4).astype("float32")
        (out,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(out, xv * 2.0 + 1.0, rtol=1e-6)


def test_mul_fc_forward():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        y = fluid.layers.fc(x, 3, bias_attr=False)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        w_name = main.all_parameters()[0].name
        xv = np.random.rand(5, 4).astype("float32")
        out, wv = exe.run(main, feed={"x": xv}, fetch_list=[y, w_name])
    np.testing.assert_allclose(out, xv @ wv, rtol=1e-5)


def test_append_backward_grads():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        y = fluid.layers.fc(x, 1, bias_attr=False)
        loss = fluid.layers.mean(y)
        params_grads = fluid.append_backward(loss)
        assert len(params_grads) == 1
        p, g = params_grads[0]
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xv = np.ones((8, 4), dtype="float32")
        (gv,) = exe.run(main, feed={"x": xv}, fetch_list=[g])
    # d(mean(xw))/dw = mean over batch of x = ones/1 → each w grad = 1
    np.testing.assert_allclose(gv, np.ones((4, 1)), rtol=1e-5)


def test_gradients_api():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.layers.data("x", [3])
        y = fluid.layers.square(x)
        loss = fluid.layers.reduce_sum(y)
        (gx,) = fluid.gradients([loss], [x])
        exe = fluid.Executor(fluid.CPUPlace())
        xv = np.array([[1.0, 2.0, 3.0]], dtype="float32")
        (gv,) = exe.run(main, feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(gv, 2 * xv, rtol=1e-6)


def test_stop_gradient_blocks_flow():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.layers.data("x", [3])
        x.stop_gradient = False
        frozen = fluid.layers.scale(x, scale=3.0)
        frozen.stop_gradient = True
        y = fluid.layers.elementwise_add(fluid.layers.square(x), frozen)
        loss = fluid.layers.reduce_sum(y)
        (gx,) = fluid.gradients([loss], [x])
        exe = fluid.Executor(fluid.CPUPlace())
        xv = np.array([[1.0, 2.0, 3.0]], dtype="float32")
        (gv,) = exe.run(main, feed={"x": xv}, fetch_list=[gx])
    # grad flows only through square branch: 2x (scale branch cut)
    np.testing.assert_allclose(gv, 2 * xv, rtol=1e-6)


def test_sgd_step_updates_param():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        y = fluid.layers.fc(x, 1, bias_attr=False)
        loss = fluid.layers.mean(y)
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
        p = main.all_parameters()[0]
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        w0 = np.array(fluid.global_scope().find_var(p.name))
        xv = np.ones((2, 4), dtype="float32")
        exe.run(main, feed={"x": xv}, fetch_list=[loss])
        w1 = np.array(fluid.global_scope().find_var(p.name))
    np.testing.assert_allclose(w1, w0 - 0.1 * np.ones((4, 1)), rtol=1e-5)


def test_program_clone_for_test_freezes_dropout():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.layers.data("x", [10])
        y = fluid.layers.dropout(x, dropout_prob=0.5,
                                 dropout_implementation="upscale_in_train")
        test_prog = main.clone(for_test=True)
        exe = fluid.Executor(fluid.CPUPlace())
        xv = np.ones((4, 10), dtype="float32")
        (out_test,) = exe.run(test_prog, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(out_test, xv)


def test_rng_reproducible_across_programs():
    def run_once():
        main = fluid.Program()
        startup = fluid.Program()
        scope = fluid.Scope()
        with fluid.scope_guard(scope), fluid.program_guard(main, startup):
            w = fluid.layers.create_global_var([4, 4], 0.0, "float32", persistable=True,
                                               name="w")
            startup.global_block().create_var(name="seeded", shape=[4, 4],
                                              dtype="float32", persistable=True)
            startup.global_block().append_op(
                "gaussian_random", outputs={"Out": ["seeded"]},
                attrs={"shape": [4, 4], "dtype": "float32", "mean": 0.0, "std": 1.0})
            main.global_block().create_var(name="seeded", shape=[4, 4],
                                           dtype="float32", persistable=True)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            return np.array(scope.find_var("seeded"))

    a = run_once()
    b = run_once()
    np.testing.assert_allclose(a, b)
    assert np.abs(a).sum() > 0


def test_gradients_multi_target():
    """calc_gradient parity (reference backward.py:820): several targets,
    per-target seed cotangents, contributions summed."""
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.layers.data("x", [3])
        t1 = fluid.layers.reduce_sum(fluid.layers.square(x))      # d/dx = 2x
        t2 = fluid.layers.reduce_sum(fluid.layers.scale(x, 3.0))  # d/dx = 3
        (gx,) = fluid.gradients([t1, t2], [x])
        exe = fluid.Executor(fluid.CPUPlace())
        xv = np.array([[1.0, 2.0, 3.0]], dtype="float32")
        (gv,) = exe.run(main, feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(gv, 2 * xv + 3.0, rtol=1e-6)


def test_gradients_multi_target_seeded():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.layers.data("x", [3])
        t1 = fluid.layers.reduce_sum(fluid.layers.square(x))
        t2 = fluid.layers.reduce_sum(fluid.layers.scale(x, 3.0))
        seed = fluid.layers.fill_constant([1], "float32", 10.0)
        (gx,) = fluid.gradients([t1, t2], [x],
                                target_gradients=[None, seed])
        exe = fluid.Executor(fluid.CPUPlace())
        xv = np.array([[1.0, 2.0, 3.0]], dtype="float32")
        (gv,) = exe.run(main, feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(gv, 2 * xv + 30.0, rtol=1e-6)


def test_gradients_wrt_intermediate_var():
    """Grad w.r.t. an op OUTPUT (not a leaf) must survive the non-SSA
    cotangent-consumption rule in the tape walk."""
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.layers.data("x", [3])
        h = fluid.layers.scale(x, 2.0)
        loss = fluid.layers.reduce_sum(h)
        (gh,) = fluid.gradients([loss], [h])
        exe = fluid.Executor(fluid.CPUPlace())
        (gv,) = exe.run(main, feed={"x": np.ones((1, 3), "float32")},
                        fetch_list=[gh])
    np.testing.assert_allclose(gv, np.ones((1, 3)))


def test_feed_validation_errors():
    """Bad feeds raise clear errors at feed time, not raw XLA errors inside
    the traced step (reference PrepareData-time checks, operator.cc:1031)."""
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        out = fluid.layers.fc(x, 2, bias_attr=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    with pytest.raises(ValueError, match="shape mismatch at dim 1"):
        exe.run(main, feed={"x": np.zeros((3, 5), "float32")},
                fetch_list=[out])
    with pytest.raises(ValueError, match="rank mismatch"):
        exe.run(main, feed={"x": np.zeros((3,), "float32")},
                fetch_list=[out])
    with pytest.raises(TypeError, match="cannot convert"):
        exe.run(main, feed={"x": object()}, fetch_list=[out])
    # correct feed still works
    got = exe.run(main, feed={"x": np.zeros((3, 4), "float32")},
                  fetch_list=[out])
    assert got[0].shape == (3, 2)


def test_state_var_shape_swap_falls_back_to_retrace():
    """Checkpoint surgery: swapping a persistable var for a DIFFERENT
    shape via scope.set_var must retrace (plain jit path), not crash the
    AOT executable — jax Format equality ignores shape, so the fast path
    needs its own shape check (review r4)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", [4], dtype="int64")
        emb = fluid.layers.embedding(ids, [8, 16],
                                     param_attr=fluid.ParamAttr(name="sw.emb"))
        loss = fluid.layers.reduce_mean(emb)
        fluid.optimizer.SGD(0.1).minimize(loss)
    feed = {"ids": np.zeros((2, 4), "int64")}
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        l0 = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
        exe.run(main, feed=feed, fetch_list=[loss])  # steady state
        # grow the vocab: same rank/dtype, new shape
        fluid.global_scope().set_var("sw.emb", np.zeros((32, 16), "float32"))
        l1 = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
        assert np.isfinite(l1)
        grown = fluid.global_scope().find_var("sw.emb")
        assert tuple(np.asarray(grown).shape) == (32, 16)
