"""Async input pipeline: DeviceLoader prefetch, FetchHandle fetches,
in-flight train_from_dataset, PyReader double buffering, and the
device-side FLAGS_check_nan_inf path."""
import threading
import time

import numpy as np
import pytest

import jax
import paddle_tpu as fluid
from paddle_tpu.dataio import DeviceLoader, FetchHandle


def _batches(n, batch=2, dim=4, seed=0):
    rng = np.random.RandomState(seed)
    return [{"x": rng.randn(batch, dim).astype("float32")} for _ in range(n)]


def _no_loader_threads():
    return [t for t in threading.enumerate() if t.name.startswith("pdtpu-")]


def _build_sgd(dim=4):
    x = fluid.layers.data("x", [dim])
    h = fluid.layers.fc(x, 8, act="relu")
    loss = fluid.layers.mean(fluid.layers.fc(h, 3))
    fluid.optimizer.SGD(0.1).minimize(loss)
    return loss


# ---------------------------------------------------------------------------
# DeviceLoader
# ---------------------------------------------------------------------------

class TestDeviceLoader:
    def test_prefetch_preserves_order(self):
        data = [{"x": np.full((2, 4), i, "float32")} for i in range(20)]

        def jittery():
            rng = np.random.RandomState(3)
            for b in data:
                time.sleep(float(rng.uniform(0, 0.002)))
                yield b

        got = [float(np.asarray(b["x"]).mean())
               for b in DeviceLoader(jittery, capacity=3)]
        assert got == [float(i) for i in range(20)]

    def test_yields_device_arrays(self):
        loader = DeviceLoader(lambda: iter(_batches(2)), capacity=2)
        for b in loader:
            assert isinstance(b["x"], jax.Array)

    def test_reader_exception_reraises_in_consumer(self):
        def bad():
            yield {"x": np.zeros((2, 4), "float32")}
            yield {"x": np.zeros((2, 4), "float32")}
            raise ValueError("reader blew up")

        loader = DeviceLoader(bad, capacity=2)
        seen = 0
        with pytest.raises(ValueError, match="reader blew up"):
            for _ in loader:
                seen += 1
        assert seen == 2
        assert not loader.running
        assert _no_loader_threads() == []

    def test_exhaustion_leaves_no_threads(self):
        list(DeviceLoader(lambda: iter(_batches(5)), capacity=2))
        assert _no_loader_threads() == []

    def test_midepoch_break_then_close(self):
        def slow():
            for b in _batches(100):
                time.sleep(0.001)
                yield b

        loader = DeviceLoader(slow, capacity=2)
        for i, _ in enumerate(loader):
            if i == 3:
                break
        loader.close()
        loader.close()  # idempotent
        assert not loader.running
        assert _no_loader_threads() == []

    def test_reiteration_is_a_fresh_epoch(self):
        loader = DeviceLoader(lambda: iter(_batches(4)), capacity=2)
        assert len(list(loader)) == 4
        assert len(list(loader)) == 4

    def test_close_from_other_thread_unblocks_consumer(self):
        def endless():
            i = 0
            while True:
                yield {"x": np.full((1,), i, "float32")}
                i += 1

        loader = DeviceLoader(endless, capacity=2)
        it = iter(loader)
        next(it)
        threading.Timer(0.05, loader.close).start()
        # consumer either sees end-of-epoch or keeps yielding until the
        # close lands; it must not hang
        for _ in it:
            pass
        assert not loader.running

    def test_capacity_validation(self):
        with pytest.raises(ValueError, match="capacity"):
            DeviceLoader(lambda: iter([]), capacity=0)

    def test_feed_validation_applies_in_worker(self):
        # program-aware conversion: the prefetch path must reject what the
        # sync path rejects (declared-shape mismatch), in the consumer
        fluid.layers.data("x", [4])
        prog = fluid.default_main_program()
        loader = DeviceLoader(
            lambda: iter([{"x": np.zeros((2, 5), "float32")}]),
            capacity=2, program=prog)
        with pytest.raises(ValueError, match="shape mismatch"):
            list(loader)

    def test_telemetry_populated(self):
        from paddle_tpu.observability import get_registry
        list(DeviceLoader(lambda: iter(_batches(3)), capacity=2))
        snap = get_registry().snapshot()
        assert snap["dataio/batches"] >= 3
        assert snap["dataio/h2d_ms"]["count"] >= 3


# ---------------------------------------------------------------------------
# FetchHandle / Executor.run(return_handle=True)
# ---------------------------------------------------------------------------

class TestFetchHandle:
    def test_bitwise_identical_to_sync_run(self):
        loss = _build_sgd()
        exe = fluid.Executor(fluid.TPUPlace())
        feeds = _batches(4)

        with fluid.scope_guard(fluid.Scope()):
            exe.run(fluid.default_startup_program())
            sync = [exe.run(feed=f, fetch_list=[loss])[0] for f in feeds]
        with fluid.scope_guard(fluid.Scope()):
            exe.run(fluid.default_startup_program())
            handles = [exe.run(feed=f, fetch_list=[loss],
                               return_handle=True) for f in feeds]
            async_ = [h.numpy()[0] for h in handles]
        for a, b in zip(sync, async_):
            assert a.dtype == b.dtype
            assert np.array_equal(a, b)

    def test_handle_protocol(self):
        loss = _build_sgd()
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(fluid.default_startup_program())
        h = exe.run(feed=_batches(1)[0], fetch_list=[loss],
                    return_handle=True)
        assert isinstance(h, FetchHandle)
        assert len(h) == 1
        assert h.names == [loss.name]
        assert isinstance(h.jax()[0], jax.Array)
        h.block_until_ready()
        assert h.is_ready()
        assert np.array_equal(h[0], h.numpy()[0])
        assert "materialized" in repr(h)

    def test_fetchless_handle_carries_probe(self):
        _build_sgd()
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(fluid.default_startup_program())
        h = exe.run(feed=_batches(1)[0], fetch_list=[],
                    return_handle=True)
        assert len(h) == 0 and h.numpy() == []
        h.block_until_ready()  # must not raise: blocks on the state probe


# ---------------------------------------------------------------------------
# train_from_dataset in-flight pipeline
# ---------------------------------------------------------------------------

class _FakeDataset:
    """Anything with batches()/set_thread() drives train_from_dataset."""

    def __init__(self, data):
        self.data = data

    def set_thread(self, n):
        pass

    def batches(self):
        for b in self.data:
            # extra key not declared by the program must be filtered out
            yield dict(b, junk=np.zeros(3))


class TestTrainFromDataset:
    def test_inflight_2_matches_inflight_1(self):
        loss = _build_sgd()
        exe = fluid.Executor(fluid.TPUPlace())
        data = _batches(7, seed=11)

        def arm(inflight):
            old = fluid.get_flags("max_inflight_steps")
            fluid.set_flags({"max_inflight_steps": inflight})
            try:
                with fluid.scope_guard(fluid.Scope()):
                    exe.run(fluid.default_startup_program())
                    return exe.train_from_dataset(
                        dataset=_FakeDataset(data), fetch_list=[loss])
            finally:
                fluid.set_flags(old)

        a, b = arm(1), arm(2)
        assert np.array_equal(a[0], b[0])
        assert _no_loader_threads() == []

    def test_no_fetch_list(self):
        _build_sgd()
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(fluid.default_startup_program())
        out = exe.train_from_dataset(dataset=_FakeDataset(_batches(3)))
        assert out == []

    def test_empty_dataset_returns_none(self):
        _build_sgd()
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(fluid.default_startup_program())
        assert exe.train_from_dataset(dataset=_FakeDataset([])) is None

    def test_executor_close_sweeps_loaders(self):
        exe = fluid.Executor(fluid.TPUPlace())
        loader = DeviceLoader(lambda: iter(_batches(50)), capacity=2)
        loader.start()
        exe._loaders.add(loader)
        assert loader.running
        exe.close()
        assert not loader.running


# ---------------------------------------------------------------------------
# PyReader double buffering
# ---------------------------------------------------------------------------

class TestPyReader:
    def _gen(self, n=5):
        def gen():
            for i in range(n):
                yield [(np.full(4, i, "float32"),) for _ in range(2)]
        return gen

    def test_double_buffer_yields_device_batches_in_order(self):
        x = fluid.layers.data("x", [4])
        r = fluid.PyReader(feed_list=[x], capacity=8, use_double_buffer=True)
        r.decorate_sample_list_generator(self._gen())
        vals = []
        for feed in r():
            assert isinstance(feed["x"], jax.Array)
            vals.append(float(np.asarray(feed["x"]).mean()))
        assert vals == [0.0, 1.0, 2.0, 3.0, 4.0]
        assert _no_loader_threads() == []

    def test_double_buffer_matches_plain(self):
        x = fluid.layers.data("x", [4])
        loss = fluid.layers.mean(fluid.layers.fc(x, 3))
        exe = fluid.Executor(fluid.TPUPlace())

        def arm(db):
            r = fluid.PyReader(feed_list=[x], capacity=8,
                               use_double_buffer=db)
            r.decorate_sample_list_generator(self._gen())
            with fluid.scope_guard(fluid.Scope()):
                exe.run(fluid.default_startup_program())
                return [exe.run(feed=f, fetch_list=[loss])[0] for f in r()]

        for a, b in zip(arm(False), arm(True)):
            assert np.array_equal(a, b)

    def test_reset_tears_down_prefetch_thread(self):
        x = fluid.layers.data("x", [4])
        r = fluid.PyReader(feed_list=[x], capacity=8, use_double_buffer=True)
        r.decorate_sample_list_generator(self._gen(100))
        it = r()
        next(it)
        assert r._loader is not None and r._loader.running
        r.reset()
        r.reset()  # idempotent
        assert r._loader is None
        assert _no_loader_threads() == []

    def test_undecorated_reader_raises(self):
        r = fluid.PyReader(feed_list=[], capacity=4)
        with pytest.raises(RuntimeError, match="decorate"):
            r()

    def test_layers_py_reader_constructs(self):
        # regression: shapes/dtypes kwargs used to raise TypeError
        r = fluid.layers.py_reader(4, [[4]], ["float32"])
        assert isinstance(r, fluid.PyReader)
        r2 = fluid.layers.create_py_reader_by_data(
            4, [fluid.layers.data("x", [4])])
        assert r2._feed_names == ["x"]

    def test_layers_double_buffer_prefetches(self):
        def reader():
            for b in _batches(3):
                yield b

        db = fluid.layers.double_buffer(reader)
        out = list(db())
        assert len(out) == 3 and isinstance(out[0]["x"], jax.Array)


# ---------------------------------------------------------------------------
# FLAGS_check_nan_inf device-side probe
# ---------------------------------------------------------------------------

class TestCheckNanInf:
    def test_nan_feed_raises_with_name(self):
        x = fluid.layers.data("x", [4])
        loss = fluid.layers.mean(fluid.layers.fc(x, 3))
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(fluid.default_startup_program())
        fluid.set_flags({"check_nan_inf": True})
        try:
            with pytest.raises(FloatingPointError, match="NaN/Inf"):
                exe.run(feed={"x": np.full((2, 4), np.nan, "float32")},
                        fetch_list=[loss])
        finally:
            fluid.set_flags({"check_nan_inf": False})

    def test_finite_run_passes(self):
        loss = _build_sgd()
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(fluid.default_startup_program())
        fluid.set_flags({"check_nan_inf": True})
        try:
            out = exe.run(feed=_batches(1)[0], fetch_list=[loss])
            assert np.isfinite(out[0]).all()
        finally:
            fluid.set_flags({"check_nan_inf": False})


# ---------------------------------------------------------------------------
# flags / persistent compilation cache
# ---------------------------------------------------------------------------

class TestFlagsAndCompileCache:
    def test_env_aliases_bootstrap(self, monkeypatch):
        from paddle_tpu import flags as flags_mod
        old = dict(flags_mod._FLAGS)
        monkeypatch.setenv("PDTPU_MAX_INFLIGHT_STEPS", "4")
        monkeypatch.setenv("PDTPU_COMPILE_CACHE_DIR", "/tmp/xyz")
        try:
            flags_mod._bootstrap_from_env()
            assert flags_mod.flag("max_inflight_steps") == 4
            assert flags_mod.flag("compile_cache_dir") == "/tmp/xyz"
        finally:
            flags_mod._FLAGS.update(old)

    def test_compile_cache_enable_records_entry_count(self, tmp_path,
                                                      monkeypatch):
        from paddle_tpu.core import executor as exe_mod
        (tmp_path / "entry0").write_bytes(b"x")
        calls = {}
        monkeypatch.setattr(jax.config, "update",
                            lambda k, v: calls.setdefault(k, v))
        was = exe_mod._COMPILE_CACHE_ENABLED[0]
        exe_mod._COMPILE_CACHE_ENABLED[0] = False
        try:
            assert exe_mod._maybe_enable_compile_cache(str(tmp_path))
            assert calls["jax_compilation_cache_dir"] == str(tmp_path)
            from paddle_tpu.observability import get_registry
            snap = get_registry().snapshot()
            assert snap["executor/compile_cache_enabled"] == 1
            assert snap["executor/compile_cache_entries_at_start"] == 1
            # and it is once-per-process from here on
            assert exe_mod._maybe_enable_compile_cache("/elsewhere")
            assert calls["jax_compilation_cache_dir"] == str(tmp_path)
        finally:
            exe_mod._COMPILE_CACHE_ENABLED[0] = was

    def test_disabled_without_flag(self):
        from paddle_tpu.core import executor as exe_mod
        was = exe_mod._COMPILE_CACHE_ENABLED[0]
        exe_mod._COMPILE_CACHE_ENABLED[0] = False
        try:
            assert not exe_mod._maybe_enable_compile_cache("")
        finally:
            exe_mod._COMPILE_CACHE_ENABLED[0] = was


# ---------------------------------------------------------------------------
# DeviceLoader deterministic resume (state / restore_state)
# ---------------------------------------------------------------------------

class TestDeviceLoaderResume:
    """The (epoch, cursor) contract run_elastic checkpoints as @dataio@*:
    a restored loader replays exactly the batches the consumer never saw."""

    @staticmethod
    def _epoch_reader(epoch):
        # batch b of epoch e is the constant e*10 + b: any cursor slip is
        # instantly visible in the delivered values
        for b in range(4):
            yield {"x": np.full((2, 2), epoch * 10 + b, "float32")}

    @staticmethod
    def _vals(batches):
        return [int(np.asarray(b["x"])[0, 0]) for b in batches]

    def test_mid_epoch_state_resume_replays_undelivered_batches(self):
        l1 = DeviceLoader(self._epoch_reader, capacity=2)
        it = iter(l1)
        got = [next(it) for _ in range(3)]
        assert self._vals(got) == [0, 1, 2]
        st = l1.state()
        l1.close()
        assert st == {"version": 1, "epoch": 0, "cursor": 3}

        # prefetched-but-undelivered batches were NOT counted: a fresh
        # loader restored from st continues at batch 3, not at the
        # worker's read-ahead position
        l2 = DeviceLoader(self._epoch_reader, capacity=2)
        l2.restore_state(st)
        assert self._vals(list(l2)) == [3]          # rest of epoch 0
        assert self._vals(list(l2)) == [10, 11, 12, 13]  # then epoch 1

    def test_epoch_boundary_state(self):
        ld = DeviceLoader(self._epoch_reader, capacity=2)
        assert self._vals(list(ld)) == [0, 1, 2, 3]
        st = ld.state()
        assert st == {"version": 1, "epoch": 1, "cursor": 0}
        l2 = DeviceLoader(self._epoch_reader, capacity=2)
        l2.restore_state(st)
        assert self._vals(list(l2)) == [10, 11, 12, 13]

    def test_stateless_reader_still_resumes_by_skip(self):
        def reader():  # no epoch arg: plain fluid-style callable
            for b in range(5):
                yield {"x": np.full((1, 1), b, "float32")}

        l1 = DeviceLoader(reader, capacity=2)
        it = iter(l1)
        next(it), next(it)
        st = l1.state()
        l1.close()
        l2 = DeviceLoader(reader, capacity=2)
        l2.restore_state(st)
        assert self._vals(list(l2)) == [2, 3, 4]

    def test_restore_state_rejects_running_or_bad_state(self):
        ld = DeviceLoader(self._epoch_reader, capacity=2)
        it = iter(ld)
        next(it)
        with pytest.raises(RuntimeError, match="running"):
            ld.restore_state({"version": 1, "epoch": 0, "cursor": 1})
        ld.close()
        with pytest.raises(ValueError, match="version"):
            ld.restore_state({"version": 2, "epoch": 0, "cursor": 0})
        with pytest.raises(ValueError):
            ld.restore_state({"version": 1, "epoch": -1, "cursor": 0})

    def test_close_mid_epoch_does_not_advance_epoch(self):
        # close() wakes a blocked consumer with an _EndOfEpoch sentinel;
        # that teardown signal must not look like a real epoch end
        ld = DeviceLoader(self._epoch_reader, capacity=2)
        it = iter(ld)
        next(it)
        ld.close()
        assert ld.state() == {"version": 1, "epoch": 0, "cursor": 1}
