"""Canned dataset readers + book-style end-to-end smokes
(reference tests/book/test_fit_a_line.py, test_recognize_digits.py shapes;
readers run synthetic in this no-egress environment)."""
import os

import numpy as np
import pytest

os.environ.setdefault("PADDLE_TPU_SYNTHETIC_DATA", "1")

import paddle_tpu as fluid
from paddle_tpu import dataset, layers
from paddle_tpu import reader as rd


def test_reader_shapes():
    img, lab = next(dataset.mnist.train()())
    assert img.shape == (784,) and 0 <= lab < 10
    assert img.min() >= -1.0 and img.max() <= 1.0

    x, y = next(dataset.uci_housing.train()())
    assert x.shape == (13,) and y.shape == (1,)

    row, lab = next(dataset.cifar.train10()())
    assert row.shape == (3072,) and 0 <= lab < 10

    ids, lab = next(dataset.imdb.train()())
    assert ids.ndim == 1 and lab in (0, 1)

    src, trg, nxt = next(dataset.wmt16.train()())
    assert len(trg) == len(nxt)
    assert trg[0] == dataset.wmt16.BOS and nxt[-1] == dataset.wmt16.EOS

    sample = next(dataset.movielens.train()())
    assert len(sample) == 8


def test_fit_a_line_book():
    """reference book/test_fit_a_line.py: linear regression on uci_housing
    converges."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [13])
        y = layers.data("y", [1])
        pred = layers.fc(x, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.01).minimize(loss)

    batched = rd.batch(dataset.uci_housing.train(), batch_size=32)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        losses = []
        for _ in range(4):
            for batch in batched():
                xs = np.stack([b[0] for b in batch])
                ys = np.stack([b[1] for b in batch])
                if xs.shape[0] != 32:
                    continue
                losses.append(float(exe.run(
                    main, feed={"x": xs, "y": ys}, fetch_list=[loss])[0]))
    assert losses[-1] < losses[0] * 0.9, (losses[0], losses[-1])


def test_recognize_digits_book():
    """reference book/test_recognize_digits.py: LeNet on mnist reader, loss
    decreases and accuracy beats chance on the synthetic digits."""
    from paddle_tpu.models import lenet

    main, startup, feeds, loss, acc = lenet.build_train_program(lr=0.01)
    batched = rd.batch(dataset.mnist.train(), batch_size=64)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        accs, losses = [], []
        for _ in range(3):
            for batch in batched():
                xs = np.stack([b[0] for b in batch]).reshape(-1, 1, 28, 28)
                ys = np.asarray([[b[1]] for b in batch], "int64")
                if xs.shape[0] != 64:
                    continue
                l, a = exe.run(main, feed={"img": xs, "label": ys},
                               fetch_list=[loss, acc])
                losses.append(float(l))
                accs.append(float(a))
    assert losses[-1] < losses[0]
    assert np.mean(accs[-5:]) > 0.5   # well above 10% chance


def test_global_shuffle_routes_disjointly(monkeypatch):
    """Hash routing property behind the cross-trainer exchange: with every
    trainer applying the same content hash, the per-destination buckets of
    the GLOBAL record set are disjoint, complete, and roughly balanced.
    (The live 2-process exchange is test_global_shuffle_crosses_trainers.)"""
    import pickle

    n, nranks, epoch = 120, 4, 1
    records = [([float(i)], [i]) for i in range(n)]
    buckets = [[] for _ in range(nranks)]
    for rec in records:
        h = hash((pickle.dumps(rec, protocol=4), epoch)) & 0x7FFFFFFF
        buckets[h % nranks].append(int(rec[1][0]))
    allv = [v for b in buckets for v in b]
    assert sorted(allv) == list(range(n))        # disjoint + complete
    sizes = [len(b) for b in buckets]
    assert max(sizes) - min(sizes) < n // 2      # no degenerate bucket


def test_global_shuffle_single_process_reshuffle():
    """Single process: global_shuffle keeps the full set and re-shuffles
    in place across calls."""
    from paddle_tpu.dataset.factory import InMemoryDataset

    n = 40
    ds = InMemoryDataset()
    ds.set_batch_size(4)
    ds._memory = [([float(i)], [i]) for i in range(n)]
    ds.global_shuffle()
    first = [int(s[1][0]) for s in ds._memory]
    assert sorted(first) == list(range(n))
    ds.global_shuffle()
    second = [int(s[1][0]) for s in ds._memory]
    assert sorted(second) == list(range(n)) and second != first


def test_train_from_dataset_multithread_loader(tmp_path):
    """Trainer runtime (executor.py:894 train_from_dataset parity): the
    N-thread native loader feeds a training program; loss decreases."""
    from paddle_tpu.native import available as native_available
    if not native_available():
        pytest.skip("no native toolchain")

    rng = np.random.RandomState(0)
    w_true = np.array([1.5, -2.0, 0.5], "float64")
    for part in range(2):
        lines = []
        for _ in range(40):
            x = rng.rand(3)
            y = float(x @ w_true)
            lines.append("3 " + " ".join(f"{v}" for v in x) + f" 1 {y}\n")
        (tmp_path / f"part-{part}").write_text("".join(lines))

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [3])
        y = layers.data("y", [1])
        pred = layers.fc(x, 1, bias_attr=False)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        fluid.optimizer.SGD(0.1).minimize(loss)

    ds = fluid.dataset.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_filelist([str(tmp_path / "part-0"), str(tmp_path / "part-1")])
    ds.set_batch_size(16)
    ds.set_use_var([x, y])
    ds.load_into_memory()
    ds.local_shuffle()

    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        first = exe.run(main, feed=next(ds.batches()), fetch_list=[loss])
        for _ in range(5):
            last = exe.train_from_dataset(main, ds, thread=2,
                                          fetch_list=[loss])
        assert float(last[0]) < float(first[0]) * 0.5


def test_remaining_dataset_modules_and_decorators():
    """The full python/paddle/dataset module surface (conll05, imikolov,
    wmt14, sentiment, mq2007, flowers, voc2012, image utils) + the last
    reader decorators (multiprocess_reader, Fake, creator)."""
    s = next(dataset.conll05.test()())
    assert len(s) == 9 and len(s[0]) == len(s[-1])  # word + label aligned
    w, p, l = dataset.conll05.get_dict()
    assert len(l) == 19

    gram = next(dataset.imikolov.train()())
    assert len(gram) == 5

    src, trg, nxt = next(dataset.wmt14.train()())
    assert trg[0] == 0 and nxt[-1] == 1 and len(trg) == len(nxt)

    ids, lab = next(dataset.sentiment.train()())
    assert lab in (0, 1) and len(ids) >= 8

    pw = next(dataset.mq2007.train()())
    assert len(pw) == 3 and pw[1].shape == (46,)

    img, label = next(dataset.flowers.train()())
    assert img.shape == (3 * 32 * 32,) and 0 <= label < 102

    im, seg = next(dataset.voc2012.train()())
    assert im.shape == (3, 32, 32) and seg.shape == (32, 32)

    # reference contract: HWC in (cv2 layout) → CHW float32 out
    x = np.random.RandomState(0).rand(60, 40, 3).astype("float32")
    out = dataset.image.simple_transform(x, 48, 32, is_train=False,
                                         mean=[0.5, 0.5, 0.5])
    assert out.shape == (3, 32, 32)

    # decorators
    fake = rd.Fake()(lambda: iter([1, 2]), length=5)
    assert list(fake()) == [1, 2, 1, 2, 1]
    r = rd.creator.np_array(np.arange(6).reshape(3, 2))
    assert len(list(r())) == 3
    mp_r = rd.multiprocess_reader(
        [rd.creator.np_array(np.arange(4)),
         rd.creator.np_array(np.arange(4, 8))])
    got = sorted(int(v) for v in mp_r())
    assert got == list(range(8))


def test_pipe_command_preprocessing(tmp_path):
    """data_feed pipe_command (reference data_feed.h:61 pipe protocol via
    shell.cc): raw lines are transformed by the shell command before
    MultiSlot parsing."""
    from paddle_tpu.native import available as native_available
    if not native_available():
        pytest.skip("no native toolchain")

    # raw CSV → awk rewrites into MultiSlot "1 <feat> 1 <label>"
    raw = tmp_path / "raw.csv"
    raw.write_text("0.5,1\n0.25,0\n0.75,1\n0.125,0\n")
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_filelist([str(raw)])
    ds.set_batch_size(2)
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        f = layers.data("f", [1])
        lab = layers.data("lab", [1], dtype="int64")
    ds.set_use_var([f, lab])
    ds.set_pipe_command("awk -F, '{print \"1 \" $1 \" 1 \" $2}'")
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 4
    batch = next(ds.batches())
    assert set(batch) == {"f", "lab"}
    vals = sorted(float(v) for b in [batch] for v in b["f"].ravel())
    assert all(v in (0.125, 0.25, 0.5, 0.75) for v in vals)


def test_global_shuffle_crosses_trainers(tmp_path):
    """2-proc cluster: disjoint per-rank records are hash-routed BETWEEN
    the trainers by global_shuffle — union preserved, no duplicates, and
    both directions actually moved records (VERDICT r2 #9; reference
    data_set.h:165 GlobalShuffle)."""
    import json
    import socket

    from paddle_tpu.distributed import launch

    runner = os.path.join(os.path.dirname(__file__),
                          "dist_shuffle_runner.py")

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    env_backup = dict(os.environ)
    for k in list(os.environ):
        if k.startswith(("PADDLE_", "XLA_", "JAX_")):
            del os.environ[k]
    try:
        procs, fds = launch.start_procs(
            2, runner, [], started_port=free_port(), log_dir=str(tmp_path))
        rc = launch.wait_procs(procs, fds)
    finally:
        os.environ.clear()
        os.environ.update(env_backup)

    ids = {}
    for rank in range(2):
        text = (tmp_path / f"workerlog.{rank}").read_text()
        assert rc == 0, f"rank{rank} log:\n{text[-2000:]}"
        line = [l for l in text.splitlines() if l.startswith("{")][-1]
        ids[rank] = json.loads(line)["ids"]

    all_ids = sorted(ids[0] + ids[1])
    assert all_ids == list(range(80))            # union preserved, no dups
    # cross-trainer movement: rank 0 loaded 0..39 — it must now hold some
    # of rank 1's records and vice versa (hash routing, not partitioning)
    assert any(i >= 40 for i in ids[0])
    assert any(i < 40 for i in ids[1])


def test_drop_last_keeps_batch_shapes_static():
    """set_drop_last(True): the ragged epoch-tail batch is dropped, so XLA
    sees ONE batch shape per epoch (VERDICT r2 weak #8)."""
    from paddle_tpu.dataset.factory import InMemoryDataset

    ds = InMemoryDataset()
    ds.set_batch_size(4)
    ds.set_use_var_names = None  # not used by _collate path below
    ds._use_var_names = ["a"]
    ds._memory = [([float(i)],) for i in range(10)]
    sizes = [b["a"].shape[0] for b in ds.batches()]
    assert sizes == [4, 4, 2]
    ds.set_drop_last(True)
    sizes = [b["a"].shape[0] for b in ds.batches()]
    assert sizes == [4, 4]
