"""Detection family numeric checks (operators/detection/ parity, padded
static-shape redesigns)."""
import numpy as np

from op_test_base import OpTest


class _T(OpTest):
    pass


def test_multiclass_nms_suppresses_overlaps():
    t = _T(); t.op_type = "multiclass_nms"
    # 3 boxes: two heavily overlapping, one distinct
    boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]]],
                     dtype="float32")
    scores = np.array([[[0.9, 0.8, 0.7]]], dtype="float32")  # one fg class 0?
    # use 2 classes with class 0 as background
    scores = np.concatenate([np.zeros_like(scores), scores], axis=1)
    out = t.run_op({"BBoxes": boxes, "Scores": scores},
                   attrs={"nms_threshold": 0.5, "score_threshold": 0.1,
                          "keep_top_k": 3, "background_label": 0})
    res = out["Out"][0]                      # [keep_top_k, 6]
    kept = res[res[:, 0] >= 0]
    assert kept.shape[0] == 2                # overlap suppressed
    np.testing.assert_allclose(sorted(kept[:, 1]), [0.7, 0.9], rtol=1e-6)


def test_anchor_generator_centers():
    t = _T(); t.op_type = "anchor_generator"
    x = np.zeros((1, 8, 2, 2), "float32")
    out = t.run_op({"Input": x},
                   attrs={"anchor_sizes": [32.0], "aspect_ratios": [1.0],
                          "stride": [16.0, 16.0], "offset": 0.5},
                   output_slots=("Anchors", "Variances"))
    an = out["Anchors"]
    assert an.shape == (2, 2, 1, 4)
    # first anchor centered at (8, 8) with 32x32 extent
    np.testing.assert_allclose(an[0, 0, 0], [8 - 16, 8 - 16, 8 + 16, 8 + 16])


def test_box_clip():
    t = _T(); t.op_type = "box_clip"
    boxes = np.array([[[-5.0, -5.0, 30.0, 40.0]]], dtype="float32")
    im_info = np.array([[20.0, 25.0, 1.0]], dtype="float32")
    out = t.run_op({"Input": boxes, "ImInfo": im_info},
                   output_slots=("Output",))
    np.testing.assert_allclose(out["Output"][0, 0], [0, 0, 24, 19])


def test_bipartite_match_greedy():
    t = _T(); t.op_type = "bipartite_match"
    dist = np.array([[0.9, 0.1, 0.3],
                     [0.2, 0.8, 0.4]], dtype="float32")
    out = t.run_op({"DistMat": dist},
                   output_slots=("ColToRowMatchIndices", "ColToRowMatchDist"))
    idx = out["ColToRowMatchIndices"][0]
    np.testing.assert_array_equal(idx, [0, 1, -1])


def test_target_assign():
    t = _T(); t.op_type = "target_assign"
    gt = np.arange(2 * 3 * 4, dtype="float32").reshape(1, 6, 4)[:, :2]
    match = np.array([[1, -1, 0]], dtype="int32")
    out = t.run_op({"X": gt, "MatchIndices": match},
                   attrs={"mismatch_value": 0},
                   output_slots=("Out", "OutWeight"))
    np.testing.assert_allclose(out["Out"][0, 0], gt[0, 1])
    np.testing.assert_allclose(out["Out"][0, 1], np.zeros(4))
    np.testing.assert_allclose(out["OutWeight"][0].ravel(), [1, 0, 1])


def test_sigmoid_focal_loss():
    t = _T(); t.op_type = "sigmoid_focal_loss"
    x = np.random.RandomState(0).randn(4, 3).astype("float32")
    lab = np.array([[0], [1], [3], [2]], dtype="int32")
    fg = np.array([3], dtype="int32")
    out = t.run_op({"X": x, "Label": lab, "FgNum": fg},
                   attrs={"gamma": 2.0, "alpha": 0.25})
    o = out["Out"]
    # reference formula
    tm = (lab == (np.arange(3)[None] + 1)).astype("float32")
    p = 1 / (1 + np.exp(-x))
    ce = np.maximum(x, 0) - x * tm + np.log1p(np.exp(-np.abs(x)))
    w = tm * 0.25 * (1 - p) ** 2 + (1 - tm) * 0.75 * p ** 2
    np.testing.assert_allclose(o, w * ce / 3.0, rtol=1e-4, atol=1e-6)


def test_roi_pool():
    t = _T(); t.op_type = "roi_pool"
    x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 3, 3]], dtype="float32")
    out = t.run_op({"X": x, "ROIs": rois},
                   attrs={"pooled_height": 2, "pooled_width": 2,
                          "spatial_scale": 1.0})
    np.testing.assert_allclose(out["Out"][0, 0], [[5, 7], [13, 15]])


def test_density_prior_box_shape():
    t = _T(); t.op_type = "density_prior_box"
    x = np.zeros((1, 4, 2, 2), "float32")
    img = np.zeros((1, 3, 32, 32), "float32")
    out = t.run_op({"Input": x, "Image": img},
                   attrs={"fixed_sizes": [16.0], "fixed_ratios": [1.0],
                          "densities": [2]},
                   output_slots=("Boxes", "Variances"))
    assert out["Boxes"].shape == (2, 2, 4, 4)   # density² priors per pixel
    assert (out["Boxes"] <= 1.5).all()


def test_mine_hard_examples():
    t = _T(); t.op_type = "mine_hard_examples"
    loss = np.array([[0.1, 0.9, 0.5, 0.3]], dtype="float32")
    match = np.array([[0, -1, -1, -1]], dtype="int32")   # 1 pos, 3 neg
    out = t.run_op({"ClsLoss": loss, "MatchIndices": match},
                   attrs={"neg_pos_ratio": 2.0},
                   output_slots=("NegIndices", "UpdatedMatchIndices"))
    # keep top-2 hardest negatives: positions 1 (0.9) and 2 (0.5)
    np.testing.assert_array_equal(out["NegIndices"][0], [0, 1, 1, 0])


def test_generate_proposals_shapes():
    t = _T(); t.op_type = "generate_proposals"
    rng = np.random.RandomState(0)
    h = w = 4; a = 3; n = 2
    scores = rng.rand(n, a, h, w).astype("float32")
    deltas = (rng.randn(n, 4 * a, h, w) * 0.1).astype("float32")
    im_info = np.array([[64, 64, 1.0]] * n, dtype="float32")
    anchors = np.abs(rng.randn(h, w, a, 4)).astype("float32")
    anchors[..., 2:] += anchors[..., :2] + 4.0
    out = t.run_op({"Scores": scores, "BboxDeltas": deltas,
                    "ImInfo": im_info, "Anchors": anchors},
                   attrs={"pre_nms_topN": 24, "post_nms_topN": 8,
                          "nms_thresh": 0.7},
                   output_slots=("RpnRois", "RpnRoiProbs"))
    assert out["RpnRois"].shape == (n, 8, 4)
    assert out["RpnRoiProbs"].shape == (n, 8)
    rois = out["RpnRois"]
    assert (rois[..., 0] >= 0).all() and (rois[..., 2] <= 63).all()


# --- round-2 detection family ---------------------------------------------


def test_rpn_target_assign_labels():
    t = _T(); t.op_type = "rpn_target_assign"
    anchors = np.array([[0, 0, 10, 10], [100, 100, 110, 110],
                        [0, 0, 9, 11], [200, 200, 210, 210]], "float32")
    gt = np.array([[[0, 0, 10, 10]]], "float32")          # matches anchor 0
    out = t.run_op({"Anchor": anchors, "GtBoxes": gt},
                   attrs={"rpn_batch_size_per_im": 4, "rpn_fg_fraction": 0.5,
                          "rpn_positive_overlap": 0.7,
                          "rpn_negative_overlap": 0.3},
                   output_slots=("TargetLabel", "TargetBBox"))
    labels = out["TargetLabel"][0]
    assert labels[0] == 1                                 # IoU 1.0 anchor is fg
    assert (labels == 0).sum() >= 1                       # far anchors are bg
    # fg target deltas for a perfect match are ~0
    np.testing.assert_allclose(out["TargetBBox"][0][0], 0.0, atol=1e-5)


def test_retinanet_target_assign_classes():
    t = _T(); t.op_type = "retinanet_target_assign"
    anchors = np.array([[0, 0, 10, 10], [100, 100, 110, 110]], "float32")
    gt = np.array([[[0, 0, 10, 10]]], "float32")
    gl = np.array([[7]], "int32")
    out = t.run_op({"Anchor": anchors, "GtBoxes": gt, "GtLabels": gl},
                   output_slots=("TargetLabel", "ForegroundNumber"))
    assert out["TargetLabel"][0][0] == 7
    assert out["TargetLabel"][0][1] == 0
    assert out["ForegroundNumber"][0] == 1


def test_distribute_fpn_proposals_levels():
    t = _T(); t.op_type = "distribute_fpn_proposals"
    # small roi -> low level, large roi -> high level
    rois = np.array([[0, 0, 20, 20], [0, 0, 500, 500]], "float32")
    out = t.run_op({"FpnRois": rois},
                   attrs={"min_level": 2, "max_level": 5,
                          "refer_level": 4, "refer_scale": 224},
                   output_slots=("MultiFpnRois", "RestoreIndex"),
                   multi_output_counts={"MultiFpnRois": 4})
    lvls = out["MultiFpnRois"]
    assert np.allclose(lvls[0][0], rois[0])               # level 2 gets small
    assert np.allclose(lvls[3][1], rois[1])               # level 5 gets large
    # restore contract: gather(concat(MultiFpnRois), RestoreIndex) == input
    cat = np.concatenate(lvls)
    np.testing.assert_allclose(cat[out["RestoreIndex"]], rois)


def test_collect_fpn_proposals_topk():
    t = _T(); t.op_type = "collect_fpn_proposals"
    r1 = np.array([[[0, 0, 1, 1], [2, 2, 3, 3]]], "float32")
    r2 = np.array([[[4, 4, 5, 5]]], "float32")
    s1 = np.array([[0.9, 0.1]], "float32")
    s2 = np.array([[0.5]], "float32")
    out = t.run_op({"MultiLevelRois": [r1, r2], "MultiLevelScores": [s1, s2]},
                   attrs={"post_nms_topN": 2}, output_slots=("FpnRois",))
    top = out["FpnRois"][0]
    np.testing.assert_allclose(top[0], [0, 0, 1, 1])      # score 0.9
    np.testing.assert_allclose(top[1], [4, 4, 5, 5])      # score 0.5


def test_generate_proposal_labels_shapes():
    t = _T(); t.op_type = "generate_proposal_labels"
    rois = np.array([[[0, 0, 10, 10], [50, 50, 60, 60], [0, 0, 9, 10]]],
                    "float32")
    gt = np.array([[[0, 0, 10, 10]]], "float32")
    gc = np.array([[3]], "int32")
    out = t.run_op({"RpnRois": rois, "GtBoxes": gt, "GtClasses": gc},
                   attrs={"batch_size_per_im": 4, "fg_fraction": 0.5,
                          "fg_thresh": 0.5, "bg_thresh_hi": 0.5,
                          "bg_thresh_lo": 0.0, "class_nums": 5},
                   output_slots=("Rois", "LabelsInt32", "BboxTargets"))
    labels = out["LabelsInt32"][0]
    assert labels.shape == (4,)
    assert (labels == 3).sum() >= 1                       # fg got the gt class


def test_yolov3_loss_perfect_prediction_low():
    t = _T(); t.op_type = "yolov3_loss"
    rng = np.random.RandomState(0)
    n, na, c, h, w = 1, 1, 2, 4, 4
    x = rng.randn(n, na * (5 + c), h, w).astype("float32") * 0.1
    gt_box = np.array([[[0.4, 0.4, 0.25, 0.25]]], "float32")  # cx,cy,w,h
    gt_label = np.array([[1]], "int32")
    attrs = {"anchors": [32, 32], "anchor_mask": [0], "class_num": c,
             "ignore_thresh": 0.7, "downsample_ratio": 32}
    out = t.run_op({"X": x, "GTBox": gt_box, "GTLabel": gt_label},
                   attrs=attrs, output_slots=("Loss",))
    loss_rand = float(out["Loss"][0])
    assert np.isfinite(loss_rand) and loss_rand > 0
    # craft logits matching the gt: loss must drop sharply
    x2 = np.full_like(x, -12.0)                            # sigmoid ~ 0
    gi, gj = int(0.4 * w), int(0.4 * h)
    xv = x2.reshape(n, na, 5 + c, h, w)
    input_size = 32 * h
    tx = 0.4 * w - gi; ty = 0.4 * h - gj
    xv[0, 0, 0, gj, gi] = np.log(tx / (1 - tx))
    xv[0, 0, 1, gj, gi] = np.log(ty / (1 - ty))
    xv[0, 0, 2, gj, gi] = np.log(0.25 * input_size / 32)
    xv[0, 0, 3, gj, gi] = np.log(0.25 * input_size / 32)
    xv[0, 0, 4, gj, gi] = 12.0                             # objectness
    xv[0, 0, 5 + 1, gj, gi] = 12.0                         # class 1
    out2 = t.run_op({"X": xv.reshape(x.shape), "GTBox": gt_box,
                     "GTLabel": gt_label}, attrs=attrs, output_slots=("Loss",))
    # sigmoid-BCE on the soft x/y offsets has an irreducible entropy floor,
    # so "perfect" is ~0.17x the random loss, not ~0
    assert float(out2["Loss"][0]) < 0.2 * loss_rand


def test_detection_map_perfect_and_miss():
    t = _T(); t.op_type = "detection_map"
    # one gt of class 1, one perfect detection
    dets = np.array([[[1, 0.9, 0, 0, 10, 10]]], "float32")
    gts = np.array([[[1, 0, 0, 10, 10]]], "float32")
    out = t.run_op({"DetectRes": dets, "Label": gts},
                   attrs={"class_num": 2, "ap_type": "integral"},
                   output_slots=("MAP",))
    np.testing.assert_allclose(float(out["MAP"]), 1.0, atol=1e-5)
    # detection far away -> AP 0
    dets2 = np.array([[[1, 0.9, 50, 50, 60, 60]]], "float32")
    out2 = t.run_op({"DetectRes": dets2, "Label": gts},
                    attrs={"class_num": 2, "ap_type": "integral"},
                    output_slots=("MAP",))
    np.testing.assert_allclose(float(out2["MAP"]), 0.0, atol=1e-5)


def test_retinanet_detection_output_batched():
    t = _T(); t.op_type = "retinanet_detection_output"
    # batch of 2 images, 2 FPN levels with DIFFERENT anchor counts
    a1 = np.array([[0, 0, 18, 18], [40, 40, 58, 58], [80, 80, 98, 98]], "float32")
    a2 = np.array([[10, 10, 28, 28]], "float32")
    s1 = np.full((2, 3, 2), 0.01, "float32")
    s2 = np.full((2, 1, 2), 0.01, "float32")
    s1[0, 1, 1] = 0.95          # image 0: class 1 at level-1 anchor 1
    s2[1, 0, 0] = 0.9           # image 1: class 0 at level-2 anchor 0
    d1 = np.zeros((2, 3, 4), "float32")
    d2 = np.zeros((2, 1, 4), "float32")
    imi = np.array([[200, 200, 1], [200, 200, 1]], "float32")
    out = t.run_op({"Scores": [s1, s2], "BBoxes": [d1, d2],
                    "Anchors": [a1, a2], "ImInfo": imi},
                   attrs={"score_threshold": 0.5, "nms_top_k": 4,
                          "keep_top_k": 3, "nms_threshold": 0.3})
    det = out["Out"]
    assert det.shape == (2, 3, 6)                         # batch-major
    assert det[0, 0, 0] == 1.0 and det[0, 0, 1] > 0.9     # img0 class 1
    assert det[1, 0, 0] == 0.0 and det[1, 0, 1] > 0.85    # img1 class 0
    # img0 top box decodes against the level-1 anchor it came from
    np.testing.assert_allclose(det[0, 0, 2:], [40, 40, 58, 58], atol=1.0)
