"""Detection family numeric checks (operators/detection/ parity, padded
static-shape redesigns)."""
import numpy as np

from op_test_base import OpTest


class _T(OpTest):
    pass


def test_multiclass_nms_suppresses_overlaps():
    t = _T(); t.op_type = "multiclass_nms"
    # 3 boxes: two heavily overlapping, one distinct
    boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]]],
                     dtype="float32")
    scores = np.array([[[0.9, 0.8, 0.7]]], dtype="float32")  # one fg class 0?
    # use 2 classes with class 0 as background
    scores = np.concatenate([np.zeros_like(scores), scores], axis=1)
    out = t.run_op({"BBoxes": boxes, "Scores": scores},
                   attrs={"nms_threshold": 0.5, "score_threshold": 0.1,
                          "keep_top_k": 3, "background_label": 0})
    res = out["Out"][0]                      # [keep_top_k, 6]
    kept = res[res[:, 0] >= 0]
    assert kept.shape[0] == 2                # overlap suppressed
    np.testing.assert_allclose(sorted(kept[:, 1]), [0.7, 0.9], rtol=1e-6)


def test_anchor_generator_centers():
    t = _T(); t.op_type = "anchor_generator"
    x = np.zeros((1, 8, 2, 2), "float32")
    out = t.run_op({"Input": x},
                   attrs={"anchor_sizes": [32.0], "aspect_ratios": [1.0],
                          "stride": [16.0, 16.0], "offset": 0.5},
                   output_slots=("Anchors", "Variances"))
    an = out["Anchors"]
    assert an.shape == (2, 2, 1, 4)
    # first anchor centered at (8, 8) with 32x32 extent
    np.testing.assert_allclose(an[0, 0, 0], [8 - 16, 8 - 16, 8 + 16, 8 + 16])


def test_box_clip():
    t = _T(); t.op_type = "box_clip"
    boxes = np.array([[[-5.0, -5.0, 30.0, 40.0]]], dtype="float32")
    im_info = np.array([[20.0, 25.0, 1.0]], dtype="float32")
    out = t.run_op({"Input": boxes, "ImInfo": im_info},
                   output_slots=("Output",))
    np.testing.assert_allclose(out["Output"][0, 0], [0, 0, 24, 19])


def test_bipartite_match_greedy():
    t = _T(); t.op_type = "bipartite_match"
    dist = np.array([[0.9, 0.1, 0.3],
                     [0.2, 0.8, 0.4]], dtype="float32")
    out = t.run_op({"DistMat": dist},
                   output_slots=("ColToRowMatchIndices", "ColToRowMatchDist"))
    idx = out["ColToRowMatchIndices"][0]
    np.testing.assert_array_equal(idx, [0, 1, -1])


def test_target_assign():
    t = _T(); t.op_type = "target_assign"
    gt = np.arange(2 * 3 * 4, dtype="float32").reshape(1, 6, 4)[:, :2]
    match = np.array([[1, -1, 0]], dtype="int32")
    out = t.run_op({"X": gt, "MatchIndices": match},
                   attrs={"mismatch_value": 0},
                   output_slots=("Out", "OutWeight"))
    np.testing.assert_allclose(out["Out"][0, 0], gt[0, 1])
    np.testing.assert_allclose(out["Out"][0, 1], np.zeros(4))
    np.testing.assert_allclose(out["OutWeight"][0].ravel(), [1, 0, 1])


def test_sigmoid_focal_loss():
    t = _T(); t.op_type = "sigmoid_focal_loss"
    x = np.random.RandomState(0).randn(4, 3).astype("float32")
    lab = np.array([[0], [1], [3], [2]], dtype="int32")
    fg = np.array([3], dtype="int32")
    out = t.run_op({"X": x, "Label": lab, "FgNum": fg},
                   attrs={"gamma": 2.0, "alpha": 0.25})
    o = out["Out"]
    # reference formula
    tm = (lab == (np.arange(3)[None] + 1)).astype("float32")
    p = 1 / (1 + np.exp(-x))
    ce = np.maximum(x, 0) - x * tm + np.log1p(np.exp(-np.abs(x)))
    w = tm * 0.25 * (1 - p) ** 2 + (1 - tm) * 0.75 * p ** 2
    np.testing.assert_allclose(o, w * ce / 3.0, rtol=1e-4, atol=1e-6)


def test_roi_pool():
    t = _T(); t.op_type = "roi_pool"
    x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 3, 3]], dtype="float32")
    out = t.run_op({"X": x, "ROIs": rois},
                   attrs={"pooled_height": 2, "pooled_width": 2,
                          "spatial_scale": 1.0})
    np.testing.assert_allclose(out["Out"][0, 0], [[5, 7], [13, 15]])


def test_density_prior_box_shape():
    t = _T(); t.op_type = "density_prior_box"
    x = np.zeros((1, 4, 2, 2), "float32")
    img = np.zeros((1, 3, 32, 32), "float32")
    out = t.run_op({"Input": x, "Image": img},
                   attrs={"fixed_sizes": [16.0], "fixed_ratios": [1.0],
                          "densities": [2]},
                   output_slots=("Boxes", "Variances"))
    assert out["Boxes"].shape == (2, 2, 4, 4)   # density² priors per pixel
    assert (out["Boxes"] <= 1.5).all()


def test_mine_hard_examples():
    t = _T(); t.op_type = "mine_hard_examples"
    loss = np.array([[0.1, 0.9, 0.5, 0.3]], dtype="float32")
    match = np.array([[0, -1, -1, -1]], dtype="int32")   # 1 pos, 3 neg
    out = t.run_op({"ClsLoss": loss, "MatchIndices": match},
                   attrs={"neg_pos_ratio": 2.0},
                   output_slots=("NegIndices", "UpdatedMatchIndices"))
    # keep top-2 hardest negatives: positions 1 (0.9) and 2 (0.5)
    np.testing.assert_array_equal(out["NegIndices"][0], [0, 1, 1, 0])


def test_generate_proposals_shapes():
    t = _T(); t.op_type = "generate_proposals"
    rng = np.random.RandomState(0)
    h = w = 4; a = 3; n = 2
    scores = rng.rand(n, a, h, w).astype("float32")
    deltas = (rng.randn(n, 4 * a, h, w) * 0.1).astype("float32")
    im_info = np.array([[64, 64, 1.0]] * n, dtype="float32")
    anchors = np.abs(rng.randn(h, w, a, 4)).astype("float32")
    anchors[..., 2:] += anchors[..., :2] + 4.0
    out = t.run_op({"Scores": scores, "BboxDeltas": deltas,
                    "ImInfo": im_info, "Anchors": anchors},
                   attrs={"pre_nms_topN": 24, "post_nms_topN": 8,
                          "nms_thresh": 0.7},
                   output_slots=("RpnRois", "RpnRoiProbs"))
    assert out["RpnRois"].shape == (n, 8, 4)
    assert out["RpnRoiProbs"].shape == (n, 8)
    rois = out["RpnRois"]
    assert (rois[..., 0] >= 0).all() and (rois[..., 2] <= 63).all()
