"""Two-process localhost 'cluster' test.

Reference analog: ``python/paddle/fluid/tests/unittests/test_dist_base.py``
(:442 TestDistBase, :608 _run_cluster) — spawn trainer subprocesses on
localhost, compare their losses against a single-process run.

Here the launcher is ``paddle_tpu.distributed.launch`` (PADDLE_TRAINER_*
env wiring), the bootstrap is ``parallel.env.init_parallel_env`` →
``jax.distributed.initialize``, and the data-parallel step runs over one
8-device mesh spanning the two processes (4 virtual CPU devices each).
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_RUNNER = os.path.join(os.path.dirname(__file__), "dist_mlp_runner.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_local():
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PADDLE_", "XLA_", "JAX_"))}
    out = subprocess.run([sys.executable, "-u", _RUNNER, "--local"],
                         capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])["losses"]


def test_two_process_cluster_loss_equality(tmp_path):
    from paddle_tpu.distributed import launch

    env_backup = dict(os.environ)
    for k in list(os.environ):
        if k.startswith(("PADDLE_", "XLA_", "JAX_")):
            del os.environ[k]
    try:
        procs, fds = launch.start_procs(
            2, _RUNNER, [], started_port=_free_port(),
            log_dir=str(tmp_path))
        rc = launch.wait_procs(procs, fds)
    finally:
        os.environ.clear()
        os.environ.update(env_backup)

    logs = {}
    for rank in range(2):
        text = (tmp_path / f"workerlog.{rank}").read_text()
        assert rc == 0, f"cluster run failed; rank{rank} log:\n{text[-2000:]}"
        line = [l for l in text.splitlines() if l.startswith("{")][-1]
        logs[rank] = json.loads(line)

    assert logs[0]["rank"] == 0 and logs[1]["rank"] == 1
    # both ranks fetch the same (replicated) global loss
    np.testing.assert_allclose(logs[0]["losses"], logs[1]["losses"],
                               rtol=1e-6)

    local = _run_local()
    # duplicated per-rank batches → global mean == single-process mean
    np.testing.assert_allclose(logs[0]["losses"], local, rtol=2e-4, atol=1e-5)
    # and training actually progressed
    assert logs[0]["losses"][-1] < logs[0]["losses"][0]


def test_two_process_dygraph_data_parallel(tmp_path):
    """VERDICT r3 #10: the dygraph DataParallel recipe (scale_loss →
    backward → apply_collective_grads) across the 2-process localhost
    cluster reproduces the single-process dygraph run exactly when both
    ranks feed the same batch."""
    from paddle_tpu.distributed import launch

    runner = os.path.join(os.path.dirname(__file__),
                          "dist_dygraph_runner.py")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PADDLE_", "XLA_", "JAX_"))}
    out = subprocess.run([sys.executable, "-u", runner, "--local"],
                         capture_output=True, text=True, timeout=300,
                         env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    local = json.loads(out.stdout.strip().splitlines()[-1])["losses"]
    assert local[-1] < local[0] * 0.7  # it actually trains

    env_backup = dict(os.environ)
    for k in list(os.environ):
        if k.startswith(("PADDLE_", "XLA_", "JAX_")):
            del os.environ[k]
    try:
        procs, fds = launch.start_procs(
            2, runner, [], started_port=_free_port(),
            log_dir=str(tmp_path))
        rc = launch.wait_procs(procs, fds)
    finally:
        os.environ.clear()
        os.environ.update(env_backup)
    for rank in range(2):
        text = (tmp_path / f"workerlog.{rank}").read_text()
        assert rc == 0, f"rank{rank} log:\n{text[-2000:]}"
        line = [l for l in text.splitlines() if l.startswith("{")][-1]
        got = json.loads(line)
        np.testing.assert_allclose(got["losses"], local, rtol=1e-5,
                                   atol=1e-7,
                                   err_msg=f"rank {rank} diverged from "
                                           f"single-process dygraph")
