"""Dygraph tests (reference test_imperative_*.py: basics, mnist, and
dygraph == static-graph loss equality)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dygraph


def test_to_variable_and_math():
    with dygraph.guard():
        x = dygraph.to_variable(np.array([[1.0, 2.0], [3.0, 4.0]], dtype="float32"))
        y = x * 2.0 + 1.0
        np.testing.assert_allclose(y.numpy(), [[3, 5], [7, 9]])
        z = x @ dygraph.to_variable(np.eye(2, dtype="float32"))
        np.testing.assert_allclose(z.numpy(), x.numpy())


def test_backward_simple():
    with dygraph.guard():
        xv = np.array([[1.0, 2.0, 3.0]], dtype="float32")
        x = dygraph.to_variable(xv)
        x.stop_gradient = False
        y = x * x
        from paddle_tpu.dygraph.tracer import trace_op
        loss = trace_op("reduce_sum", {"X": [y]}, {"reduce_all": True})["Out"][0]
        loss.backward()
        np.testing.assert_allclose(x.gradient, 2 * xv, rtol=1e-6)


def test_linear_layer_train():
    with dygraph.guard():
        layer = dygraph.Linear(4, 1, bias_attr=False)
        opt = fluid.optimizer.SGD(0.1)
        xv = np.ones((2, 4), dtype="float32")
        w0 = layer.weight.numpy()
        for _ in range(3):
            x = dygraph.to_variable(xv)
            out = layer(x)
            from paddle_tpu.dygraph.tracer import trace_op
            loss = trace_op("mean", {"X": [out]}, {})["Out"][0]
            loss.backward()
            opt.minimize(loss, parameter_list=layer.parameters())
            layer.clear_gradients()
        w1 = layer.weight.numpy()
    # grad of mean(xw) wrt w = 0.5*[2,2,2,2]^T/... each col mean of x = 1 → w decreases
    assert (w1 < w0).all()


def test_dygraph_mnist_mlp_converges():
    rng = np.random.RandomState(0)
    xs = rng.rand(128, 64).astype("float32")
    w_true = rng.rand(64, 1).astype("float32")
    ys = (xs @ w_true > w_true.sum() / 2).astype("int64")

    with dygraph.guard():
        class MLP(dygraph.Layer):
            def __init__(self):
                super().__init__()
                self.l1 = dygraph.Linear(64, 32, act="relu")
                self.l2 = dygraph.Linear(32, 2)

            def forward(self, x):
                return self.l2(self.l1(x))

        model = MLP()
        opt = fluid.optimizer.Adam(5e-3)
        losses = []
        from paddle_tpu.dygraph.tracer import trace_op
        for i in range(40):
            x = dygraph.to_variable(xs)
            label = dygraph.to_variable(ys)
            logits = model(x)
            out = trace_op("softmax_with_cross_entropy",
                           {"Logits": [logits], "Label": [label]}, {})
            loss = trace_op("mean", {"X": [out["Loss"][0]]}, {})["Out"][0]
            loss.backward()
            opt.minimize(loss, parameter_list=model.parameters())
            model.clear_gradients()
            losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.5, f"{losses[0]} -> {losses[-1]}"


def test_dygraph_equals_static():
    """Same model+seed: dygraph loss == static-graph loss (reference
    test_imperative_resnet.py pattern)."""
    xv = np.random.RandomState(1).rand(4, 8).astype("float32")
    w_init = np.random.RandomState(2).rand(8, 3).astype("float32")
    yv = np.array([[0], [1], [2], [0]], dtype="int64")

    from paddle_tpu.initializer import NumpyArrayInitializer
    from paddle_tpu.param_attr import ParamAttr

    # static
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8])
        label = fluid.layers.data("y", [1], dtype="int64")
        out = fluid.layers.fc(x, 3, bias_attr=False,
                              param_attr=ParamAttr(name="w", initializer=NumpyArrayInitializer(w_init)))
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(out, label))
        fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        static_losses = [float(exe.run(main, feed={"x": xv, "y": yv},
                                       fetch_list=[loss])[0]) for _ in range(5)]

    # dygraph
    with dygraph.guard():
        layer = dygraph.Linear(8, 3, bias_attr=False,
                               param_attr=ParamAttr(initializer=NumpyArrayInitializer(w_init)))
        opt = fluid.optimizer.SGD(0.1)
        from paddle_tpu.dygraph.tracer import trace_op
        dy_losses = []
        for _ in range(5):
            xb = dygraph.to_variable(xv)
            yb = dygraph.to_variable(yv)
            logits = layer(xb)
            o = trace_op("softmax_with_cross_entropy",
                         {"Logits": [logits], "Label": [yb]}, {})
            l = trace_op("mean", {"X": [o["Loss"][0]]}, {})["Out"][0]
            l.backward()
            opt.minimize(l, parameter_list=layer.parameters())
            layer.clear_gradients()
            dy_losses.append(float(l.numpy()))

    np.testing.assert_allclose(static_losses, dy_losses, rtol=1e-4, atol=1e-5)


def test_state_dict_save_load(tmp_path):
    with dygraph.guard():
        layer = dygraph.Linear(4, 2)
        sd = layer.state_dict()
        dygraph.save_dygraph(sd, str(tmp_path / "model"))
        para, _ = dygraph.load_dygraph(str(tmp_path / "model"))
        layer2 = dygraph.Linear(4, 2)
        # instance names differ; map by structural order
        keys1 = list(sd.keys())
        keys2 = list(layer2.state_dict().keys())
        layer2.set_dict({k2: para[k1] for k1, k2 in zip(keys1, keys2)})
        sd2 = layer2.state_dict()
        for k1, k2 in zip(keys1, keys2):
            np.testing.assert_allclose(sd[k1], sd2[k2])
    assert para is not None and len(para) == 2


def test_batch_norm_layer_updates_stats():
    with dygraph.guard():
        bn = dygraph.BatchNorm(3)
        x = dygraph.to_variable(np.random.rand(8, 3, 4, 4).astype("float32") + 5.0)
        bn(x)
        mean_after = bn._mean.numpy()
    assert np.abs(mean_after).sum() > 0  # moved toward batch mean ~5


def test_dygraph_jit_matches_eager():
    with dygraph.guard():
        layer = dygraph.Linear(6, 3, act="tanh")
        layer.eval()
        x = np.random.rand(2, 6).astype("float32")
        eager_out = layer(dygraph.to_variable(x)).numpy()
        fast = dygraph.jit(layer)
        jit_out = fast(x).numpy()
    np.testing.assert_allclose(eager_out, jit_out, rtol=1e-5, atol=1e-6)


def test_no_grad():
    with dygraph.guard():
        x = dygraph.to_variable(np.ones((2, 2), dtype="float32"))
        x.stop_gradient = False
        with dygraph.no_grad():
            y = x * 3.0
        assert y.stop_gradient


def test_dygraph_layer_zoo_round2():
    """Round-2 dygraph layer additions (reference dygraph/nn.py classes:
    Conv2DTranspose :1981, Conv3D :258, NCE :1579, BilinearTensorProduct
    :1881, SequenceConv :2216, RowConv :2306, GroupNorm :2382, SpectralNorm
    :2481, TreeConv :2581): forward shapes + a gradient through each."""
    from paddle_tpu.dygraph.tracer import trace_op

    rng = np.random.RandomState(0)
    with dygraph.guard():
        x4 = dygraph.to_variable(rng.rand(2, 3, 8, 8).astype("float32"))
        y = dygraph.nn.Conv2DTranspose(3, 5, 3)(x4)
        assert y.shape == (2, 5, 10, 10)

        x5 = dygraph.to_variable(rng.rand(2, 3, 4, 8, 8).astype("float32"))
        y = dygraph.nn.Conv3D(3, 5, 3)(x5)
        assert y.shape == (2, 5, 2, 6, 6)
        y = dygraph.nn.Conv3DTranspose(3, 5, 3)(x5)
        assert y.shape == (2, 5, 6, 10, 10)

        nce = dygraph.nn.NCE(num_total_classes=20, dim=6, num_neg_samples=4)
        cost = nce(dygraph.to_variable(rng.rand(3, 6).astype("float32")),
                   dygraph.to_variable(rng.randint(0, 20, (3, 1))))
        assert cost.shape == (3, 1)
        loss = trace_op("reduce_sum", {"X": [cost]}, {"reduce_all": True})["Out"][0]
        loss.backward()
        assert np.isfinite(nce.weight.gradient).all()

        blt = dygraph.nn.BilinearTensorProduct(4, 5, 6)
        out = blt(dygraph.to_variable(rng.rand(3, 4).astype("float32")),
                  dygraph.to_variable(rng.rand(3, 5).astype("float32")))
        assert out.shape == (3, 6)

        sc = dygraph.nn.SequenceConv(8, 16, filter_size=3, act="tanh")
        out = sc(dygraph.to_variable(rng.rand(2, 6, 8).astype("float32")),
                 dygraph.to_variable(np.array([[6], [4]], "int64")))
        assert out.shape == (2, 6, 16)

        rc = dygraph.nn.RowConv(8, future_context_size=2)
        out = rc(dygraph.to_variable(rng.rand(2, 6, 8).astype("float32")))
        assert out.shape == (2, 6, 8)

        gn = dygraph.nn.GroupNorm(6, groups=3)
        out = gn(dygraph.to_variable(rng.rand(2, 6, 4, 4).astype("float32")))
        assert out.shape == (2, 6, 4, 4)
        got = out.numpy().reshape(2, 3, 2, 4, 4)
        np.testing.assert_allclose(got.mean(axis=(2, 3, 4)), 0, atol=1e-4)

        sn = dygraph.nn.SpectralNorm([6, 4], power_iters=3)
        w = dygraph.to_variable(rng.rand(6, 4).astype("float32"))
        wn = sn(w)
        assert wn.shape == (6, 4)
        # spectral norm of the output ≈ 1
        s = np.linalg.svd(wn.numpy(), compute_uv=False)
        assert abs(s[0] - 1.0) < 0.15

        tc = dygraph.nn.TreeConv(feature_size=5, output_size=4, max_depth=2)
        nodes = dygraph.to_variable(rng.rand(1, 6, 5).astype("float32"))
        edges = dygraph.to_variable(
            np.array([[[1, 2], [1, 3], [2, 4], [2, 5]]], "int32"))
        out = tc(nodes, edges)
        assert out.shape[0] == 1 and out.shape[1] == 6


def test_dygraph_layer_zoo_fixes():
    """Review fixes: GroupNorm with bias_attr=False and NHWC layout,
    Conv2DTranspose output_size, NCE rejects non-uniform samplers."""
    rng = np.random.RandomState(0)
    with dygraph.guard():
        gn = dygraph.nn.GroupNorm(6, groups=3, param_attr=False,
                                  bias_attr=False)
        out = gn(dygraph.to_variable(rng.rand(2, 6, 4, 4).astype("float32")))
        assert out.shape == (2, 6, 4, 4)

        x_nhwc = rng.rand(2, 4, 4, 6).astype("float32")
        gn2 = dygraph.nn.GroupNorm(6, groups=3, data_layout="NHWC")
        out2 = gn2(dygraph.to_variable(x_nhwc))
        assert out2.shape == (2, 4, 4, 6)
        got = out2.numpy().transpose(0, 3, 1, 2).reshape(2, 3, 2, 4, 4)
        np.testing.assert_allclose(got.mean(axis=(2, 3, 4)), 0, atol=1e-4)

        ct = dygraph.nn.Conv2DTranspose(3, 5, 3, output_size=16, stride=2)
        y = ct(dygraph.to_variable(rng.rand(2, 3, 7, 7).astype("float32")))
        assert y.shape == (2, 5, 16, 16)

        with pytest.raises(NotImplementedError):
            dygraph.nn.NCE(num_total_classes=10, dim=4, sampler="log_uniform")


def test_eager_jit_cache_matches_direct_dispatch():
    """The per-op jit cache (PreparedOp analog) must be numerically
    invisible: same losses and updated params with PDTPU_EAGER_JIT=0."""
    import os

    from paddle_tpu.ops import eager as _eager

    os.environ.pop("PDTPU_EAGER_JIT", None)  # ambient disable → vacuous

    def run():
        _eager._jit_cache.clear()
        with dygraph.guard(seed=9):
            m = dygraph.Linear(8, 4, act="tanh")
            head = dygraph.Linear(4, 1)
            opt = fluid.optimizer.Adam(0.05)
            rng = np.random.RandomState(0)
            X = rng.rand(16, 8).astype("float32")
            Y = rng.rand(16, 1).astype("float32")
            from paddle_tpu.dygraph.tracer import trace_op
            params = m.parameters() + head.parameters()
            losses = []
            for _ in range(5):
                out = head(m(dygraph.to_variable(X)))
                d = trace_op("elementwise_sub",
                             {"X": [out], "Y": [dygraph.to_variable(Y)]},
                             {"axis": -1})["Out"][0]
                loss = trace_op("mean", {"X": [trace_op(
                    "square", {"X": [d]}, {})["Out"][0]]}, {})["Out"][0]
                losses.append(float(np.asarray(loss.value)))
                loss.backward()
                opt.minimize(loss, parameter_list=params)
                m.clear_gradients(); head.clear_gradients()
            w = np.asarray(m.weight.value)
        return losses, w

    cached_losses, cached_w = run()
    os.environ["PDTPU_EAGER_JIT"] = "0"
    try:
        direct_losses, direct_w = run()
    finally:
        os.environ.pop("PDTPU_EAGER_JIT", None)
    np.testing.assert_allclose(cached_losses, direct_losses, rtol=1e-5,
                               atol=1e-7)
    np.testing.assert_allclose(cached_w, direct_w, rtol=1e-5, atol=1e-6)
