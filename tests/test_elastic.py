"""Elastic / preemption-aware training (beats the reference bar: SURVEY §5
notes the reference has no automatic restart or elastic recovery — only a
pserver checkpoint-notify RPC). A trainer subprocess is SIGTERMed mid-run,
relaunched, and must resume from its last durable checkpoint with loss
continuity vs an uninterrupted run."""
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
RUNNER = os.path.join(HERE, "elastic_runner.py")


def _launch(ckpt, steps=12, delay=0.0):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    return subprocess.Popen(
        [sys.executable, RUNNER, "--ckpt", ckpt, "--steps", str(steps),
         "--save-interval", "2", "--step-delay", str(delay)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True, env=env)


def _parse(out):
    losses = {}
    nxt = None
    for line in out.splitlines():
        if line.startswith("step "):
            _, i, lv = line.split()
            losses[int(i)] = float(lv)
        elif line.startswith("done "):
            nxt = int(line.split()[1])
    return losses, nxt


def test_preempt_resume_loss_continuity(tmp_path):
    steps = 12

    # uninterrupted reference run
    p = _launch(str(tmp_path / "ref"), steps=steps)
    out, _ = p.communicate(timeout=300)
    assert p.returncode == 0, out
    ref_losses, nxt = _parse(out)
    assert nxt == steps and len(ref_losses) == steps

    # preempted run: SIGTERM after the 4th step line appears
    ck = str(tmp_path / "el")
    p = _launch(ck, steps=steps, delay=0.25)
    seen = 0
    t0 = time.time()
    lines = []
    while seen < 4 and time.time() - t0 < 240:
        line = p.stdout.readline()
        if not line:
            break
        lines.append(line)
        if line.startswith("step "):
            seen += 1
    assert seen >= 4, "".join(lines)
    p.send_signal(signal.SIGTERM)
    out, _ = p.communicate(timeout=120)
    assert p.returncode == 0  # graceful: final checkpoint written
    losses_a, resume_at = _parse("".join(lines) + out)
    assert resume_at is not None and 4 <= resume_at < steps

    # heartbeat file recorded the last completed step
    hb = open(os.path.join(ck, "heartbeat")).read().split()
    assert int(hb[0]) == resume_at

    # relaunch: resumes at resume_at, finishes the remaining steps
    p = _launch(ck, steps=steps)
    out, _ = p.communicate(timeout=300)
    assert p.returncode == 0, out
    losses_b, nxt = _parse(out)
    assert nxt == steps
    assert min(losses_b) == resume_at  # first step after resume

    # loss continuity: the stitched trajectory equals the uninterrupted one
    stitched = dict(losses_a)
    stitched.update(losses_b)
    for i in range(steps):
        np.testing.assert_allclose(stitched[i], ref_losses[i], rtol=1e-5,
                                   err_msg=f"step {i}")
