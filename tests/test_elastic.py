"""Elastic / preemption-aware training (beats the reference bar: SURVEY §5
notes the reference has no automatic restart or elastic recovery — only a
pserver checkpoint-notify RPC). A trainer subprocess is SIGTERMed mid-run,
relaunched, and must resume from its last durable checkpoint with loss
continuity vs an uninterrupted run.

The chaos matrix goes further: ``PDTPU_FAULT_SPEC`` kills the trainer at
every commit edge of the checkpoint writer (bundle write, bundle rename,
shard write) and corrupts committed bundles; every cell must resume from
a *verified* checkpoint — never from a torn one — and the stitched loss
trajectory must match an uninterrupted reference. Over a stateful reader
(``--reader``) the match must be bitwise: the input-pipeline cursor rides
in the checkpoint."""
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
RUNNER = os.path.join(HERE, "elastic_runner.py")

STEPS = 12
BATCHES_PER_EPOCH = 4  # must match elastic_runner.BATCHES_PER_EPOCH


def _launch(ckpt, steps=STEPS, delay=0.0, extra_args=(), env_extra=None,
            capture_stderr=False):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("PDTPU_FAULT_SPEC", None)
    if env_extra:
        env.update(env_extra)
    return subprocess.Popen(
        [sys.executable, RUNNER, "--ckpt", ckpt, "--steps", str(steps),
         "--save-interval", "2", "--step-delay", str(delay),
         *extra_args],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE if capture_stderr else subprocess.DEVNULL,
        text=True, env=env)


def _parse(out):
    losses = {}
    nxt = None
    for line in out.splitlines():
        parts = line.split()
        if len(parts) == 3 and parts[0] == "step":
            try:
                losses[int(parts[1])] = float(parts[2])
            except ValueError:
                pass  # line torn by an injected mid-print crash
        elif len(parts) == 2 and parts[0] == "done":
            nxt = int(parts[1])
    return losses, nxt


@pytest.fixture(scope="module")
def ref_reader(tmp_path_factory):
    """Uninterrupted 12-step reference over the stateful epoch-aware
    reader — the bitwise ground truth for every --reader resume test."""
    p = _launch(str(tmp_path_factory.mktemp("ref_reader")),
                extra_args=("--reader",))
    out, _ = p.communicate(timeout=300)
    assert p.returncode == 0, out
    losses, nxt = _parse(out)
    assert nxt == STEPS and len(losses) == STEPS
    return losses


@pytest.fixture(scope="module")
def ref_tp(tmp_path_factory):
    """Uninterrupted 12-step reference with a tensor-parallel weight (the
    mode whose checkpoints carry per-rank shard files)."""
    p = _launch(str(tmp_path_factory.mktemp("ref_tp")),
                extra_args=("--tp", "2"))
    out, _ = p.communicate(timeout=300)
    assert p.returncode == 0, out
    losses, nxt = _parse(out)
    assert nxt == STEPS and len(losses) == STEPS
    return losses


def test_preempt_resume_loss_continuity(tmp_path):
    steps = STEPS

    # uninterrupted reference run
    p = _launch(str(tmp_path / "ref"), steps=steps)
    out, _ = p.communicate(timeout=300)
    assert p.returncode == 0, out
    ref_losses, nxt = _parse(out)
    assert nxt == steps and len(ref_losses) == steps

    # preempted run: SIGTERM after the 4th step line appears
    ck = str(tmp_path / "el")
    p = _launch(ck, steps=steps, delay=0.25)
    seen = 0
    t0 = time.time()
    lines = []
    while seen < 4 and time.time() - t0 < 240:
        line = p.stdout.readline()
        if not line:
            break
        lines.append(line)
        if line.startswith("step "):
            seen += 1
    assert seen >= 4, "".join(lines)
    p.send_signal(signal.SIGTERM)
    out, _ = p.communicate(timeout=120)
    assert p.returncode == 0  # graceful: final checkpoint written
    losses_a, resume_at = _parse("".join(lines) + out)
    assert resume_at is not None and 4 <= resume_at < steps

    # heartbeat file recorded the last completed step
    hb = open(os.path.join(ck, "heartbeat")).read().split()
    assert int(hb[0]) == resume_at

    # relaunch: resumes at resume_at, finishes the remaining steps
    p = _launch(ck, steps=steps)
    out, _ = p.communicate(timeout=300)
    assert p.returncode == 0, out
    losses_b, nxt = _parse(out)
    assert nxt == steps
    assert min(losses_b) == resume_at  # first step after resume

    # loss continuity: the stitched trajectory equals the uninterrupted one
    stitched = dict(losses_a)
    stitched.update(losses_b)
    for i in range(steps):
        np.testing.assert_allclose(stitched[i], ref_losses[i], rtol=1e-5,
                                   err_msg=f"step {i}")


def test_sigterm_mid_epoch_resume_is_bitwise_identical(tmp_path, ref_reader):
    """ROADMAP item 5 acceptance: SIGTERM mid-epoch over a STATEFUL reader,
    relaunch, and the stitched loss trajectory is bitwise-identical to an
    uninterrupted run — possible only because run_elastic checkpoints the
    DeviceLoader's (epoch, cursor) and the resumed loader replays exactly
    the batches the killed run never consumed."""
    ck = str(tmp_path / "el")
    p = _launch(ck, delay=0.25, extra_args=("--reader",))
    seen = 0
    t0 = time.time()
    lines = []
    while seen < 5 and time.time() - t0 < 240:
        line = p.stdout.readline()
        if not line:
            break
        lines.append(line)
        if line.startswith("step "):
            seen += 1
    assert seen >= 5, "".join(lines)
    p.send_signal(signal.SIGTERM)
    out, _ = p.communicate(timeout=120)
    assert p.returncode == 0
    losses_a, resume_at = _parse("".join(lines) + out)
    # the signal lands within a step or two of the 5th line: squarely
    # inside epoch 1 (epochs are BATCHES_PER_EPOCH=4 steps)
    assert resume_at is not None and 5 <= resume_at <= 7, resume_at
    assert resume_at % BATCHES_PER_EPOCH != 0  # genuinely mid-epoch

    hb = open(os.path.join(ck, "heartbeat")).read().split()
    assert int(hb[0]) == resume_at

    p = _launch(ck, extra_args=("--reader",))
    out, _ = p.communicate(timeout=300)
    assert p.returncode == 0, out
    losses_b, nxt = _parse(out)
    assert nxt == STEPS
    assert min(losses_b) == resume_at

    stitched = dict(losses_a)
    stitched.update(losses_b)
    for i in range(STEPS):
        assert stitched[i] == ref_reader[i], (
            f"step {i}: {stitched[i]!r} != {ref_reader[i]!r} — resume is "
            "not bitwise-deterministic over the stateful reader")


# chaos matrix: (fault spec, runner mode, expected resume step, bitwise?)
# - bundle_write crash@2: dies during the SECOND save (step 4) after the
#   bundle tmp is written but before its rename — step 4 never commits,
#   resume must come from step 2;
# - rename crash@2: dies after the bundle rename but before the manifest
#   commit record — the step-4 bundle is complete (atomic rename), so the
#   fallback walk may trust it and resume at 4;
# - shard_write crash@2 (tensor-parallel mode): dies after a per-rank
#   shard tmp write, before any of step 4's files commit — resume from 2.
CHAOS_CELLS = [
    ("bundle", "ckpt.bundle_write:crash@2", ("--reader",), 2, True),
    ("rename", "ckpt.rename:crash@2", ("--reader",), 4, True),
    ("shard", "ckpt.shard_write:crash@2", ("--tp", "2"), 2, False),
]


@pytest.mark.parametrize("spec,mode,resume_expected,exact",
                         [c[1:] for c in CHAOS_CELLS],
                         ids=[c[0] for c in CHAOS_CELLS])
def test_chaos_matrix_crash_resumes_from_verified_checkpoint(
        spec, mode, resume_expected, exact, tmp_path, ref_reader, ref_tp):
    from paddle_tpu import faults

    ref = ref_reader if "--reader" in mode else ref_tp
    ck = str(tmp_path / "ck")
    p = _launch(ck, extra_args=mode, env_extra={"PDTPU_FAULT_SPEC": spec})
    out, _ = p.communicate(timeout=300)
    assert p.returncode == faults.CRASH_EXIT_CODE, out
    losses_a, nxt = _parse(out)
    assert nxt is None  # killed mid-run, not completed

    # relaunch with no faults: must resume from the newest checkpoint that
    # VERIFIES, never from the torn step the crash left behind
    p = _launch(ck, extra_args=mode)
    out, _ = p.communicate(timeout=300)
    assert p.returncode == 0, out
    losses_b, nxt = _parse(out)
    assert nxt == STEPS
    assert min(losses_b) == resume_expected

    stitched = dict(losses_a)
    stitched.update(losses_b)
    for i in range(STEPS):
        if exact:
            assert stitched[i] == ref[i], f"step {i}"
        else:
            np.testing.assert_allclose(stitched[i], ref[i], rtol=1e-6,
                                       err_msg=f"step {i}")


def test_corrupt_latest_bundle_falls_back_to_older_verified(tmp_path,
                                                            ref_reader):
    """The 4th bundle write (the final step-8 save) is corrupted AFTER its
    hash was recorded — the write 'succeeds', the file is committed, and
    only the manifest knows. The relaunch must detect the mismatch, warn
    naming the bad file, and fall back to the step-6 checkpoint."""
    ck = str(tmp_path / "ck")
    p = _launch(ck, steps=8, extra_args=("--reader",),
                env_extra={"PDTPU_FAULT_SPEC": "ckpt.bundle_write:corrupt@4"})
    out, _ = p.communicate(timeout=300)
    assert p.returncode == 0, out  # corruption is silent at write time
    losses_a, nxt = _parse(out)
    assert nxt == 8
    for i in range(8):
        assert losses_a[i] == ref_reader[i], f"step {i}"

    p = _launch(ck, steps=STEPS, extra_args=("--reader",),
                capture_stderr=True)
    out, err = p.communicate(timeout=300)
    assert p.returncode == 0, err
    losses_b, nxt = _parse(out)
    assert nxt == STEPS
    assert min(losses_b) == 6, (out, err)  # fell back past corrupt step 8
    assert "ckpt-8" in err and "sha256 mismatch" in err, err
    for i in range(6, STEPS):
        assert losses_b[i] == ref_reader[i], f"step {i}"


def test_healthz_reports_elastic_checks_and_wedge(tmp_path, monkeypatch):
    """While run_elastic runs, /healthz must expose elastic/checkpoint
    (degraded while an async save is in flight) and elastic/progress
    (failing — HTTP 503 — once no step completes for PDTPU_WEDGE_TIMEOUT);
    off the main thread the PreemptionGuard degradation is visible on the
    elastic/guard_degraded gauge; on exit both checks unregister."""
    import paddle_tpu as fluid
    from paddle_tpu import faults
    from paddle_tpu.distributed import run_elastic
    from paddle_tpu.observability.http import (IntrospectionServer,
                                               run_health_checks)
    from paddle_tpu.observability.registry import get_registry

    monkeypatch.setenv("PDTPU_WEDGE_TIMEOUT", "0.25")

    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data("x", [4])
        loss = fluid.layers.mean(fluid.layers.fc(x, 4))
        fluid.optimizer.SGD(0.1).minimize(loss)
    feed = {"x": np.ones((2, 4), np.float32)}

    srv = IntrospectionServer(port=0).start()
    faults.clear()
    # every save's bundle write stalls 250 ms: a wide, deterministic
    # "save in flight" window for the degraded assertion
    faults.install("ckpt.bundle_write", "delay_ms", value=250.0)
    release = threading.Event()
    result = []

    def healthz():
        try:
            r = urllib.request.urlopen(srv.url + "/healthz", timeout=5)
            return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)

        def step_fn(i):
            exe.run(main_p, feed=feed, fetch_list=[loss])
            if i == 5:
                release.wait(timeout=30)  # wedge: no step completes

        th = threading.Thread(target=lambda: result.append(
            run_elastic(step_fn, str(tmp_path / "hc"), 8, save_interval=1,
                        program=main_p)))
        th.start()
        try:
            saw_degraded = saw_failing = False
            deadline = time.time() + 30
            while (time.time() < deadline
                   and not (saw_degraded and saw_failing)):
                code, body = healthz()
                checks = body.get("checks", {})
                ck = checks.get("elastic/checkpoint", {})
                if ck.get("status") == "degraded":
                    saw_degraded = True
                pg = checks.get("elastic/progress", {})
                if pg.get("status") == "failing":
                    saw_failing = True
                    assert code == 503 and body["status"] == "failing"
                time.sleep(0.02)
            assert saw_degraded, "never saw an in-flight save as degraded"
            assert saw_failing, "wedged step never turned /healthz failing"
            # run_elastic is on a worker thread here, so its guard cannot
            # install signal handlers — the degradation must be LOUD
            assert get_registry().gauge("elastic/guard_degraded").value == 1
        finally:
            release.set()
            th.join(timeout=120)
            faults.clear()
            srv.stop()

    assert result == [8]
    _, checks = run_health_checks()
    assert not any(k.startswith("elastic/") for k in checks), checks
