"""paddle_tpu.faults: the chaos harness itself — spec grammar, count
triggering, the corrupt action, env-var arming, and the injected-fault
metrics counter. (The end-to-end kills live in tests/test_elastic.py's
chaos matrix; these pin the harness semantics those tests lean on.)"""
import time

import pytest

from paddle_tpu import faults
from paddle_tpu.observability import get_registry


@pytest.fixture(autouse=True)
def _clean_harness():
    faults.clear()
    yield
    faults.clear()


class TestSpecGrammar:
    def test_full_grammar_roundtrip(self):
        rules = faults.parse_spec(
            "ckpt.shard_write:crash@2, loader.next:delay_ms=50,"
            "ckpt.bundle_write:corrupt")
        assert [repr(r) for r in rules] == [
            "ckpt.shard_write:crash@2", "loader.next:delay_ms=50",
            "ckpt.bundle_write:corrupt"]

    @pytest.mark.parametrize("bad,msg", [
        ("ckpt.rename", "site:action"),
        ("ckpt.rename:explode", "unknown"),
        ("ckpt.rename:crash@x", "not an integer"),
        ("ckpt.rename:crash@0", ">= 1"),
        ("ckpt.rename:delay_ms", "needs a value"),
        ("ckpt.rename:delay_ms=fast", "not a number"),
    ])
    def test_malformed_entries_raise(self, bad, msg):
        with pytest.raises(ValueError, match=msg):
            faults.parse_spec(bad)

    def test_install_rejects_unknown_action(self):
        with pytest.raises(ValueError, match="unknown fault action"):
            faults.install("x", "explode")


class TestTriggering:
    def test_counted_rule_fires_on_nth_hit_only(self):
        faults.install("t.site", "raise", count=2)
        faults.fault_point("t.site")  # hit 1: armed, silent
        with pytest.raises(faults.InjectedFault, match="t.site"):
            faults.fault_point("t.site")  # hit 2: fires
        faults.fault_point("t.site")  # hit 3: one-shot, spent
        assert faults.hits("t.site") == 3

    def test_uncounted_rule_fires_every_hit(self):
        faults.install("t.every", "raise")
        for _ in range(3):
            with pytest.raises(faults.InjectedFault):
                faults.fault_point("t.every")

    def test_idle_harness_is_a_noop_and_counts_nothing(self):
        faults.fault_point("t.idle")
        assert faults.hits("t.idle") == 0  # counting starts when armed
        assert faults.active_rules() == []

    def test_injected_fault_is_an_oserror(self):
        # the checkpoint writer's transient-I/O retry loop must treat an
        # injected failure exactly like a real one
        assert issubclass(faults.InjectedFault, OSError)

    def test_env_spec_arms_and_rearms(self, monkeypatch):
        monkeypatch.setenv("PDTPU_FAULT_SPEC", "t.env:raise@1")
        with pytest.raises(faults.InjectedFault):
            faults.fault_point("t.env")
        # changing the variable re-parses on the next probe
        monkeypatch.delenv("PDTPU_FAULT_SPEC")
        faults.fault_point("t.env")  # no rules: no-op

    def test_delay_action_sleeps_and_counts_metric(self):
        c = get_registry().counter("faults/injected", site="t.slow",
                                   action="delay_ms")
        before = c.value
        faults.install("t.slow", "delay_ms", value=40)
        t0 = time.perf_counter()
        faults.fault_point("t.slow")
        assert time.perf_counter() - t0 >= 0.03
        assert c.value == before + 1

    def test_corrupt_action_flips_bytes_in_place(self, tmp_path):
        p = tmp_path / "f.bin"
        p.write_bytes(b"\x00" * 4096)
        faults.install("t.rot", "corrupt")
        faults.fault_point("t.rot", path=str(p))
        after = p.read_bytes()
        assert len(after) == 4096  # same size: corruption, not truncation
        assert after != b"\x00" * 4096
        # pathless probes and missing files are tolerated (no crash)
        faults.fault_point("t.rot")
        faults.fault_point("t.rot", path=str(tmp_path / "missing"))
