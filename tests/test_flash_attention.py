"""flash_attention correctness: blockwise/pallas path vs naive reference.

Mirrors the OpTest contract (SURVEY §4.1): numeric check of the op output vs
a dense numpy/jax reference, plus analytic-gradient checks of the custom_vjp
against jax.grad of the naive formulation."""
import numpy as np
import pytest


def _naive_attention(q, k, v, bias=None, causal=False):
    import jax.numpy as jnp

    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if bias is not None:
        s = s + bias
    if causal:
        t = q.shape[2]
        mask = np.tril(np.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jnp.exp(s - jnp.max(s, -1, keepdims=True))
    p = p / jnp.sum(p, -1, keepdims=True)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _rand(shape, seed):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_naive(causal):
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_kernels import flash_attention

    b, h, t, d = 2, 3, 64, 16
    q, k, v = (_rand((b, h, t, d), i) for i in range(3))
    ref = _naive_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                           causal=causal)
    got = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          causal=causal)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=2e-5, atol=2e-5)


def test_flash_with_bert_style_mask():
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_kernels import flash_attention

    b, h, t, d = 2, 2, 32, 8
    q, k, v = (_rand((b, h, t, d), i) for i in range(3))
    # BERT mask: [B,1,1,T] additive, -1e4 at padded positions
    mask = np.zeros((b, 1, 1, t), np.float32)
    mask[:, :, :, t // 2:] = -1e4
    ref = _naive_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                           bias=jnp.asarray(mask))
    got = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          bias=jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_naive(causal):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_kernels import flash_attention

    b, h, t, d = 1, 2, 32, 8
    q, k, v = (jnp.asarray(_rand((b, h, t, d), i)) for i in range(3))
    mask = jnp.asarray(np.where(
        np.random.RandomState(9).rand(b, 1, 1, t) > 0.3, 0.0, -1e4
    ).astype(np.float32))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, bias=mask, causal=causal) ** 2)

    def loss_naive(q, k, v):
        return jnp.sum(_naive_attention(q, k, v, bias=mask, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_naive = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for gf, gn in zip(g_flash, g_naive):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gn),
                                   rtol=1e-4, atol=1e-4)


def test_flash_dropout_deterministic_and_scaled():
    """Dropout path: same key → same output; mean magnitude preserved."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_kernels import flash_attention

    b, h, t, d = 2, 2, 32, 8
    q, k, v = (jnp.asarray(_rand((b, h, t, d), i)) for i in range(3))
    key = jax.random.PRNGKey(7)
    o1 = flash_attention(q, k, v, dropout_rate=0.3, dropout_key=key)
    o2 = flash_attention(q, k, v, dropout_rate=0.3, dropout_key=key)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    o3 = flash_attention(q, k, v, dropout_rate=0.3,
                         dropout_key=jax.random.PRNGKey(8))
    assert np.abs(np.asarray(o1) - np.asarray(o3)).max() > 1e-6
    # dropout on probs keeps outputs in the same ballpark (unbiased weights)
    o0 = flash_attention(q, k, v)
    assert np.abs(np.asarray(o1)).mean() == pytest.approx(
        np.abs(np.asarray(o0)).mean(), rel=0.5)
    # gradient through the dropout path works
    g = jax.grad(lambda q: jnp.sum(
        flash_attention(q, k, v, dropout_rate=0.3, dropout_key=key) ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()


def test_flash_attention_op_and_layer():
    """The registered op + layers.flash_attention through a real program."""
    import paddle_tpu as fluid

    b, h, t, d = 2, 2, 32, 8
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        q = fluid.layers.data("q", shape=[h, t, d], dtype="float32")
        k = fluid.layers.data("k", shape=[h, t, d], dtype="float32")
        v = fluid.layers.data("v", shape=[h, t, d], dtype="float32")
        out = fluid.layers.flash_attention(q, k, v, is_test=True)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    qv, kv, vv = (_rand((b, h, t, d), i) for i in range(3))
    got = exe.run(main, feed={"q": qv, "k": kv, "v": vv},
                  fetch_list=[out.name])[0]
    import jax.numpy as jnp
    ref = _naive_attention(jnp.asarray(qv), jnp.asarray(kv), jnp.asarray(vv))
    np.testing.assert_allclose(np.asarray(ref), got, rtol=2e-5, atol=2e-5)


def test_bert_flash_matches_naive_path():
    """BERT encoder with use_flash_attention on/off gives the same loss
    (dropout disabled)."""
    import paddle_tpu as fluid
    from paddle_tpu.models import bert

    losses = {}
    feed_cache = {}
    for flash in (False, True):
        cfg = bert.BertConfig(vocab_size=128, hidden_size=32, num_layers=1,
                              num_heads=2, ffn_size=64, max_position=32,
                              hidden_dropout=0.0, attn_dropout=0.0,
                              use_flash_attention=flash)
        main, startup, feeds, loss = bert.build_pretrain_program(
            cfg, 2, 16, optimizer_factory=None, is_test=True)
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.TPUPlace())
            exe.run(startup)
            if not feed_cache:
                rng = np.random.RandomState(0)
                feed_cache.update({
                    "src_ids": rng.randint(0, 128, (2, 16)).astype("int64"),
                    "pos_ids": np.tile(np.arange(16), (2, 1)).astype("int64"),
                    "sent_ids": np.zeros((2, 16), "int64"),
                    "input_mask": np.ones((2, 16), "float32"),
                    "mlm_labels": rng.randint(0, 128, (2, 16, 1)).astype("int64"),
                })
            losses[flash] = exe.run(main, feed=dict(feed_cache),
                                    fetch_list=[loss.name])[0]
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-4)


@pytest.mark.parametrize("causal,with_bias", [(False, False), (True, False),
                                              (False, True)])
@pytest.mark.parametrize("force_general", [False, True])
def test_pallas_kernel_interpret_mode(causal, with_bias, force_general,
                                      monkeypatch):
    """The actual Pallas kernels, run through the interpreter on CPU, against
    the naive reference — validates what executes on the real chip. At these
    single-block shapes the one-pass grouped kernel dispatches by default;
    force_general pins group=1 so the online-softmax _fwd_kernel keeps
    interpreter coverage too."""
    import jax.numpy as jnp
    import importlib
    fa_mod = importlib.import_module(
        "paddle_tpu.ops.pallas_kernels.flash_attention")
    if force_general:
        monkeypatch.setattr(fa_mod, "_pick_group", lambda *a, **k: 1)

    b, h, t, d = 1, 2, 256, 64
    bh = b * h
    q, k, v = (jnp.asarray(_rand((bh, t, d), i)) for i in range(3))
    bias = None
    bias4 = None
    if with_bias:
        mask = np.zeros((bh, 1, t), np.float32)
        mask[:, :, t // 3:] = -1e4
        bias = jnp.asarray(mask)
        bias4 = jnp.asarray(mask.reshape(b, h, 1, t))
    out, lse = fa_mod._flash_fwd_pallas(
        q, k, v, bias, 1.0 / np.sqrt(d), causal,
        fa_mod.DEFAULT_BLOCK_Q, fa_mod.DEFAULT_BLOCK_K, interpret=True)
    ref = _naive_attention(q.reshape(b, h, t, d), k.reshape(b, h, t, d),
                           v.reshape(b, h, t, d), bias=bias4, causal=causal)
    np.testing.assert_allclose(np.asarray(out).reshape(b, h, t, d),
                               np.asarray(ref), rtol=2e-5, atol=2e-5)
    # lse must match dense logsumexp of the scores
    s = jnp.einsum("btd,bkd->btk", q, k) / np.sqrt(d)
    if bias is not None:
        s = s + bias
    if causal:
        tri = np.tril(np.ones((t, t), bool))
        s = jnp.where(tri[None], s, -1e30)
    ref_lse = jax.nn.logsumexp(s, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               rtol=1e-5, atol=1e-5)


import jax  # noqa: E402  (used in interpret-mode lse check)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_multiblock_full_bias(causal):
    """t=256 spans multiple K blocks (nk>1): exercises the online-softmax
    correction across blocks AND the dbias block reassembly, including the
    gradient w.r.t. a full trainable [B,H,T,T] bias (ALiBi-style)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_kernels import flash_attention

    b, h, t, d = 1, 1, 256, 16
    q, k, v = (jnp.asarray(_rand((b, h, t, d), i)) for i in range(3))
    bias = jnp.asarray(0.1 * _rand((b, h, t, t), 7))

    def loss_flash(q, k, v, bias):
        return jnp.sum(flash_attention(q, k, v, bias=bias, causal=causal) ** 2)

    def loss_naive(q, k, v, bias):
        return jnp.sum(_naive_attention(q, k, v, bias=bias, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2, 3))(q, k, v, bias)
    g_naive = jax.grad(loss_naive, argnums=(0, 1, 2, 3))(q, k, v, bias)
    for gf, gn in zip(g_flash, g_naive):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gn),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal,bias_kind", [
    (False, "none"), (True, "none"), (False, "mask"), (True, "mask"),
    (False, "full"), (True, "full"),
])
@pytest.mark.parametrize("force_general", [False, True])
def test_pallas_backward_interpret_mode(causal, bias_kind, force_general,
                                        monkeypatch):
    """The Pallas backward kernels through the interpreter on CPU against
    the naive dense gradients. At t=256 the single-block shapes dispatch to
    the grouped one-pass kernels; force_general pins the group to 1 so the
    general dq and dk/dv kernels (incl. the col-bias accumulation) keep
    interpreter coverage too."""
    import importlib
    import jax
    import jax.numpy as jnp
    fa_mod = importlib.import_module(
        "paddle_tpu.ops.pallas_kernels.flash_attention")
    if force_general:
        monkeypatch.setattr(fa_mod, "_pick_group", lambda *a, **k: 1)

    b, h, t, d = 1, 2, 256, 64
    q, k, v = (jnp.asarray(_rand((b, h, t, d), i)) for i in range(3))
    bias = None
    if bias_kind == "mask":
        m = np.where(np.random.RandomState(9).rand(b, 1, 1, t) > 0.3,
                     0.0, -1e4).astype(np.float32)
        bias = jnp.asarray(m)
    elif bias_kind == "full":
        bias = jnp.asarray(0.1 * _rand((b, h, t, t), 7))

    def loss(fn):
        def f(q, k, v, *rest):
            bb = rest[0] if rest else bias
            return jnp.sum(fn(q, k, v, bias=bb, causal=causal) ** 2)
        return f

    argnums = (0, 1, 2, 3) if bias_kind == "full" else (0, 1, 2)
    args = (q, k, v, bias) if bias_kind == "full" else (q, k, v)

    fa_mod.FORCE_PALLAS_INTERPRET = True
    try:
        assert fa_mod._pallas_ok(t, d)
        g_pallas = jax.grad(loss(fa_mod.flash_attention), argnums)(*args)
        out_pallas = fa_mod.flash_attention(q, k, v, bias=bias, causal=causal)
    finally:
        fa_mod.FORCE_PALLAS_INTERPRET = False
    g_naive = jax.grad(loss(_naive_attention), argnums)(*args)
    out_naive = _naive_attention(q, k, v, bias=bias, causal=causal)
    np.testing.assert_allclose(np.asarray(out_pallas), np.asarray(out_naive),
                               rtol=2e-4, atol=2e-4)
    for gp, gn in zip(g_pallas, g_naive):
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gn),
                                   rtol=2e-4, atol=2e-4)


def test_dropout_without_key_raises():
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_kernels import flash_attention

    q = jnp.zeros((1, 1, 32, 8))
    with pytest.raises(ValueError, match="dropout_key"):
        flash_attention(q, q, q, dropout_rate=0.1)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="in-kernel PRNG numerics need a real TPU")
def test_pallas_dropout_on_tpu():
    """On hardware: in-kernel dropout is deterministic per key, consistent
    between forward and backward, and statistically ≈ the requested rate."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_kernels import flash_attention

    b, h, t, d = 2, 2, 256, 64
    q, k, v = (jnp.asarray(_rand((b, h, t, d), i)) for i in range(3))
    key = jax.random.PRNGKey(3)
    o1 = flash_attention(q, k, v, dropout_rate=0.3, dropout_key=key)
    o2 = flash_attention(q, k, v, dropout_rate=0.3, dropout_key=key)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    o0 = flash_attention(q, k, v)
    assert np.abs(np.asarray(o1)).mean() == pytest.approx(
        np.abs(np.asarray(o0)).mean(), rel=0.5)
    g = jax.grad(lambda q: jnp.sum(
        flash_attention(q, k, v, dropout_rate=0.3, dropout_key=key) ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_multi_kblock(causal):
    """Gradients with t > block (nk > 1) exercise the online-softmax
    correction across K blocks and the dbias reassembly — the paths a
    single-block seq len never reaches (ADVICE r1). Runs the Pallas
    kernels through the interpreter; full [B,H,T,T] trainable bias
    included, compared against the naive attention gradient."""
    import importlib
    import jax
    import jax.numpy as jnp
    fa_mod = importlib.import_module(
        "paddle_tpu.ops.pallas_kernels.flash_attention")

    b, h, d = 1, 2, 64  # d must satisfy the _pallas_ok d%64 gate
    t = fa_mod.DEFAULT_BLOCK_Q * 2  # guarantees nq = nk = 2
    old = fa_mod.FORCE_PALLAS_INTERPRET
    fa_mod.FORCE_PALLAS_INTERPRET = True
    try:
        assert fa_mod._pallas_ok(t, d), "test must exercise the Pallas path"
        q, k, v = (jnp.asarray(_rand((b, h, t, d), i)) for i in range(3))
        bias = jnp.asarray(_rand((b, h, t, t), 7) * 0.5)

        def loss_flash(q, k, v, bias):
            o = fa_mod.flash_attention(q, k, v, bias=bias, causal=causal)
            return jnp.sum(o * jnp.cos(o))

        def loss_naive(q, k, v, bias):
            o = _naive_attention(q, k, v, bias=bias, causal=causal)
            return jnp.sum(o * jnp.cos(o))

        gf = jax.grad(loss_flash, argnums=(0, 1, 2, 3))(q, k, v, bias)
        gn = jax.grad(loss_naive, argnums=(0, 1, 2, 3))(q, k, v, bias)
        for name, a, bb in zip(("dq", "dk", "dv", "dbias"), gf, gn):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(bb), rtol=5e-4, atol=5e-4,
                err_msg=f"{name} mismatch at t={t} (multi-block)")
    finally:
        fa_mod.FORCE_PALLAS_INTERPRET = old


# ---------------------------------------------------------------------------
# block-sparse packed-segment attention (ISSUE 19)
# ---------------------------------------------------------------------------

def _seg_mask(q_seg, k_seg, causal):
    """Dense boolean visibility the compact descriptor must reproduce:
    same (non-pad) segment, optionally global-position causal."""
    m = ((q_seg[:, :, None] == k_seg[:, None, :])
         & (q_seg[:, :, None] > 0) & (k_seg[:, None, :] > 0))
    if causal:
        tq, tk = q_seg.shape[1], k_seg.shape[1]
        m = m & (np.arange(tk)[None, None, :] <= np.arange(tq)[None, :, None])
    return m


def _ref_sparse(q, k, v, nh, q_seg, k_seg, causal):
    """Dense-mask reference on the [B, T, H] packed layout; fully-masked
    query rows (pad) produce exactly 0, matching the kernel contract."""
    import jax.numpy as jnp

    b, tq, hd = q.shape
    tk = k.shape[1]
    d = hd // nh
    qh = q.reshape(b, tq, nh, d).transpose(0, 2, 1, 3)
    kh = k.reshape(b, tk, nh, d).transpose(0, 2, 1, 3)
    vh = v.reshape(b, tk, nh, d).transpose(0, 2, 1, 3)
    mask = jnp.asarray(_seg_mask(q_seg, k_seg, causal))[:, None]
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(d)
    s = jnp.where(mask, s, -1e30)
    p = jnp.where(mask, jnp.exp(s - jnp.max(s, -1, keepdims=True)), 0.0)
    l = jnp.sum(p, -1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p / jnp.maximum(l, 1e-30), vh)
    return o.transpose(0, 2, 1, 3).reshape(b, tq, hd)


def _uneven_segs(b, t, rng, max_seg=4, pad_last=True):
    """Packed rows with uneven bucket boundaries; row b-1 gets a long pad
    tail, row 0 is entirely pad (a fully-masked query/key row)."""
    segs = np.zeros((b, t), np.int32)
    for i in range(1, b):
        pos = 0
        for sid in range(1, max_seg + 1):
            ln = int(rng.randint(3, max(4, t // max_seg)))
            if pos + ln > t or (sid == max_seg and pad_last and i == b - 1):
                break
            segs[i, pos:pos + ln] = sid
            pos += ln
    return segs


def _sparse_mod():
    import importlib
    return importlib.import_module(
        "paddle_tpu.ops.pallas_kernels.flash_attention")


@pytest.mark.parametrize("causal", [False, True])
def test_sparse_matches_dense_reference(causal):
    """jax fallback path (blocks < 64): uneven buckets incl. a fully
    pad row, fwd + all three grads vs the dense boolean-mask reference."""
    import jax
    import jax.numpy as jnp
    fa = _sparse_mod()

    b, t, nh, d = 3, 48, 2, 16
    rng = np.random.RandomState(0)
    seg = _uneven_segs(b, t, rng)
    q, k, v = (jnp.asarray(_rand((b, t, nh * d), i)) for i in range(3))

    got = fa.flash_attention_packed_sparse(q, k, v, nh, seg, seg,
                                           causal=causal)
    ref = _ref_sparse(q, k, v, nh, seg, seg, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # pad queries: exactly zero, not just close
    assert not np.asarray(got)[0].any()

    dy = jnp.asarray(_rand((b, t, nh * d), 7))
    gg = jax.grad(lambda *a: jnp.sum(
        fa.flash_attention_packed_sparse(*a, nh, seg, seg, causal=causal)
        * dy), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(
        _ref_sparse(*a, nh, seg, seg, causal) * dy),
        argnums=(0, 1, 2))(q, k, v)
    for a, r, nm in zip(gg, gr, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-4, atol=2e-4, err_msg=nm)
        # grads flowing into the pad row are exactly zero
        assert not np.asarray(a)[0].any(), nm


@pytest.mark.parametrize("causal", [False, True])
def test_sparse_pallas_interpret_matches_reference(causal):
    """Pallas grid path (interpret mode, T=128 ≥ block minimum): fwd +
    grads vs the dense reference on uneven buckets."""
    import jax
    import jax.numpy as jnp
    fa = _sparse_mod()

    b, t, nh, d = 2, 128, 2, 64
    rng = np.random.RandomState(1)
    seg = _uneven_segs(b, t, rng, max_seg=3)
    q, k, v = (jnp.asarray(_rand((b, t, nh * d), i)) for i in range(3))

    fa.FORCE_PALLAS_INTERPRET = True
    try:
        assert fa._sparse_pallas_ok(t, t, d)
        got = fa.flash_attention_packed_sparse(q, k, v, nh, seg, seg,
                                               causal=causal)
        dy = jnp.asarray(_rand((b, t, nh * d), 9))
        gg = jax.grad(lambda *a: jnp.sum(
            fa.flash_attention_packed_sparse(*a, nh, seg, seg,
                                             causal=causal) * dy),
            argnums=(0, 1, 2))(q, k, v)
    finally:
        fa.FORCE_PALLAS_INTERPRET = False
    ref = _ref_sparse(q, k, v, nh, seg, seg, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    gr = jax.grad(lambda *a: jnp.sum(
        _ref_sparse(*a, nh, seg, seg, causal) * dy),
        argnums=(0, 1, 2))(q, k, v)
    for a, r, nm in zip(gg, gr, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-4, atol=2e-4, err_msg=nm)


@pytest.mark.parametrize("dropout", [0.0, 0.15])
def test_sparse_block_skip_is_bitwise_invisible(dropout, monkeypatch):
    """The whole point of the packed descriptor: skipping a fully-masked
    KV block must be BITWISE identical to processing it (the masked lanes
    contribute exact zeros). Compare computed block visibility vs a
    monkeypatched all-visible grid, fwd and bwd, with dropout on."""
    import jax
    import jax.numpy as jnp
    fa = _sparse_mod()

    b, t, nh, d = 2, 128, 2, 64
    rng = np.random.RandomState(2)
    seg = _uneven_segs(b, t, rng, max_seg=3)
    q, k, v = (jnp.asarray(_rand((b, t, nh * d), i)) for i in range(3))
    key = jax.random.PRNGKey(11) if dropout else None
    dy = jnp.asarray(_rand((b, t, nh * d), 5))

    def run():
        def loss(q, k, v):
            return jnp.sum(fa.flash_attention_packed_sparse(
                q, k, v, nh, seg, seg, causal=True,
                dropout_rate=dropout, dropout_key=key) * dy)
        out = fa.flash_attention_packed_sparse(
            q, k, v, nh, seg, seg, causal=True,
            dropout_rate=dropout, dropout_key=key)
        return (out,) + jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    fa.FORCE_PALLAS_INTERPRET = True
    try:
        skipping = run()
        monkeypatch.setattr(
            fa, "_compute_block_vis",
            lambda se, tq, tk, bq, bk, causal: jnp.ones(
                (se.shape[0], -(-tq // bq), -(-tk // bk)), jnp.int32))
        dense_grid = run()
    finally:
        fa.FORCE_PALLAS_INTERPRET = False
    for a, r, nm in zip(skipping, dense_grid, ("out", "dq", "dk", "dv")):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(r),
                                      err_msg=nm)


def test_sparse_cross_attention_uneven_lengths():
    """Cross attention, Tq != Tk: decoder rows attend their own source
    segment only."""
    import jax
    import jax.numpy as jnp
    fa = _sparse_mod()

    b, tq, tk, nh, d = 2, 40, 56, 2, 16
    rng = np.random.RandomState(4)
    q_seg = _uneven_segs(b, tq, rng, max_seg=3)
    k_seg = _uneven_segs(b, tk, rng, max_seg=3)
    q = jnp.asarray(_rand((b, tq, nh * d), 0))
    k = jnp.asarray(_rand((b, tk, nh * d), 1))
    v = jnp.asarray(_rand((b, tk, nh * d), 2))

    got = fa.flash_attention_packed_sparse(q, k, v, nh, q_seg, k_seg)
    ref = _ref_sparse(q, k, v, nh, q_seg, k_seg, False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    dy = jnp.asarray(_rand((b, tq, nh * d), 8))
    gg = jax.grad(lambda *a: jnp.sum(fa.flash_attention_packed_sparse(
        *a, nh, q_seg, k_seg) * dy), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(
        _ref_sparse(*a, nh, q_seg, k_seg, False) * dy),
        argnums=(0, 1, 2))(q, k, v)
    for a, r, nm in zip(gg, gr, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-4, atol=2e-4, err_msg=nm)


def test_sparse_dropout_deterministic_and_scaled():
    """Dropout keyed by logical block index: same key -> bitwise same,
    different key -> different, and the kept mass is 1/(1-rate) scaled."""
    import jax
    import jax.numpy as jnp
    fa = _sparse_mod()

    b, t, nh, d = 2, 48, 2, 16
    rng = np.random.RandomState(6)
    seg = _uneven_segs(b, t, rng)
    q, k, v = (jnp.asarray(_rand((b, t, nh * d), i)) for i in range(3))
    k1, k2 = jax.random.PRNGKey(0), jax.random.PRNGKey(1)

    a1 = fa.flash_attention_packed_sparse(q, k, v, nh, seg, seg,
                                          dropout_rate=0.3, dropout_key=k1)
    a2 = fa.flash_attention_packed_sparse(q, k, v, nh, seg, seg,
                                          dropout_rate=0.3, dropout_key=k1)
    a3 = fa.flash_attention_packed_sparse(q, k, v, nh, seg, seg,
                                          dropout_rate=0.3, dropout_key=k2)
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    assert np.abs(np.asarray(a1) - np.asarray(a3)).max() > 1e-4
    with pytest.raises(ValueError):
        fa.flash_attention_packed_sparse(q, k, v, nh, seg, seg,
                                         dropout_rate=0.3)


def test_sparse_op_and_layer():
    """flash_attention_sparse as a program op: lowering matches the direct
    kernel call on the same inputs."""
    import jax.numpy as jnp
    import paddle_tpu as fluid
    from paddle_tpu import layers
    fa = _sparse_mod()

    b, t, nh, d = 2, 32, 2, 8
    rng = np.random.RandomState(5)
    seg = _uneven_segs(b, t, rng, max_seg=2)
    q, k, v = (_rand((b, t, nh * d), i) for i in range(3))

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        qv = layers.data("q", [t, nh * d])
        kv = layers.data("k", [t, nh * d])
        vv = layers.data("v", [t, nh * d])
        qs = layers.data("q_seg", [t], dtype="int32")
        ks = layers.data("k_seg", [t], dtype="int32")
        out = layers.flash_attention_sparse(qv, kv, vv, nh, qs, ks,
                                            causal=True)
    exe = fluid.Executor(fluid.TPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        got = exe.run(main, feed={"q": q, "k": k, "v": v,
                                  "q_seg": seg, "k_seg": seg},
                      fetch_list=[out])[0]
    ref = fa.flash_attention_packed_sparse(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), nh, seg, seg,
        causal=True)
    np.testing.assert_allclose(got, np.asarray(ref), rtol=2e-5, atol=2e-5)
