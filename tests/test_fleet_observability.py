"""Cross-process observability (ISSUE 13): trace context propagation
over the PS and fleet RPC planes, metrics federation, and the autoscaler
signal surface.

The load-bearing claims: (1) one routed request / one training step is
ONE distributed trace — client spans in the caller, server spans in the
pserver / worker subprocess, linked by trace_id/parent_id over the
existing JSON frame header, surviving torn-frame retries with the same
trace_id; (2) a `FederatedScraper` sweep reaches every process kind
(HTTP introspection, pserver socket op, in-process handle), re-exports
with process/role/shard labels through the SAME renderer as local
/metrics, and distills the ROADMAP-5 autoscaler gauges; (3) the fleet
timeline merger aligns per-process clocks from RPC send/recv pairs and
draws flow arrows.
"""
import json
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid  # noqa: F401  (backend init, scope fixtures)
from paddle_tpu.observability import context as trace_ctx
from paddle_tpu.observability.federate import (FederatedScraper,
                                               ScrapeTarget,
                                               install_scraper)
from paddle_tpu.observability.registry import (Registry, get_registry,
                                               render_prometheus)
from paddle_tpu.observability.tracer import (get_tracer, server_span,
                                             start_trace, trace_span)
from paddle_tpu.ps import (EmbeddingShard, RangeSpec, ShardServer,
                           SocketClient)

from test_ps_faults import _TearingProxy, _fast_retry

V = 64


def _events(trace=None):
    """Non-metadata events of a chrome trace (default: local tracer)."""
    trace = trace or get_tracer().export_chrome_trace()
    return [e for e in trace["traceEvents"] if e.get("ph") != "M"]


def _spans_named(events, name):
    return [e for e in events if e.get("name") == name
            and e.get("ph") == "B"]


# -- context ---------------------------------------------------------------

def test_trace_context_identity_and_wire():
    root = trace_ctx.new_trace()
    assert root.parent_id is None
    child = root.child()
    assert child.trace_id == root.trace_id
    assert child.span_id != root.span_id
    assert child.parent_id == root.span_id
    # server-side adoption: fresh span in the sender's trace, parented
    # to the SENDER'S span (not its parent)
    adopted = trace_ctx.from_wire(child.to_wire())
    assert adopted.trace_id == root.trace_id
    assert adopted.parent_id == child.span_id
    assert adopted.span_id not in (root.span_id, child.span_id)
    # malformed headers never fail an RPC
    for bad in (None, "x", {}, {"trace_id": "t"}, {"trace_id": 3,
                                                   "span_id": "s"}):
        assert trace_ctx.from_wire(bad) is None


def test_trace_context_thread_local_use():
    assert trace_ctx.current() is None
    ctx = trace_ctx.new_trace()
    with trace_ctx.use(ctx):
        assert trace_ctx.current() is ctx
        seen = []
        t = threading.Thread(  # thread-locals don't follow threads...
            target=lambda: seen.append(trace_ctx.current()))
        t.start()
        t.join()
        assert seen == [None]
        # ...the hop idiom re-activates the captured context
        t = threading.Thread(
            target=lambda: [seen.append(trace_ctx.current())
                            for _ in [trace_ctx.use(ctx).__enter__()]])
        t.start()
        t.join()
        assert seen[-1] is ctx
    assert trace_ctx.current() is None
    with trace_ctx.use(None):  # no-op form: call sites don't branch
        assert trace_ctx.current() is None


def test_spans_stamp_distributed_ids():
    tr = get_tracer()
    tr.clear()
    with trace_span("plain"):  # no active trace: no ids, no cost
        pass
    with start_trace("root") as _:
        root = trace_ctx.current()
        with trace_span("inner"):
            inner = trace_ctx.current()
            assert inner.trace_id == root.trace_id
            assert inner.parent_id == root.span_id
    assert trace_ctx.current() is None
    evs = _events()
    (plain,) = _spans_named(evs, "plain")
    assert "trace_id" not in (plain.get("args") or {})
    (root_ev,) = _spans_named(evs, "root")
    (inner_ev,) = _spans_named(evs, "inner")
    assert root_ev["args"]["trace_id"] == inner_ev["args"]["trace_id"]
    assert inner_ev["args"]["parent_id"] == root_ev["args"]["span_id"]
    # server_span with a bad header degrades to a plain local span
    with server_span("srv", None):
        pass
    (srv,) = _spans_named(_events(), "srv")
    assert "trace_id" not in (srv.get("args") or {})


# -- satellite 1: exposition conformance local vs federated ----------------

def test_prometheus_federated_output_matches_local():
    """`prometheus_text` == `render_prometheus(series())` by
    construction; the federated renderer must emit IDENTICAL lines plus
    appended process/role labels — same # TYPE lines, same escaping of
    hostile label values (quotes, backslashes, newlines)."""
    reg = Registry()
    hostile = 'x:f32[8,128] "quoted" back\\slash\nnewline'
    reg.counter("t/reqs", sig=hostile).inc(3)
    reg.gauge("t/depth").set(2.0)
    reg.histogram("t/lat_ms", sig=hostile).observe(1.5)
    local = reg.prometheus_text(deep=True)
    assert local == render_prometheus(reg.series(deep=True))
    # one # TYPE line per metric name, typed correctly
    assert local.count("# TYPE t_reqs counter") == 1
    assert local.count("# TYPE t_depth gauge") == 1
    assert local.count("# TYPE t_lat_ms summary") == 1
    # escaping: raw newline/quote/backslash never appear un-escaped
    esc = 'x:f32[8,128] \\"quoted\\" back\\\\slash\\nnewline'
    assert f'sig="{esc}"' in local

    fed = FederatedScraper(
        [ScrapeTarget.call(lambda: reg.series(deep=True),
                           name='w "1"', role="worker")]
    ).prometheus_text(refresh=True)
    # by construction: the federated text IS the shared renderer with
    # extra labels, nothing else
    assert fed == render_prometheus(
        reg.series(deep=True),
        extra_labels=(("process", 'w "1"'), ("role", "worker")))
    # every labeled local sample reappears verbatim with the target
    # labels appended inside the same brace group (quantile pseudo-label
    # sorts after the extras, checked separately below)
    for line in local.splitlines():
        if line.startswith("#") or "{" not in line or "quantile=" in line:
            continue
        head, tail = line.rsplit("}", 1)
        assert f'{head},process="w \\"1\\"",role="worker"}}{tail}' in fed
    assert (f't_lat_ms{{sig="{esc}",process="w \\"1\\"",role="worker",'
            'quantile="0.5"} 1.5') in fed
    # label-less local samples gain a brace group in federated output
    assert 't_depth{process="w \\"1\\"",role="worker"} 2.0' in fed
    assert fed.count("# TYPE t_depth gauge") == 1


# -- satellite 4: trace propagation across real sockets --------------------

def test_ps_trace_propagates_to_subprocess_shard_server():
    """A pull against a REAL pserver subprocess: the server-side span
    comes back (trace_export op) carrying the client's trace_id and the
    client RPC span's id as parent."""
    import os
    runner = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "ps_server_runner.py")
    p = subprocess.Popen([sys.executable, runner, "--port", "0",
                          "--table", f"tb:0:{V}"],
                         stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, text=True)
    try:
        ep = p.stdout.readline().strip()
        assert ep, "pserver runner died at boot"
        get_tracer().clear()
        c = SocketClient(ep, retries=0)
        try:
            with start_trace("test/req"):
                root = trace_ctx.current()
                c.pull("tb", np.array([1, 5, V - 1], dtype=np.int64))
            remote = c.trace_export()
        finally:
            c.close()
        # client side: ps/rpc/pull span in OUR trace
        (cli,) = [e for e in _spans_named(_events(), "ps/rpc/pull")
                  if (e.get("args") or {}).get("trace_id")
                  == root.trace_id]
        assert cli["args"]["rpc"] == "client"
        assert cli["args"]["endpoint"] == ep
        # server side: ps/pull span in the SUBPROCESS trace, parented to
        # the client span
        srv_spans = [e for e in _spans_named(_events(remote), "ps/pull")
                     if (e.get("args") or {}).get("trace_id")
                     == root.trace_id]
        assert len(srv_spans) == 1
        assert srv_spans[0]["args"]["parent_id"] == cli["args"]["span_id"]
        assert srv_spans[0]["args"]["rpc"] == "server"
        assert srv_spans[0]["pid"] != cli["pid"]
    finally:
        p.kill()
        p.wait()


def test_torn_frame_retry_keeps_trace_id_fresh_span(monkeypatch):
    """A torn reply forces a re-send: the retry attempt must be a SECOND
    client span in the SAME trace — fresh span_id, `retry: 1` tag — so
    the timeline shows two RPCs, not a forked trace."""
    _fast_retry(monkeypatch)
    srv = ShardServer([EmbeddingShard("tb", 0, V)]).serve_in_thread()
    proxy = _TearingProxy(srv.endpoint)
    proxy.start()
    c = SocketClient(proxy.endpoint)
    try:
        get_tracer().clear()
        with start_trace("test/torn"):
            root = trace_ctx.current()
            c.pull("tb", np.array([1, 2], dtype=np.int64))
        assert proxy.tears_left == 0
        attempts = [e for e in _spans_named(_events(), "ps/rpc/pull")
                    if (e.get("args") or {}).get("trace_id")
                    == root.trace_id]
        assert len(attempts) == 2
        first, second = sorted(attempts, key=lambda e: e["ts"])
        assert "retry" not in first["args"]
        assert second["args"]["retry"] == 1
        assert second["args"]["span_id"] != first["args"]["span_id"]
    finally:
        c.close()
        proxy.stop()
        srv.stop()


def test_fleet_worker_rpc_propagates_trace(xla_8dev_subprocess_env):
    """The other RPC plane: a ProcessReplica infer carries the header to
    the fleet worker subprocess, whose `serve/infer` server span adopts
    the caller's trace."""
    import test_serving_fleet as tsf
    from paddle_tpu.serving.fleet.registry import ModelRegistry
    from paddle_tpu.serving.fleet.replica import ProcessReplica

    d = tsf._save_mlp("/tmp/pdtpu_obs_worker_model", seed=3)
    mv = ModelRegistry().register("v1", d)
    rep = None
    try:
        rep = ProcessReplica("r0", mv, buckets=tsf.BUCKETS,
                             env=xla_8dev_subprocess_env,
                             server_kwargs={"max_batch_delay_ms": 1.0})
        get_tracer().clear()
        feed = {"x": np.random.RandomState(0).rand(
            2, tsf.IN_DIM).astype(np.float32)}
        with start_trace("test/infer"):
            root = trace_ctx.current()
            out = rep.submit(feed).result(timeout=120)
        assert out[0].shape == (2, tsf.CLASSES)
        (cli,) = [e for e in _spans_named(_events(), "fleet/rpc/infer")
                  if (e.get("args") or {}).get("trace_id")
                  == root.trace_id]
        remote = rep.trace_export()
        srv = [e for e in _spans_named(_events(remote), "serve/infer")
               if (e.get("args") or {}).get("trace_id") == root.trace_id]
        assert len(srv) == 1
        assert srv[0]["args"]["parent_id"] == cli["args"]["span_id"]
        assert srv[0]["pid"] != cli["pid"]
        # the worker's metrics surface exists too (federation target)
        names = {s["name"] for s in rep.metrics()}
        assert "serving/requests" in names
    finally:
        if rep is not None:
            rep.stop()


# -- federation ------------------------------------------------------------

def test_federated_scraper_merges_and_derives_signals():
    """One sweep over a pserver socket target, an in-process call
    target, and a dead endpoint: per-target labels land in the doc, the
    dead target is recorded (not raised), and the autoscaler gauges
    distill out of the merged series."""
    srv = ShardServer([EmbeddingShard("tb", 0, V)]).serve_in_thread()
    # The in-thread pserver target serves the process-global registry, so
    # straggler anomalies recorded by earlier tests in this process ride
    # along in its series — only the stub's contribution is exact.
    pre_anomalies = sum(
        float(s.get("value") or 0.0)
        for s in get_registry().series(deep=True)
        if s.get("name") == "steps/anomalies")
    stub = [{"name": "ps/shard_pull_ms", "type": "summary",
             "labels": {"shard": "0"},
             "summary": {"count": 4, "sum": 8.0, "p50": 2.0, "p95": 3.0,
                         "p99": 3.5}},
            {"name": "serving/queue_depth", "type": "gauge", "labels": {},
             "value": 7.0},
            {"name": "steps/anomalies", "type": "counter",
             "labels": {"reason": "slow_step"}, "value": 2}]
    try:
        sc = FederatedScraper(
            [ScrapeTarget.ps(srv.endpoint, shard=0),
             ScrapeTarget.call(lambda: stub, name="w0", role="worker"),
             ScrapeTarget.ps("127.0.0.1:9", shard=1)])
        doc = sc.scrape_once()
        assert doc["ok"] is False  # port 9 refused
        by_name = {t["process"]: t for t in doc["targets"]}
        assert by_name["w0"]["ok"] and by_name["w0"]["role"] == "worker"
        ps_t = by_name[f"pserver:{srv.endpoint}"]
        assert ps_t["ok"] and ps_t["shard"] == 0
        assert any(s["name"] == "ps/server_requests"
                   for s in ps_t["series"])
        sig = doc["signals"]
        # per-key: the pserver target may carry real shard_pull/queue
        # series from earlier in-process tests alongside the stub's
        assert sig["ps_pull_p99_ms"]["0"] == 3.5
        assert sig["queue_depth"]["w0"] == 7.0
        assert sig["stragglers"] == 2.0 + pre_anomalies
        assert sig["targets_unreachable"] == 1
        reg = get_registry()
        assert reg.gauge("autoscale/ps_pull_p99_ms",
                         shard="0").value == 3.5
        assert reg.gauge("autoscale/queue_depth",
                         process="w0").value == 7.0
        assert reg.gauge("autoscale/targets_unreachable").value == 1.0
    finally:
        srv.stop()


@pytest.fixture()
def introspection():
    from paddle_tpu.observability import http as ihttp
    s = ihttp.IntrospectionServer(port=0)
    s.start()
    yield s
    s.stop()


def test_fleet_endpoint_and_metrics_series(introspection):
    """/metrics/series is the structured scrape; /fleet 404s with no
    scraper, then serves the federated doc (503 while any target is
    down, 200 when all answer); federated text rides /metrics."""
    from test_observability import _http_get

    code, body = _http_get(introspection.url + "/metrics/series")
    assert code == 200
    series = json.loads(body)
    assert isinstance(series, list) and all("name" in s for s in series)

    code, _ = _http_get(introspection.url + "/fleet")
    assert code == 404
    srv = ShardServer([EmbeddingShard("tb", 0, V)]).serve_in_thread()
    sc = FederatedScraper([
        ScrapeTarget.ps(srv.endpoint, shard=0),
        ScrapeTarget.http(introspection.url, name="self", role="worker")])
    install_scraper(sc)
    try:
        code, body = _http_get(introspection.url + "/fleet")
        assert code == 200
        doc = json.loads(body)
        assert doc["ok"] is True
        assert {t["process"] for t in doc["targets"]} == {
            f"pserver:{srv.endpoint}", "self"}
        # the last scrape's federated text is appended to /metrics with
        # per-process labels
        code, body = _http_get(introspection.url + "/metrics")
        assert code == 200
        assert f'process="pserver:{srv.endpoint}"' in body
        assert 'shard="0"' in body
        srv.stop()
        code, body = _http_get(introspection.url + "/fleet")
        assert code == 503
        assert json.loads(body)["ok"] is False
    finally:
        install_scraper(None)
        srv.stop()
    code, _ = _http_get(introspection.url + "/fleet")
    assert code == 404


def test_ps_admin_fleet_subcommand(capsys):
    """Operator surface: one table row per process, exit 0 when every
    scrape answered, 1 when any failed, --json emits the /fleet doc."""
    from paddle_tpu.tools import ps_admin

    srv = ShardServer([EmbeddingShard("tb", 0, V)]).serve_in_thread()
    try:
        rc = ps_admin.main(["fleet", "--endpoints", srv.endpoint])
        out = capsys.readouterr().out
        assert rc == 0
        assert "pserver" in out and "autoscaler signals:" in out
        rc = ps_admin.main(["fleet", "--endpoints",
                            srv.endpoint + ",127.0.0.1:9", "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 1 and doc["ok"] is False
        assert [t["ok"] for t in doc["targets"]] == [True, False]
        # no endpoints anywhere is a usage error, not a crash
        with pytest.raises(SystemExit):
            ps_admin.main(["fleet", "--endpoints", ""])
    finally:
        srv.stop()


# -- timeline merge --------------------------------------------------------

def test_merge_fleet_traces_aligns_clocks_and_links():
    """Two processes whose perf_counter epochs differ by 5000 us: the
    RPC send/recv pair recovers the offset, the server span lands inside
    the client span on the merged timeline, s/f flow events link them,
    and each source keeps its own pid."""
    from paddle_tpu.tools.timeline import merge_fleet_traces

    client = {"traceEvents": [
        {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
         "args": {"name": "client host"}},
        {"name": "fleet/rpc/infer", "ph": "B", "ts": 100.0, "pid": 1,
         "tid": 7, "args": {"rpc": "client", "trace_id": "t1",
                            "span_id": "c1"}},
        {"name": "fleet/rpc/infer", "ph": "E", "ts": 200.0, "pid": 1,
         "tid": 7}]}
    server = {"traceEvents": [
        {"name": "serve/infer", "ph": "B", "ts": 5120.0, "pid": 1,
         "tid": 9, "args": {"rpc": "server", "trace_id": "t1",
                            "span_id": "s1", "parent_id": "c1"}},
        {"name": "serve/infer", "ph": "E", "ts": 5180.0, "pid": 1,
         "tid": 9}]}
    merged = merge_fleet_traces([client, server], ["client", "server"])
    evs = merged["traceEvents"]
    (srv_b,) = [e for e in evs if e.get("name") == "serve/infer"
                and e.get("ph") == "B"]
    (cli_b,) = [e for e in evs if e.get("name") == "fleet/rpc/infer"
                and e.get("ph") == "B"]
    # theta = ((5120-100)+(5180-200))/2 = 5000 -> 5120 aligns to 120
    assert srv_b["ts"] == pytest.approx(120.0)
    assert cli_b["ts"] == pytest.approx(100.0)
    assert srv_b["pid"] != cli_b["pid"]  # distinct tracks per process
    flows = [e for e in evs if e.get("ph") in ("s", "f")]
    assert sorted(e["ph"] for e in flows) == ["f", "s"]
    assert len({e["id"] for e in flows}) == 1
    names = [e["args"]["name"] for e in evs
             if e.get("name") == "process_name"]
    assert any("client" in n for n in names)
    assert any("server" in n for n in names)


# -- satellite 2: anomalies as instant events ------------------------------

def test_step_anomalies_emit_instant_and_flight_events():
    from paddle_tpu.observability.flight import get_flight_recorder
    from paddle_tpu.observability.steps import StepProfiler

    reg = get_registry()
    get_tracer().clear()
    prof = StepProfiler(window=64, min_samples=8)
    slow0 = reg.counter("steps/anomalies", reason="slow_step").value
    rec0 = reg.counter("steps/anomalies", reason="recompile").value
    for _ in range(10):
        prof.record(1.0, program_id=1, sig="s", sample_env=False)
    prof.record(50.0, program_id=1, sig="s", sample_env=False)
    prof.record(5.0, program_id=1, sig="s", compiled=True,
                sample_env=False)
    assert reg.counter("steps/anomalies",
                       reason="slow_step").value == slow0 + 1
    assert reg.counter("steps/anomalies",
                       reason="recompile").value == rec0 + 1
    evs = [e for e in _events() if e.get("ph") == "i"]
    (slow,) = [e for e in evs if e["name"] == "steps/slow_step"]
    assert slow["args"]["reason"] == "slow_step"
    assert slow["args"]["wall_ms"] == 50.0
    assert slow["args"]["deviation"] >= 1
    assert any(e["name"] == "steps/recompile" for e in evs)
    flight = [e for e in get_flight_recorder().contents()["events"]
              if e.get("reason") in ("slow_step", "recompile")]
    assert len(flight) >= 2


# -- end to end: step-rooted PS trace --------------------------------------

def test_train_step_roots_one_trace_across_shard_pulls():
    """`PsEmbeddingTier.run_step` roots a trace; the pulls it triggers
    (socket RPCs on pool threads) must join it, proving the thread-hop
    re-activation in ShardedTable works under the real tier."""
    from paddle_tpu.ps import ShardedTable, make_shards

    spec = RangeSpec.even(V, 2)
    servers = [ShardServer([sh]).serve_in_thread()
               for sh in make_shards("tb", spec)]
    table = ShardedTable("tb", spec,
                         [SocketClient(s.endpoint) for s in servers])
    try:
        get_tracer().clear()
        with start_trace("ps/train_step"):
            root = trace_ctx.current()
            table.pull(np.arange(V, dtype=np.int64))
        pulls = [e for e in _spans_named(_events(), "ps/rpc/pull")
                 if (e.get("args") or {}).get("trace_id")
                 == root.trace_id]
        # one client RPC span per shard, all in the step's trace even
        # though they ran on pool threads
        assert len(pulls) == 2
        assert {e["args"]["endpoint"] for e in pulls} == {
            s.endpoint for s in servers}
    finally:
        table.close()
        for s in servers:
            s.stop()


# -- target churn (ISSUE 17 satellite) -------------------------------------

def test_scraper_target_churn_retires_stale_autoscale_gauges():
    """An autoscaled fleet adds and removes targets between sweeps. The
    distilled autoscale/* gauges must follow: a vanished shard/process
    leaves NO stale gauge behind (an autoscaler keying on it would act
    on a ghost), and re-adding a target under the SAME name replaces
    the old one instead of double-counting its series."""
    def stub(shard, depth):
        return [{"name": "ps/shard_pull_ms", "type": "summary",
                 "labels": {"shard": str(shard)},
                 "summary": {"count": 4, "sum": 8.0, "p50": 2.0,
                             "p95": 3.0, "p99": 3.5}},
                {"name": "serving/queue_depth", "type": "gauge",
                 "labels": {}, "value": float(depth)}]

    reg = get_registry()
    sc = FederatedScraper(
        [ScrapeTarget.call(lambda: stub(77, 5), name="churn-a",
                           role="worker"),
         ScrapeTarget.call(lambda: stub(78, 9), name="churn-b",
                           role="worker")])
    try:
        sc.scrape_once()
        assert reg.gauge("autoscale/ps_pull_p99_ms",
                         shard="77").value == 3.5
        assert reg.gauge("autoscale/queue_depth",
                         process="churn-b").value == 9.0

        # target vanishes: its per-shard and per-process gauges retire
        # on the next sweep rather than freezing at the last value
        assert sc.remove_target("churn-b") is True
        assert sc.remove_target("churn-b") is False  # already gone
        doc = sc.scrape_once()
        assert {t["process"] for t in doc["targets"]} == {"churn-a"}
        live = {(s["name"], tuple(sorted(s["labels"].items())))
                for s in reg.series()}
        assert ("autoscale/ps_pull_p99_ms",
                (("shard", "78"),)) not in live
        assert ("autoscale/queue_depth",
                (("process", "churn-b"),)) not in live
        assert ("autoscale/ps_pull_p99_ms", (("shard", "77"),)) in live

        # same-name re-add REPLACES: one target row, one series set, the
        # new reader's numbers (not a sum with the stale registration)
        sc.add_target(ScrapeTarget.call(lambda: stub(78, 2),
                                        name="churn-b", role="worker"))
        sc.add_target(ScrapeTarget.call(lambda: stub(78, 4),
                                        name="churn-b", role="worker"))
        doc = sc.scrape_once()
        rows = [t for t in doc["targets"] if t["process"] == "churn-b"]
        assert len(rows) == 1
        assert doc["signals"]["queue_depth"]["churn-b"] == 4.0
        assert reg.gauge("autoscale/queue_depth",
                         process="churn-b").value == 4.0
        assert reg.gauge("autoscale/ps_pull_p99_ms",
                         shard="78").value == 3.5
    finally:
        for g in (("autoscale/ps_pull_p99_ms", {"shard": "77"}),
                  ("autoscale/ps_pull_p99_ms", {"shard": "78"}),
                  ("autoscale/queue_depth", {"process": "churn-a"}),
                  ("autoscale/queue_depth", {"process": "churn-b"})):
            reg.remove(g[0], **g[1])
