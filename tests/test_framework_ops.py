"""Framework/runtime op checks (save/load ops, coalesce_tensor,
average_accumulates, LoD workflow machinery parity)."""
import numpy as np

from op_test_base import OpTest


class _T(OpTest):
    pass


def test_save_load_roundtrip(tmp_path):
    path = str(tmp_path / "var.pkl")
    x = np.random.RandomState(0).randn(3, 4).astype("float32")
    t = _T(); t.op_type = "save"
    t.run_op({"X": x}, attrs={"file_path": path}, output_slots=())
    t2 = _T(); t2.op_type = "load"
    out = t2.run_op({}, attrs={"file_path": path})
    np.testing.assert_allclose(out["Out"], x)


def test_save_combine_load_combine(tmp_path):
    path = str(tmp_path / "bundle.pkl")
    a = np.ones((2, 2), "float32")
    b = np.arange(3, dtype="float32")
    t = _T(); t.op_type = "save_combine"
    t.run_op({"X": [a, b]}, attrs={"file_path": path}, output_slots=())
    t2 = _T(); t2.op_type = "load_combine"
    out = t2.run_op({}, attrs={"file_path": path},
                    multi_output_counts={"Out": 2})
    np.testing.assert_allclose(out["Out"][0], a)
    np.testing.assert_allclose(out["Out"][1], b)


def test_coalesce_tensor_views():
    t = _T(); t.op_type = "coalesce_tensor"
    a = np.ones((2, 3), "float32")
    b = 2 * np.ones((4,), "float32")
    out = t.run_op({"Input": [a, b]}, output_slots=("Output", "FusedOutput"),
                   multi_output_counts={"Output": 2})
    assert out["FusedOutput"].shape == (10,)
    np.testing.assert_allclose(out["Output"][0], a)
    np.testing.assert_allclose(out["Output"][1], b)
    np.testing.assert_allclose(out["FusedOutput"][:6], 1.0)
    np.testing.assert_allclose(out["FusedOutput"][6:], 2.0)


def test_average_accumulates_window_cascade():
    t = _T(); t.op_type = "average_accumulates"
    p = np.full((2,), 3.0, "float32")
    zeros = np.zeros((2,), "float32")
    cnt = np.zeros((), "int64")
    # min window 2: first call accumulates, second call closes the window
    s1, s2, s3 = zeros, zeros, zeros
    na, no, nu = cnt, cnt, cnt
    for step in range(2):
        out = t.run_op(
            {"param": p, "in_sum_1": s1, "in_sum_2": s2, "in_sum_3": s3,
             "in_num_accumulates": na, "in_old_num_accumulates": no,
             "in_num_updates": nu},
            attrs={"average_window": 1.0, "max_average_window": 2,
                   "min_average_window": 2},
            output_slots=("out_sum_1", "out_sum_2", "out_sum_3",
                          "out_num_accumulates", "out_old_num_accumulates",
                          "out_num_updates"))
        s1, s2, s3 = out["out_sum_1"], out["out_sum_2"], out["out_sum_3"]
        na, no, nu = (out["out_num_accumulates"],
                      out["out_old_num_accumulates"], out["out_num_updates"])
    # reference cascade: sum_3 takes the closed window, sum_1/sum_2 reset,
    # old_num ASSIGNED the window size
    np.testing.assert_allclose(s1, 0.0)
    np.testing.assert_allclose(s2, 0.0)
    np.testing.assert_allclose(s3, 6.0)
    assert int(no) == 2 and int(nu) == 2 and int(na) == 0
    # downstream ModelAverage estimate: (s1+s2+s3)/(na+no) == param
    np.testing.assert_allclose(
        (s1 + s2 + s3) / (int(na) + int(no)), p, rtol=1e-6)


def test_lod_rank_table_sorts_by_length():
    t = _T(); t.op_type = "lod_rank_table"
    x = np.zeros((3, 5, 2), "float32")
    length = np.array([2, 5, 3], "int32")
    out = t.run_op({"X": x, "Length": length})
    np.testing.assert_array_equal(out["Out"][:, 0], [1, 2, 0])
    np.testing.assert_array_equal(out["Out"][:, 1], [5, 3, 2])


def test_reorder_and_shrink_rnn_memory():
    t = _T(); t.op_type = "lod_rank_table"
    x = np.arange(12, dtype="float32").reshape(3, 2, 2)
    length = np.array([1, 2, 1], "int32")
    table = t.run_op({"X": x, "Length": length})["Out"]
    t2 = _T(); t2.op_type = "reorder_lod_tensor_by_rank"
    ordered = t2.run_op({"X": x, "RankTable": table})["Out"]
    np.testing.assert_allclose(ordered[0], x[1])   # longest first
    t3 = _T(); t3.op_type = "shrink_rnn_memory"
    # shrink consumes X in RANK-TABLE order (reorder output), like the
    # reference DynamicRNN program
    out = t3.run_op({"X": ordered, "RankTable": table,
                     "I": np.array([1], "int64")})["Out"]
    # at step 1 only the longest sequence (orig sample 1, rank row 0) lives
    np.testing.assert_allclose(out[0], x[1])
    np.testing.assert_allclose(out[1], 0.0)
    np.testing.assert_allclose(out[2], 0.0)


def test_split_merge_lod_tensor_roundtrip():
    x = np.arange(8, dtype="float32").reshape(4, 2)
    mask = np.array([[1], [0], [1], [0]], "bool")
    t = _T(); t.op_type = "split_lod_tensor"
    parts = t.run_op({"X": x, "Mask": mask},
                     output_slots=("OutTrue", "OutFalse"))
    np.testing.assert_allclose(parts["OutTrue"][0], x[0])
    np.testing.assert_allclose(parts["OutTrue"][1], 0.0)
    t2 = _T(); t2.op_type = "merge_lod_tensor"
    merged = t2.run_op({"InTrue": parts["OutTrue"],
                        "InFalse": parts["OutFalse"], "Mask": mask})["Out"]
    np.testing.assert_allclose(merged, x)


def test_lod_tensor_array_roundtrip():
    x = np.random.RandomState(0).randn(2, 3, 4).astype("float32")
    t = _T(); t.op_type = "lod_tensor_to_array"
    tm = t.run_op({"X": x})["Out"]
    assert tm.shape == (3, 2, 4)
    t2 = _T(); t2.op_type = "array_to_lod_tensor"
    back = t2.run_op({"X": tm})["Out"]
    np.testing.assert_allclose(back, x)


def test_fake_init_and_get_places():
    t = _T(); t.op_type = "fake_init"
    out = t.run_op({}, attrs={"shape": [2, 3], "dtype": "float32"})
    assert out["Out"].shape == (2, 3)
    t2 = _T(); t2.op_type = "get_places"
    places = t2.run_op({}, attrs={"device_count": 4})["Out"]
    np.testing.assert_array_equal(places, [0, 1, 2, 3])


def test_sync_batch_norm_matches_batch_norm():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 3, 2, 2).astype("float32")
    scale = np.ones((3,), "float32")
    bias = np.zeros((3,), "float32")
    mean = np.zeros((3,), "float32")
    var = np.ones((3,), "float32")
    ins = {"X": x, "Scale": scale, "Bias": bias, "Mean": mean, "Variance": var}
    slots = ("Y",)
    t = _T(); t.op_type = "sync_batch_norm"
    a = t.run_op(dict(ins), attrs={"epsilon": 1e-5}, output_slots=slots)
    t2 = _T(); t2.op_type = "batch_norm"
    b = t2.run_op(dict(ins), attrs={"epsilon": 1e-5}, output_slots=slots)
    np.testing.assert_allclose(a["Y"], b["Y"], rtol=1e-5)
