"""Pallas fused batch-norm kernel (interpret mode) vs the jnp reference, and
the batch_norm layer's act-folding contract.

The kernel is opt-in on TPU (PDTPU_BN_MODE=pallas; measured slower than the
default one-pass XLA lowering on v5e, kept for other-chip experiments), but
its numerics must stay correct either way.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops.pallas_kernels import fused_bn


def _ref_bn(x, scale, bias, eps, act, residual=None):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=(0, 2, 3))
    var = jnp.var(xf, axis=(0, 2, 3))
    sh = (1, x.shape[1], 1, 1)
    y = ((xf - mean.reshape(sh)) * jax.lax.rsqrt(var.reshape(sh) + eps)
         * scale.reshape(sh) + bias.reshape(sh))
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype), mean, var


@pytest.fixture(autouse=True)
def _interpret():
    fused_bn.FORCE_PALLAS_INTERPRET = True
    yield
    fused_bn.FORCE_PALLAS_INTERPRET = False


@pytest.mark.parametrize("shape,act", [
    ((4, 16, 8, 32), "relu"),
    ((4, 16, 8, 32), ""),
    ((2, 32, 16, 16), "relu"),
])
def test_fused_bn_forward_and_grads(shape, act):
    rng = np.random.RandomState(0)
    n, c, h, w = shape
    x = jnp.asarray(rng.randn(*shape).astype("float32") * 1.5 + 0.3)
    scale = jnp.asarray(rng.rand(c).astype("float32") + 0.5)
    bias = jnp.asarray(rng.randn(c).astype("float32") * 0.2)
    dy = jnp.asarray(rng.randn(*shape).astype("float32"))

    def loss_p(x, s, b):
        y, m, v = fused_bn.fused_bn_act(x, s, b, 1e-5, act, False)
        return jnp.sum(y * dy), (y, m, v)

    def loss_r(x, s, b):
        y, m, v = _ref_bn(x, s, b, 1e-5, act)
        return jnp.sum(y * dy), (y, m, v)

    (lp, (yp, mp, vp)), gp = jax.value_and_grad(
        loss_p, argnums=(0, 1, 2), has_aux=True)(x, scale, bias)
    (lr, (yr, mr, vr)), gr = jax.value_and_grad(
        loss_r, argnums=(0, 1, 2), has_aux=True)(x, scale, bias)

    np.testing.assert_allclose(np.asarray(yp), np.asarray(yr), atol=1e-5)
    np.testing.assert_allclose(np.asarray(mp), np.asarray(mr), atol=1e-5)
    np.testing.assert_allclose(np.asarray(vp), np.asarray(vr), atol=1e-4,
                               rtol=1e-5)
    for a, b, nm in zip(gp, gr, ("dx", "dscale", "dbias")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4,
                                   rtol=1e-4, err_msg=nm)


def test_fused_bn_residual_grad():
    rng = np.random.RandomState(1)
    shape = (2, 16, 8, 16)
    x = jnp.asarray(rng.randn(*shape).astype("float32"))
    res = jnp.asarray(rng.randn(*shape).astype("float32"))
    scale = jnp.asarray(rng.rand(16).astype("float32") + 0.5)
    bias = jnp.zeros((16,), jnp.float32)
    dy = jnp.asarray(rng.randn(*shape).astype("float32"))

    def loss_p(x, s, b, r):
        y, _, _ = fused_bn.fused_bn_act(x, s, b, 1e-5, "relu", True, r)
        return jnp.sum(y * dy)

    def loss_r(x, s, b, r):
        y, _, _ = _ref_bn(x, s, b, 1e-5, "relu", residual=r)
        return jnp.sum(y * dy)

    gp = jax.grad(loss_p, argnums=(0, 1, 2, 3))(x, scale, bias, res)
    gr = jax.grad(loss_r, argnums=(0, 1, 2, 3))(x, scale, bias, res)
    for a, b, nm in zip(gp, gr, ("dx", "dscale", "dbias", "dres")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4,
                                   rtol=1e-4, err_msg=nm)


def test_batch_norm_layer_act_folding():
    """batch_norm(act='relu') folds the relu into the op (no separate relu
    op in the program) and still produces relu'd output on the default
    lowering."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data("x", [8, 6, 6])
        out = layers.batch_norm(xv, act="relu")
        loss = layers.mean(out)
    assert not any(op.type == "relu" for op in main.global_block().ops)
    bn_ops = [op for op in main.global_block().ops if op.type == "batch_norm"]
    assert bn_ops and bn_ops[0].attrs.get("act") == "relu"

    exe = fluid.Executor(fluid.TPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        x = np.random.RandomState(0).randn(4, 8, 6, 6).astype("float32")
        got = exe.run(main, feed={"x": x}, fetch_list=[out])[0]
    assert (got >= 0).all()
    ref = x - x.mean(axis=(0, 2, 3), keepdims=True)
    ref = ref / np.sqrt(x.var(axis=(0, 2, 3), keepdims=True) + 1e-5)
    np.testing.assert_allclose(got, np.maximum(ref, 0), atol=1e-4)


# ---------------------------------------------------------------------------
# fused 1x1-conv + BN (+residual +relu) epilogue kernels (ISSUE 19)
# ---------------------------------------------------------------------------

def _ref_conv_bn(x, w, scale, bias, eps, act, stride, residual=None):
    return fused_bn.conv_bn_xla(x, w, scale, bias, eps, act, stride,
                                residual=residual)


@pytest.mark.parametrize("stride,act,with_res", [
    (1, "relu", True),
    (1, "", False),
    (2, "relu", False),
    (2, "", True),
])
def test_fused_conv_bn_interpret_parity(stride, act, with_res):
    """Pallas conv+BN kernel (interpret mode) vs the exact XLA composition:
    forward outputs, batch stats, and all five grads."""
    rng = np.random.RandomState(0)
    n, ci, co, hw = 4, 16, 32, 16
    x = jnp.asarray(rng.randn(n, ci, hw, hw).astype("float32"))
    w = jnp.asarray((rng.randn(co, ci, 1, 1) * 0.1).astype("float32"))
    scale = jnp.asarray(rng.rand(co).astype("float32") + 0.5)
    bias = jnp.asarray(rng.randn(co).astype("float32") * 0.2)
    hs = -(-hw // stride)
    res = (jnp.asarray(rng.randn(n, co, hs, hs).astype("float32"))
           if with_res else None)
    dy = jnp.asarray(rng.randn(n, co, hs, hs).astype("float32"))

    def loss_p(x, w, s, b, r):
        y, m, v = fused_bn.fused_conv_bn_act(x, w, s, b, 1e-5, act, stride,
                                             with_res, r)
        return jnp.sum(y * dy), (y, m, v)

    def loss_r(x, w, s, b, r):
        y, m, v = _ref_conv_bn(x, w, s, b, 1e-5, act, stride, residual=r)
        return jnp.sum(y * dy), (y, m, v)

    argnums = (0, 1, 2, 3, 4) if with_res else (0, 1, 2, 3)
    (_, (yp, mp, vp)), gp = jax.value_and_grad(
        loss_p, argnums=argnums, has_aux=True)(x, w, scale, bias, res)
    (_, (yr, mr, vr)), gr = jax.value_and_grad(
        loss_r, argnums=argnums, has_aux=True)(x, w, scale, bias, res)

    np.testing.assert_allclose(np.asarray(yp), np.asarray(yr), atol=1e-5)
    np.testing.assert_allclose(np.asarray(mp), np.asarray(mr), atol=1e-6)
    np.testing.assert_allclose(np.asarray(vp), np.asarray(vr), atol=1e-5,
                               rtol=1e-5)
    names = ("dx", "dw", "dscale", "dbias", "dres")
    for a, b, nm in zip(gp, gr, names):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4,
                                   rtol=1e-4, err_msg=nm)


def test_conv_bn_supports_gate():
    """Static support gate: 1x1 only, stride 1/2, lane-aligned channels,
    enough output rows to tile."""
    ok = fused_bn.conv_bn_supports((8, 64, 16, 16), (128, 64, 1, 1), 1)
    assert ok == fused_bn._HAVE_PALLAS
    assert not fused_bn.conv_bn_supports((8, 64, 16, 16), (128, 64, 3, 3), 1)
    assert not fused_bn.conv_bn_supports((8, 64, 16, 16), (128, 64, 1, 1), 4)
    assert not fused_bn.conv_bn_supports((8, 60, 16, 16), (128, 60, 1, 1), 1)
    assert not fused_bn.conv_bn_supports((1, 64, 8, 8), (128, 64, 1, 1), 1)


def _bottleneck_prog(fusion_mode, ci, filters):
    """Build x -> bottleneck(x) under PDTPU_CONV_BN_FUSION=fusion_mode
    (None = unfused seed graph). Same param names either way."""
    import os

    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    prev = os.environ.get("PDTPU_CONV_BN_FUSION")
    if fusion_mode is None:
        os.environ.pop("PDTPU_CONV_BN_FUSION", None)
    else:
        os.environ["PDTPU_CONV_BN_FUSION"] = fusion_mode
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            from paddle_tpu import layers
            x = layers.data("x", [ci, 8, 8])
            y = resnet.bottleneck(x, filters, 1, "blk")
        return main, startup, y
    finally:
        if prev is None:
            os.environ.pop("PDTPU_CONV_BN_FUSION", None)
        else:
            os.environ["PDTPU_CONV_BN_FUSION"] = prev


def test_fused_conv_bn_e2e_bitwise_at_model_widths():
    """End-to-end contract that makes per-model enablement safe: a resnet
    bottleneck at model widths (256->64->256) built with the fused op
    (XLA lowering) is BITWISE-identical to the unfused seed graph — the
    two programs share one scope and one startup (same param names), so
    the only variable is the lowering."""
    import paddle_tpu as fluid

    fused_main, fused_st, fy = _bottleneck_prog("xla", 256, 64)
    unf_main, _unf_st, uy = _bottleneck_prog(None, 256, 64)
    # the fused graph really did fuse: one op for the .c tail, no separate
    # add/relu on the residual path
    types_f = [op.type for op in fused_main.global_block().ops]
    types_u = [op.type for op in unf_main.global_block().ops]
    assert "fused_conv_bn" in types_f
    assert "fused_conv_bn" not in types_u

    exe = fluid.Executor(fluid.TPUPlace())
    rng = np.random.RandomState(3)
    x = rng.randn(2, 256, 8, 8).astype("float32")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(fused_st)                      # ONE init for both arms
        got_f = exe.run(fused_main, feed={"x": x}, fetch_list=[fy])[0]
        got_u = exe.run(unf_main, feed={"x": x}, fetch_list=[uy])[0]
    assert got_f.shape == (2, 256, 8, 8)
    np.testing.assert_array_max_ulp(got_f, got_u, maxulp=1)
    np.testing.assert_array_equal(got_f, got_u)
