"""Pallas fused batch-norm kernel (interpret mode) vs the jnp reference, and
the batch_norm layer's act-folding contract.

The kernel is opt-in on TPU (PDTPU_BN_MODE=pallas; measured slower than the
default one-pass XLA lowering on v5e, kept for other-chip experiments), but
its numerics must stay correct either way.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops.pallas_kernels import fused_bn


def _ref_bn(x, scale, bias, eps, act, residual=None):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=(0, 2, 3))
    var = jnp.var(xf, axis=(0, 2, 3))
    sh = (1, x.shape[1], 1, 1)
    y = ((xf - mean.reshape(sh)) * jax.lax.rsqrt(var.reshape(sh) + eps)
         * scale.reshape(sh) + bias.reshape(sh))
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype), mean, var


@pytest.fixture(autouse=True)
def _interpret():
    fused_bn.FORCE_PALLAS_INTERPRET = True
    yield
    fused_bn.FORCE_PALLAS_INTERPRET = False


@pytest.mark.parametrize("shape,act", [
    ((4, 16, 8, 32), "relu"),
    ((4, 16, 8, 32), ""),
    ((2, 32, 16, 16), "relu"),
])
def test_fused_bn_forward_and_grads(shape, act):
    rng = np.random.RandomState(0)
    n, c, h, w = shape
    x = jnp.asarray(rng.randn(*shape).astype("float32") * 1.5 + 0.3)
    scale = jnp.asarray(rng.rand(c).astype("float32") + 0.5)
    bias = jnp.asarray(rng.randn(c).astype("float32") * 0.2)
    dy = jnp.asarray(rng.randn(*shape).astype("float32"))

    def loss_p(x, s, b):
        y, m, v = fused_bn.fused_bn_act(x, s, b, 1e-5, act, False)
        return jnp.sum(y * dy), (y, m, v)

    def loss_r(x, s, b):
        y, m, v = _ref_bn(x, s, b, 1e-5, act)
        return jnp.sum(y * dy), (y, m, v)

    (lp, (yp, mp, vp)), gp = jax.value_and_grad(
        loss_p, argnums=(0, 1, 2), has_aux=True)(x, scale, bias)
    (lr, (yr, mr, vr)), gr = jax.value_and_grad(
        loss_r, argnums=(0, 1, 2), has_aux=True)(x, scale, bias)

    np.testing.assert_allclose(np.asarray(yp), np.asarray(yr), atol=1e-5)
    np.testing.assert_allclose(np.asarray(mp), np.asarray(mr), atol=1e-5)
    np.testing.assert_allclose(np.asarray(vp), np.asarray(vr), atol=1e-4,
                               rtol=1e-5)
    for a, b, nm in zip(gp, gr, ("dx", "dscale", "dbias")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4,
                                   rtol=1e-4, err_msg=nm)


def test_fused_bn_residual_grad():
    rng = np.random.RandomState(1)
    shape = (2, 16, 8, 16)
    x = jnp.asarray(rng.randn(*shape).astype("float32"))
    res = jnp.asarray(rng.randn(*shape).astype("float32"))
    scale = jnp.asarray(rng.rand(16).astype("float32") + 0.5)
    bias = jnp.zeros((16,), jnp.float32)
    dy = jnp.asarray(rng.randn(*shape).astype("float32"))

    def loss_p(x, s, b, r):
        y, _, _ = fused_bn.fused_bn_act(x, s, b, 1e-5, "relu", True, r)
        return jnp.sum(y * dy)

    def loss_r(x, s, b, r):
        y, _, _ = _ref_bn(x, s, b, 1e-5, "relu", residual=r)
        return jnp.sum(y * dy)

    gp = jax.grad(loss_p, argnums=(0, 1, 2, 3))(x, scale, bias, res)
    gr = jax.grad(loss_r, argnums=(0, 1, 2, 3))(x, scale, bias, res)
    for a, b, nm in zip(gp, gr, ("dx", "dscale", "dbias", "dres")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4,
                                   rtol=1e-4, err_msg=nm)


def test_batch_norm_layer_act_folding():
    """batch_norm(act='relu') folds the relu into the op (no separate relu
    op in the program) and still produces relu'd output on the default
    lowering."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data("x", [8, 6, 6])
        out = layers.batch_norm(xv, act="relu")
        loss = layers.mean(out)
    assert not any(op.type == "relu" for op in main.global_block().ops)
    bn_ops = [op for op in main.global_block().ops if op.type == "batch_norm"]
    assert bn_ops and bn_ops[0].attrs.get("act") == "relu"

    exe = fluid.Executor(fluid.TPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        x = np.random.RandomState(0).randn(4, 8, 6, 6).astype("float32")
        got = exe.run(main, feed={"x": x}, fetch_list=[out])[0]
    assert (got >= 0).all()
    ref = x - x.mean(axis=(0, 2, 3), keepdims=True)
    ref = ref / np.sqrt(x.var(axis=(0, 2, 3), keepdims=True) + 1e-5)
    np.testing.assert_allclose(got, np.maximum(ref, 0), atol=1e-4)
