"""Fusion/sequence-model op family checks (fused/fusion_*_op.cc,
lstmp_op.cc, warpctc_op.cc, match_matrix_tensor_op.cc parity)."""
import itertools

import numpy as np

from op_test_base import OpTest


class _T(OpTest):
    pass


def _np_softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def test_fc_matches_matmul():
    t = _T(); t.op_type = "fc"
    rng = np.random.RandomState(0)
    x = rng.randn(3, 4).astype("float32")
    w = rng.randn(4, 5).astype("float32")
    b = rng.randn(5).astype("float32")
    out = t.run_op({"Input": x, "W": w, "Bias": b},
                   attrs={"activation_type": "relu"})
    np.testing.assert_allclose(out["Out"], np.maximum(x @ w + b, 0),
                               rtol=1e-4, atol=1e-5)


def test_warpctc_vs_brute_force():
    """CTC loss equals -log sum over all alignments (path enumeration)."""
    rng = np.random.RandomState(0)
    B, T, C, L = 1, 4, 3, 2
    logits = rng.randn(B, T, C).astype("float32")
    label = np.array([[1, 2]], "int32")
    t = _T(); t.op_type = "warpctc"
    out = t.run_op({"Logits": logits, "Label": label,
                    "LogitsLength": np.array([T], "int32"),
                    "LabelLength": np.array([L], "int32")},
                   attrs={"blank": 0}, output_slots=("Loss",))
    # brute force: every length-T path over C symbols that collapses
    # (remove repeats then blanks) to the label
    probs = _np_softmax(logits[0])
    total = 0.0
    for path in itertools.product(range(C), repeat=T):
        collapsed = [k for k, _ in itertools.groupby(path)]
        collapsed = [c for c in collapsed if c != 0]
        if collapsed == [1, 2]:
            p = 1.0
            for step, sym in enumerate(path):
                p *= probs[step, sym]
            total += p
    expected = -np.log(total)
    np.testing.assert_allclose(float(out["Loss"]), expected, rtol=1e-4)


def test_warpctc_respects_lengths():
    """Padding steps/labels beyond the declared lengths must not change
    the loss."""
    rng = np.random.RandomState(1)
    logits = rng.randn(1, 6, 4).astype("float32")
    label = np.array([[2, 3, 0]], "int32")       # only first 2 valid
    t = _T(); t.op_type = "warpctc"
    kw = dict(attrs={"blank": 0}, output_slots=("Loss",))
    l1 = t.run_op({"Logits": logits, "Label": label,
                   "LogitsLength": np.array([4], "int32"),
                   "LabelLength": np.array([2], "int32")}, **kw)
    # garbage in the padded region
    logits2 = logits.copy(); logits2[0, 4:] = 99.0
    label2 = label.copy(); label2[0, 2] = 1
    l2 = t.run_op({"Logits": logits2, "Label": label2,
                   "LogitsLength": np.array([4], "int32"),
                   "LabelLength": np.array([2], "int32")}, **kw)
    np.testing.assert_allclose(float(l1["Loss"]), float(l2["Loss"]), rtol=1e-5)


def test_lstmp_projection_shape_and_dynamics():
    rng = np.random.RandomState(0)
    B, T, H, P = 2, 3, 4, 2
    x = rng.randn(B, T, 4 * H).astype("float32") * 0.1
    w = rng.randn(P, 4 * H).astype("float32") * 0.1
    wp = rng.randn(H, P).astype("float32") * 0.1
    t = _T(); t.op_type = "lstmp"
    out = t.run_op({"Input": x, "Weight": w, "ProjWeight": wp},
                   output_slots=("Projection", "Cell"))
    assert out["Projection"].shape == (B, T, P)
    assert out["Cell"].shape == (B, T, H)
    # projection is bounded by tanh
    assert np.abs(out["Projection"]).max() <= 1.0


def test_fusion_lstm_equals_fc_plus_lstm():
    rng = np.random.RandomState(0)
    B, T, D, H = 2, 3, 4, 5
    x = rng.randn(B, T, D).astype("float32") * 0.3
    wx = rng.randn(D, 4 * H).astype("float32") * 0.3
    wh = rng.randn(H, 4 * H).astype("float32") * 0.3
    b = rng.randn(4 * H).astype("float32") * 0.3
    t = _T(); t.op_type = "fusion_lstm"
    fused = t.run_op({"X": x, "WeightX": wx, "WeightH": wh, "Bias": b},
                     output_slots=("Hidden",))
    t2 = _T(); t2.op_type = "lstm"
    ref = t2.run_op({"Input": (x.reshape(-1, D) @ wx).reshape(B, T, 4 * H),
                     "Weight": wh, "Bias": b}, output_slots=("Hidden",))
    np.testing.assert_allclose(fused["Hidden"], ref["Hidden"],
                               rtol=1e-4, atol=1e-5)


def test_fusion_gru_runs_and_masks():
    rng = np.random.RandomState(0)
    B, T, D, H = 2, 4, 3, 5
    x = rng.randn(B, T, D).astype("float32")
    wx = rng.randn(D, 3 * H).astype("float32") * 0.3
    wh = rng.randn(H, 3 * H).astype("float32") * 0.3
    length = np.array([4, 2], "int32")
    t = _T(); t.op_type = "fusion_gru"
    out = t.run_op({"X": x, "WeightX": wx, "WeightH": wh, "Length": length},
                   output_slots=("Hidden",))
    h = out["Hidden"]
    # beyond sample 1's length the hidden state stays frozen
    np.testing.assert_allclose(h[1, 2], h[1, 1], rtol=1e-6)
    np.testing.assert_allclose(h[1, 3], h[1, 1], rtol=1e-6)


def test_attention_lstm_uniform_attention_at_init():
    rng = np.random.RandomState(0)
    B, T, D, H = 1, 3, 4, 2
    x = rng.randn(B, T, D).astype("float32")
    w_att = np.zeros((D + H, 1), "float32")      # zero scores -> uniform att
    w_lstm = rng.randn(D + H, 4 * H).astype("float32") * 0.1
    t = _T(); t.op_type = "attention_lstm"
    out = t.run_op({"X": x, "AttentionWeight": w_att, "LSTMWeight": w_lstm},
                   output_slots=("Hidden", "Cell"))
    assert out["Hidden"].shape == (B, T, H)
    assert np.isfinite(out["Hidden"]).all()


def test_fused_embedding_seq_pool():
    t = _T(); t.op_type = "fused_embedding_seq_pool"
    w = np.arange(12, dtype="float32").reshape(4, 3)
    ids = np.array([[1, 2, 0], [3, 0, 0]], "int32")
    length = np.array([2, 1], "int32")
    out = t.run_op({"Ids": ids, "W": w, "Length": length})
    np.testing.assert_allclose(out["Out"][0], w[1] + w[2])
    np.testing.assert_allclose(out["Out"][1], w[3])


def test_fusion_seqpool_concat():
    t = _T(); t.op_type = "fusion_seqpool_concat"
    x1 = np.ones((2, 3, 2), "float32")
    x2 = 2 * np.ones((2, 3, 4), "float32")
    l = np.array([3, 1], "int32")
    out = t.run_op({"X": [x1, x2], "Length": [l, l]},
                   attrs={"pooltype": "SUM"})
    assert out["Out"].shape == (2, 6)
    np.testing.assert_allclose(out["Out"][0], [3, 3, 6, 6, 6, 6])
    np.testing.assert_allclose(out["Out"][1], [1, 1, 2, 2, 2, 2])


def test_fusion_repeated_fc_relu():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3).astype("float32")
    w1 = rng.randn(3, 4).astype("float32")
    b1 = rng.randn(4).astype("float32")
    w2 = rng.randn(4, 2).astype("float32")
    b2 = rng.randn(2).astype("float32")
    t = _T(); t.op_type = "fusion_repeated_fc_relu"
    out = t.run_op({"X": x, "W": [w1, w2], "Bias": [b1, b2]})
    ref = np.maximum(x @ w1 + b1, 0) @ w2 + b2
    np.testing.assert_allclose(out["Out"], ref, rtol=1e-4, atol=1e-5)


def test_fusion_squared_mat_sub():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3).astype("float32")
    y = rng.randn(3, 4).astype("float32")
    t = _T(); t.op_type = "fusion_squared_mat_sub"
    out = t.run_op({"X": x, "Y": y}, attrs={"scalar": 0.5})
    ref = 0.5 * ((x @ y) ** 2 - (x ** 2) @ (y ** 2))
    np.testing.assert_allclose(out["Out"], ref, rtol=1e-4, atol=1e-4)


def test_match_matrix_tensor():
    rng = np.random.RandomState(0)
    B, Tx, Ty, D, dim_t = 2, 3, 4, 5, 2
    x = rng.randn(B, Tx, D).astype("float32")
    y = rng.randn(B, Ty, D).astype("float32")
    w = rng.randn(D, dim_t, D).astype("float32")
    t = _T(); t.op_type = "match_matrix_tensor"
    out = t.run_op({"X": x, "Y": y, "W": w}, output_slots=("Out",))
    ref = np.einsum("bxd,dte,bye->btxy", x, w, y)
    np.testing.assert_allclose(out["Out"], ref, rtol=1e-3, atol=1e-4)


def test_filter_by_instag():
    t = _T(); t.op_type = "filter_by_instag"
    ins = np.arange(6, dtype="float32").reshape(3, 2)
    tags = np.array([[1, -1], [2, 3], [4, -1]], "int32")
    filt = np.array([3, 7], "int32")
    out = t.run_op({"Ins": ins, "Ins_tag": tags, "Filter_tag": filt},
                   output_slots=("Out", "LossWeight"))
    np.testing.assert_allclose(out["LossWeight"].ravel(), [0, 1, 0])
    np.testing.assert_allclose(out["Out"][1], ins[1])
    np.testing.assert_allclose(out["Out"][0], 0)


def test_fusion_seqpool_concat_max_empty_sequence():
    t = _T(); t.op_type = "fusion_seqpool_concat"
    x = np.ones((2, 2, 3), "float32")
    l = np.array([2, 0], "int32")
    out = t.run_op({"X": [x], "Length": [l]}, attrs={"pooltype": "MAX"})
    np.testing.assert_allclose(out["Out"][0], 1.0)
    np.testing.assert_allclose(out["Out"][1], 0.0)   # empty -> pad, not -1e30


def test_fusion_seqpool_cvm_concat_heterogeneous_widths():
    t = _T(); t.op_type = "fusion_seqpool_cvm_concat"
    # widths 3 and 4; use_cvm=False must drop 2 LEADING slots of each block
    x1 = np.tile(np.array([10, 1, 2], "float32"), (1, 2, 1))
    x2 = np.tile(np.array([20, 30, 5, 6], "float32"), (1, 2, 1))
    l = np.array([1], "int32")
    out = t.run_op({"X": [x1, x2], "Length": [l, l]},
                   attrs={"pooltype": "SUM", "use_cvm": False})
    np.testing.assert_allclose(out["Out"][0], [2, 5, 6])


def test_lstmp_proj_clip():
    rng = np.random.RandomState(0)
    B, T, H, P = 1, 2, 3, 2
    x = (rng.randn(B, T, 4 * H) * 5).astype("float32")
    w = (rng.randn(P, 4 * H)).astype("float32")
    wp = (rng.randn(H, P) * 5).astype("float32")
    t = _T(); t.op_type = "lstmp"
    out = t.run_op({"Input": x, "Weight": w, "ProjWeight": wp},
                   attrs={"proj_activation": "identity", "proj_clip": 0.1},
                   output_slots=("Projection",))
    assert np.abs(out["Projection"]).max() <= 0.1 + 1e-6


def test_attention_lstm_respects_initial_state():
    rng = np.random.RandomState(0)
    B, T, D, H = 1, 2, 3, 2
    x = rng.randn(B, T, D).astype("float32")
    w_att = rng.randn(D + H, 1).astype("float32")
    w_lstm = rng.randn(D + H, 4 * H).astype("float32") * 0.3
    t = _T(); t.op_type = "attention_lstm"
    base = t.run_op({"X": x, "AttentionWeight": w_att, "LSTMWeight": w_lstm},
                    output_slots=("Hidden",))
    warm = t.run_op({"X": x, "AttentionWeight": w_att, "LSTMWeight": w_lstm,
                     "H0": np.full((B, H), 2.0, "float32"),
                     "C0": np.full((B, H), -2.0, "float32")},
                    output_slots=("Hidden",))
    assert not np.allclose(base["Hidden"], warm["Hidden"])


def test_fusion_seqpool_concat_sqrt():
    t = _T(); t.op_type = "fusion_seqpool_concat"
    x = np.ones((1, 4, 2), "float32")
    l = np.array([4], "int32")
    out = t.run_op({"X": [x], "Length": [l]}, attrs={"pooltype": "SQRT"})
    np.testing.assert_allclose(out["Out"][0], 4.0 / 2.0)   # sum/sqrt(len)


def test_lstmp_peepholes_and_reverse():
    rng = np.random.RandomState(0)
    B, T, H, P = 1, 3, 2, 2
    x = rng.randn(B, T, 4 * H).astype("float32") * 0.2
    w = rng.randn(P, 4 * H).astype("float32") * 0.2
    wp = rng.randn(H, P).astype("float32") * 0.2
    b4 = rng.randn(4 * H).astype("float32") * 0.2
    b7 = np.concatenate([b4, rng.randn(3 * H).astype("float32")])
    t = _T(); t.op_type = "lstmp"
    plain = t.run_op({"Input": x, "Weight": w, "ProjWeight": wp, "Bias": b4},
                     output_slots=("Projection",))
    peep = t.run_op({"Input": x, "Weight": w, "ProjWeight": wp, "Bias": b7},
                    attrs={"use_peepholes": True},
                    output_slots=("Projection",))
    assert not np.allclose(plain["Projection"], peep["Projection"])
    rev = t.run_op({"Input": x, "Weight": w, "ProjWeight": wp, "Bias": b4},
                   attrs={"is_reverse": True}, output_slots=("Projection",))
    # reversed scan of reversed input == forward scan, re-reversed
    fwd_of_flipped = t.run_op({"Input": x[:, ::-1].copy(), "Weight": w,
                               "ProjWeight": wp, "Bias": b4},
                              output_slots=("Projection",))
    np.testing.assert_allclose(rev["Projection"],
                               fwd_of_flipped["Projection"][:, ::-1],
                               rtol=1e-5, atol=1e-6)
