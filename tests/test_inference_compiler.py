"""Inference compiler acceptance surface: PassPipeline attribution,
int8 post-training quantization (calibrate → rewrite → gate), the fleet
registry's int8 promotion gate, quantized PS-lookup serving with
delta-push re-quantization, and multi-tenant co-hosting (routing
isolation, weighted admission throttling, per-tenant p99 SLOs).
"""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as fluid

IN_DIM, HID, CLASSES = 16, 32, 4


def _save_mlp(model_dir, seed=0):
    import jax.numpy as jnp
    from paddle_tpu.core.scope import global_scope

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [IN_DIM])
        h = fluid.layers.fc(x, HID, act="relu")
        out = fluid.layers.fc(h, CLASSES, act="softmax")
    exe = fluid.Executor(fluid.TPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        sc = global_scope()
        rng = np.random.RandomState(seed)
        for n in sc.var_names():
            v = np.asarray(sc.find_var(n))
            if v.dtype == np.float32:
                sc.set_var(n, jnp.asarray(
                    rng.uniform(-0.5, 0.5, v.shape).astype(np.float32)))
        fluid.io.save_inference_model(model_dir, ["x"], [out], exe, main)
    return model_dir


@pytest.fixture(scope="module")
def mlp_dir(tmp_path_factory):
    from paddle_tpu.core import program as prog_mod
    from paddle_tpu.core import scope as scope_mod

    old = (prog_mod._main_program, prog_mod._startup_program,
           scope_mod._global_scope, scope_mod._current_scope)
    prog_mod._main_program = prog_mod.Program()
    prog_mod._startup_program = prog_mod.Program()
    scope_mod._global_scope = scope_mod.Scope()
    scope_mod._current_scope = scope_mod._global_scope
    try:
        return _save_mlp(str(tmp_path_factory.mktemp("infc") / "mlp"))
    finally:
        (prog_mod._main_program, prog_mod._startup_program,
         scope_mod._global_scope, scope_mod._current_scope) = old


def _samples(n=4, batch=4, seed=0):
    rng = np.random.RandomState(seed)
    return [{"x": rng.randn(batch, IN_DIM).astype(np.float32)}
            for _ in range(n)]


# -- pass pipeline + perf-ledger attribution ------------------------------

def test_predictor_pass_report_lands_in_ledger(mlp_dir):
    from paddle_tpu import inference
    from paddle_tpu.observability import perf

    pred = inference.create_predictor(inference.Config(mlp_dir))
    report = pred.pass_report
    assert report is not None
    names = [r["pass"] for r in report["passes"]]
    # the tentpole pipeline: fusion + DCE + the new dead-var/layout passes
    for expected in ("fc_fuse_pass", "dead_code_elimination_pass",
                     "dead_var_elimination_pass", "layout_assignment_pass",
                     "memory_optimize_pass"):
        assert expected in names, names
    for rec in report["passes"]:
        for key in ("neutrality", "ops_before", "ops_after",
                    "flops_delta", "bytes_delta", "wall_ms"):
            assert key in rec, rec
    # fc fusion really removed ops and the totals account for it
    fc = next(r for r in report["passes"] if r["pass"] == "fc_fuse_pass")
    assert fc["ops_before"] > fc["ops_after"]
    assert report["ops_total_removed"] >= (
        fc["ops_before"] - fc["ops_after"])
    # the ledger holds the same report, keyed by the predictor label
    assert report["label"].startswith("infer:")
    assert perf.get_ledger().pass_reports().get(
        report["label"]) is not None
    # layout annotation rode along
    assert pred._program._layout_plan["matmul_ops"]


def test_compiled_program_inference_optimize_runs_pipeline():
    from paddle_tpu import compiler, inference

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [IN_DIM])
        h = fluid.layers.fc(x, HID, act="relu")
        out = fluid.layers.fc(h, CLASSES)  # noqa: F841
    cp = compiler.CompiledProgram(main).with_inference_optimize(
        inference.Config())
    ops = [op.type for op in cp._program.global_block().ops]
    assert "fused_fc" in ops
    assert cp._program._pass_report["passes"]


# -- int8 post-training quantization --------------------------------------

def test_int8_quantizes_and_matches_fp32(mlp_dir):
    from paddle_tpu import inference

    samples = _samples()
    p32 = inference.create_predictor(inference.Config(mlp_dir))
    cfg = inference.Config(mlp_dir)
    cfg.enable_int8(samples)
    p8 = inference.create_predictor(cfg)

    ops = [op.type for op in p8._program.global_block().ops]
    assert ops.count("quantized_fc") == 2 and "fused_fc" not in ops
    # fp32 weights left the device; int8 twins + scales arrived
    dtypes = {k: str(v.dtype) for k, v in p8._state.items()}
    assert [k for k in dtypes if k.endswith("@int8")]
    assert all(dtypes[k] == "int8" for k in dtypes if k.endswith("@int8"))
    assert not [k for k, d in dtypes.items()
                if d == "float32" and k.endswith(".w_0")]

    meta = p8.quant_meta
    assert meta["precision"] == "int8"
    assert meta["samples"] == len(samples)
    assert 0.0 <= meta["accuracy_delta"] <= meta["accuracy_budget"]
    assert meta["fc"] and meta["act_scales"]

    for f in samples:
        ref = np.asarray(p32.run(f)[0])
        got = np.asarray(p8.run(f)[0])
        assert float(np.mean(np.abs(got - ref))) <= 0.05 * (
            float(np.mean(np.abs(ref))) + 1e-8)

    # int8_quantize_pass is attributed in the same pass report
    assert "int8_quantize_pass" in [r["pass"]
                                    for r in p8.pass_report["passes"]]

    # clones share the quantized program + meta and serve identically
    c = p8.clone()
    assert c.quant_meta is p8.quant_meta
    np.testing.assert_array_equal(np.asarray(p8.run(samples[0])[0]),
                                  np.asarray(c.run(samples[0])[0]))


def test_int8_accuracy_gate_rejects_over_budget(mlp_dir):
    from paddle_tpu import inference
    from paddle_tpu.inference import QuantizationError

    cfg = inference.Config(mlp_dir)
    cfg.enable_int8(_samples(), accuracy_budget=1e-9)
    with pytest.raises(QuantizationError, match="accuracy gate"):
        inference.create_predictor(cfg)


def test_int8_without_calibration_stream_raises(mlp_dir):
    from paddle_tpu import inference
    from paddle_tpu.inference import QuantizationError

    with pytest.raises(QuantizationError, match="calibration stream"):
        inference.create_predictor(inference.Config(mlp_dir),
                                   precision="int8")
    with pytest.raises(ValueError, match="at least one sample"):
        inference.Config(mlp_dir).enable_int8([])


def test_unknown_precision_raises_not_silent_fp32(mlp_dir):
    """Satellite contract: a typo'd precision string must raise, never
    fall back to fp32."""
    from paddle_tpu import inference

    with pytest.raises(ValueError, match="unknown precision"):
        inference.create_predictor(inference.Config(mlp_dir),
                                   precision="fp31")
    with pytest.raises(ValueError, match="unknown precision"):
        inference.Config(mlp_dir).enable_tpu(precision="in8")
    # the known spellings resolve
    for ok in ("fp32", "float32", "bf16", "int8", "i8"):
        assert inference._resolve_precision(ok)


# -- registry promotion gate ----------------------------------------------

def test_registry_gates_int8_promotion(mlp_dir):
    from paddle_tpu.serving import fleet

    reg = fleet.ModelRegistry()
    with pytest.raises(ValueError, match="no calibration metadata"):
        reg.register("q-bad", mlp_dir, precision="int8")
    with pytest.raises(ValueError, match="exceeds budget"):
        reg.register("q-worse", mlp_dir, precision="int8",
                     calibration={"accuracy_delta": 0.2,
                                  "accuracy_budget": 0.05, "samples": 4})
    mv = reg.register("q-ok", mlp_dir, precision="int8",
                      calibration={"accuracy_delta": 0.008,
                                   "accuracy_budget": 0.05, "samples": 4})
    assert mv.meta["calibration"]["accuracy_delta"] == 0.008
    # fp32 versions are untouched by the gate
    reg.register("f32", mlp_dir)
    assert len(reg) == 2


# -- quantized PS-lookup serving + delta-push re-quantization -------------

V, D, MULT, F, CAP = 128, 4, 2, 3, 24


def _save_ctr(model_dir, vocab_rows, packed=None, dense=None):
    import jax.numpy as jnp
    from paddle_tpu import layers
    from paddle_tpu.core.scope import global_scope
    from paddle_tpu.initializer import RowPackInitializer
    from paddle_tpu.param_attr import ParamAttr

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", [F], dtype="int64")
        emb = layers.embedding(
            ids, [vocab_rows, D * MULT], is_sparse=True, row_pack=True,
            param_attr=ParamAttr(name="tb", initializer=RowPackInitializer(
                D, D * MULT, -1.0, 1.0)))
        emb = layers.slice(emb, axes=[2], starts=[0], ends=[D])
        r = layers.reshape(emb, [-1, F * D])
        out = layers.fc(r, CLASSES, act="softmax")
    exe = fluid.Executor(fluid.TPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        sc = global_scope()
        if packed is not None:
            sc.set_var("tb", jnp.asarray(packed))
            dense = {n: np.asarray(sc.find_var(n))
                     for n in sc.var_names()
                     if n != "tb"
                     and np.asarray(sc.find_var(n)).dtype == np.float32}
        else:
            for n, v in dense.items():
                sc.set_var(n, jnp.asarray(v))
            sc.set_var("tb", jnp.zeros((vocab_rows, 128), jnp.uint16))
        fluid.io.save_inference_model(model_dir, ["ids"], [out], exe, main)
    return dense


def test_ps_lookup_int8_delta_push_requantizes(tmp_path):
    """Satellite regression: an int8-resident PS serving table must
    re-quantize delta-pushed rows with the stored scale — u16 wire bytes
    must never land in the int8 cache raw."""
    import jax.numpy as jnp
    from paddle_tpu import inference
    from paddle_tpu.inference.quant import requantize_packed_rows
    from paddle_tpu.ops.deferred_rows import pack_rows
    from paddle_tpu.ps import RangeSpec, ShardedTable

    vis = np.random.RandomState(7).uniform(-1, 1, (V, D)).astype("float32")
    full = np.zeros((V, D * MULT), "float32")
    full[:, :D] = vis
    packed = np.asarray(pack_rows(jnp.asarray(full)))
    dense = _save_ctr(str(tmp_path / "local"), V, packed=packed)
    _save_ctr(str(tmp_path / "ps"), CAP, dense=dense)
    table_scale = float(np.max(np.abs(vis)))

    rng = np.random.RandomState(3)
    samples = [{"ids": rng.randint(0, CAP, (2, F)).astype(np.int64)}
               for _ in range(3)]
    table = ShardedTable.build_in_process(
        "tb", RangeSpec.even(V, 3), full_rows=packed)
    try:
        cfg = inference.Config(str(tmp_path / "ps"))
        # placeholder cache table is zeros → pin the real table's scale
        cfg.enable_int8(samples, accuracy_budget=10.0,
                        table_scales={"tb": table_scale})
        base = inference.create_predictor(cfg)
        ops = [op.type for op in base._program.global_block().ops]
        assert "quantized_lookup_table" in ops
        ps = inference.PsLookupPredictor(
            base, [inference.PsLookupBinding("tb", table, ["ids"])],
            cache_rows_per_table=32)
        q = ps._quant["tb"]
        cache = ps._caches["tb"]
        assert str(cache.dtype) == "int8"

        # int8 PS serving tracks the fp32 local-table reference closely
        ref = inference.create_predictor(
            inference.Config(str(tmp_path / "local")))
        ids = rng.randint(0, V, (2, F)).astype(np.int64)
        o_ref = np.asarray(ref.run({"ids": ids})[0])
        o_ps = np.asarray(ps.run({"ids": ids})[0])
        assert float(np.abs(o_ref - o_ps).max()) < 0.05

        # delta push: fresh training bytes arrive as packed u16
        touched = np.unique(ids.reshape(-1))
        nvis = np.random.RandomState(9).uniform(
            -1, 1, (touched.size, D)).astype("float32")
        nrows = np.zeros((touched.size, D * MULT), "float32")
        nrows[:, :D] = nvis
        new_packed = np.asarray(pack_rows(jnp.asarray(nrows)))
        assert ps.apply_delta("tb", touched, new_packed) == touched.size

        got, miss = cache.lookup(touched)
        assert not miss.any()
        want = requantize_packed_rows(new_packed, q["dt"], q["scale"])
        np.testing.assert_array_equal(got, want)
        # raw u16 truncation would look nothing like the requantized rows
        raw = new_packed[:, :q["dt"]].astype(np.int8)
        assert not np.array_equal(got, raw)

        # and the served output reflects the new rows through dequant
        o2 = np.asarray(ps.run({"ids": ids})[0])
        assert float(np.abs(o2 - o_ps).max()) > 1e-6
    finally:
        table.close()


# -- multi-tenant co-hosting ----------------------------------------------

def _two_model_registry(tmp_path):
    from paddle_tpu.serving import fleet

    reg = fleet.ModelRegistry()
    reg.register("v1", _save_mlp(str(tmp_path / "v1"), seed=1))
    reg.register("v2", _save_mlp(str(tmp_path / "v2"), seed=2))
    return reg


def test_multi_tenant_fleet_routing_and_slo(tmp_path):
    """Tentpole (c): N=3 tenants co-hosted on one fleet — weighted
    replica partitions, per-tenant routing to the right model version,
    per-tenant p99 within the declared SLO under mixed load."""
    from paddle_tpu import inference
    from paddle_tpu.serving import fleet

    reg = _two_model_registry(tmp_path)
    ref1 = inference.create_predictor(
        inference.Config(reg.resolve("v1").model_dir))
    ref2 = inference.create_predictor(
        inference.Config(reg.resolve("v2").model_dir))
    tenants = {
        "ads": {"version": "v1", "weight": 2.0, "slo_p99_ms": 5000.0},
        "feed": {"version": "v2", "weight": 1.0, "slo_p99_ms": 5000.0},
        "search": {"version": "v1", "weight": 1.0, "slo_p99_ms": 5000.0},
    }
    fl = fleet.ServingFleet(
        reg, replicas=4, buckets=(1, 2, 4),
        server_kwargs={"max_batch_delay_ms": 1.0},
        health_interval_s=0.1, tenants=tenants)
    with fl:
        # weighted partition: 2/1/1, every replica tenant-tagged
        by_tenant = {}
        for r in fl.replicas:
            by_tenant.setdefault(r.tenant, []).append(r.version)
        assert sorted(len(v) for v in by_tenant.values()) == [1, 1, 2]
        assert set(by_tenant) == set(tenants)
        assert set(by_tenant["feed"]) == {"v2"}

        rng = np.random.RandomState(0)
        feeds = [rng.randn(2, IN_DIM).astype(np.float32)
                 for _ in range(6)]
        for x in feeds:
            o_ads = fl.infer({"x": x}, tenant="ads")[0]
            o_feed = fl.infer({"x": x}, tenant="feed")[0]
            np.testing.assert_array_equal(
                np.asarray(o_ads), np.asarray(ref1.run({"x": x})[0]))
            np.testing.assert_array_equal(
                np.asarray(o_feed), np.asarray(ref2.run({"x": x})[0]))
            fl.infer({"x": x}, tenant="search")

        stats = fl.tenant_stats()
        assert set(stats) == set(tenants)
        for name, st in stats.items():
            assert st["requests"] >= 6, (name, st)
            assert st["p99_ms"] is not None
            assert st["slo_ok"] is True, (name, st)
        # weighted admission shares: ads (w=2) gets double the share
        assert stats["ads"]["share"] == 2 * stats["feed"]["share"]

        with pytest.raises(ValueError, match="unknown tenant"):
            fl.infer({"x": feeds[0]}, tenant="video")


def test_tenant_throttling_and_isolation(tmp_path):
    """A tenant at its admission share is throttled at the door
    (TenantThrottledError) without consuming another tenant's
    capacity."""
    from paddle_tpu.serving import fleet
    from paddle_tpu.serving.fleet import TenantThrottledError

    reg = _two_model_registry(tmp_path)
    fl = fleet.ServingFleet(
        reg, replicas=2, buckets=(1, 2, 4),
        server_kwargs={"max_batch_delay_ms": 1.0},
        health_interval_s=0.1,
        tenants={"a": {"version": "v1", "weight": 1.0},
                 "b": {"version": "v1", "weight": 1.0}},
        tenant_capacity=2)  # 1 in-flight slot per tenant
    x = np.zeros((1, IN_DIM), np.float32)
    with fl:
        fl.infer({"x": x}, tenant="a")  # warm
        # hold tenant a's only slot open by faking an in-flight request
        fl.router._tenant_out["a"] = 1
        with pytest.raises(TenantThrottledError):
            fl.submit({"x": x}, tenant="a")
        assert fl.tenant_stats()["a"]["throttled"] == 1
        # tenant b is unaffected by a's saturation
        assert np.asarray(fl.infer({"x": x}, tenant="b")[0]).shape == (
            1, CLASSES)
        fl.router._tenant_out["a"] = 0
        fl.infer({"x": x}, tenant="a")  # a recovers once slots free


def test_tenant_rollout_swaps_only_that_partition(tmp_path):
    from paddle_tpu.serving import fleet

    reg = _two_model_registry(tmp_path)
    fl = fleet.ServingFleet(
        reg, replicas=2, buckets=(1, 2, 4),
        server_kwargs={"max_batch_delay_ms": 1.0},
        health_interval_s=0.1,
        tenants={"a": {"version": "v1", "weight": 1.0},
                 "b": {"version": "v1", "weight": 1.0}})
    with fl:
        report = fl.rollout("v2", tenant="a")
        assert all(name.startswith("a/")
                   for name in report["replicas"]), report
        versions = {r.tenant: r.version for r in fl.replicas}
        assert versions == {"a": "v2", "b": "v1"}
