"""IO round-trips + data pipeline + native loader tests (reference:
test_inference_model_io.py, reader decorator tests, dataset tests)."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import io as pio
from paddle_tpu import reader as preader
from paddle_tpu.data_feeder import DataFeeder
from paddle_tpu.dataset import DatasetFactory
from paddle_tpu.native import NativeDataLoader, available as native_available


def _build_net():
    x = fluid.layers.data("x", [4])
    y = fluid.layers.fc(x, 2, param_attr="w", bias_attr="b")
    return x, y


def test_save_load_persistables(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x, y = _build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        w0 = np.array(fluid.global_scope().find_var("w"))
        pio.save_persistables(exe, str(tmp_path / "ckpt"), main)
        # clobber then reload
        fluid.global_scope().set_var("w", np.zeros_like(w0))
        missing = pio.load_persistables(exe, str(tmp_path / "ckpt"), main)
        w1 = np.array(fluid.global_scope().find_var("w"))
    assert not missing
    np.testing.assert_allclose(w0, w1)


def test_inference_model_roundtrip(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x, y = _build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xv = np.random.rand(3, 4).astype("float32")
        (ref,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
        pio.save_inference_model(str(tmp_path / "model"), ["x"], [y], exe, main)

    # fresh scope + program: load and run
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe2 = fluid.Executor(fluid.CPUPlace())
        prog, feeds, fetches = pio.load_inference_model(str(tmp_path / "model"), exe2)
        (out,) = exe2.run(prog, feed={feeds[0]: xv}, fetch_list=fetches)
    np.testing.assert_allclose(ref, out, rtol=1e-6)


def test_reader_decorators():
    def raw():
        yield from range(10)

    batched = preader.batch(raw, 3)
    batches = list(batched())
    assert batches[0] == [0, 1, 2] and len(batches) == 4
    assert list(preader.firstn(raw, 4)()) == [0, 1, 2, 3]
    shuffled = list(preader.shuffle(raw, 5)())
    assert sorted(shuffled) == list(range(10))
    buffered = list(preader.buffered(raw, 2)())
    assert buffered == list(range(10))
    mapped = list(preader.map_readers(lambda a: a * 2, raw)())
    assert mapped == [0, 2, 4, 6, 8, 10, 12, 14, 16, 18]


def test_data_feeder_pads_ragged():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        ids = fluid.layers.data("ids", [-1], dtype="int64", append_batch_size=False)
        feeder = DataFeeder([ids])
        feed = feeder.feed([(np.array([1, 2, 3]),), (np.array([4]),)])
    assert feed["ids"].shape == (2, 3)
    np.testing.assert_array_equal(feed["ids_len"], [3, 1])


@pytest.mark.skipif(not native_available(), reason="no native toolchain")
def test_native_loader_parses_multislot(tmp_path):
    # two slots: float dense[2], int64 ids[var]
    f1 = tmp_path / "part-0"
    f1.write_text("2 0.5 1.5 3 7 8 9\n2 2.5 3.5 1 42\n")
    f2 = tmp_path / "part-1"
    f2.write_text("2 9.0 10.0 2 1 2\n")
    loader = NativeDataLoader([str(f1), str(f2)], "fi", num_threads=2)
    samples = sorted(list(loader), key=lambda s: float(s[0][0]))
    loader.close()
    assert len(samples) == 3
    np.testing.assert_allclose(samples[0][0], [0.5, 1.5])
    np.testing.assert_array_equal(samples[0][1], [7, 8, 9])
    np.testing.assert_array_equal(samples[1][1], [42])


@pytest.mark.skipif(not native_available(), reason="no native toolchain")
def test_dataset_batches(tmp_path):
    f1 = tmp_path / "data.txt"
    lines = []
    for i in range(10):
        lines.append(f"3 {i}.0 {i}.5 {i}.25 1 {i}\n")
    f1.write_text("".join(lines))

    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        feats = fluid.layers.data("feats", [3])
        label = fluid.layers.data("label", [1], dtype="int64")
        ds = DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_filelist([str(f1)])
        ds.set_batch_size(4)
        ds.set_thread(2)
        ds.set_use_var([feats, label])
        ds.load_into_memory()
        ds.local_shuffle()
        batches = list(ds.batches())
    total = sum(b["feats"].shape[0] for b in batches)
    assert total == 10
    assert batches[0]["feats"].shape[1] == 3


def test_native_queue_roundtrip():
    if not native_available():
        pytest.skip("no native toolchain")
    import ctypes
    from paddle_tpu import native
    lib = native._ensure_built()
    q = lib.ptq_create(4)
    payload = np.arange(10, dtype=np.uint8)
    lib.ptq_push(q, payload.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), 10)
    buf = np.zeros(64, dtype=np.uint8)
    n = lib.ptq_pop(q, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), 64)
    assert n == 10
    np.testing.assert_array_equal(buf[:10], payload)
    lib.ptq_close(q)
    lib.ptq_destroy(q)
