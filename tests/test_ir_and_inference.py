"""ir pass framework + inference engine tests.

Mirrors the reference's pass unit tests (ir/*_pass_tester.cc style: build a
small program, apply the pass, assert on the op set AND on numeric equality)
and the inference save/load round-trip tests (test_inference_model_io.py,
analyzer_*_tester.cc shape)."""
import numpy as np
import pytest


def _build_mlp(seed=0):
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[16], dtype="float32")
        h = fluid.layers.fc(x, 32, act="relu")
        out = fluid.layers.fc(h, 4, act=None)
    return main, startup, out


def test_pass_registry_lists_standard_passes():
    from paddle_tpu import ir

    have = ir.registered_passes()
    for name in ["dead_code_elimination_pass", "fc_fuse_pass",
                 "fuse_elewise_add_act_pass", "constant_folding_pass",
                 "memory_optimize_pass", "graph_viz_pass",
                 "delete_dropout_op_pass"]:
        assert name in have


def test_graph_topology_and_consumers():
    import paddle_tpu as fluid
    from paddle_tpu import ir

    main, _, out = _build_mlp()
    g = ir.Graph(main.global_block())
    order = g.topology_sort()
    assert [o.type for o in order] == [o.type for o in main.global_block().ops]
    # hidden activation of first fc is consumed exactly once
    first_relu_out = [op for op in g.ops if op.type == "relu"][0].output("Out")[0]
    assert g.num_consumers(first_relu_out) == 1


def test_dce_removes_unused_branch():
    import paddle_tpu as fluid
    from paddle_tpu import ir

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        kept = fluid.layers.fc(x, 4)
        dead = fluid.layers.fc(x, 9)  # noqa: F841 — never fetched
    n_before = len(main.global_block().ops)
    ir.apply_pass(main, "dead_code_elimination_pass", keep=[kept.name])
    n_after = len(main.global_block().ops)
    assert n_after < n_before
    names = {n for op in main.global_block().ops for n in op.output_names()}
    assert kept.name in names
    assert dead.name not in names


def _run_simple(main, startup, feed, fetch):
    import paddle_tpu as fluid

    exe = fluid.Executor(fluid.TPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        return exe.run(main, feed=feed, fetch_list=[fetch])[0]


def test_fc_fuse_pass_numerics():
    import paddle_tpu as fluid
    from paddle_tpu import ir

    main, startup, out = _build_mlp()
    x = np.random.RandomState(0).randn(4, 16).astype(np.float32)

    ref_main = main.clone()
    ref = _run_simple(ref_main, startup.clone(), {"x": x}, out.name)

    ir.apply_pass(main, "fc_fuse_pass")
    types = [op.type for op in main.global_block().ops]
    assert "fused_fc" in types
    assert "mul" not in types
    # the relu of the first fc must be folded INTO fused_fc (act-first match)
    assert "relu" not in types
    fused = [op for op in main.global_block().ops if op.type == "fused_fc"]
    assert any(op.attr("activation_type") == "relu" for op in fused)
    got = _run_simple(main, startup, {"x": x}, out.name)
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-5)


def test_fuse_passes_keep_fetched_intermediates():
    """A fetched intermediate var must survive fusion (review finding: fetch
    is not an op-consumer, so single-consumer chains could erase it)."""
    import paddle_tpu as fluid
    from paddle_tpu import ir

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        y = fluid.layers.data("y", shape=[8], dtype="float32")
        s = fluid.layers.elementwise_add(x, y)   # fetched intermediate
        out = fluid.layers.relu(s)
    ir.apply_pass(main, "fuse_elewise_add_act_pass",
                  keep=[s.name, out.name])
    names = {n for op in main.global_block().ops for n in op.output_names()}
    assert s.name in names and out.name in names
    rng = np.random.RandomState(2)
    xv, yv = rng.randn(2, 8).astype(np.float32), rng.randn(2, 8).astype(np.float32)
    got = _run_simple(main, startup, {"x": xv, "y": yv}, s.name)
    np.testing.assert_allclose(got, xv + yv, rtol=1e-6)


def test_fc_fuse_rejects_nonvector_bias():
    """elementwise_add with a per-row (axis=0) bias must NOT fc-fuse (review
    finding: fused_fc hard-codes a last-dim bias broadcast)."""
    import paddle_tpu as fluid
    from paddle_tpu import ir

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[-1, 6], dtype="float32",
                              append_batch_size=False)
        w = fluid.layers.create_parameter([6, 3], "float32", name="w_nb")
        b = fluid.layers.data("b", shape=[-1], dtype="float32",
                              append_batch_size=False)  # per-row bias
        m = fluid.layers.mul(x, w)
        out = fluid.layers.elementwise_add(m, b, axis=0)
    ir.apply_pass(main, "fc_fuse_pass", fetch_names=[out.name])
    assert "fused_fc" not in [op.type for op in main.global_block().ops]


def test_fuse_elewise_add_act_numerics():
    import paddle_tpu as fluid
    from paddle_tpu import ir

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        y = fluid.layers.data("y", shape=[8], dtype="float32")
        s = fluid.layers.elementwise_add(x, y)
        out = fluid.layers.relu(s)
    rng = np.random.RandomState(1)
    xv = rng.randn(3, 8).astype(np.float32)
    yv = rng.randn(3, 8).astype(np.float32)
    ref = _run_simple(main.clone(), startup.clone(), {"x": xv, "y": yv}, out.name)

    ir.apply_pass(main, "fuse_elewise_add_act_pass")
    types = [op.type for op in main.global_block().ops]
    assert "fused_elemwise_activation" in types
    assert "relu" not in types
    got = _run_simple(main, startup, {"x": xv, "y": yv}, out.name)
    np.testing.assert_allclose(ref, got, rtol=1e-6)


def test_constant_folding_pass():
    import paddle_tpu as fluid
    from paddle_tpu import ir

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        c1 = fluid.layers.fill_constant([4], "float32", 2.0)
        c2 = fluid.layers.fill_constant([4], "float32", 3.0)
        csum = fluid.layers.elementwise_add(c1, c2)  # foldable → 5.0
        out = fluid.layers.elementwise_add(x, csum)
    ir.apply_pass(main, "constant_folding_pass")
    ir.apply_pass(main, "dead_code_elimination_pass", keep=[out.name])
    types = [op.type for op in main.global_block().ops]
    assert "assign_value" in types
    # the add of two constants is gone; only the x + const add remains
    assert types.count("elementwise_add") == 1
    xv = np.ones((2, 4), dtype=np.float32)
    got = _run_simple(main, startup, {"x": xv}, out.name)
    np.testing.assert_allclose(got, np.full((2, 4), 6.0), rtol=1e-6)


def test_delete_dropout_and_memory_plan():
    import paddle_tpu as fluid
    from paddle_tpu import ir

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        h = fluid.layers.fc(x, 8, act="relu")
        d = fluid.layers.dropout(h, dropout_prob=0.5)
        out = fluid.layers.fc(d, 2)
    ir.apply_pass(main, "delete_dropout_op_pass")
    assert "dropout" not in [op.type for op in main.global_block().ops]
    ir.apply_pass(main, "memory_optimize_pass", fetch_names=[out.name])
    plan = main._memory_plan
    assert plan["n_temporaries"] > 0
    assert "x" in main._donatable_feeds


def test_graph_viz_pass(tmp_path):
    import paddle_tpu as fluid
    from paddle_tpu import ir

    main, _, out = _build_mlp()
    path = str(tmp_path / "g.dot")
    ir.apply_pass(main, "graph_viz_pass", path=path)
    dot = open(path).read()
    assert "digraph" in dot and "mul" in dot


def test_predictor_round_trip(tmp_path):
    import paddle_tpu as fluid
    from paddle_tpu import inference

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[16], dtype="float32")
        h = fluid.layers.fc(x, 32, act="relu")
        h = fluid.layers.dropout(h, dropout_prob=0.3)
        out = fluid.layers.fc(h, 4, act="softmax")

    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    xv = np.random.RandomState(3).randn(5, 16).astype(np.float32)
    ref = exe.run(main.clone(for_test=True), feed={"x": xv},
                  fetch_list=[out.name])[0]

    model_dir = str(tmp_path / "model")
    fluid.io.save_inference_model(model_dir, ["x"], [out], exe, main)

    config = inference.Config(model_dir)
    pred = inference.create_predictor(config)
    assert pred.get_input_names() == ["x"]
    assert pred.get_output_names() == [out.name]
    # fused/optimized program must numerically match the executor
    got = pred.run({"x": xv})[0]
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-5)

    # zero-copy handle style
    h_in = pred.get_input_handle("x")
    h_in.copy_from_cpu(xv)
    pred.run()
    got2 = pred.get_output_handle(out.name).copy_to_cpu()
    np.testing.assert_allclose(ref, got2, rtol=1e-5, atol=1e-5)

    # clone shares weights, produces same result
    clone = pred.clone()
    got3 = clone.run({"x": xv})[0]
    np.testing.assert_allclose(ref, got3, rtol=1e-5, atol=1e-5)
    assert clone._state is pred._state or all(
        clone._state[k] is pred._state[k] for k in pred._state)


def test_predictor_bf16_precision(tmp_path):
    import paddle_tpu as fluid
    from paddle_tpu import inference

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        out = fluid.layers.fc(x, 4)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    model_dir = str(tmp_path / "m")
    fluid.io.save_inference_model(model_dir, ["x"], [out], exe, main)

    cfg = inference.Config(model_dir)
    cfg.enable_tpu(precision=inference.PrecisionType.Bfloat16)
    pred = inference.create_predictor(cfg)
    xv = np.random.RandomState(0).randn(2, 8).astype(np.float32)
    f32 = inference.create_predictor(inference.Config(model_dir)).run({"x": xv})[0]
    bf16 = pred.run({"x": xv})[0]
    np.testing.assert_allclose(f32, np.asarray(bf16, np.float32), rtol=0.05, atol=0.05)


def test_conv_bn_fuse_pass_numerics(tmp_path):
    """conv_bn_fuse_pass (reference ir/conv_bn_fuse_pass.cc): inference
    outputs are unchanged after BN is folded into the conv weights, and the
    optimized program contains no batch_norm op."""
    import paddle_tpu as fluid
    from paddle_tpu import inference

    rng = np.random.RandomState(7)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3, 8, 8], dtype="float32")
        h = fluid.layers.conv2d(x, 8, 3, padding=1, bias_attr=False)
        h = fluid.layers.batch_norm(h, act="relu")
        h = fluid.layers.conv2d(h, 4, 3, padding=1, bias_attr=False)
        h = fluid.layers.batch_norm(h)
        out = fluid.layers.reduce_mean(h, dim=[2, 3])

    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        # a couple of train steps so BN stats are non-trivial
        for _ in range(3):
            exe.run(main, feed={"x": rng.rand(4, 3, 8, 8).astype("float32")},
                    fetch_list=[out.name])
        xv = rng.rand(5, 3, 8, 8).astype("float32")
        ref = exe.run(main.clone(for_test=True), feed={"x": xv},
                      fetch_list=[out.name])[0]
        model_dir = str(tmp_path / "convbn")
        fluid.io.save_inference_model(model_dir, ["x"], [out], exe, main)

    config = inference.Config(model_dir)
    pred = inference.create_predictor(config)
    got = pred.run({"x": xv})[0]
    np.testing.assert_allclose(ref, got, rtol=2e-5, atol=2e-5)
    types = [op.type for op in pred._program.global_block().ops]
    assert "batch_norm" not in types, types
    assert types.count("conv2d") == 2
