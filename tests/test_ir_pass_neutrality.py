"""Pass-pipeline neutrality contracts on the seed models.

Every pass in ``ir/passes.py`` declares a neutrality contract
(``bitwise`` / ``precision`` / ``annotation`` — see ir/pass_base.py);
this suite *proves* the bitwise ones on real forward programs — bert,
resnet, deepfm and transformer-NMT — by running each program before and
after optimization in the SAME scope and comparing output bits, the
ir-pass analog of the reference's per-pass tester pairs
(fc_fuse_pass_tester.cc etc., which assert op sets but only allclose
numerics; the TPU backend's deterministic executor lets us demand
equality).

conv_bn_fuse_pass declares ``precision`` (folding γ/√(σ²+ε) into conv
weights re-rounds them) but is a structural no-op without a scope, so
the full default pipeline stays bitwise in these tests — asserted, not
assumed.
"""
import numpy as np
import pytest

import paddle_tpu as fluid


def _run_bits(exe, program, feed, fetch_name):
    (out,) = exe.run(program, feed=feed, fetch_list=[fetch_name])
    return np.asarray(out)


def _assert_pipeline_bitwise(main, startup, feed, fetch_name,
                             prune_feeds=None):
    """Run fp32 reference vs default-inference-pipeline-optimized clone
    on identical weights; bits must match. Returns the pass report.
    ``prune_feeds`` strips training ops (autodiff/optimizer) first — a
    program that updates weights per run can't be compared across
    runs."""
    from paddle_tpu.inference import Config
    from paddle_tpu.ir import PassPipeline

    exe = fluid.Executor(fluid.TPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        if prune_feeds is not None:
            fwd = main._prune_for_inference(prune_feeds, [fetch_name])
        else:
            fwd = main.clone(for_test=True)
        ref = _run_bits(exe, fwd, feed, fetch_name)
        opt = fwd.clone(for_test=True)
        PassPipeline(Config().pass_builder(), record=False).run(
            opt, keep=[fetch_name], fetch_names=[fetch_name])
        got = _run_bits(exe, opt, feed, fetch_name)
    np.testing.assert_array_equal(ref, got)
    report = opt._pass_report
    assert [r["pass"] for r in report["passes"]], "pipeline ran no passes"
    for rec in report["passes"]:
        assert rec["neutrality"] in ("bitwise", "precision", "annotation")
    return report


def test_bert_forward_pipeline_bitwise():
    from paddle_tpu.models import bert

    cfg = bert.BertConfig(vocab_size=64, hidden_size=16, num_layers=1,
                          num_heads=2, ffn_size=32, max_position=16,
                          hidden_dropout=0.1, attn_dropout=0.1)
    main, startup, feeds, loss = bert.build_pretrain_program(
        cfg, 2, 8, optimizer_factory=None, is_test=True)
    rng = np.random.RandomState(0)
    feed = {
        "src_ids": rng.randint(0, 64, (2, 8)).astype("int64"),
        "pos_ids": np.tile(np.arange(8), (2, 1)).astype("int64"),
        "sent_ids": np.zeros((2, 8), "int64"),
        "input_mask": np.ones((2, 8), "float32"),
        "mlm_labels": rng.randint(0, 64, (2, 8, 1)).astype("int64"),
    }
    report = _assert_pipeline_bitwise(main, startup, feed, loss.name)
    # bert has live dropout ops at build time; the delete pass must act
    deleted = {r["pass"]: r for r in report["passes"]}
    assert "delete_dropout_op_pass" in deleted


def test_resnet_forward_pipeline_bitwise():
    from paddle_tpu.models import resnet

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", [3, 32, 32])
        out = resnet.resnet(img, depth=50, num_classes=10, is_test=True)
    feed = {"img": np.random.RandomState(1)
            .randn(2, 3, 32, 32).astype("float32") * 0.1}
    _assert_pipeline_bitwise(main, startup, feed, out.name)


def test_deepfm_forward_pipeline_bitwise():
    from paddle_tpu.models import deepfm

    main, startup, feeds, loss, prob = deepfm.build_train_program(
        vocab_size=64, num_fields=4, num_dense=4, embed_dim=8,
        hidden_sizes=(16, 8))
    rng = np.random.RandomState(2)
    feed = {
        "sparse_ids": rng.randint(0, 64, (4, 4)).astype("int64"),
        "dense": rng.randn(4, 4).astype("float32"),
    }
    _assert_pipeline_bitwise(main, startup, feed, prob.name,
                             prune_feeds=["sparse_ids", "dense"])


def test_nmt_forward_pipeline_bitwise():
    from paddle_tpu.models import transformer_nmt as nmt

    cfg = nmt.TransformerConfig(src_vocab=32, tgt_vocab=32, d_model=16,
                                n_heads=2, d_ff=32, n_enc=1, n_dec=1,
                                dropout=0.1, max_len=16)
    main, startup, feeds, loss = nmt.build_train_program(
        cfg, src_len=8, tgt_len=8, is_test=True)
    rng = np.random.RandomState(3)
    causal = np.triu(np.full((8, 8), -1e4, np.float32), 1)[None, None]
    feed = {
        "src_ids": rng.randint(1, 32, (2, 8)).astype("int64"),
        "tgt_ids": rng.randint(1, 32, (2, 8)).astype("int64"),
        "lbl_ids": rng.randint(1, 32, (2, 8, 1)).astype("int64"),
        "src_mask": np.zeros((2, 1, 1, 8), "float32"),
        "tgt_mask": np.broadcast_to(causal, (2, 1, 8, 8)).copy(),
    }
    _assert_pipeline_bitwise(
        main, startup, feed, loss.name,
        prune_feeds=["src_ids", "tgt_ids", "lbl_ids", "src_mask",
                     "tgt_mask"])


def test_each_bitwise_pass_individually_neutral():
    """Apply every registered bitwise-contract pass ALONE to an
    mlp+embedding+dropout program — each one must preserve output bits
    by itself, not just inside the pipeline ordering."""
    from paddle_tpu import ir

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", [3], dtype="int64")
        x = fluid.layers.data("x", [8])
        emb = fluid.layers.embedding(ids, size=[32, 8])
        e = fluid.layers.reshape(emb, [-1, 24])
        h = fluid.layers.concat([e, x], axis=1)
        h = fluid.layers.fc(h, 16, act="relu")
        h = fluid.layers.dropout(h, dropout_prob=0.3)
        dead = fluid.layers.fc(h, 5)  # noqa: F841 — never fetched
        out = fluid.layers.fc(h, 4, act="softmax")
    rng = np.random.RandomState(4)
    feed = {"ids": rng.randint(0, 32, (2, 3)).astype("int64"),
            "x": rng.randn(2, 8).astype("float32")}

    bitwise = [n for n in ir.registered_passes()
               if getattr(ir.get_pass(n), "neutrality", "bitwise")
               == "bitwise"]
    assert {"fc_fuse_pass", "constant_folding_pass",
            "dead_code_elimination_pass", "dead_var_elimination_pass",
            "fuse_elewise_add_act_pass",
            "delete_dropout_op_pass"} <= set(bitwise)

    exe = fluid.Executor(fluid.TPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        fwd = main.clone(for_test=True)
        ref = _run_bits(exe, fwd, feed, out.name)
        for name in bitwise:
            opt = fwd.clone(for_test=True)
            ir.apply_pass(opt, name, keep=[out.name],
                          fetch_names=[out.name])
            got = _run_bits(exe, opt, feed, out.name)
            np.testing.assert_array_equal(
                ref, got, err_msg=f"{name} broke bitwise neutrality")


def test_dead_var_elimination_prunes_unreferenced_vars():
    from paddle_tpu import ir

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8])
        kept = fluid.layers.fc(x, 4)
        dead = fluid.layers.fc(x, 9)  # noqa: F841
    blk = main.global_block()
    ir.apply_pass(main, "dead_code_elimination_pass", keep=[kept.name])
    n_vars = len(blk.vars)
    ir.apply_pass(main, "dead_var_elimination_pass", keep=[kept.name])
    assert len(blk.vars) < n_vars
    # data vars and everything the surviving ops touch stay
    assert "x" in blk.vars and kept.name in blk.vars
    live = {n for op in blk.ops for n in op.input_names()} | \
           {n for op in blk.ops for n in op.output_names()}
    assert live <= set(blk.vars)


def test_layout_assignment_annotates_tpu_tiling():
    """layout_assignment_pass computes (8,128)-tile padding waste and
    matmul alignment without touching any op — pure annotation."""
    from paddle_tpu import ir

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [17])  # deliberately lane-misaligned
        out = fluid.layers.fc(x, 3)
    ops_before = [op.type for op in main.global_block().ops]
    ir.apply_pass(main, "layout_assignment_pass", keep=[out.name])
    assert [op.type for op in main.global_block().ops] == ops_before
    plan = main._layout_plan
    assert plan["padded_bytes"] >= plan["natural_bytes"] > 0
    assert 0.0 < plan["waste_fraction"] < 1.0
    assert plan["matmul_ops"], "fc matmul should be recorded"
    rec = plan["matmul_ops"][0]
    assert rec["k"] == 17 and not rec["k_aligned"]
    assert rec["n"] == 3 and not rec["n_aligned"]
