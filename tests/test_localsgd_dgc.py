"""LocalSGD + DGC (reference transpiler/collective.py:269 LocalSGD,
optimizer.py:799 DGCMomentumOptimizer + sparse_all_reduce_op_handle.cc):
the TPU-native functional forms over shard_map replicas."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from paddle_tpu.parallel import (average_params, dgc_allreduce,
                                 local_sgd_step, replicate_params,
                                 sparse_allgather_exchange, top_k_sparsify)


def _mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), ("dp",))


def test_local_sgd_diverges_then_syncs():
    n = 4
    mesh = _mesh(n)
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.rand(8, 1).astype("float32"))
    params = replicate_params({"w": w}, n)
    w_true = rng.rand(8, 1).astype("float32")
    x = jnp.asarray(rng.rand(n * 8, 8).astype("float32"))
    y = x @ w_true

    def grad_fn(p, batch):
        bx, by = batch
        def loss(p):
            return jnp.mean((bx @ p["w"] - by) ** 2)
        l, g = jax.value_and_grad(loss)(p)
        return l, g

    step = local_sgd_step(grad_fn, mesh, k_steps=3, lr=0.2)
    losses = []
    for i in range(9):
        params, loss = step(params, (x, y), i)
        losses.append(float(loss))
        ws = np.asarray(params["w"])
        spread = np.abs(ws - ws.mean(0, keepdims=True)).max()
        if (i + 1) % 3 == 0:
            assert spread < 1e-6, f"step {i}: replicas should be synced"
        else:
            assert spread > 1e-8, f"step {i}: replicas should diverge"
    assert losses[-1] < losses[0] * 0.5

    # explicit average matches pmean
    avg = average_params(params, mesh)
    ws = np.asarray(avg["w"])
    assert np.abs(ws - ws.mean(0, keepdims=True)).max() < 1e-6


def test_top_k_sparsify_error_feedback():
    g = jnp.asarray([1.0, -5.0, 0.1, 3.0])
    sparse, resid = top_k_sparsify(g, ratio=0.5)
    np.testing.assert_allclose(sparse, [0.0, -5.0, 0.0, 3.0])
    np.testing.assert_allclose(resid, [1.0, 0.0, 0.1, 0.0])
    np.testing.assert_allclose(sparse + resid, g)  # nothing lost


def test_dgc_allreduce_matches_dense_sum_of_topk():
    n = 4
    mesh = _mesh(n)
    rng = np.random.RandomState(1)
    g = jnp.asarray(rng.randn(n, 64).astype("float32"))
    r = jnp.zeros_like(g)
    summed, new_r = dgc_allreduce(g, r, mesh, ratio=0.25)
    # manual reference
    exp_sum = np.zeros(64, "float32")
    for d in range(n):
        s, _ = top_k_sparsify(g[d], 0.25)
        exp_sum += np.asarray(s)
    got = np.asarray(summed)
    assert got.shape == (1, 64)  # the replicated sum (out_specs=P())
    np.testing.assert_allclose(got[0], exp_sum, rtol=1e-5, atol=1e-6)
    # residual carries exactly the dropped mass
    np.testing.assert_allclose(np.asarray(new_r) + np.vstack(
        [np.asarray(top_k_sparsify(g[d], 0.25)[0]) for d in range(n)]),
        np.asarray(g), rtol=1e-5, atol=1e-6)


def test_sparse_allgather_exchange_equals_masked_psum():
    n = 4
    mesh = _mesh(n)
    rng = np.random.RandomState(2)
    g = jnp.asarray(rng.randn(n, 32).astype("float32"))
    r = jnp.zeros_like(g)
    dense_sum, _ = dgc_allreduce(g, r, mesh, ratio=0.25)
    sparse_sum, _ = sparse_allgather_exchange(g, r, mesh, ratio=0.25)
    np.testing.assert_allclose(np.asarray(sparse_sum)[0],
                               np.asarray(dense_sum)[0],
                               rtol=1e-5, atol=1e-6)


def test_dgc_training_converges_with_95pct_sparsity():
    """Linear regression trained on DGC-exchanged grads at ratio=0.05 still
    converges thanks to error feedback."""
    n = 4
    mesh = _mesh(n)
    rng = np.random.RandomState(3)
    w_true = rng.rand(32, 1).astype("float32")
    x = rng.rand(n * 16, 32).astype("float32")
    y = x @ w_true
    xs = jnp.asarray(x.reshape(n, 16, 32))
    ys = jnp.asarray(y.reshape(n, 16, 1))
    w = jnp.zeros((32, 1), "float32")
    resid = jnp.zeros((n, 32, 1), "float32")

    def per_dev_grad(xb, yb, w):
        return jax.grad(lambda w: jnp.mean((xb @ w - yb) ** 2))(w)

    losses = []
    for step in range(60):
        grads = jnp.stack([per_dev_grad(xs[d], ys[d], w) for d in range(n)])
        summed, resid = dgc_allreduce(grads, resid, mesh, ratio=0.05)
        w = w - 0.3 * summed[0] / n
        losses.append(float(jnp.mean((jnp.asarray(x) @ w - jnp.asarray(y)) ** 2)))
    assert losses[-1] < losses[0] * 0.05, (losses[0], losses[-1])


def test_dgc_momentum_multistage_rampup_keep_counts():
    """ADVICE r3: with an ascending sparsity schedule the keep-set must
    actually shrink through the stages (k sized from the LOOSEST sparsity,
    masked down per stage) — not jump straight to the final sparsity."""
    from paddle_tpu.ops.optimizer_ops import _dgc_momentum

    n = 1000
    rng = np.random.RandomState(7)
    p = jnp.asarray(rng.randn(n).astype("float32"))
    sparsity = [0.75, 0.9375, 0.999]
    rampup_begin, rampup_step = 4, 30  # 3 stages of 10 steps each

    def nnz_update(step):
        g = jnp.asarray(rng.randn(n).astype("float32"))
        out = _dgc_momentum(
            None,
            {"Param": [p], "Grad": [g],
             "Velocity": [jnp.zeros_like(p)],
             "Residual": [jnp.zeros_like(p)],
             "Step": [jnp.asarray([float(step)], "float32")],
             "LearningRate": [jnp.asarray(0.1, "float32")]},
            {"mu": 0.9, "sparsity": sparsity, "clip_norm": 0.0,
             "rampup_begin_step": rampup_begin, "rampup_step": rampup_step})
        return int(jnp.sum(out["ParamOut"][0] != p))

    assert nnz_update(0) == n  # dense phase
    stage_nnz = [nnz_update(rampup_begin + 10 * s) for s in range(3)]
    # expected keep counts: 250, ~62, 1
    assert 200 <= stage_nnz[0] <= 260, stage_nnz
    assert 40 <= stage_nnz[1] <= 70, stage_nnz
    assert 1 <= stage_nnz[2] <= 3, stage_nnz
    assert stage_nnz[0] > stage_nnz[1] > stage_nnz[2]


def test_program_path_dgc_converges():
    """Program-level DGCMomentumOptimizer (VERDICT r2 #6): dgc_momentum ops
    in the program, 99% sparsity after a short dense rampup, convergence
    within reach of dense momentum on the same problem."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    def train(dgc, steps=600):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            main.random_seed = startup.random_seed = 9
            x = layers.data("x", [16])
            y = layers.data("y", [1])
            h = layers.fc(x, 64, act="tanh",
                          param_attr=fluid.ParamAttr(name="w1"))
            out = layers.fc(h, 1, param_attr=fluid.ParamAttr(name="w2"))
            loss = layers.mean(layers.square(layers.elementwise_sub(out, y)))
            if dgc:
                opt = fluid.optimizer.DGCMomentumOptimizer(
                    0.01, 0.9, rampup_begin_step=20, rampup_step=5,
                    sparsity=[0.99])
            else:
                opt = fluid.optimizer.Momentum(0.01, 0.9)
            opt.minimize(loss)
        if dgc:
            assert any(op.type == "dgc_momentum"
                       for op in main.global_block().ops)
        rng = np.random.RandomState(0)
        X = rng.randn(64, 16).astype("float32")
        W = rng.randn(16, 1).astype("float32")
        Y = np.tanh(X @ W) * 0.5
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.TPUPlace())
            exe.run(startup)
            losses = [float(exe.run(main, feed={"x": X, "y": Y},
                                    fetch_list=[loss])[0])
                      for _ in range(steps)]
        return losses

    dense = train(False)
    dgc = train(True)
    # both converge; sparse sends make the DGC tail oscillate, so judge the
    # tail AVERAGE: an order of magnitude below the start and in the dense
    # solution's basin
    tail = float(np.mean(dgc[-100:]))
    assert tail < dgc[0] * 0.2, (dgc[0], tail)
    assert tail < max(dense[-1] * 100.0, 1e-1), (dense[-1], tail)
    assert dense[-1] < dense[0] * 0.05
