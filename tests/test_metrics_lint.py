"""Metric-name lint (ISSUE 17 satellite): the tier-1 gate that keeps
every literal ``counter(...)``/``gauge(...)``/``histogram(...)``
registration in the package exposition-legal, type-consistent, and
collision-free after Prometheus name sanitization — plus unit coverage
of the linter itself over synthetic trees."""
import subprocess
import sys

from paddle_tpu.tools.metrics_lint import (default_root, lint_source_tree,
                                           main, scan_file)


def test_package_source_is_lint_clean():
    """THE gate: any metric-name drift in paddle_tpu fails tier-1."""
    assert lint_source_tree(default_root()) == []


def test_scan_finds_literal_registrations(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text(
        "reg.counter('a/b').inc()\n"
        "x = reg.gauge(\"c/d\", shard='0')\n"
        "reg.histogram( 'e_f' ).observe(1)\n"
        "def counter(self, name):  # a definition, not a call\n"
        "    pass\n"
        "reg.counter(f'dyn/{name}')  # dynamic: caller's problem\n"
        "reg.counter(name)  # non-literal\n")
    assert scan_file(str(p)) == [
        ("counter", "a/b", 1), ("gauge", "c/d", 2), ("histogram", "e_f", 3)]


def test_lint_flags_illegal_names(tmp_path):
    (tmp_path / "bad.py").write_text(
        "reg.counter('has-dash')\n"
        "reg.gauge('0leading')\n"
        "reg.histogram('ok/name')\n")
    problems = lint_source_tree(str(tmp_path))
    assert len(problems) == 2
    assert any("has-dash" in p and "bad.py:1" in p for p in problems)
    assert any("0leading" in p and "bad.py:2" in p for p in problems)


def test_lint_flags_type_conflicts_across_files(tmp_path):
    (tmp_path / "a.py").write_text("reg.counter('x/y')\n")
    (tmp_path / "sub").mkdir()
    (tmp_path / "sub" / "b.py").write_text("reg.gauge('x/y')\n")
    (problem,) = lint_source_tree(str(tmp_path))
    assert "conflicting types" in problem
    assert "'x/y'" in problem and "a.py:1" in problem
    assert "counter" in problem and "gauge" in problem


def test_lint_flags_post_sanitization_collisions(tmp_path):
    # distinct raw names that fold to the same exposition name
    (tmp_path / "a.py").write_text(
        "reg.counter('x/y')\nreg.counter('x_y')\n")
    (problem,) = lint_source_tree(str(tmp_path))
    assert "sanitize to 'x_y'" in problem
    # same raw name twice is NOT a collision
    (tmp_path / "a.py").write_text(
        "reg.counter('x/y')\nreg.counter('x/y')\n")
    assert lint_source_tree(str(tmp_path)) == []


def test_lint_skips_pycache_and_itself(tmp_path):
    (tmp_path / "__pycache__").mkdir()
    (tmp_path / "__pycache__" / "junk.py").write_text(
        "reg.counter('very-bad')\n")
    # the linter's own docstring is full of deliberately-bad examples
    (tmp_path / "metrics_lint.py").write_text("reg.counter('also-bad')\n")
    assert lint_source_tree(str(tmp_path)) == []


def test_history_segment_lint_flags_each_contract_break(tmp_path):
    import json

    from paddle_tpu.tools.metrics_lint import lint_history_segments

    ok = {"t": 1.0, "series": [
        {"name": "a/b", "labels": {"p": "w0"}, "field": "value", "v": 1}]}
    (tmp_path / "history_1_00001.jsonl").write_text(
        json.dumps(ok) + "\n"
        + "not json\n"                                      # torn? no: mid
        + json.dumps({"t": 0.5, "series": []}) + "\n"       # t backwards
        + json.dumps({"t": 2.0, "series": [
            {"name": "has-dash", "field": "value", "v": 1},
            {"name": "a/b", "field": "p17", "v": 1},
            {"name": "a/b", "field": "p99", "v": "x"},
            {"name": "a/b", "field": "p50", "v": 1,
             "labels": {"p": 3}}]}) + "\n")
    problems = lint_history_segments(str(tmp_path))
    assert any("not valid JSON" in p for p in problems)
    assert any("backwards" in p for p in problems)
    assert any("has-dash" in p for p in problems)
    assert any("p17" in p for p in problems)
    assert any("non-numeric" in p for p in problems)
    assert any("str->str" in p for p in problems)
    # a torn FINAL line of the NEWEST segment is the crash contract
    (tmp_path / "history_1_00002.jsonl").write_text(
        json.dumps(ok) + "\n" + '{"t": 3.0, "ser')
    assert not any("00002" in p
                   for p in lint_history_segments(str(tmp_path)))


def test_cli_history_mode(tmp_path, capsys):
    import json

    (tmp_path / "history_1_00001.jsonl").write_text(
        json.dumps({"t": 1.0, "series": []}) + "\n")
    assert main(["--history", str(tmp_path)]) == 0
    assert "history segments clean" in capsys.readouterr().out
    (tmp_path / "history_1_00001.jsonl").write_text("garbage\ngarbage\n")
    assert main(["--history", str(tmp_path)]) == 1


def test_cli_exit_codes(tmp_path, capsys):
    assert main([str(tmp_path)]) == 0
    assert "clean" in capsys.readouterr().out
    (tmp_path / "bad.py").write_text("reg.counter('has-dash')\n")
    assert main([str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "has-dash" in out and "1 problem(s)" in out


def test_module_entrypoint_runs_clean():
    """`python -m paddle_tpu.tools.metrics_lint` is the CI invocation;
    it must work without JAX-level setup (bastion-grade tooling)."""
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.tools.metrics_lint"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout
