"""Numeric checks for the misc op families (OpTest contract, SURVEY §4.1)."""
import numpy as np
import pytest

from op_test_base import OpTest


class _T(OpTest):
    pass


def _r(shape, seed=0, scale=1.0):
    return (np.random.RandomState(seed).randn(*shape) * scale).astype("float32")


def test_hinge_loss():
    t = _T(); t.op_type = "hinge_loss"
    x = _r((4, 1)); y = (np.random.RandomState(1).rand(4, 1) > 0.5).astype("float32")
    out = t.run_op({"Logits": x, "Labels": y}, output_slots=("Loss",))
    np.testing.assert_allclose(out["Loss"],
                               np.maximum(1 - x * (2 * y - 1), 0), rtol=1e-6)


def test_rank_loss():
    t = _T(); t.op_type = "rank_loss"
    l, r = _r((5, 1), 1), _r((5, 1), 2)
    lab = (np.random.RandomState(3).rand(5, 1) > 0.5).astype("float32")
    out = t.run_op({"Label": lab, "Left": l, "Right": r})
    np.testing.assert_allclose(out["Out"],
                               np.log1p(np.exp(l - r)) - lab * (l - r),
                               rtol=1e-5)


def test_modified_huber_loss():
    t = _T(); t.op_type = "modified_huber_loss"
    x = _r((8, 1), 4)
    y = (np.random.RandomState(5).rand(8, 1) > 0.5).astype("float32")
    out = t.run_op({"X": x, "Y": y}, output_slots=("IntermediateVal", "Out"))
    v = x * (2 * y - 1)
    ref = np.where(v < -1, -4 * v, np.where(v < 1, (1 - v) ** 2, 0.0))
    np.testing.assert_allclose(out["Out"], ref, rtol=1e-5)


def test_bpr_loss():
    t = _T(); t.op_type = "bpr_loss"
    x = _r((4, 6), 7)
    lab = np.random.RandomState(8).randint(0, 6, (4, 1)).astype("int64")
    out = t.run_op({"X": x, "Label": lab}, output_slots=("Y",))
    ref = np.zeros((4, 1), "float32")
    for i in range(4):
        p = x[i, lab[i, 0]]
        ref[i, 0] = sum(np.log1p(np.exp(x[i, j] - p))
                        for j in range(6) if j != lab[i, 0]) / 5
    np.testing.assert_allclose(out["Y"], ref, rtol=1e-5)


def test_squared_l2_distance():
    t = _T(); t.op_type = "squared_l2_distance"
    x, y = _r((3, 5), 1), _r((3, 5), 2)
    out = t.run_op({"X": x, "Y": y}, output_slots=("sub_result", "Out"))
    np.testing.assert_allclose(out["Out"],
                               ((x - y) ** 2).sum(1, keepdims=True), rtol=1e-5)


def test_label_smooth():
    t = _T(); t.op_type = "label_smooth"
    x = np.eye(4, dtype="float32")
    out = t.run_op({"X": x}, attrs={"epsilon": 0.1})
    np.testing.assert_allclose(out["Out"], 0.9 * x + 0.1 / 4, rtol=1e-6)


def test_selu_and_grad():
    t = _T(); t.op_type = "selu"
    x = _r((6,), 3)
    out = t.run_op({"X": x})
    scale, alpha = 1.0507009873554805, 1.6732632423543772
    ref = scale * np.where(x > 0, x, alpha * (np.exp(x) - 1))
    np.testing.assert_allclose(out["Out"], ref, rtol=1e-5)
    t.check_grad({"X": x}, {}, "X", "Out")


def test_norm():
    t = _T(); t.op_type = "norm"
    x = _r((3, 4), 2)
    out = t.run_op({"X": x}, attrs={"axis": 1}, output_slots=("Out", "Norm"))
    nrm = np.sqrt((x ** 2).sum(1, keepdims=True) + 1e-10)
    np.testing.assert_allclose(out["Out"], x / nrm, rtol=1e-5)


def test_multiplex():
    t = _T(); t.op_type = "multiplex"
    xs = [_r((4, 3), i) for i in range(3)]
    ids = np.array([[2], [0], [1], [2]], dtype="int32")
    out = t.run_op({"Ids": ids, "X": xs})
    ref = np.stack([xs[ids[i, 0]][i] for i in range(4)])
    np.testing.assert_allclose(out["Out"], ref, rtol=1e-6)


def test_reverse_crop_pad():
    t = _T(); t.op_type = "reverse"
    x = _r((3, 4), 1)
    out = t.run_op({"X": x}, attrs={"axis": [1]})
    np.testing.assert_allclose(out["Out"], x[:, ::-1], rtol=1e-6)

    t2 = _T(); t2.op_type = "crop"
    out = t2.run_op({"X": x}, attrs={"offsets": [1, 1], "shape": [2, 2]})
    np.testing.assert_allclose(out["Out"], x[1:3, 1:3], rtol=1e-6)

    t3 = _T(); t3.op_type = "pad_constant_like"
    y = _r((2, 2), 2)
    out = t3.run_op({"X": x, "Y": y}, attrs={"pad_value": 0.5})
    ref = np.full((3, 4), 0.5, "float32"); ref[:2, :2] = y
    np.testing.assert_allclose(out["Out"], ref, rtol=1e-6)


def test_space_to_depth_pixel_shuffle_roundtrip():
    t = _T(); t.op_type = "space_to_depth"
    x = _r((2, 3, 4, 4), 5)
    out = t.run_op({"X": x}, attrs={"blocksize": 2})
    assert out["Out"].shape == (2, 12, 2, 2)

    t2 = _T(); t2.op_type = "pixel_shuffle"
    y = _r((2, 8, 3, 3), 6)
    out2 = t2.run_op({"X": y}, attrs={"upscale_factor": 2})
    assert out2["Out"].shape == (2, 2, 6, 6)
    # matches the torch/paddle pixel_shuffle reference
    ref = y.reshape(2, 2, 2, 2, 3, 3).transpose(0, 1, 4, 2, 5, 3).reshape(2, 2, 6, 6)
    np.testing.assert_allclose(out2["Out"], ref, rtol=1e-6)


def test_shuffle_channel():
    t = _T(); t.op_type = "shuffle_channel"
    x = np.arange(2 * 6 * 1 * 1, dtype="float32").reshape(2, 6, 1, 1)
    out = t.run_op({"X": x}, attrs={"group": 2})
    ref = x.reshape(2, 2, 3, 1, 1).transpose(0, 2, 1, 3, 4).reshape(2, 6, 1, 1)
    np.testing.assert_allclose(out["Out"], ref)


def test_affine_channel():
    t = _T(); t.op_type = "affine_channel"
    x = _r((2, 3, 2, 2), 1)
    s, b = _r((3,), 2), _r((3,), 3)
    out = t.run_op({"X": x, "Scale": s, "Bias": b})
    np.testing.assert_allclose(
        out["Out"], x * s.reshape(1, 3, 1, 1) + b.reshape(1, 3, 1, 1),
        rtol=1e-5)


def test_lrn():
    t = _T(); t.op_type = "lrn"
    x = _r((1, 6, 2, 2), 4)
    out = t.run_op({"X": x}, attrs={"n": 5}, output_slots=("Out", "MidOut"))
    # numpy reference
    sq = x ** 2
    pad = np.pad(sq, ((0, 0), (2, 2), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + 6] for i in range(5))
    mid = 2.0 + 1e-4 * acc
    np.testing.assert_allclose(out["Out"], x / mid ** 0.75, rtol=1e-5)


def test_add_position_encoding():
    t = _T(); t.op_type = "add_position_encoding"
    x = np.zeros((1, 4, 6), "float32")
    out = t.run_op({"X": x}, attrs={"alpha": 1.0, "beta": 1.0})
    o = out["Out"]
    pos = np.arange(4)[:, None]
    i = np.arange(3)[None, :]
    ang = pos / np.power(10000.0, 2.0 * i / 6)
    ref = np.concatenate([np.sin(ang), np.cos(ang)], 1)[None]
    np.testing.assert_allclose(o, ref, rtol=1e-5)


def test_bilinear_tensor_product():
    t = _T(); t.op_type = "bilinear_tensor_product"
    x, y = _r((3, 4), 1), _r((3, 5), 2)
    w = _r((2, 4, 5), 3)
    out = t.run_op({"X": x, "Y": y, "Weight": w})
    ref = np.einsum("bi,kij,bj->bk", x, w, y)
    np.testing.assert_allclose(out["Out"], ref, rtol=1e-4, atol=1e-5)


def test_row_conv():
    t = _T(); t.op_type = "row_conv"
    x = _r((2, 5, 3), 1)
    w = _r((2, 3), 2)
    out = t.run_op({"X": x, "Filter": w})
    ref = np.zeros_like(x)
    for ti in range(5):
        for i in range(2):
            if ti + i < 5:
                ref[:, ti] += x[:, ti + i] * w[i]
    np.testing.assert_allclose(out["Out"], ref, rtol=1e-5)


def test_grid_sampler_identity():
    t = _T(); t.op_type = "grid_sampler"
    x = _r((1, 2, 4, 4), 3)
    ys, xs = np.meshgrid(np.linspace(-1, 1, 4), np.linspace(-1, 1, 4),
                         indexing="ij")
    grid = np.stack([xs, ys], -1)[None].astype("float32")
    out = t.run_op({"X": x, "Grid": grid}, output_slots=("Output",))
    np.testing.assert_allclose(out["Output"], x, rtol=1e-4, atol=1e-5)


def test_interp_nearest_and_bilinear():
    t = _T(); t.op_type = "nearest_interp"
    x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    out = t.run_op({"X": x}, attrs={"out_h": 2, "out_w": 2,
                                    "align_corners": False})
    np.testing.assert_allclose(out["Out"], x[:, :, ::2, ::2])

    t2 = _T(); t2.op_type = "bilinear_interp"
    out2 = t2.run_op({"X": x}, attrs={"out_h": 8, "out_w": 8,
                                      "align_corners": False})
    assert out2["Out"].shape == (1, 1, 8, 8)


def test_max_pool2d_with_index_and_unpool():
    t = _T(); t.op_type = "max_pool2d_with_index"
    x = _r((1, 1, 4, 4), 9)
    out = t.run_op({"X": x}, attrs={"ksize": [2, 2], "strides": [2, 2],
                                    "paddings": [0, 0]},
                   output_slots=("Out", "Mask"))
    ref = x.reshape(1, 1, 2, 2, 2, 2).transpose(0, 1, 2, 4, 3, 5).reshape(
        1, 1, 2, 2, 4).max(-1)
    np.testing.assert_allclose(out["Out"], ref, rtol=1e-6)

    t2 = _T(); t2.op_type = "unpool"
    out2 = t2.run_op({"X": out["Out"], "Indices": out["Mask"]},
                     attrs={"unpooled_size": [4, 4]})
    up = out2["Out"]
    # every max value lands back at its argmax position, zeros elsewhere
    assert up.shape == (1, 1, 4, 4)
    np.testing.assert_allclose(np.sort(up[up != 0]),
                               np.sort(out["Out"].ravel()), rtol=1e-6)


def test_pool3d():
    t = _T(); t.op_type = "pool3d"
    x = _r((1, 2, 4, 4, 4), 2)
    out = t.run_op({"X": x}, attrs={"ksize": [2, 2, 2], "strides": [2, 2, 2],
                                    "paddings": [0, 0, 0],
                                    "pooling_type": "max"})
    assert out["Out"].shape == (1, 2, 2, 2, 2)
    np.testing.assert_allclose(
        out["Out"][0, 0, 0, 0, 0], x[0, 0, :2, :2, :2].max(), rtol=1e-6)


def test_v2_aliases_emit_xshape():
    t = _T(); t.op_type = "reshape2"
    x = _r((2, 6), 1)
    out = t.run_op({"X": x}, attrs={"shape": [3, 4]},
                   output_slots=("Out", "XShape"))
    np.testing.assert_allclose(out["Out"], x.reshape(3, 4))
    assert out["XShape"].shape == (0, 2, 6)

    t2 = _T(); t2.op_type = "transpose2"
    out2 = t2.run_op({"X": x}, attrs={"axis": [1, 0]},
                     output_slots=("Out", "XShape"))
    np.testing.assert_allclose(out2["Out"], x.T)

    t3 = _T(); t3.op_type = "unsqueeze2"
    out3 = t3.run_op({"X": x}, attrs={"axes": [0]},
                     output_slots=("Out", "XShape"))
    assert out3["Out"].shape == (1, 2, 6)


def test_cross_entropy2():
    t = _T(); t.op_type = "cross_entropy2"
    p = np.random.RandomState(2).dirichlet(np.ones(5), 4).astype("float32")
    lab = np.random.RandomState(3).randint(0, 5, (4, 1)).astype("int64")
    out = t.run_op({"X": p, "Label": lab},
                   output_slots=("Y", "MatchX", "XShape"))
    ref = -np.log([p[i, lab[i, 0]] for i in range(4)]).astype("float32")
    np.testing.assert_allclose(out["Y"].ravel(), ref, rtol=1e-5)


def test_mean_iou():
    t = _T(); t.op_type = "mean_iou"
    pred = np.array([[0, 1], [1, 1]], dtype="int32")
    lab = np.array([[0, 1], [0, 1]], dtype="int32")
    out = t.run_op({"Predictions": pred, "Labels": lab},
                   attrs={"num_classes": 2},
                   output_slots=("OutMeanIou", "OutWrong", "OutCorrect"))
    # class0: inter 1, union 2 → 0.5 ; class1: inter 2, union 3 → 2/3
    np.testing.assert_allclose(out["OutMeanIou"], [(0.5 + 2 / 3) / 2],
                               rtol=1e-5)


def test_temporal_shift():
    t = _T(); t.op_type = "temporal_shift"
    x = _r((4, 4, 2, 2), 6)   # N*T=4 with T=2
    out = t.run_op({"X": x}, attrs={"seg_num": 2, "shift_ratio": 0.25})
    assert out["Out"].shape == x.shape
    v = x.reshape(2, 2, 4, 2, 2)
    # first quarter shifted forward: out[:,0,0] = v[:,1,0]
    np.testing.assert_allclose(out["Out"].reshape(2, 2, 4, 2, 2)[:, 0, 0],
                               v[:, 1, 0], rtol=1e-6)


def test_sampling_id_and_batch_size_like():
    t = _T(); t.op_type = "sampling_id"
    p = np.array([[0.0, 1.0, 0.0], [1.0, 0.0, 0.0]], dtype="float32")
    out = t.run_op({"X": p})
    np.testing.assert_array_equal(out["Out"].astype(int), [1, 0])

    t2 = _T(); t2.op_type = "uniform_random_batch_size_like"
    ref = np.zeros((5, 3), "float32")
    out2 = t2.run_op({"Input": ref}, attrs={"shape": [1, 7], "min": 0.0,
                                            "max": 1.0})
    assert out2["Out"].shape == (5, 7)
    assert (out2["Out"] >= 0).all() and (out2["Out"] <= 1).all()
