"""MNIST LeNet convergence smoke — reference parity:
python/paddle/fluid/tests/book/test_recognize_digits.py (BASELINE config 1).

Uses synthetic separable data (no dataset download in CI); checks the full
spine: layers → IR → executor → XLA, loss decreasing, accuracy rising.
"""
import numpy as np

import paddle_tpu as fluid


def _make_data(n, seed=0):
    """Synthetic 'digits': class k = template k + noise."""
    rng = np.random.RandomState(seed)
    templates = rng.rand(10, 1, 28, 28).astype("float32")
    labels = rng.randint(0, 10, size=n).astype("int64")
    imgs = templates[labels] + 0.1 * rng.randn(n, 1, 28, 28).astype("float32")
    return imgs, labels.reshape(-1, 1)


def lenet(img, label):
    conv1 = fluid.layers.conv2d(img, num_filters=6, filter_size=5, padding=2, act="relu")
    pool1 = fluid.layers.pool2d(conv1, pool_size=2, pool_stride=2)
    conv2 = fluid.layers.conv2d(pool1, num_filters=16, filter_size=5, act="relu")
    pool2 = fluid.layers.pool2d(conv2, pool_size=2, pool_stride=2)
    fc1 = fluid.layers.fc(pool2, size=120, act="relu")
    fc2 = fluid.layers.fc(fc1, size=84, act="relu")
    logits = fluid.layers.fc(fc2, size=10)
    loss = fluid.layers.softmax_with_cross_entropy(logits, label)
    avg_loss = fluid.layers.mean(loss)
    probs = fluid.layers.softmax(logits)
    acc = fluid.layers.accuracy(probs, label)
    return avg_loss, acc


def test_mnist_lenet_converges():
    main = fluid.Program()
    startup = fluid.Program()
    main.random_seed = 42
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", [1, 28, 28])
        label = fluid.layers.data("label", [1], dtype="int64")
        avg_loss, acc = lenet(img, label)
        opt = fluid.optimizer.Adam(learning_rate=5e-3)
        opt.minimize(avg_loss)

        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)

        imgs, labels = _make_data(256)
        bs = 64
        losses, accs = [], []
        for epoch in range(12):
            for i in range(0, len(imgs), bs):
                lv, av = exe.run(
                    main,
                    feed={"img": imgs[i:i + bs], "label": labels[i:i + bs]},
                    fetch_list=[avg_loss, acc])
                losses.append(float(lv))
                accs.append(float(av))

    first = np.mean(losses[:4])
    last = np.mean(losses[-4:])
    assert last < first * 0.5, f"loss did not converge: {first} -> {last}"
    assert np.mean(accs[-4:]) > 0.9, f"accuracy too low: {np.mean(accs[-4:])}"


def test_mnist_mlp_infer_matches_train_graph():
    """clone(for_test) path: inference program shares trained params."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", [784])
        label = fluid.layers.data("label", [1], dtype="int64")
        h = fluid.layers.fc(img, 64, act="relu")
        h = fluid.layers.dropout(h, 0.3, dropout_implementation="upscale_in_train")
        logits = fluid.layers.fc(h, 10)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        test_prog = main.clone(for_test=True)
        opt = fluid.optimizer.SGD(0.1)
        opt.minimize(loss)

        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        x = np.random.rand(8, 784).astype("float32")
        y = np.random.randint(0, 10, (8, 1)).astype("int64")
        exe.run(main, feed={"img": x, "label": y}, fetch_list=[loss])
        # inference is deterministic (dropout off)
        (a,) = exe.run(test_prog, feed={"img": x, "label": y}, fetch_list=[logits])
        (b,) = exe.run(test_prog, feed={"img": x, "label": y}, fetch_list=[logits])
        np.testing.assert_allclose(a, b)


def test_resnet50_convergence_smoke():
    """Depth-50 static-graph ResNet trains and the loss decreases
    (BASELINE config 2; reference book/test_image_classification.py)."""
    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    with fluid.scope_guard(fluid.Scope()):
        main, startup, feeds, loss, acc = resnet.build_train_program(
            depth=50, num_classes=10, lr=0.01, img_shape=(3, 32, 32))
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(0)
        img = rng.randn(4, 3, 32, 32).astype("float32") * 0.1
        label = rng.randint(0, 10, (4, 1)).astype("int64")
        losses = []
        for _ in range(6):
            l, _ = exe.run(main, feed={"img": img, "label": label},
                           fetch_list=[loss, acc])
            losses.append(float(l))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses
