"""MoE + expert parallelism (new capability — no reference analog; the
reference's sparse story is pserver embeddings, parameter_prefetch.cc).

Checks: static-capacity router invariants, dense == expert-parallel outputs
and gradients on the 8-device CPU mesh, balance loss behavior."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from paddle_tpu.parallel import moe


def _params(d=16, h=32, e=8, seed=0):
    return moe.init_moe_params(jax.random.PRNGKey(seed), d, h, e)


def test_gating_capacity_and_weights():
    d, e, n = 16, 8, 64
    x = jax.random.normal(jax.random.PRNGKey(1), (n, d))
    gw = jax.random.normal(jax.random.PRNGKey(2), (d, e)) * 0.2
    out = moe.top_k_gating(x, gw, k=2, capacity_factor=1.0)
    nc = out.dispatch.shape[2]
    # no expert slot double-booked: each (e, c) pair holds at most one token
    per_slot = np.asarray(out.dispatch).sum(axis=0)
    assert per_slot.max() <= 1
    # combine weights of a kept token sum to ≤ 1 (renormalized top-k)
    tok_mass = np.asarray(out.combine).sum(axis=(1, 2))
    assert tok_mass.max() <= 1.0 + 1e-5
    # capacity = ceil(k*n/e * 1.0)
    assert nc == int(np.ceil(2 * n / e))
    assert np.isfinite(float(out.aux_loss))


def test_dense_moe_shapes_and_grads():
    d, h, e, n = 16, 32, 8, 32
    gw, w1, b1, w2, b2 = _params(d, h, e)
    x = jax.random.normal(jax.random.PRNGKey(3), (n, d))

    def loss_fn(params):
        y, aux = moe.moe_ffn(x, *params, k=2, capacity_factor=2.0)
        return jnp.mean(y ** 2) + 0.01 * aux

    loss, grads = jax.value_and_grad(loss_fn)((gw, w1, b1, w2, b2))
    assert np.isfinite(float(loss))
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).max()) > 0.0


@pytest.mark.parametrize("ep", [4, 8])
def test_expert_parallel_matches_dense(ep):
    d, h, e = 16, 32, 8
    n = 8 * 16  # divisible by ep
    gw, w1, b1, w2, b2 = _params(d, h, e)
    x = jax.random.normal(jax.random.PRNGKey(4), (n, d))
    mesh = Mesh(np.array(jax.devices()[:ep]), ("ep",))

    y_ep, aux_ep = moe.moe_ffn_expert_parallel(
        x, gw, w1, b1, w2, b2, mesh, axis="ep", k=2, capacity_factor=8.0)

    # dense reference on each shard's tokens independently (the EP router
    # runs per-shard); ample capacity → no drops → results equal
    ys = []
    auxs = []
    for s in range(ep):
        xs = x[s * (n // ep):(s + 1) * (n // ep)]
        y, aux = moe.moe_ffn(xs, gw, w1, b1, w2, b2, k=2, capacity_factor=8.0)
        ys.append(y)
        auxs.append(aux)
    y_ref = jnp.concatenate(ys)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                               rtol=2e-5, atol=2e-5)
    # EP aux loss is the pmean of per-shard stats; compare to the average
    np.testing.assert_allclose(
        float(aux_ep),
        float(e * jnp.sum(
            jnp.mean(jnp.stack([_top1_frac(xs_i, gw, e) for xs_i in
                                jnp.split(x, ep)]), 0)
            * jnp.mean(jnp.stack([_prob_frac(xs_i, gw) for xs_i in
                                  jnp.split(x, ep)]), 0))),
        rtol=1e-4)


def _top1_frac(xs, gw, e):
    p = jax.nn.softmax(xs.astype(jnp.float32) @ gw, -1)
    return jnp.mean(jax.nn.one_hot(jnp.argmax(p, -1), e), axis=0)


def _prob_frac(xs, gw):
    return jnp.mean(jax.nn.softmax(xs.astype(jnp.float32) @ gw, -1), axis=0)


def test_expert_parallel_grads_match_dense():
    d, h, e, ep = 8, 16, 4, 4
    n = 4 * 8
    gw, w1, b1, w2, b2 = _params(d, h, e, seed=7)
    x = jax.random.normal(jax.random.PRNGKey(5), (n, d))
    mesh = Mesh(np.array(jax.devices()[:ep]), ("ep",))

    def loss_ep(params):
        y, aux = moe.moe_ffn_expert_parallel(
            x, *params, mesh=mesh, axis="ep", k=1, capacity_factor=8.0)
        return jnp.sum(y ** 2) + 0.1 * aux

    def loss_dense(params):
        gw = params[0]
        tot = 0.0
        for xs in jnp.split(x, ep):
            y, _ = moe.moe_ffn(xs, *params, k=1, capacity_factor=8.0)
            tot = tot + jnp.sum(y ** 2)
        # EP aux pools f/P stats across shards BEFORE the product
        shards = jnp.split(x, ep)
        f = jnp.mean(jnp.stack([_top1_frac(s, gw, e) for s in shards]), 0)
        p = jnp.mean(jnp.stack([_prob_frac(s, gw) for s in shards]), 0)
        return tot + 0.1 * (e * jnp.sum(f * p))

    g_ep = jax.grad(loss_ep)((gw, w1, b1, w2, b2))
    g_dn = jax.grad(loss_dense)((gw, w1, b1, w2, b2))
    for a, b in zip(g_ep, g_dn):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_moe_under_jit_train_step():
    """One Adam-style step of a tiny MoE block, jitted over the ep mesh."""
    import optax  # baked in

    d, h, e, ep, n = 8, 16, 8, 8, 64
    params = _params(d, h, e, seed=9)
    mesh = Mesh(np.array(jax.devices()[:ep]), ("ep",))
    x = jax.random.normal(jax.random.PRNGKey(6), (n, d))
    opt = optax.adam(1e-3)
    state = opt.init(params)

    @jax.jit
    def step(params, state, x):
        def loss_fn(p):
            y, aux = moe.moe_ffn_expert_parallel(
                x, *p, mesh=mesh, axis="ep", k=2, capacity_factor=2.0)
            return jnp.mean((y - x) ** 2) + 0.01 * aux
        loss, grads = jax.value_and_grad(loss_fn)(params)
        upd, state = opt.update(grads, state)
        return optax.apply_updates(params, upd), state, loss

    p1, s1, l1 = step(params, state, x)
    p2, s2, l2 = step(p1, s1, x)
    assert np.isfinite(float(l1)) and np.isfinite(float(l2))
    assert float(l2) < float(l1)


def test_moe_layer_static_graph_trains():
    """layers.moe_ffn in a static program: trains dense, loss decreases."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [16])
        y = layers.data("y", [16])
        h, aux = layers.moe_ffn(x, num_experts=4, hidden_size=32, k=2,
                                capacity_factor=4.0)
        mse = layers.reduce_mean(layers.square(layers.elementwise_sub(h, y)))
        loss = layers.elementwise_add(mse, layers.scale(aux, scale=0.01))
        fluid.optimizer.Adam(0.01).minimize(loss)

    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(32, 16).astype("float32"),
            "y": rng.rand(32, 16).astype("float32")}
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        losses = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])
                  for _ in range(15)]
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_moe_layer_expert_parallel_matches_dense():
    """Same program compiled over an ep mesh == plain executor losses."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.parallel import make_mesh

    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            main.random_seed = startup.random_seed = 7
            x = layers.data("x", [16])
            y = layers.data("y", [16])
            h, aux = layers.moe_ffn(x, num_experts=8, hidden_size=32, k=1,
                                    capacity_factor=8.0)
            mse = layers.reduce_mean(
                layers.square(layers.elementwise_sub(h, y)))
            loss = layers.elementwise_add(mse, layers.scale(aux, scale=0.01))
            fluid.optimizer.SGD(0.05).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(1)
    feed = {"x": rng.rand(32, 16).astype("float32"),
            "y": rng.rand(32, 16).astype("float32")}

    main, startup, loss = build()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        ref = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])
               for _ in range(4)]

    main, startup, loss = build()
    mesh = make_mesh({"ep": 8})
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        prog = fluid.CompiledProgram(main).with_mesh(mesh, data_axis="ep")
        got = [float(exe.run(prog, feed=feed, fetch_list=[loss])[0])
               for _ in range(4)]

    # EP router runs per-shard (local capacity/cumsum); with ample capacity
    # no tokens drop, so combine weights — and losses — match the dense run.
    # aux differs only by stat pooling order, covered by the tolerance on
    # the 0.01-scaled term.
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_moe_layer_custom_param_attr_distinct_params():
    """A user-supplied param_attr must yield five distinct parameters (a
    shared attr would alias all five under one name)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.param_attr import ParamAttr

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [8])
        h, aux = layers.moe_ffn(x, num_experts=2, hidden_size=4,
                                param_attr=ParamAttr(name="moe0",
                                                     learning_rate=0.5))
    names = [v.name for v in main.global_block().all_parameters()]
    assert len(names) == len(set(names)) == 5, names
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        out = exe.run(main, feed={"x": np.zeros((4, 8), "float32")},
                      fetch_list=[h])
        assert out[0].shape == (4, 8)
